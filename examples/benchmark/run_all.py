"""Run every measured benchmark config and print the README table's numbers.

One command reproduces the performance claims (the reference's benchmark suite
was likewise driven per-config by flags; this adds the sweep driver):

    python examples/benchmark/run_all.py                 # everything (~20 min)
    python examples/benchmark/run_all.py --only resnet50,bert_base
    python examples/benchmark/run_all.py --steps 30      # quicker, noisier

Each config runs in a fresh subprocess (one AutoDist instance per process, the
reference's own isolation rule) and reports its average throughput; results
print as a table and optionally a JSON file.
"""

import argparse
import json
import os
import re
import subprocess
import sys

ROOT = os.path.join(os.path.dirname(__file__), "..", "..")

# --update_baseline refuses runs shorter than the sweep length: sub-sweep
# rates are noisy, and the baseline only ratchets up.
MIN_BASELINE_STEPS = 60


def _probe_devices():
    """(device_count, backend) of the platform the benchmark subprocesses will
    see — probed in a subprocess so run_all itself never initializes a chip."""
    probe = subprocess.run(
        [sys.executable, "-c",
         "import jax; print(len(jax.devices()), jax.default_backend())"],
        capture_output=True, text=True)
    try:
        count, backend = probe.stdout.strip().split()[-2:]
        return int(count), backend
    except (ValueError, IndexError):
        return 1, "unknown"

# name -> (argv builder, unit, number regex over combined output)
RATE = r"([\d,]+\.?\d*)"
CONFIGS = {
    "flagship": (lambda s: [os.path.join(ROOT, "bench.py")],
                 "tokens/s", r'"value": ([\d.]+)'),
    "resnet50": (lambda s: [os.path.join(ROOT, "examples/benchmark/imagenet.py"),
                            "--model", "resnet50", "--strategy", "AllReduce",
                            "--batch_size", "256", "--steps", s, "--log_every", s],
                 "examples/s", RATE + r" examples/sec"),
    "vgg16": (lambda s: [os.path.join(ROOT, "examples/benchmark/imagenet.py"),
                         "--model", "vgg16", "--strategy", "PartitionedPS",
                         "--batch_size", "256", "--steps", s, "--log_every", s],
              "examples/s", RATE + r" examples/sec"),
    "densenet121": (lambda s: [os.path.join(ROOT, "examples/benchmark/imagenet.py"),
                               "--model", "densenet121", "--batch_size", "128",
                               "--steps", s, "--log_every", s],
                    "examples/s", RATE + r" examples/sec"),
    "inceptionv3": (lambda s: [os.path.join(ROOT, "examples/benchmark/imagenet.py"),
                               "--model", "inceptionv3", "--batch_size", "128",
                               "--steps", s, "--log_every", s],
                    "examples/s", RATE + r" examples/sec"),
    "bert_base": (lambda s: [os.path.join(ROOT, "examples/benchmark/bert.py"),
                             "--size", "base", "--batch_size", "2048",
                             "--accum", "8", "--steps", s, "--log_every", s],
                  "examples/s", RATE + r" examples/sec"),
    "bert_large": (lambda s: [os.path.join(ROOT, "examples/benchmark/bert.py"),
                              "--size", "large", "--batch_size", "128",
                              "--steps", s, "--log_every", s],
                   "examples/s", RATE + r" examples/sec"),
    "lm1b_lstm": (lambda s: [os.path.join(ROOT, "examples/lm1b/lm1b_train.py"),
                             "--model", "lstm", "--steps", s, "--log_every", s],
                  "words/s", RATE + r" words/sec"),
    "ncf": (lambda s: [os.path.join(ROOT, "examples/benchmark/ncf.py"),
                       "--steps", s, "--log_every", s],
            "examples/s", RATE + r" examples/sec"),
    "moe": (lambda s: [os.path.join(ROOT, "examples/moe_lm.py"),
                       "--batch_size", "512", "--accum", "4",
                       "--steps", s, "--log_every", s],
            "tokens/s", RATE + r" tokens/sec"),
}


def run_config(name: str, steps: str, attempts: int = 2):
    builder, unit, pattern = CONFIGS[name]
    cmd = [sys.executable] + builder(steps)
    for attempt in range(attempts):
        proc = subprocess.run(cmd, capture_output=True, text=True)
        out = proc.stdout + proc.stderr
        if proc.returncode == 0:
            break
        # Transient platform failures (HBM-margin OOM right after another
        # config's process released memory, compile-tunnel hiccups) deserve
        # one retry before the row reads FAILED.
        if attempt < attempts - 1:
            print(f"  {name}: attempt {attempt + 1} failed, retrying ...",
                  flush=True)
    if proc.returncode != 0:
        return {"name": name, "unit": unit, "rate": None, "mfu_pct": None,
                "error": out.strip().splitlines()[-1] if out.strip() else "failed"}
    matches = re.findall(pattern, out)
    if not matches:
        return {"name": name, "unit": unit, "rate": None, "mfu_pct": None,
                "error": "no rate found in output"}
    rate = float(matches[-1].replace(",", ""))
    # Scripts print a shared "mfu N.NN%" line (flops.report_mfu); bench.py
    # reports the fraction in its JSON line instead.
    mfu_pct = None
    m = re.findall(r"mfu ([\d.]+)%", out)
    if m:
        mfu_pct = float(m[-1])
    else:
        m = re.findall(r'"mfu": ([\d.]+)', out)
        if m:
            mfu_pct = round(100.0 * float(m[-1]), 2)
    return {"name": name, "unit": unit, "rate": rate, "mfu_pct": mfu_pct,
            "error": None}


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--only", type=str, default="",
                        help="comma-separated subset of: " + ",".join(CONFIGS))
    parser.add_argument("--steps", type=int, default=60,
                        help="steps per config (flagship runs bench.py, which "
                             "has its own fixed length and ignores this)")
    parser.add_argument("--json", type=str, default="",
                        help="also write results to this JSON file")
    parser.add_argument("--list", action="store_true", help="list configs and exit")
    parser.add_argument("--baseline", type=str,
                        default=os.path.join(ROOT, "PERF_BASELINE.json"),
                        help="recorded-best snapshot to diff against "
                             "('' disables the comparison)")
    parser.add_argument("--update_baseline", action="store_true",
                        help="raise snapshot rows that this run beat "
                             "(never lowers a row)")
    args = parser.parse_args(argv)

    if args.list:
        for name in CONFIGS:
            print(name)
        return []

    if args.update_baseline and args.steps < MIN_BASELINE_STEPS:
        # Reject the combination BEFORE the (potentially hour-long) run, not
        # after it: short runs are noisy, and the baseline only ratchets up.
        parser.error(f"--update_baseline needs --steps >= {MIN_BASELINE_STEPS}"
                     f": a ratcheted noise outlier makes every honest later "
                     f"run read as a regression")

    names = [n.strip() for n in args.only.split(",") if n.strip()] or list(CONFIGS)
    unknown = [n for n in names if n not in CONFIGS]
    if unknown:
        parser.error(f"unknown configs {unknown}; valid: {sorted(CONFIGS)}")

    results = []
    for name in names:
        print(f"running {name} ...", flush=True)
        results.append(run_config(name, str(args.steps)))

    # Regression gate: diff each row against the recorded best. Steps below
    # the sweep length are noisier, so the gate only annotates — failures
    # stay human decisions; the >threshold rows are impossible to miss.
    # The snapshot records PER-CHIP ACCELERATOR rates: normalize by device
    # count, and skip the comparison entirely on CPU (a different machine).
    baseline = {}
    snapshot = None
    threshold = 2.0
    n_dev, backend = _probe_devices()
    if backend in ("cpu", "unknown"):
        # "unknown" means the probe subprocess itself failed: comparing host
        # rates against recorded per-chip accelerator bests would print
        # spurious REGRESSION rows, so treat it like CPU — but surface the
        # probe failure instead of silently skipping.
        if backend == "unknown":
            print("\nWARNING: device probe failed (could not determine the "
                  "backend); PERF_BASELINE comparison skipped — recorded "
                  "bests are accelerator chip rates", file=sys.stderr)
        else:
            print("\n(CPU backend: PERF_BASELINE comparison skipped — "
                  "recorded bests are chip rates)")
    elif args.baseline and os.path.exists(args.baseline):
        with open(args.baseline) as f:
            snapshot = json.load(f)
        baseline = snapshot.get("rows", {})
        threshold = snapshot.get("threshold_pct", 2.0)
    elif args.baseline and args.update_baseline:
        # First measured run on a fresh checkout: start a snapshot so every
        # config gains a gate row now rather than never.
        snapshot = {"threshold_pct": threshold, "rows": {}}

    width = max(len(r["name"]) for r in results)
    regressions = []
    print()
    for r in results:
        if r["rate"] is None:
            print(f"{r['name']:<{width}}  FAILED: {r['error']}")
            continue
        mfu = (f"  mfu {r['mfu_pct']:.1f}%" if r.get("mfu_pct") is not None
               else "")
        delta = ""
        best = baseline.get(r["name"], {}).get("rate")
        if best:
            per_chip = r["rate"] / max(n_dev, 1)
            pct = 100.0 * (per_chip / best - 1.0)
            r["vs_best_pct"] = round(pct, 2)
            delta = f"  {pct:+.1f}% vs best"
            if pct < -threshold:
                delta += "  << REGRESSION"
                regressions.append((r["name"], pct))
        print(f"{r['name']:<{width}}  {r['rate']:>14,.1f} {r['unit']}{mfu}{delta}")
    if regressions:
        print(f"\n{len(regressions)} row(s) regressed more than {threshold}% "
              f"vs {args.baseline}: "
              + ", ".join(f"{n} ({p:+.1f}%)" for n, p in regressions))
    if args.update_baseline and snapshot is not None:
        raised, created = [], []
        for r in results:
            per_chip = (r["rate"] / max(n_dev, 1)
                        if r["rate"] is not None else None)
            if per_chip is None:
                continue
            row = snapshot.setdefault("rows", {}).get(r["name"])
            if row is None:
                # A renamed/new benchmark config must enter the regression
                # gate on its first measured run, not silently escape it.
                snapshot["rows"][r["name"]] = {
                    "rate": round(per_chip, 1), "unit": r["unit"],
                    "recorded": "run_all --update_baseline (per-chip, new row)"}
                created.append(r["name"])
            elif per_chip > row["rate"]:
                row["rate"] = round(per_chip, 1)
                row["recorded"] = "run_all --update_baseline (per-chip)"
                raised.append(r["name"])
        if raised or created:
            with open(args.baseline, "w") as f:
                json.dump(snapshot, f, indent=1)
            if raised:
                print(f"baseline raised for: {', '.join(raised)}")
            if created:
                print(f"baseline rows created for: {', '.join(created)}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
    return results


if __name__ == "__main__":
    main()
