"""Training-health monitors: on-device numerics plane + host-side policy.

A NaN that enters the parameters propagates silently — through reduce-scatter
under ZeRO sharding, through every later step's gradients — until someone
notices the loss curve days later. This module makes numeric health a
first-class per-step signal with ZERO extra dispatches:

- **Device side** (:func:`device_bundle`): inside the EXISTING jitted train
  step (``runner._make_step_body``), one fused scalar bundle is computed from
  the step's own intermediates — non-finite count over gradients+loss, global
  gradient norm, update norm, parameter norm. The bundle is four f32 scalars
  appended to the step's outputs, so it compiles into the same program and
  rides the same async dispatch; ``unroll=K`` blocks reduce it on device
  (:func:`reduce_bundle`) so a K-step program still reads back four scalars.
- **Host side** (:class:`HealthMonitor`): ``train()`` feeds the monitor at
  its EXISTING log boundaries (where the loss readback already syncs — the
  bundle readback is free), and the monitor books ``train.health.*`` gauges,
  runs an EWMA z-score loss-spike detector over the period's per-step losses,
  records structured ``health.anomaly`` events, and applies the
  ``AUTODIST_HEALTH_ACTION`` policy: ``warn`` logs, ``record`` captures a
  flight-recorder snapshot (:mod:`autodist_tpu.telemetry.recorder`), ``halt``
  raises :class:`HealthHalt` with the current :class:`TrainState` attached so
  the caller can checkpoint or inspect it.

Cost contract: with ``AUTODIST_HEALTH`` off (the default) the step body is
UNCHANGED (the branch is resolved at trace time — the disabled runner pays
one attribute read, nothing in the compiled program) and the train loop pays
one ``is None`` check per step. Enabled, the bundle is a handful of fused
reductions gated at <= 2% of a host-bound step by ``bench.py
--health-overhead``; monitored and unmonitored runs produce BIT-IDENTICAL
parameters (test-pinned) because the bundle only reads the step's
intermediates.
"""

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from autodist_tpu import const
from autodist_tpu.telemetry import metrics as _metrics
from autodist_tpu.utils import logging

__all__ = ["BUNDLE_FIELDS", "device_bundle", "reduce_bundle", "HealthConfig",
           "HealthMonitor", "HealthHalt", "HealthRecover"]

# The fused scalar bundle's layout (one f32 per field, this order). Kept
# tiny on purpose: the readback rides the log boundary's existing sync.
BUNDLE_FIELDS = ("nonfinite", "grad_norm", "update_norm", "param_norm")

ACTIONS = ("warn", "record", "halt", "recover")


class HealthHalt(RuntimeError):
    """Raised by ``train()`` under ``AUTODIST_HEALTH_ACTION=halt``: a health
    anomaly stopped the run. Carries ``step`` (the global step at the
    boundary that observed it), ``state`` (the live :class:`TrainState` —
    intact, so the caller can checkpoint or autopsy it), and ``anomalies``
    (the structured records that tripped the halt)."""

    def __init__(self, step: int, state, anomalies: List[Dict[str, Any]]):
        kinds = ",".join(sorted({a["kind"] for a in anomalies}))
        super().__init__(
            f"training halted at step {step}: health anomaly ({kinds}); "
            f"the live TrainState rides on this exception as `.state`")
        self.step = step
        self.state = state
        self.anomalies = anomalies


class HealthRecover(HealthHalt):
    """The ``recover`` action's control signal, raised at the anomalous
    boundary and CAUGHT INSIDE ``train()``: the loop rolls back to the
    newest last-known-good snapshot (``parallel/recovery.py``'s ring) and
    resumes, escalating to a plain :class:`HealthHalt` after
    ``AUTODIST_RECOVER_MAX`` attempts. A :class:`HealthHalt` subclass so
    a bare ``except HealthHalt`` in a caller that drives the loop pieces
    directly still observes it (same payload: step/state/anomalies)."""


def device_bundle(grads, updates, params, loss):
    """The fused health bundle, traced INTO the jitted step: a float32[4]
    of (non-finite probe count, global grad L2 norm, update L2 norm,
    parameter L2 norm). Pure function of the step's existing intermediates —
    it adds three tree-wide reductions to the program, never a dispatch.

    Non-finite detection rides the norms instead of a dedicated
    ``isfinite`` pass over every element (which would double the bundle's
    cost): any NaN/Inf anywhere in a tree propagates into its sum of
    squares, so the ``nonfinite`` field counts how many of the four probes
    (grad/update/param squared norms + the loss) went non-finite. A squared
    norm that OVERFLOWS f32 (a true norm above ~1e19) also flags — a
    gradient that size is an anomaly by any definition. Integer/bool leaves
    are skipped (no float numerics to go bad)."""
    import jax
    import jax.numpy as jnp

    def _sq_norm(tree):
        leaves = [l for l in jax.tree_util.tree_leaves(tree)
                  if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)]
        if not leaves:
            return jnp.zeros((), jnp.float32)
        return sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                   for l in leaves)

    g2, u2, p2 = _sq_norm(grads), _sq_norm(updates), _sq_norm(params)
    probes = jnp.stack([g2, u2, p2, jnp.asarray(loss, jnp.float32)])
    nonfinite = jnp.sum(~jnp.isfinite(probes)).astype(jnp.float32)
    return jnp.stack([nonfinite, jnp.sqrt(g2), jnp.sqrt(u2), jnp.sqrt(p2)])


def reduce_bundle(stacked):
    """Reduce a ``[K, 4]`` per-step bundle stack (an ``unroll=K`` block) to
    one ``[4]`` bundle ON DEVICE, inside the same scanned program: non-finite
    counts SUM over the block (any step's NaN survives the reduction), the
    norms take their block MAX (the worst step is the anomaly signal)."""
    import jax.numpy as jnp
    return jnp.concatenate([jnp.sum(stacked[:, :1], axis=0),
                            jnp.max(stacked[:, 1:], axis=0)])


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Monitor knobs (defaults from the ``AUTODIST_HEALTH*`` flags via
    :meth:`from_env`)."""

    action: str = "warn"   # AUTODIST_HEALTH_ACTION: warn|record|halt|recover
    z_max: float = 6.0          # AUTODIST_HEALTH_ZMAX: loss-spike threshold
    ewma_decay: float = 0.9     # EWMA decay for the loss mean/variance
    warmup: int = 8             # losses observed before z-scores can fire

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(f"unknown health action {self.action!r}; "
                             f"valid: {', '.join(ACTIONS)}")
        if not (0.0 < self.ewma_decay < 1.0):
            raise ValueError("ewma_decay must be in (0, 1)")

    @staticmethod
    def from_env(**overrides) -> "HealthConfig":
        base = dict(action=str(const.ENV.AUTODIST_HEALTH_ACTION.val),
                    z_max=const.ENV.AUTODIST_HEALTH_ZMAX.val)
        base.update(overrides)
        return HealthConfig(**base)


class HealthMonitor:
    """Host-side consumer of the device bundle + per-step losses.

    ``train()`` calls :meth:`observe` at every log boundary with the period's
    per-step losses (already synced for the throughput log line) and the
    runner's latest bundle readback. The monitor:

    - books ``train.health.{grad_norm,update_ratio,param_norm,nonfinite,
      loss_z}`` gauges and a ``train.health.grad_norm`` histogram,
    - detects NON-FINITE numerics (bundle count > 0, or a NaN/Inf boundary
      loss) and LOSS SPIKES (EWMA z-score of a finite loss above ``z_max``,
      after ``warmup`` observations),
    - records a structured ``health.anomaly`` event per finding and bumps
      ``train.health.anomalies``,
    - applies the action policy: ``warn`` logs a rate-limited warning;
      ``record`` captures a flight-recorder snapshot (the recorder is
      created on demand when none is installed); ``halt`` additionally makes
      :attr:`should_halt` true — ``train()`` raises :class:`HealthHalt` with
      the live state (the monitor never owns the state, so the raise happens
      at the call site).

    One monitor per ``train()`` call; it is NOT thread-safe (the train loop
    is its only caller).
    """

    WARN_EVERY_S = 60.0

    def __init__(self, config: Optional[HealthConfig] = None, recorder=None):
        self.config = config or HealthConfig.from_env()
        self._recorder = recorder   # None -> resolved lazily on first record
        reg = _metrics.registry()
        self._g = {f: reg.gauge(f"train.health.{f}")
                   for f in ("grad_norm", "update_ratio", "param_norm",
                             "nonfinite", "loss_z")}
        # Distribution next to the last-value gauge (the `.dist` suffix keeps
        # the name inside the NORM_BUCKETS family and out of the gauge's).
        self._grad_hist = reg.histogram("train.health.grad_norm.dist")
        self._anomaly_counter = reg.counter("train.health.anomalies")
        self._ewma: Optional[float] = None
        self._ewvar = 0.0
        self._seen = 0
        self._last_warn = -math.inf
        self.anomalies: List[Dict[str, Any]] = []   # every anomaly observed

    @property
    def should_halt(self) -> bool:
        return bool(self.anomalies) and self.config.action == "halt"

    @property
    def should_recover(self) -> bool:
        """True under ``action=recover`` with anomalies observed — the train
        loop's cue to raise :class:`HealthRecover` at the boundary (the
        monitor never owns the state, so the raise happens at the call
        site, exactly like ``should_halt``)."""
        return bool(self.anomalies) and self.config.action == "recover"

    @staticmethod
    def from_env(recorder=None) -> Optional["HealthMonitor"]:
        """The train-loop entry point: a monitor when ``AUTODIST_HEALTH`` is
        on, else None (the loop's disabled cost is one ``is None`` check)."""
        if not const.ENV.AUTODIST_HEALTH.val:
            return None
        return HealthMonitor(recorder=recorder)

    # ------------------------------------------------------------- detection

    def observe(self, step: int, losses: Sequence[float],
                bundle=None) -> List[Dict[str, Any]]:
        """Consume one log period: ``losses`` are the period's per-step loss
        values (host floats/ndarray), ``bundle`` the latest device-bundle
        readback (``float32[4]`` per :data:`BUNDLE_FIELDS`, or None when the
        runner computes no bundle). Returns the period's NEW anomaly records
        (empty when healthy)."""
        found: List[Dict[str, Any]] = []
        if bundle is not None:
            b = np.asarray(bundle, np.float64).reshape(-1)
            nonfinite = float(b[0]) if math.isfinite(float(b[0])) else 1.0
            grad_norm, update_norm, param_norm = (float(b[1]), float(b[2]),
                                                  float(b[3]))
            ratio = update_norm / max(param_norm, 1e-12)
            self._g["grad_norm"].set(grad_norm)
            self._g["update_ratio"].set(round(ratio, 8))
            self._g["param_norm"].set(param_norm)
            self._g["nonfinite"].set(nonfinite)
            if math.isfinite(grad_norm):
                self._grad_hist.observe(grad_norm)
            if nonfinite > 0 or not math.isfinite(grad_norm):
                found.append({"kind": "nonfinite", "step": step,
                              "nonfinite": nonfinite,
                              "grad_norm": grad_norm})
        for loss in np.asarray(losses, np.float64).reshape(-1):
            loss = float(loss)
            if not math.isfinite(loss):
                if not any(a["kind"] == "nonfinite" and a["step"] == step
                           for a in found):
                    found.append({"kind": "nonfinite", "step": step,
                                  "loss": loss})
                continue
            z = self._z_score(loss)
            self._g["loss_z"].set(round(z, 4))
            if self._seen > self.config.warmup and z > self.config.z_max:
                found.append({"kind": "loss_spike", "step": step,
                              "loss": round(loss, 6), "z": round(z, 3)})
            self._update_ewma(loss)
        if found:
            self._react(step, found)
        return found

    def _z_score(self, loss: float) -> float:
        if self._ewma is None or self._ewvar <= 0.0:
            return 0.0
        return (loss - self._ewma) / math.sqrt(self._ewvar)

    def _update_ewma(self, loss: float):
        self._seen += 1
        if self._ewma is None:
            self._ewma = loss
            return
        d = self.config.ewma_decay
        delta = loss - self._ewma
        self._ewma += (1.0 - d) * delta
        # EW variance (West 1979 form): tracks the loss's own scatter, so the
        # z threshold adapts to noisy objectives instead of a fixed epsilon.
        self._ewvar = d * (self._ewvar + (1.0 - d) * delta * delta)

    # ---------------------------------------------------------------- policy

    def _react(self, step: int, found: List[Dict[str, Any]]):
        import time
        from autodist_tpu import telemetry
        from autodist_tpu.telemetry import recorder as _recorder
        self.anomalies.extend(found)
        for a in found:
            self._anomaly_counter.inc()
            telemetry.event("health.anomaly", **a)
        kinds = ",".join(sorted({a["kind"] for a in found}))
        if self.config.action == "record":
            # record EXPLICITLY asks for snapshots: arm a default recorder
            # on demand when none was supplied or installed.
            if self._recorder is None:
                self._recorder = _recorder.get_or_create()
            path = self._recorder.maybe_record(f"health.{kinds}")
        elif self._recorder is not None:
            # warn/halt with a constructor-supplied recorder: honor it.
            path = self._recorder.maybe_record(f"health.{kinds}")
        else:
            # warn/halt otherwise snapshot only through an ARMED recorder
            # (AUTODIST_RECORDER=1 or telemetry.set_recorder) — the anomaly
            # event is the trigger, the action only decides how loudly to
            # react; un-armed, halt just raises and warn just logs.
            path = _recorder.maybe_record(f"health.{kinds}")
        if path:
            logging.warning("train: health anomaly (%s) at step %d — "
                            "flight-recorder snapshot at %s",
                            kinds, step, path)
            return
        now = time.monotonic()
        if now - self._last_warn >= self.WARN_EVERY_S:
            self._last_warn = now
            logging.warning("train: health anomaly (%s) at step %d: %s",
                            kinds, step, found[-1])
