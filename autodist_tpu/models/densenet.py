"""DenseNet-121 for ImageNet-class benchmarks.

Counterpart of the reference's Keras DenseNet121 benchmark entry
(``examples/benchmark/imagenet.py:150-170`` selects it with per-model AllReduce
chunk sizes). Same TPU-first choices as ``models/resnet.py``: NHWC layout,
bfloat16 activations over float32 parameters, and GroupNorm instead of BatchNorm
so the train step stays a pure function of (params, batch) with no running
statistics to synchronize. Dense blocks use pre-activation norm→relu→conv
ordering; concatenations are along the channel axis, which XLA fuses into the
following 1x1 conv on the MXU.
"""

import dataclasses
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class DenseNet121Config:
    num_classes: int = 1000
    block_sizes: Sequence[int] = (6, 12, 24, 16)   # DenseNet-121
    growth_rate: int = 32
    init_features: int = 64
    bottleneck_width: int = 4                      # 1x1 conv emits width*growth chans
    compression: float = 0.5                       # transition channel reduction
    dtype: Any = jnp.bfloat16
    norm_groups: int = 32


def _norm(channels: int, cfg: DenseNet121Config, name: str):
    from autodist_tpu.models.common import num_groups
    return nn.GroupNorm(num_groups=num_groups(channels, cfg.norm_groups),
                        dtype=cfg.dtype, name=name)


class DenseLayer(nn.Module):
    """norm→relu→1x1 conv (bottleneck) → norm→relu→3x3 conv, concat with input."""

    config: DenseNet121Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        inter = cfg.bottleneck_width * cfg.growth_rate
        y = nn.relu(_norm(x.shape[-1], cfg, "norm1")(x))
        y = nn.Conv(inter, (1, 1), use_bias=False, dtype=cfg.dtype,
                    param_dtype=jnp.float32, name="conv1")(y)
        y = nn.relu(_norm(inter, cfg, "norm2")(y))
        y = nn.Conv(cfg.growth_rate, (3, 3), use_bias=False, dtype=cfg.dtype,
                    param_dtype=jnp.float32, name="conv2")(y)
        return jnp.concatenate([x, y], axis=-1)


class Transition(nn.Module):
    """norm→relu→1x1 conv (compression) → 2x2 average pool."""

    config: DenseNet121Config
    out_channels: int

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        y = nn.relu(_norm(x.shape[-1], cfg, "norm")(x))
        y = nn.Conv(self.out_channels, (1, 1), use_bias=False, dtype=cfg.dtype,
                    param_dtype=jnp.float32, name="conv")(y)
        return nn.avg_pool(y, (2, 2), strides=(2, 2))


class DenseNet(nn.Module):
    config: DenseNet121Config

    @nn.compact
    def __call__(self, images):
        cfg = self.config
        x = images.astype(cfg.dtype)
        x = nn.Conv(cfg.init_features, (7, 7), strides=(2, 2), use_bias=False,
                    dtype=cfg.dtype, param_dtype=jnp.float32, name="conv_init")(x)
        x = nn.relu(_norm(cfg.init_features, cfg, "norm_init")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        channels = cfg.init_features
        for stage, n_layers in enumerate(cfg.block_sizes):
            for layer in range(n_layers):
                x = DenseLayer(cfg, name=f"block{stage}_layer{layer}")(x)
                channels += cfg.growth_rate
            if stage != len(cfg.block_sizes) - 1:
                channels = int(channels * cfg.compression)
                x = Transition(cfg, channels, name=f"transition{stage}")(x)

        x = nn.relu(_norm(channels, cfg, "norm_final")(x))
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(x)


def make_loss_fn(model: DenseNet) -> Callable:
    from autodist_tpu.models.common import make_classification_loss_fn
    return make_classification_loss_fn(model)


def init_params(config: DenseNet121Config, rng=None, image_size: int = 224):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    model = DenseNet(config)
    images = jnp.zeros((2, image_size, image_size, 3), jnp.float32)
    from autodist_tpu.models.common import jit_init
    return model, jit_init(model, images, rng=rng)


def synthetic_batch(config: DenseNet121Config, batch_size: int,
                    image_size: int = 224, seed: int = 0):
    rng = np.random.RandomState(seed)
    return {
        "images": rng.randn(batch_size, image_size, image_size, 3).astype(np.float32),
        "labels": rng.randint(0, config.num_classes, size=(batch_size,)).astype(np.int32),
    }
