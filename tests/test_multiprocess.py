"""Real 2-process execution of the distributed runtime.

The reference proved its cluster runtime by actually running it: a 2-machine CI
stage started real tf.Servers and re-executed the user script per node
(reference ``Jenkinsfile:91-131``, ``cluster.py:160-210``). The equivalent here
is two OS processes on the CPU backend: the chief runs
``examples/multiprocess_linear_regression.py``, the Coordinator re-launches the
same script as the worker (loopback, no SSH), both call
``maybe_initialize_multihost`` and join one
``jax.distributed`` coordination service, build a global 4-device mesh
(2 processes x 2 devices), and step the minimum slice with real cross-process
collectives (gloo). Value-exactness is asserted against a hand-computed
single-process SGD run — the reference's c0 criterion
(``tests/integration/cases/c0.py:88-121``) across a process boundary.
"""

import json

import numpy as np

import examples.multiprocess_linear_regression as mp_script
from shardmap_compat import requires_shard_map


def _expected_params():
    """Hand-computed 3-step SGD on the full batch (closed form, pure numpy)."""
    w = b = 0.0
    losses = []
    for step in range(mp_script.STEPS):
        batch = mp_script.make_batch(step)
        x, y = batch["x"], batch["y"]
        resid = y - (w * x + b)
        losses.append(float(np.mean(resid ** 2)))
        w -= mp_script.LR * float(np.mean(-2.0 * x * resid))
        b -= mp_script.LR * float(np.mean(-2.0 * resid))
    return w, b, losses


def test_two_process_training_matches_single_process(tmp_path):
    out = tmp_path / "result.json"
    proc = mp_script.run_two_process_chief(str(out), str(tmp_path / "workdir"))
    assert proc.returncode == 0, (
        f"chief failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")
    result = json.loads(out.read_text())

    assert result["process_count"] == 2
    assert result["device_count"] == 4
    want_w, want_b, want_losses = _expected_params()
    np.testing.assert_allclose(result["w"], want_w, rtol=1e-5)
    np.testing.assert_allclose(result["b"], want_b, rtol=1e-5)
    np.testing.assert_allclose(result["losses"], want_losses, rtol=1e-5)


def test_heterogeneous_device_counts_weighted_mean(tmp_path):
    """2 devices on the chief + 1 on the worker (the reference's r4.yml shape):
    the 3-shard batch split must produce exactly the full-batch gradient update
    (c0's weighted-mean assertion, tests/integration/cases/c0.py:110-120)."""
    import os

    import tests.hetero_mp_script as hetero

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "hetero_mp_script.py")
    out = tmp_path / "result.json"
    proc = mp_script.run_two_process_chief(
        str(out), str(tmp_path / "workdir"), script=script)
    assert proc.returncode == 0, (
        f"chief failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")
    result = json.loads(out.read_text())
    assert result["device_count"] == 3

    w = b = 0.0
    for step in range(hetero.STEPS):
        batch = hetero.make_batch(step)
        x, y = batch["x"], batch["y"]
        resid = y - (w * x + b)
        w -= hetero.LR * float(np.mean(-2.0 * x * resid))
        b -= hetero.LR * float(np.mean(-2.0 * resid))
    np.testing.assert_allclose(result["w"], w, rtol=1e-5)
    np.testing.assert_allclose(result["b"], b, rtol=1e-5)


def test_cross_process_bounded_staleness_ps(tmp_path):
    """The c9 timing assertion across a real process boundary: a fast remote
    worker (own process, PS transport) completes exactly `staleness` steps ahead
    of the slow chief-side worker, then each further step blocks on the chief's
    gate until the slow worker advances (reference c9.py:92-126)."""
    import os
    import subprocess
    import sys

    import tests.async_ps_script as aps

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "async_ps_script.py")
    out = tmp_path / "async_result.json"
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "AUTODIST_WORKING_DIR": str(tmp_path / "workdir"),
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep + env.get("PYTHONPATH", ""),
    })
    from examples.multiprocess_linear_regression import ROLE_ENV_VARS
    for k in ROLE_ENV_VARS:
        env.pop(k, None)

    # The unblocked-steps-are-fast signature is wall-clock-based: a transient
    # host load spike (sharded CI saturating the core) can push an unblocked
    # step past the bound with the gate semantics perfectly healthy. The
    # CORRECTNESS assertions stay hard every attempt; only a failed timing
    # signature retries on a fresh run.
    for attempt in range(3):
        proc = subprocess.run([sys.executable, script, str(out)], env=env,
                              cwd=os.path.dirname(os.path.dirname(script)),
                              capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, (
            f"chief failed (rc={proc.returncode})\n"
            f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")
        result = json.loads(out.read_text())

        assert result["fast_steps"] == aps.FAST_STEPS
        assert result["slow_steps"] == aps.SLOW_STEPS
        # Every gradient from both processes was applied by the shared service.
        assert result["final_version"] == aps.FAST_STEPS + aps.SLOW_STEPS

        durations = result["durations"]
        # First `staleness` steps run unblocked (fast); each following step
        # must wait for the slow worker's ~SLOW_SLEEP cadence at the gate.
        fast, gated = durations[:aps.STALENESS], durations[aps.STALENESS:]
        timing_ok = (all(d < aps.SLOW_SLEEP * 0.6 for d in fast)
                     and all(d > aps.SLOW_SLEEP * 0.3 for d in gated))
        if timing_ok:
            break
        print(f"staleness timing signature failed under load "
              f"(attempt {attempt + 1}): {durations}; retrying")
    else:
        # Sustained host oversubscription can deschedule the fast worker for
        # seconds, letting the slow worker lap it — the wall-clock signature
        # is then legitimately absent (the gate never needed to block). The
        # gate SEMANTICS are still assertable without a clock: the version
        # read at the fast worker's k-th step already includes its own k
        # prior applies (step = pull->apply), so the slow worker's share is
        # v - k, and the gate bounds the fast worker's lead over it:
        # k - (v - k) <= staleness.
        versions = result["versions_read"]
        for k, v in enumerate(versions):
            assert 2 * k - v <= aps.STALENESS, (k, v, versions)
        print(f"timing signature unavailable under sustained load; "
              f"version invariant held: {versions}")


def _run_matrix_config(tmp_path, config):
    """Run one strategy-matrix config in BOTH modes and return (single, two)."""
    import os

    import tests.strategy_matrix_mp_script as matrix

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "strategy_matrix_mp_script.py")
    single_out = tmp_path / f"{config}_single.json"
    proc = matrix.run_single_reference(str(single_out), config,
                                       str(tmp_path / "workdir_single"))
    assert proc.returncode == 0, (
        f"single-process reference failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")
    two_out = tmp_path / f"{config}_two.json"
    proc = mp_script.run_two_process_chief(
        str(two_out), str(tmp_path / "workdir_two"), script=script,
        extra_args=(config,))
    assert proc.returncode == 0, (
        f"2-process chief failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")
    single = json.loads(single_out.read_text())
    two = json.loads(two_out.read_text())
    procs = int(os.environ.get("AUTODIST_MATRIX_PROCS", "2"))
    assert two["process_count"] == procs \
        and two["device_count"] == 2 * procs
    assert single["process_count"] == 1 \
        and single["device_count"] == 2 * procs
    # Same global mesh => the distributed run must be value-exact vs the
    # single-process reference (the reference's c0 criterion per strategy,
    # tests/integration/test_dist.py:14-42).
    np.testing.assert_allclose(two["losses"], single["losses"],
                               rtol=1e-5, atol=1e-6)
    for k in single["params"]:
        np.testing.assert_allclose(two["params"][k], single["params"][k],
                                   rtol=1e-5, atol=1e-6, err_msg=k)
    return single, two


@requires_shard_map
def test_cross_process_ps_zero_sharded_opt_state(tmp_path):
    """PS/ZeRO across 2 real processes: Adam moments physically sharded along
    the reduce axis that spans the process boundary, training value-exact."""
    single, two = _run_matrix_config(tmp_path, "ps")
    # w2 is (4,4); ZeRO shards dim0 over reduce=4, so the chief's 2 local
    # devices each hold a (1,4) tile of each Adam moment — across processes.
    assert two["w2_opt_shard_shapes"] == [[1, 4]]
    assert single["w2_opt_shard_shapes"] == [[1, 4]]


def test_cross_process_partitioned_padded_uneven_storage(tmp_path):
    """UnevenPartitionedPS across 2 real processes: the 7-row parameter lives
    padded to 8 on a model axis spanning both processes, each device holding a
    (4, DIM) tile; updates stay value-exact (pad rows masked)."""
    single, two = _run_matrix_config(tmp_path, "partitioned")
    assert two["wu_storage_shape"] == [8, 4]
    assert two["wu_shard_shapes"] == [[4, 4]]


@requires_shard_map
def test_cross_process_parallax_sparse_wire_with_ef(tmp_path):
    """Parallax + BF16_EF across 2 real processes: the explicit shard_map
    lowering — sparse (indices, rows) wire for the embedding, bf16 error
    feedback on dense gradients — runs over a cross-process mesh and matches
    the single-process run exactly (same shard count => same rounding)."""
    single, two = _run_matrix_config(tmp_path, "parallax")
    assert two["sparse_wire_params"] == ["emb"]
    # Three dense params (wu, w2, b) carry per-replica EF residuals at dp=4.
    assert two["ef_params_dp"] == [4, 4, 4]


@requires_shard_map
def test_cross_process_hierarchical_dcn_reduce(tmp_path):
    """The DCN two-phase reduce laid out the way a real pod would be: inner
    `reduce` axis within each process's devices (ICI tier), outer `data` axis
    spanning the two processes (DCN tier). Value-exact vs single-process on
    the same mesh (test_ar_knobs proves the lowering is two-phase; this
    proves it EXECUTES across a process boundary)."""
    single, two = _run_matrix_config(tmp_path, "dcn")
    assert two["mesh"]["data"] == 2 and two["mesh"]["reduce"] == 2


def test_four_process_tp_zero_mesh(tmp_path, monkeypatch):
    """The 3-tier mesh over 4 REAL processes (8 devices): model axis inside
    each process, reduce across process pairs (Adam moments ZeRO-sharded over
    the boundary), data across pair groups — coordinate arithmetic a
    2-process run cannot exercise. Value-exact vs a single-process 8-device
    run on the identical mesh."""
    monkeypatch.setenv("AUTODIST_MATRIX_PROCS", "4")
    single, two = _run_matrix_config(tmp_path, "tp_zero")
    assert two["process_count"] == 4 and two["device_count"] == 8
    assert two["mesh"]["model"] == 2 and two["mesh"]["reduce"] == 2 \
        and two["mesh"]["data"] == 2
    # The 7-row parameter lives padded to 8 on the in-process model axis.
    assert two["wu_storage_shape"] == [8, 4]
    assert two["wu_shard_shapes"] == [[4, 4]]


def test_cross_process_partitioned_allreduce(tmp_path):
    """PartitionedAR across 2 real processes: model-sharded (padded-uneven)
    parameter storage with the per-shard gradient all-reduce crossing the
    process boundary (the data axis spans the processes; the model shards
    live in-process under the canonical axis order), value-exact."""
    single, two = _run_matrix_config(tmp_path, "par")
    assert two["mesh"]["model"] == 2 and two["mesh"]["data"] == 2
    # Physical evidence: the 7-row param is padded to 8 and stored as (4, 4)
    # tiles; w2's Adam moments follow the (2, 4) model sharding.
    assert two["wu_storage_shape"] == [8, 4]
    assert two["wu_shard_shapes"] == [[4, 4]]
    assert two["w2_opt_shard_shapes"] == [[2, 4]]


@requires_shard_map
def test_cross_process_powersgd(tmp_path):
    """PowerSGD's factor pmeans (P/Q low-rank wire) across 2 real processes,
    exact vs the single-process run (deterministic QR + same shard count)."""
    single, two = _run_matrix_config(tmp_path, "powersgd")
    assert two["ef_params_dp"] == []  # PowerSGDState, not EFState, carries EF


def _run_matrix_ckpt(tmp_path, monkeypatch, config):
    """The reference c10 contract against cross-process-sharded state: a
    2-process run saves (collective sharded write), DIES, a fresh 2-process
    run restores and continues — and the stitched trajectory must match an
    uninterrupted single-process run value-exactly."""
    import os

    import tests.strategy_matrix_mp_script as matrix

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "strategy_matrix_mp_script.py")
    ckpt_dir = tmp_path / "ckpt"
    ckpt_dir.mkdir()
    monkeypatch.setenv("AUTODIST_MATRIX_CKPT_DIR", str(ckpt_dir))

    straight_out = tmp_path / "straight.json"
    proc = matrix.run_single_reference(str(straight_out), config,
                                       str(tmp_path / "wd_straight"),
                                       phase="straight")
    assert proc.returncode == 0, (
        f"straight reference failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")

    save_out = tmp_path / "save.json"
    proc = mp_script.run_two_process_chief(
        str(save_out), str(tmp_path / "wd_save"), script=script,
        extra_args=(config, "ckpt_save"))
    assert proc.returncode == 0, (
        f"2-process save phase failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")

    restore_out = tmp_path / "restore.json"
    proc = mp_script.run_two_process_chief(
        str(restore_out), str(tmp_path / "wd_restore"), script=script,
        extra_args=(config, "ckpt_restore"))
    assert proc.returncode == 0, (
        f"2-process restore phase failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")

    straight = json.loads(straight_out.read_text())
    saved = json.loads(save_out.read_text())
    restored = json.loads(restore_out.read_text())
    assert saved["process_count"] == 2 and restored["process_count"] == 2

    # The checkpoint is in the sharded format (per-process shard files +
    # manifest) and no monolithic <name>-<step>.npz was ever assembled.
    # Whether BOTH processes wrote depends on the config's layout (ownership
    # dedups replicas to the lowest device id): the ZeRO test asserts it.
    files = saved["ckpt_files"]
    assert any(".shard00000-of-00002" in f for f in files), files
    assert any(f == "model-3.json" for f in files), files
    assert not any(f.endswith(".npz") and ".shard" not in f for f in files), files

    # Stitched = straight, value-exact: losses before the kill, losses after
    # the restore, and the final logical params.
    np.testing.assert_allclose(saved["losses"],
                               straight["losses"][:matrix.STEPS],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(restored["losses"],
                               straight["losses"][matrix.STEPS:],
                               rtol=1e-5, atol=1e-6)
    for k in straight["params"]:
        np.testing.assert_allclose(restored["params"][k], straight["params"][k],
                                   rtol=1e-5, atol=1e-6, err_msg=k)
    return saved, restored


@requires_shard_map
def test_cross_process_checkpoint_zero_opt_state(tmp_path, monkeypatch):
    """Save/kill/restore/continue with Adam moments physically sharded along
    the process-spanning reduce axis (the state device_get cannot assemble)."""
    saved, restored = _run_matrix_ckpt(tmp_path, monkeypatch, "ps")
    # The restored run re-sharded the moments across processes again.
    assert restored["w2_opt_shard_shapes"] == [[1, 4]]
    # ZeRO moments span the process boundary, so BOTH processes wrote shards.
    assert any(".shard00001-of-00002" in f for f in saved["ckpt_files"]), \
        saved["ckpt_files"]


def test_cross_process_checkpoint_padded_uneven(tmp_path, monkeypatch):
    """Save/kill/restore/continue with the 7-row padded-to-8 parameter (and
    its Adam moments) stored model-sharded across both processes; the
    checkpoint itself holds logical (unpadded) shapes."""
    saved, restored = _run_matrix_ckpt(tmp_path, monkeypatch, "partitioned")
    assert restored["wu_storage_shape"] == [8, 4]
    assert restored["wu_shard_shapes"] == [[4, 4]]


@requires_shard_map
def test_cross_process_train_loop_checkpoint_resume(tmp_path, monkeypatch):
    """training.train's own save path inside a real 2-process run: collective
    final save, then a fresh 2-process train() resumes from the latest
    checkpoint automatically and finishes — params exactly match an
    uninterrupted single-process straight run."""
    import os

    import tests.strategy_matrix_mp_script as matrix

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "strategy_matrix_mp_script.py")
    ckpt_dir = tmp_path / "ckpt"
    ckpt_dir.mkdir()
    monkeypatch.setenv("AUTODIST_MATRIX_CKPT_DIR", str(ckpt_dir))

    straight_out = tmp_path / "straight.json"
    proc = matrix.run_single_reference(str(straight_out), "ps",
                                       str(tmp_path / "wd_straight"),
                                       phase="straight")
    assert proc.returncode == 0, proc.stderr

    for phase, out in (("train_save", tmp_path / "a.json"),
                       ("train_resume", tmp_path / "b.json")):
        proc = mp_script.run_two_process_chief(
            str(out), str(tmp_path / f"wd_{phase}"), script=script,
            extra_args=("ps", phase))
        assert proc.returncode == 0, (
            f"{phase} failed (rc={proc.returncode})\n"
            f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")

    straight = json.loads(straight_out.read_text())
    resumed = json.loads((tmp_path / "b.json").read_text())
    assert resumed["step"] == matrix.STEPS_TOTAL
    # trainloop-3 was rotated/kept and trainloop-5 exists as sharded files.
    assert any("trainloop-5" in f and ".shard" in f
               for f in resumed["ckpt_files"]), resumed["ckpt_files"]
    for k in straight["params"]:
        np.testing.assert_allclose(resumed["params"][k], straight["params"][k],
                                   rtol=1e-5, atol=1e-6, err_msg=k)


@requires_shard_map
def test_cross_process_ring_attention_sequence_parallel(tmp_path):
    """Long-context across REAL processes: a 4-way seq axis spanning the
    2-process boundary, so ring attention's K/V ppermute hops cross between
    OS processes — value-exact vs the single-process run on the same mesh."""
    import os

    import tests.seq_parallel_mp_script as sp

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "seq_parallel_mp_script.py")
    single_out = tmp_path / "sp_single.json"
    proc = sp.run_single_reference(str(single_out), str(tmp_path / "wd_single"))
    assert proc.returncode == 0, (
        f"single-process SP reference failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")

    two_out = tmp_path / "sp_two.json"
    proc = mp_script.run_two_process_chief(
        str(two_out), str(tmp_path / "wd_two"), script=script)
    assert proc.returncode == 0, (
        f"2-process SP chief failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")

    single = json.loads(single_out.read_text())
    two = json.loads(two_out.read_text())
    assert two["process_count"] == 2 and two["mesh"]["seq"] == 4
    np.testing.assert_allclose(two["losses"], single["losses"],
                               rtol=1e-5, atol=1e-6)
    for k in single["params_sample"]:
        np.testing.assert_allclose(two["params_sample"][k],
                                   single["params_sample"][k],
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_async_ps_example_runs(tmp_path):
    """The documented async-PS example (examples/async_ps_train.py) runs
    end-to-end: 2 processes, all updates applied, wire accounting reported."""
    import os

    script = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                          "examples", "async_ps_train.py")
    out = tmp_path / "example_summary.json"
    proc = mp_script.run_two_process_chief(
        str(out), str(tmp_path / "workdir"), script=script,
        extra_args=("--steps", "4", "--out", str(out)))
    assert proc.returncode == 0, (
        f"example failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")
    summary = json.loads(out.read_text())
    assert summary["applied_updates"] == 8  # 4 chief + 4 worker
    assert summary["worker_wire_received_bytes"] > 0


def test_auto_wired_cross_process_async_ps(tmp_path):
    """The public API alone (2-node spec + PS(staleness)) wires the whole async
    protocol: worker launch, transport address shipping, chief-side serving,
    worker-side remote stepping — no manual plumbing in the user script."""
    import os

    import tests.auto_async_script as aas

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "auto_async_script.py")
    out = tmp_path / "auto_async.json"
    proc = mp_script.run_two_process_chief(
        str(out), str(tmp_path / "workdir"), script=script)
    assert proc.returncode == 0, (
        f"chief failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")
    result = json.loads(out.read_text())

    assert result["num_worker_slots"] == 2
    # Every step from BOTH processes was applied by the chief's service.
    assert result["final_version"] == result["chief_steps"] + result["worker_steps"]
    assert result["chief_losses"][-1] < result["chief_losses"][0]
    assert np.isfinite(result["w"]) and result["w"] != 0.0
