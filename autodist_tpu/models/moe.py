"""Mixture-of-Experts Transformer LM — the expert-parallel workload.

The reference has no MoE or expert parallelism (its strategy nodes are variables
only, ``strategy.proto:36-42``); this extends the framework beyond reference parity
using the mesh's ``expert`` axis. The design is the standard TPU MoE formulation
(GShard/Switch): routing is expressed as dense einsums against one-hot dispatch and
combine tensors with a **static capacity** per expert, and expert FFN weights carry
a leading expert dimension sharded ``P("expert", ...)``. Under ``jit`` the XLA SPMD
partitioner turns the dispatch/return einsums into ``all_to_all``s over the expert
axis — no manual collectives, and the per-expert matmuls stay MXU-shaped batched
GEMMs.

Top-1 (Switch) routing keeps shapes static: tokens beyond an expert's capacity are
dropped (their combine weight is zero, so they pass through the residual only), the
standard TPU-friendly trade.
"""

import dataclasses
from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.models.transformer_lm import (MultiHeadAttention,
                                                TransformerLMConfig, causal_mask)


@dataclasses.dataclass(frozen=True)
class MoETransformerLMConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_len: int = 1024
    n_experts: int = 8
    capacity_factor: float = 1.25   # capacity = ceil(tokens/expert * factor)
    router_aux_weight: float = 1e-2  # Switch load-balancing loss weight
    dtype: Any = jnp.bfloat16
    # Fused pallas head+loss (ops/fused_xent): logits never materialize in HBM;
    # same win as the flagship (transformer_lm.fused_head).
    fused_head: bool = False

    def __post_init__(self):
        if self.d_model % self.n_heads:
            raise ValueError("d_model must be divisible by n_heads")
        if self.n_experts < 2:
            raise ValueError("n_experts must be >= 2")

    def attn_config(self) -> TransformerLMConfig:
        """The dense attention sub-config reused from the dense LM."""
        return TransformerLMConfig(
            vocab_size=self.vocab_size, d_model=self.d_model, n_heads=self.n_heads,
            n_layers=self.n_layers, d_ff=self.d_ff, max_len=self.max_len,
            dtype=self.dtype, tied_output=False)


def switch_route(logits: jax.Array, capacity: int
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-1 routing with static capacity.

    logits: [B, S, E] router scores. Returns (dispatch [B, S, E, C] one-hot,
    combine [B, S, E, C] = dispatch * router probability, aux_loss scalar).
    All shapes static; overflow tokens get all-zero dispatch rows.
    """
    n_experts = logits.shape[-1]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                       # [B, S]
    assignment = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.float32)

    # Position of each token within its expert's queue, in sequence order.
    position = jnp.cumsum(assignment, axis=1) * assignment - 1.0   # [B, S, E]
    in_capacity = (position >= 0) & (position < capacity)
    dispatch = jnp.einsum(
        "bse,bsec->bsec", assignment * in_capacity,
        jax.nn.one_hot(jnp.clip(position, 0, capacity - 1).astype(jnp.int32),
                       capacity, dtype=jnp.float32))

    top_prob = jnp.max(probs, axis=-1)                             # [B, S]
    combine = dispatch * top_prob[..., None, None]

    # Switch aux loss: E * mean_e(fraction routed to e * mean router prob for e).
    frac_routed = assignment.mean(axis=(0, 1))                     # [E]
    mean_prob = probs.mean(axis=(0, 1))                            # [E]
    aux = n_experts * jnp.sum(frac_routed * mean_prob)
    return dispatch, combine, aux


class MoEFFN(nn.Module):
    """Expert-parallel FFN: route -> all_to_all (implicit) -> batched GEMM -> return."""

    config: MoETransformerLMConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b, s, m = x.shape
        capacity = int(np.ceil(s * cfg.capacity_factor / cfg.n_experts)) or 1

        router = nn.Dense(cfg.n_experts, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="router")
        # Expert weights: leading expert dim — the plan shards it P("expert",..).
        w_in = self.param("experts_in", nn.initializers.lecun_normal(),
                          (cfg.n_experts, m, cfg.d_ff), jnp.float32)
        w_out = self.param("experts_out", nn.initializers.lecun_normal(),
                           (cfg.n_experts, cfg.d_ff, m), jnp.float32)

        dispatch, combine, aux = switch_route(router(x), capacity)
        dispatch = dispatch.astype(cfg.dtype)
        combine = combine.astype(cfg.dtype)

        # Dispatch einsum: XLA inserts the token all_to_all (data <-> expert axes).
        expert_in = jnp.einsum("bsec,bsm->ebcm", dispatch, x)
        h = jnp.einsum("ebcm,emf->ebcf", expert_in, w_in.astype(cfg.dtype))
        h = nn.gelu(h)
        expert_out = jnp.einsum("ebcf,efm->ebcm", h, w_out.astype(cfg.dtype))
        y = jnp.einsum("bsec,ebcm->bsm", combine, expert_out)
        return y, aux


class MoEBlock(nn.Module):
    config: MoETransformerLMConfig

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.config
        attn_cfg = cfg.attn_config()
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln_attn")(x)
        x = x + MultiHeadAttention(attn_cfg, name="attn")(h, mask)
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln_moe")(x)
        y, aux = MoEFFN(cfg, name="moe")(h)
        return x + y, aux


class MoETransformerLM(nn.Module):
    """Decoder-only LM with an MoE FFN in every block. Returns (logits, aux_loss)."""

    config: MoETransformerLMConfig

    @nn.compact
    def __call__(self, tokens, return_hidden=False):
        cfg = self.config
        _, length = tokens.shape
        emb = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="embed")
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (cfg.max_len, cfg.d_model), jnp.float32)
        x = emb(tokens) + pos[None, :length, :].astype(cfg.dtype)
        mask = causal_mask(length, cfg.dtype)

        aux_total = jnp.zeros((), jnp.float32)
        for i in range(cfg.n_layers):
            x, aux = MoEBlock(cfg, name=f"block_{i}")(x, mask)
            aux_total = aux_total + aux

        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        if return_hidden:
            # The fused-head loss owns the projection; head params exist from
            # init (which runs the normal path below).
            return x, aux_total / cfg.n_layers
        # Head matmul in compute dtype (the loss upcasts for the softmax) — an
        # f32 vocab projection runs at a fraction of the bf16 MXU rate.
        logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype, param_dtype=jnp.float32,
                          use_bias=False, name="lm_head")(x)
        return logits, aux_total / cfg.n_layers


def make_loss_fn(model: MoETransformerLM) -> Callable:
    """Next-token cross entropy + router load-balancing aux loss."""
    cfg = model.config

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        if cfg.fused_head:
            from autodist_tpu.models.common import fused_lm_head_nll
            h, aux = model.apply({"params": params}, inputs, return_hidden=True)
            nll = fused_lm_head_nll(h, params, targets)
            return nll.mean() + cfg.router_aux_weight * aux
        logits, aux = model.apply({"params": params}, inputs)
        logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
        return nll.mean() + cfg.router_aux_weight * aux

    return loss_fn


def init_params(config: MoETransformerLMConfig, rng: Optional[jax.Array] = None,
                batch_size: int = 2):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    model = MoETransformerLM(config)
    tokens = jnp.zeros((batch_size, min(8, config.max_len)), jnp.int32)
    from autodist_tpu.models.common import jit_init
    return model, jit_init(model, tokens, rng=rng)


def synthetic_batch(config: MoETransformerLMConfig, batch_size: int, seq_len: int,
                    seed: int = 0):
    rng = np.random.RandomState(seed)
    return {"tokens": rng.randint(0, config.vocab_size,
                                  size=(batch_size, seq_len + 1)).astype(np.int32)}
