"""Saver: strategy-independent checkpoints under original parameter names.

Reference parity (``autodist/checkpoint/saver.py``):

- Saves under ORIGINAL single-node names whatever the strategy (``:47-61``): each
  parameter is gathered to a full logical array first — the inverse of the
  reference's ``SaveSliceInfo`` reassembly of partitioned variables
  (``kernel/partitioner.py:251-347``).
- Restoring reshards onto whatever mesh/strategy the reader uses (the reference
  restored a checkpoint into differently-distributed runs or plain TF).
- ``max_to_keep`` rotation and a ``checkpoint`` state file mirror ``tf.train.Saver``
  semantics the reference inherited.

Format: one ``<prefix>.npz`` holding ``{name: full ndarray}`` plus a JSON manifest
(``<prefix>.json``) with names, shapes, dtypes, and the saved step. Optimizer state
is saved under an ``__opt__/`` prefix, the step counter under ``__step__``.
"""

import glob
import json
import os
import re
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from autodist_tpu.utils import logging

PyTree = Any

_OPT_PREFIX = "__opt__/"
_EF_PREFIX = "__ef__/"
_STEP_KEY = "__step__"
_STATE_FILE = "checkpoint"  # directory-level latest-pointer, like TF's


def _scan_checkpoints(base: str):
    """``[(step, prefix)]`` for every ``<base>-<step>.npz`` on disk, step-ascending.
    The single name-exact filename parse shared by rotation adoption and
    name-filtered latest lookup."""
    found = []
    for path in glob.glob(glob.escape(base) + "-*.npz"):
        m = re.fullmatch(re.escape(base) + r"-(\d+)\.npz", path)
        if m:
            found.append((int(m.group(1)), path[:-len(".npz")]))
    return sorted(found)


def _read_recorded(save_path: str):
    """The directory-level state file's recorded rotation list (``[]`` when
    missing/corrupt) plus the regex matching THIS name's prefixes — the one
    read/parse shared by rotation adoption and state-file rewriting, so the
    two can never disagree about which entries belong to a name."""
    state_path = os.path.join(os.path.dirname(save_path) or ".", _STATE_FILE)
    recorded = []
    if os.path.exists(state_path):
        try:
            with open(state_path) as f:
                recorded = json.load(f).get("all") or []
        except (ValueError, OSError):
            recorded = []
    return state_path, recorded, re.compile(re.escape(save_path) + r"-\d+")


def _flatten_named(tree: PyTree) -> Dict[str, np.ndarray]:
    """Flatten a pytree to {original-name: full host ndarray}.

    ``jax.device_get`` on a sharded Array assembles the full logical value — the
    TPU-native equivalent of reassembling partitioned shards via SaveSliceInfo.
    """
    from autodist_tpu.model_spec import _path_name
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        out[_path_name(path)] = np.asarray(jax.device_get(leaf))
    return out


def _nest(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Rebuild a nested dict from '/'-joined names (inverse of _flatten_named for
    dict-based pytrees, which is what flax params are)."""
    root: Dict[str, Any] = {}
    for name, value in flat.items():
        parts = name.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return root


class Saver:
    """Save/restore train state or bare params, strategy-independently."""

    def __init__(self, max_to_keep: int = 5):
        self._max_to_keep = max_to_keep
        self._kept: List[str] = []
        self._rotation_loaded = False

    # ------------------------------------------------------------------- save
    def save(self, state_or_params: PyTree, save_path: str,
             global_step: Optional[int] = None, plan=None, runner=None) -> str:
        """Write a checkpoint. Accepts a TrainState (params + opt state + step) or a
        bare params pytree. Returns the checkpoint prefix.

        A TrainState carries its runner's plan, so padded (uneven-partition)
        storage is automatically sliced back to original logical shapes — the
        checkpoint stays strategy-independent (the reference's SaveSliceInfo
        reassembly invariant). ``runner``/``plan`` override that for bare params
        trees that came from a padded runner."""
        from autodist_tpu.runner import TrainState

        if plan is None and runner is not None:
            plan = runner.plan
        if plan is None and isinstance(state_or_params, TrainState):
            plan = state_or_params.plan
        unpad = plan.unpad_params if plan is not None else (lambda t: t)
        flat: Dict[str, np.ndarray] = {}
        if isinstance(state_or_params, TrainState):
            flat.update(_flatten_named(unpad(state_or_params.params)))
            flat.update({_OPT_PREFIX + k: v for k, v in
                         _flatten_named(unpad(state_or_params.opt_state)).items()})
            flat.update({_EF_PREFIX + k: v for k, v in
                         _flatten_ef_state(state_or_params.ef_state).items()})
            step = int(np.asarray(jax.device_get(state_or_params.step)))
        else:
            flat.update(_flatten_named(unpad(state_or_params)))
            step = 0
        # An explicit global_step overrides the state's counter for BOTH the file
        # name and the stored step, so they can never disagree.
        if global_step is not None:
            step = global_step
        flat[_STEP_KEY] = np.asarray(step)
        prefix = f"{save_path}-{step}"

        os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)
        tmp = prefix + ".npz.tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **flat)
        os.replace(tmp, prefix + ".npz")  # atomic publish

        manifest = {
            "step": step,
            "params": {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                       for k, v in flat.items() if not k.startswith("__")},
        }
        with open(prefix + ".json", "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)

        self._load_rotation_state(save_path)  # adopt pre-restart checkpoints
        self._rotate(prefix)
        self._update_state_file(save_path, prefix)  # after rotation: lists live files
        logging.info("Saved checkpoint %s (step %d, %d tensors)",
                     prefix, step, len(flat))
        return prefix

    def _load_rotation_state(self, save_path: str):
        """Seed the rotation list from the files on disk so a restarted trainer
        keeps rotating checkpoints written before the restart. Scanning
        ``<save_path>-<step>.npz`` (instead of trusting the directory's shared
        ``checkpoint`` state file) keeps rotation per *name*: two models
        checkpointing into one directory under different names never adopt —
        or delete — each other's files.

        When the state file records a rotation list for THIS name, only files in
        it are adopted: a ``<name>-<step>.npz`` the user copied aside / renamed
        into the directory to preserve beyond ``max_to_keep`` was never
        rotation-managed and must not be rotate-deleted after a restart."""
        if self._rotation_loaded:
            return
        self._rotation_loaded = True
        on_disk = [prefix for _, prefix in _scan_checkpoints(save_path)]
        _, recorded, name_pat = _read_recorded(save_path)
        ours_recorded = {p for p in recorded if name_pat.fullmatch(p)}
        if ours_recorded:
            # A previous run of this name left its rotation list: honor it.
            on_disk = [p for p in on_disk if p in ours_recorded]
        # else: no state for this name (fresh dir, deleted state file, or a state
        # file written by another name sharing the directory) — adopt the scan.
        for prefix in on_disk:
            if prefix not in self._kept:
                self._kept.append(prefix)

    def _update_state_file(self, save_path: str, prefix: str):
        """Rewrite the shared ``checkpoint`` state file, merging per name: only
        THIS name's entries are replaced by our rotation list. Two models
        checkpointing into one directory keep independent rotation records —
        the other name's entries survive, so its restarted Saver adopts its own
        recorded list instead of falling back to a full scan (which could
        rotate-delete a user-preserved ``<name>-<step>.npz``)."""
        state_path, recorded, name_pat = _read_recorded(save_path)
        others = [p for p in recorded
                  if not name_pat.fullmatch(p) and p not in self._kept]
        with open(state_path, "w") as f:
            json.dump({"latest": prefix, "all": others + list(self._kept)}, f)

    def _rotate(self, prefix: str):
        if prefix in self._kept:  # re-saving a step (e.g. checkpoint-on-resume)
            self._kept.remove(prefix)
        self._kept.append(prefix)
        while len(self._kept) > self._max_to_keep:
            victim = self._kept.pop(0)
            for suffix in (".npz", ".json"):
                try:
                    os.remove(victim + suffix)
                except OSError:
                    pass

    # ---------------------------------------------------------------- restore
    @staticmethod
    def latest_checkpoint(directory: str, name: Optional[str] = None) -> Optional[str]:
        """Most recent checkpoint prefix in ``directory``.

        With ``name``, only checkpoints saved as ``<name>-<step>`` count — the
        directory-level ``checkpoint`` state file records whichever save ran
        last, so a directory shared by multiple names needs the filter."""
        state_path = os.path.join(directory, _STATE_FILE)
        latest = None
        if os.path.exists(state_path):
            with open(state_path) as f:
                latest = json.load(f).get("latest")
        if name is None:
            return latest
        # Exact-name match only: startswith would let "gen-ema-50" satisfy
        # name="gen" and resume the wrong model's weights.
        if latest and re.fullmatch(re.escape(name) + r"-\d+",
                                   os.path.basename(latest)) \
                and os.path.exists(latest + ".npz"):
            return latest
        # The state file points at another name's save: scan for this name's.
        found = _scan_checkpoints(os.path.join(directory, name))
        return found[-1][1] if found else None

    def restore_params(self, prefix: str) -> Dict[str, Any]:
        """Load the parameter tree as a nested host-numpy dict (original names)."""
        flat = dict(np.load(prefix + ".npz"))
        params = {k: v for k, v in flat.items() if not k.startswith("__")}
        return _nest(params)

    def restore(self, prefix: str, runner=None, params_template: PyTree = None):
        """Restore a checkpoint.

        With ``runner``: returns a fully-placed TrainState on the runner's mesh
        (params + optimizer state + step), resharded per the runner's plan — this is
        the cross-strategy restore path.
        With only ``params_template``: returns a params pytree matching the
        template's structure (for single-device / different-framework use).
        """
        flat = dict(np.load(prefix + ".npz"))
        step = int(flat.pop(_STEP_KEY, np.asarray(0)))
        params_flat = {k: v for k, v in flat.items()
                       if not k.startswith("__")}
        opt_flat = {k[len(_OPT_PREFIX):]: v for k, v in flat.items()
                    if k.startswith(_OPT_PREFIX)}
        ef_flat = {k[len(_EF_PREFIX):]: v for k, v in flat.items()
                   if k.startswith(_EF_PREFIX)}

        if runner is None:
            if params_template is None:
                return _nest(params_flat)
            return _fill_template(params_template, params_flat)

        # Rebuild state through the runner: init gives correctly-structured,
        # correctly-sharded state; we then overwrite leaves from the checkpoint.
        template_params = _fill_template_like_names(runner, params_flat)
        state = runner.init(template_params)
        if opt_flat:
            # Checkpoints hold logical shapes; the live opt state may be padded
            # (uneven partitioning) — fill at logical shapes, re-pad for storage.
            opt_template = runner.plan.unpad_params(state.opt_state)
            opt_state = runner.plan.pad_params(
                _fill_template(opt_template, opt_flat, strict=False))
            o_sh = runner.plan.opt_sharding_tree(runner.mesh, opt_state)
            opt_state = jax.device_put(opt_state, o_sh)
        else:
            opt_state = state.opt_state
        if ef_flat:
            ef_state = _fill_template(state.ef_state, ef_flat, strict=False,
                                      on_mismatch="reinit")
            ef_state = jax.device_put(
                ef_state, jax.tree_util.tree_map(lambda l: l.sharding, state.ef_state))
        else:
            ef_state = state.ef_state
        from autodist_tpu.runner import TrainState
        return TrainState(step=np.asarray(step, np.int32), params=state.params,
                          opt_state=opt_state, ef_state=ef_state, plan=runner.plan)


def _flatten_ef_state(ef_state: PyTree) -> Dict[str, np.ndarray]:
    """Flatten compressor state, dropping per-replica residuals by leaf identity.

    Per-replica [dp, ...] error-feedback residuals are transient worker-local
    state (the reference kept them in-memory per worker, compressor.py:120-143):
    checkpointing them would cost dp x parameter size and they cannot restore onto
    a different topology anyway. Shape-stable compressor state (PowerSGD's Q) is
    checkpointed. Residuals are identified as the ``error`` *attribute* of the
    EFState/PowerSGDState dataclasses (a GetAttrKey in the tree path) — a model
    parameter that happens to be named 'error' (a DictKey) is saved normally."""
    from autodist_tpu.model_spec import _path_name
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(ef_state)[0]:
        last = path[-1] if path else None
        if isinstance(last, jax.tree_util.GetAttrKey) and last.name == "error":
            continue
        out[_path_name(path)] = np.asarray(jax.device_get(leaf))
    return out


def _fill_template(template: PyTree, flat: Dict[str, np.ndarray], strict: bool = True,
                   on_mismatch: str = "raise"):
    """Replace template leaves by name; leaves missing from the checkpoint are kept
    (strict=False) or are an error (strict=True). A shape mismatch raises
    (``on_mismatch='raise'``) or keeps the template leaf with a warning
    (``on_mismatch='reinit'`` — used for compressor state whose shapes depend on the
    data-parallel topology)."""
    from autodist_tpu.model_spec import _path_name

    def fill(path, leaf):
        name = _path_name(path)
        if name in flat:
            value = flat[name]
            if tuple(value.shape) != tuple(getattr(leaf, "shape", value.shape)):
                if on_mismatch == "reinit":
                    logging.warning(
                        "Reinitializing %s: saved shape %s does not match current %s "
                        "(topology changed)", name, tuple(value.shape), tuple(leaf.shape))
                    return leaf
                raise ValueError(f"Checkpoint shape mismatch for {name}: "
                                 f"{value.shape} vs {leaf.shape}")
            return value
        if strict:
            raise KeyError(f"Checkpoint missing parameter {name!r}")
        return leaf

    return jax.tree_util.tree_map_with_path(fill, template)


def _fill_template_like_names(runner, params_flat):
    """Build a params pytree for runner.init from checkpoint names using the
    runner's recorded tree structure."""
    spec = runner._model_spec
    leaves = []
    for name in spec.names:
        if name not in params_flat:
            raise KeyError(f"Checkpoint missing parameter {name!r}")
        leaves.append(params_flat[name])
    return spec.unflatten(leaves)
