"""PS strategy: every parameter synchronized parameter-server style.

Reference ``autodist/strategy/ps_strategy.py:37-56`` placed all variables on the first
CPU device and replicated computation on all GPUs. The TPU compilation of "PS" is
weight-update sharding: gradients reduce-scatter onto the parameter's home shard along
the ``reduce`` mesh axis, the optimizer update runs there, and parameters all-gather
back. A single logical destination (``reduce:0``) is recorded for protocol parity.
"""

from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import PS_DEFAULT_AXES, Strategy, StrategyBuilder


class PS(StrategyBuilder):
    """All parameters -> one PS destination (reference ps_strategy.py)."""

    def __init__(self, local_proxy_variable: bool = False, sync: bool = True,
                 staleness: int = 0):
        if staleness < 0:
            raise ValueError("staleness must be >= 0")
        self._local_proxy_variable = local_proxy_variable
        self._sync = sync
        self._staleness = staleness

    def build(self, model_spec: ModelSpec, resource_spec: ResourceSpec) -> Strategy:
        strategy = Strategy()
        for name in model_spec.trainable:
            node = strategy.proto.node_config.add(var_name=name)
            node.ps_synchronizer.reduction_destination = "reduce:0"
            node.ps_synchronizer.local_replication = self._local_proxy_variable
            node.ps_synchronizer.sync = self._sync
            node.ps_synchronizer.staleness = self._staleness
            node.sparse = model_spec[name].sparse
        self._fill_mesh_config(strategy, resource_spec,
                               self._resolved_axes(resource_spec, PS_DEFAULT_AXES))
        return strategy
