"""Image classifier — the 3-step API demo.

Port of reference ``examples/image_classifier.py:7-60`` (Fashion-MNIST-class CNN):
(1) wrap model code in ``AutoDist(...).scope()``, (2) get a step function, (3)
train. Synthetic 28x28 data keeps it self-contained (no dataset download). Feeding
uses the native prefetch DataLoader + on-device prefetch, so batch assembly and
host->HBM transfer overlap the step.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu import AutoDist
from autodist_tpu.data import DataLoader, device_prefetch
from autodist_tpu.strategy import PSLoadBalancing


class SmallCNN(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Conv(32, (3, 3), name="conv1")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3), name="conv2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128, name="fc")(x))
        return nn.Dense(self.num_classes, name="head")(x)


def main(epochs: int = 5, batch_size: int = 64):
    rng = np.random.RandomState(0)
    images = rng.randn(512, 28, 28, 1).astype(np.float32)
    labels = rng.randint(0, 10, size=(512,)).astype(np.int32)

    # Step 1: wrap the model code in the AutoDist scope.
    ad = AutoDist(strategy_builder=PSLoadBalancing())
    with ad.scope():
        model = SmallCNN()
        params = jax.jit(model.init)(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))["params"]

        def loss_fn(p, batch):
            logits = model.apply({"params": p}, batch["images"])
            logp = jax.nn.log_softmax(logits)
            return -jnp.take_along_axis(logp, batch["labels"][:, None], axis=-1).mean()

    # Step 2: build the distributed step function.
    step = ad.function(loss_fn, params, optax.adam(1e-3),
                       example_batch={"images": images[:8], "labels": labels[:8]})

    # Step 3: train, fed by the native prefetch loader (shuffled, drop-last,
    # double-buffered onto the device).
    loader = DataLoader({"images": images, "labels": labels},
                        batch_size=batch_size, shuffle=True, seed=0)
    feed = device_prefetch(loader, step.runner, depth=2)
    steps_per_epoch = len(images) // batch_size
    losses = []
    try:
        for epoch in range(epochs):
            for _ in range(steps_per_epoch):
                loss = step(next(feed))
            losses.append(float(loss))
            print(f"epoch {epoch}: loss={losses[-1]:.4f} "
                  f"(loader={'native' if loader.is_native else 'numpy'})")
    finally:
        feed.close()     # stop the producer before its loader goes away
        loader.close()
    assert losses[-1] < losses[0]
    return losses


if __name__ == "__main__":
    main()
