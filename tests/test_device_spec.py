"""DeviceSpec string round-trip — parity with reference tests/test_device_spec.py:11-20."""

from autodist_tpu.resource_spec import Connectivity, DeviceSpec, DeviceType


def test_tpu_device_string_roundtrip():
    d = DeviceSpec("10.0.0.1", DeviceType.TPU, 3)
    assert d.name_string == "10.0.0.1:TPU:3"
    d2 = DeviceSpec.from_string(d.name_string)
    assert d2 == d
    assert d2.device_type is DeviceType.TPU
    assert d2.device_index == 3


def test_cpu_device_string_is_bare_host():
    d = DeviceSpec("localhost")
    assert d.name_string == "localhost"
    assert DeviceSpec.from_string("localhost") == d


def test_gpu_device_string_accepted_for_compat():
    d = DeviceSpec.from_string("1.2.3.4:GPU:0")
    assert d.device_type is DeviceType.GPU


def test_malformed_device_string_raises():
    import pytest
    with pytest.raises(ValueError):
        DeviceSpec.from_string("a:b:c:d")


def test_connectivity():
    a = DeviceSpec("h1", DeviceType.TPU, 0)
    b = DeviceSpec("h1", DeviceType.TPU, 1)
    c = DeviceSpec("h2", DeviceType.TPU, 0)
    assert a.connectivity_with(b) is Connectivity.SAME_HOST
    assert a.connectivity_with(c) is Connectivity.ETHERNET
    assert a.connectivity_with(a) is Connectivity.SAME_DEVICE
