"""Random-axis partitioned AllReduce strategy.

Port of reference ``random_axis_partition_all_reduce_strategy.py:118-141``: like
PartitionedAR, but dense parameters partition a randomly chosen axis with size >= 2
(sparse parameters are forced to axis 0 so row updates stay shard-local). Seeded for
reproducibility across chief and workers.
"""

import random

from autodist_tpu.strategy.partition_utils import smallest_divisor_at_least_2
from autodist_tpu.strategy.partitioned_all_reduce_strategy import PartitionedAR


class RandomAxisPartitionAR(PartitionedAR):
    def __init__(self, chunk_size: int = 128, seed: int = 0, **kwargs):
        super().__init__(chunk_size=chunk_size, **kwargs)
        self._rng = random.Random(seed)

    def _choose_axis_and_count(self, spec, seed_idx: int):
        if spec.sparse:
            axis = 0 if spec.shape and spec.shape[0] >= 2 else None
        else:
            candidates = [i for i, d in enumerate(spec.shape) if d >= 2]
            axis = self._rng.choice(candidates) if candidates else None
        if axis is None:
            return None, None
        return axis, smallest_divisor_at_least_2(spec.shape[axis])
