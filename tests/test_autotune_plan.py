"""Plan autotuner: enumerate, predict-prune, probe, cache, explain.

Covers the predict-prune-probe contract end to end
(docs/usage/performance.md "Plan autotuning"):

- candidate enumeration from AutoStrategy's analytic rules (regime/sparse/
  memory gates) jointly with the unroll/zero/accum/overlap knobs;
- the compile-only cost probe (``DistributedRunner.plan_costs``): real XLA
  cost analysis, scaling across unroll factors, and NO step dispatches;
- stage-1 pruning: at most top-k candidates are measured, and the measured
  winner's knobs match the actually-fastest config within a band on the
  CPU micro-model;
- the persistent plan cache: schema-versioned file, warm hit applies the
  tuned plan with ZERO probe steps (test-pinned via a poisoned probe loop),
  invalidation by model-signature/topology key change, corrupt-file and
  wrong-schema tolerance;
- ``explain()``/``to_dict()`` schema, the applied-plan record riding
  profile documents and flight-recorder manifests, and flag typing.

Pure in-process host tests — no subprocess spawns (GL008-clean), named to
sort inside the tier-1 window (before test_image_data).
"""

import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from autodist_tpu import AutoDist, const, telemetry  # noqa: E402
from autodist_tpu.model_spec import ModelSpec  # noqa: E402
from autodist_tpu.resource_spec import ResourceSpec  # noqa: E402
from autodist_tpu.strategy import (AllReduce, Candidate,  # noqa: E402
                                   PSLoadBalancing, TunedPlan, autotune,
                                   enumerate_candidates, plan_cache_key)
# The package re-exports the `autotune` FUNCTION under the submodule's
# name, so attribute-style imports resolve the function; fetch the module.
import importlib  # noqa: E402
autotune_mod = importlib.import_module("autodist_tpu.strategy.autotune")
from autodist_tpu.telemetry import costmodel, profiling  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_state():
    """Leave process-global telemetry/profiling/applied-plan as found."""
    telemetry.disable()
    telemetry.clear()
    profiling.disable()
    profiling.reset()
    profiling.set_applied_plan(None)
    yield
    telemetry.disable()
    telemetry.clear()
    profiling.disable()
    profiling.reset()
    profiling.set_applied_plan(None)


# ------------------------------------------------------------------ fixtures

def _loss(p, b):
    return jnp.mean((b["y"] - b["x"] @ p["w"]) ** 2)


def _params():
    return {"w": np.random.RandomState(0).randn(8, 4).astype(np.float32)}


def _batch(rows=16):
    rng = np.random.RandomState(1)
    return {"x": rng.randn(rows, 8).astype(np.float32),
            "y": rng.randn(rows, 4).astype(np.float32)}


def _model_spec():
    return ModelSpec.from_loss_fn(_loss, _params(), _batch())


def _fast_autotune(**kw):
    kw.setdefault("warmup_steps", 1)
    kw.setdefault("measure_steps", 2)
    kw.setdefault("unrolls", (1, 8))
    kw.setdefault("top_k", 2)
    kw.setdefault("plan_cache", "")
    return autotune(_loss, _params(), optax.sgd(0.1), _batch(), **kw)


@pytest.fixture(scope="module")
def searched(tmp_path_factory):
    """ONE real end-to-end search shared by every test that only reads its
    result (ranking, cache file, explain table) — searches compile several
    candidate programs, so each extra one costs seconds of tier-1 window."""
    cache = str(tmp_path_factory.mktemp("plans") / "plan_cache.json")
    return _fast_autotune(plan_cache=cache), cache


# -------------------------------------------------------------------- flags

def test_new_flags_registered_and_typed(monkeypatch):
    for flag in ("AUTODIST_TUNE", "AUTODIST_PLAN_CACHE",
                 "AUTODIST_TUNE_TOPK", "AUTODIST_TUNE_BUDGET"):
        assert flag in const.KNOWN_FLAGS and const.KNOWN_FLAGS[flag]
        assert hasattr(const.ENV, flag)
    monkeypatch.setenv("AUTODIST_TUNE", "1")
    assert const.ENV.AUTODIST_TUNE.val is True
    monkeypatch.setenv("AUTODIST_PLAN_CACHE", "/tmp/pc.json")
    assert const.ENV.AUTODIST_PLAN_CACHE.val == "/tmp/pc.json"
    monkeypatch.setenv("AUTODIST_TUNE_TOPK", "5")
    assert const.ENV.AUTODIST_TUNE_TOPK.val == 5
    monkeypatch.setenv("AUTODIST_TUNE_BUDGET", "7")
    assert const.ENV.AUTODIST_TUNE_BUDGET.val == 7


# -------------------------------------------------------------- enumeration

def test_enumerate_joint_space_and_determinism():
    spec, rs = _model_spec(), ResourceSpec(None)
    cands = enumerate_candidates(spec, rs, optax.sgd(0.1),
                                 unrolls=(1, 2), accums=(1,))
    names = [c.name for c in cands]
    # Deterministic order, AllReduce and the PS default both compete, and
    # the unroll x zero grid crosses every builder (8 local devices => the
    # zero knob is live).
    assert names == [c.name for c in enumerate_candidates(
        spec, rs, optax.sgd(0.1), unrolls=(1, 2), accums=(1,))]
    assert "AllReduce" in names and "PSLoadBalancing" in names
    assert "AllReduce[unroll=2]" in names
    assert "AllReduce[zero=1]" in names and "AllReduce[unroll=2,zero=1]" in names
    # Small dense model on a roomy budget: no async regime, no partitioning.
    assert not any(c.asynchronous for c in cands)
    assert not any("Partitioned" in n for n in names)
    assert all(c.why for c in cands)


def test_enumerate_async_overlap_knob_when_requested():
    cands = enumerate_candidates(_model_spec(), ResourceSpec(None),
                                 optax.sgd(0.1), unrolls=(1,),
                                 include_async=True)
    async_c = [c for c in cands if c.asynchronous]
    # The async regime enumerates the overlap knob on/off, at unroll=1 only
    # (no fused block in the host-driven loop).
    assert {c.overlap for c in async_c} == {True, False}
    assert all(c.unroll == 1 for c in async_c)


def test_enumerate_budget_cap():
    cands = enumerate_candidates(_model_spec(), ResourceSpec(None),
                                 optax.sgd(0.1), unrolls=(1, 2, 4, 8),
                                 budget=5)
    assert len(cands) == 5


# ----------------------------------------------------- compile-only probe

def test_plan_costs_compile_only_no_step_dispatch(monkeypatch):
    """The stage-1 probe must never execute a step: runner.run/run_many are
    poisoned, and the probe still returns real XLA costs that scale ~Kx
    across unroll factors."""
    from autodist_tpu.runner import DistributedRunner
    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(_loss, _params(), optax.sgd(0.1),
                                           example_batch=_batch())

    def boom(*a, **k):
        raise AssertionError("plan_costs dispatched a training step")

    monkeypatch.setattr(DistributedRunner, "run", boom)
    monkeypatch.setattr(DistributedRunner, "run_many", boom)
    c1 = runner.plan_costs(_params(), _batch(), unroll=1)
    c4 = runner.plan_costs(_params(), _batch(), unroll=4)
    assert c1["flops"] > 0 and c1["steps"] == 1 and c1["dispatches"] == 1
    assert c4["steps"] == 4
    # The fused block is the scanned body xK (+ constant overhead): the
    # probe's flops must scale close to linearly.
    assert 2.0 < c4["flops"] / c1["flops"] < 6.0
    # No dispatch was counted against the profiling plane either.
    assert profiling.program_costs() == {}


def test_plan_costs_feeds_costmodel_predict():
    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(_loss, _params(), optax.sgd(0.1),
                                           example_batch=_batch())
    rec = runner.plan_costs(_params(), _batch(), unroll=4)
    calib = costmodel.Calibration(flops_per_s=1e9, bytes_per_s=1e9,
                                  host_s_per_dispatch=1e-3)
    out = costmodel.predict(rec, calib)
    # 4 steps ride one dispatch: the host term amortizes to 1ms/4.
    assert out["step_s"] > 0
    assert abs(out["breakdown"]["host_s"] - 1e-3 / 4) < 1e-9


# ------------------------------------------------------------ search + prune

def test_autotune_prunes_to_topk_and_winner_within_band(searched):
    """End-to-end search on the micro-model: at most top-k candidates get
    measured probes, the winner IS a measured candidate, and its measured
    rate is the best of the probes (the prune must not have dropped the
    measured-best survivor)."""
    plan, _ = searched
    probed = [c for c in plan.candidates if c.probe is not None]
    assert 0 < len(probed) <= 2 and plan.probed == len(probed)
    measured = [c for c in probed if c.probe.steps_per_sec is not None]
    assert measured, "at least one probe must succeed on the micro-model"
    best = max(measured, key=lambda c: c.probe.steps_per_sec)
    assert plan.measured_steps_per_s == best.probe.steps_per_sec
    assert plan.knobs_dict()["builder"] == best.builder_spec
    assert plan.unroll == best.unroll
    # Everything NOT probed carries a prune/skip reason.
    assert all(c.pruned for c in plan.candidates if c.probe is None)
    assert plan.enumerated == len(plan.candidates)
    assert plan.search_s > 0
    # With the bundled calibration (host cost per dispatch dominates the
    # micro-model), stage 1 must rank deeper unrolls ahead: the measured
    # survivors are all unroll=8 candidates.
    assert all(c.unroll == 8 for c in probed)
    assert plan.unroll == 8


def test_autotune_rejects_multinode_and_bad_topk():
    two_nodes = ResourceSpec(
        "nodes: [{address: 10.0.0.1, tpus: 4, chief: true}, "
        "{address: 10.0.0.2, tpus: 4}]")
    with pytest.raises(ValueError, match="multi-node"):
        _fast_autotune(resource_spec=two_nodes)
    with pytest.raises(ValueError, match="top_k"):
        _fast_autotune(top_k=0)


# ------------------------------------------------------------------- cache

def test_cache_hit_skips_probing(searched, monkeypatch):
    """Warm plan-cache launch applies the tuned plan with ZERO probe steps:
    after the first search persists, the probe loop and the compile probe
    are both poisoned and the second call still returns the plan."""
    plan, cache = searched
    assert not plan.from_cache and os.path.exists(cache)

    def boom(*a, **k):
        raise AssertionError("a warm cache hit ran a probe")

    monkeypatch.setattr(autotune_mod, "measure_candidate", boom)
    monkeypatch.setattr(autotune_mod, "_probe_base_costs", boom)
    warm = _fast_autotune(plan_cache=cache)
    assert warm.from_cache
    assert warm.knobs_dict() == plan.knobs_dict()
    assert warm.measured_steps_per_s == plan.measured_steps_per_s
    assert isinstance(warm.make_builder(), (AllReduce, PSLoadBalancing))


def test_cache_schema_and_invalidation_by_key(searched):
    plan, cache = searched
    doc = json.load(open(cache))
    assert doc["schema"] == autotune_mod.PLAN_SCHEMA
    assert doc["schema_version"] == autotune_mod.PLAN_SCHEMA_VERSION
    assert plan.cache_key in doc["plans"]
    entry = doc["plans"][plan.cache_key]
    for key in ("cache_key", "knobs", "predicted", "measured_steps_per_s",
                "search_s", "created"):
        assert key in entry, key
    # A different model signature keys differently -> the lookup misses.
    other = ModelSpec({"w": np.zeros((16, 4), np.float32)})
    other_key = plan_cache_key(other, _batch(), ResourceSpec(None))
    assert other_key != plan.cache_key
    assert autotune_mod.load_cached_plan(cache, other_key) is None
    # Same model, different batch shape: also a distinct problem.
    spec = _model_spec()
    k_b16 = plan_cache_key(spec, _batch(16), ResourceSpec(None))
    k_b32 = plan_cache_key(spec, _batch(32), ResourceSpec(None))
    assert k_b16 != k_b32


def test_cache_key_depends_on_topology_and_version(monkeypatch):
    spec = _model_spec()
    base = plan_cache_key(spec, _batch(), ResourceSpec(None))
    assert base == plan_cache_key(spec, _batch(), ResourceSpec(None))
    import autodist_tpu.version as version_mod
    monkeypatch.setattr(version_mod, "__version__", "999.0.0")
    assert plan_cache_key(spec, _batch(), ResourceSpec(None)) != base
    monkeypatch.undo()
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    assert plan_cache_key(spec, _batch(), ResourceSpec(None)) != base


def test_cache_tolerates_corrupt_and_wrong_schema_files(tmp_path):
    corrupt = tmp_path / "corrupt.json"
    corrupt.write_text("{not json")
    assert autotune_mod.load_cached_plan(str(corrupt), "k") is None
    wrong = tmp_path / "wrong.json"
    wrong.write_text(json.dumps({"schema": "other", "schema_version": 99,
                                 "plans": {"k": {}}}))
    assert autotune_mod.load_cached_plan(str(wrong), "k") is None
    # store_plan over a corrupt file recreates it.
    plan = TunedPlan(builder_spec={"name": "AllReduce"}, cache_key="k2")
    assert autotune_mod.store_plan(str(corrupt), plan)
    assert autotune_mod.load_cached_plan(str(corrupt), "k2") is not None
    # A second job's entry MERGES (read-modify-write under the lock): the
    # first plan survives the second store.
    other = TunedPlan(builder_spec={"name": "PSLoadBalancing"},
                      cache_key="k3")
    assert autotune_mod.store_plan(str(corrupt), other)
    assert autotune_mod.load_cached_plan(str(corrupt), "k2") is not None
    assert autotune_mod.load_cached_plan(str(corrupt), "k3") is not None


# ------------------------------------------------------- explain + plan API

def test_explain_schema_search_and_cached(searched):
    plan, _ = searched
    text = plan.explain()
    head = text.splitlines()[0]
    assert "candidates" in head and "probed" in head and plan.cache_key in head
    assert "ms/step" in text and "<- winner" in text
    assert ("pruned:" in text) or ("not probed" in text)
    # A cache-loaded plan (no candidate table) still explains itself.
    warm = TunedPlan.from_dict(plan.to_dict())
    warm.from_cache = True
    warm.cache_key = plan.cache_key
    assert "plan cache" in warm.explain()
    # to_dict round-trips the knobs.
    assert TunedPlan.from_dict(plan.to_dict()).knobs_dict() == plan.knobs_dict()


def test_candidate_name_and_builder_spec_roundtrip():
    c = Candidate({"name": "PS", "kwargs": {"sync": False}}, unroll=1,
                  asynchronous=True, overlap=False)
    assert c.name == "PS[async,overlap=0]"
    from autodist_tpu.strategy import PS
    assert isinstance(c.make_builder(), PS)
    with pytest.raises(ValueError, match="unknown builder"):
        autotune_mod.builder_from_spec({"name": "NoSuchBuilder"})


# --------------------------------------------------------- session plumbing

def test_session_tune_applies_plan_and_records_it(tmp_path, monkeypatch):
    monkeypatch.setenv("AUTODIST_PLAN_CACHE", str(tmp_path / "pc.json"))
    ad = AutoDist(strategy_builder="autotune")
    runner = ad.create_distributed_session(
        _loss, _params(), optax.sgd(0.1), example_batch=_batch())
    plan = runner.tuned_plan
    assert plan is not None and plan.measured_steps_per_s > 0
    assert type(runner.plan) is not type(None)  # session built and usable
    state = runner.init(_params())
    state, loss = runner.run(state, _batch())
    assert np.isfinite(float(loss))
    # The applied plan rides the profile document and recorder manifest.
    applied = profiling.applied_plan()
    assert applied and applied["cache_key"] == plan.cache_key
    assert applied["knobs"]["unroll"] == plan.unroll
    profiling.enable()
    doc = profiling.profile_document()
    assert doc["plan"]["name"] == plan.name
    manifest = telemetry.build_manifest("test")
    assert manifest["plan"]["cache_key"] == plan.cache_key
    # train() adopts the tuned unroll when none is passed.
    from autodist_tpu import train
    final = train(runner, _params(), lambda i: _batch(), steps=plan.unroll,
                  log_every=0)
    assert int(final.step) == plan.unroll


def test_session_warm_cache_zero_probe_steps(tmp_path, monkeypatch):
    """The acceptance pin: a second launch with a warm cache builds its
    session without a single probe step or compile probe."""
    monkeypatch.setenv("AUTODIST_PLAN_CACHE", str(tmp_path / "pc.json"))
    ad = AutoDist(strategy_builder="autotune")
    ad.create_distributed_session(_loss, _params(), optax.sgd(0.1),
                                  example_batch=_batch())

    def boom(*a, **k):
        raise AssertionError("warm launch ran a probe")

    monkeypatch.setattr(autotune_mod, "measure_candidate", boom)
    monkeypatch.setattr(autotune_mod, "_probe_base_costs", boom)
    ad2 = AutoDist(strategy_builder="autotune")
    runner2 = ad2.create_distributed_session(
        _loss, _params(), optax.sgd(0.1), example_batch=_batch())
    assert runner2.tuned_plan.from_cache


def test_session_degrades_to_default_builder_when_search_fails(monkeypatch):
    """Tuning is an optimization: a search that raises (backend with no
    cost analysis, every probe failing) falls back to the default builder
    with a warning instead of killing the launch."""
    def boom(*a, **k):
        raise RuntimeError("no candidate could be compile-probed")

    monkeypatch.setattr(autotune_mod, "autotune", boom)
    ad = AutoDist(strategy_builder="autotune")
    runner = ad.create_distributed_session(
        _loss, _params(), optax.sgd(0.1), example_batch=_batch())
    assert runner.tuned_plan is None
    assert type(ad._strategy_builder) is PSLoadBalancing  # the default
    state = runner.init(_params())
    state, loss = runner.run(state, _batch())
    assert np.isfinite(float(loss))


def test_measure_candidate_argument_errors_raise(monkeypatch):
    """Argument errors surface as the caller's mistake, not as recorded
    candidate failures (the failure-skip guard is for candidate faults)."""
    from autodist_tpu.strategy import measure_candidate
    with pytest.raises(ValueError, match="warmup_steps"):
        measure_candidate(AllReduce(), _loss, _params(), optax.sgd(0.1),
                          _batch(), warmup_steps=0)
    with pytest.raises(ValueError, match="unroll"):
        measure_candidate(AllReduce(), _loss, _params(), optax.sgd(0.1),
                          _batch(), unroll=0)


def test_session_tune_false_by_default_and_bad_name(monkeypatch):
    monkeypatch.delenv("AUTODIST_TUNE", raising=False)
    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(
        _loss, _params(), optax.sgd(0.1), example_batch=_batch())
    assert runner.tuned_plan is None
    with pytest.raises(ValueError, match="autotune"):
        AutoDist(strategy_builder="fastest_please")


def test_tune_telemetry_gauges_booked(searched):
    """The search books the tune.* gauges (the module fixture's real search
    already ran — instruments book whether or not telemetry is enabled) and
    a warm relaunch counts a cache hit. Counters are process-global and
    monotonic: assert DELTAS, not totals."""
    plan, cache = searched
    snap = telemetry.snapshot()
    # Gauges are last-write-wins across the process (other tests in this
    # file also search): pin presence + sanity, not the fixture's exact run.
    assert snap["tune.candidates"] > 0
    assert snap["tune.probed"] >= 1
    assert snap["tune.best_steps_per_s"] > 0
    assert snap["tune.search_s"] > 0
    assert snap.get("tune.cache_miss", 0) >= 1
    before = snap.get("tune.cache_hit", 0)
    _fast_autotune(plan_cache=cache)
    assert telemetry.snapshot()["tune.cache_hit"] - before == 1
