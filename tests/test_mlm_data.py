"""MLM pretrain pipeline: corpus prep, dynamic masking, disk-fed BERT training.

Parity target: the reference BERT benchmark consumed pre-masked pretrain
tfrecords (``examples/benchmark/bert.py:82-98`` ->
``utils/input_pipeline.py::create_pretrain_dataset``). Here masking is dynamic
(drawn per batch, deterministic under a seed) over raw token shards — these
tests pin the prep layout, the 80/10/10 recipe, determinism, and an
end-to-end BERT train step from disk.
"""

import os

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.data import DataLoader, mlm
from autodist_tpu.data.text_corpus import Vocabulary
from shardmap_compat import requires_shard_map


def _write_corpus(path, n_words=4000, vocab=40, seed=0):
    rng = np.random.RandomState(seed)
    words = [f"w{i}" for i in range(vocab)]
    with open(path, "w") as f:
        for _ in range(n_words // 10):
            f.write(" ".join(words[rng.randint(0, vocab)] for _ in range(10)))
            f.write("\n")
    return words


def _prep(tmp_path, seq_len=16, segments=False, n_words=4000):
    corpus = str(tmp_path / "corpus.txt")
    words = _write_corpus(corpus, n_words=n_words)
    vocab = Vocabulary(words, oov_buckets=1)
    out = str(tmp_path / "mlm")
    paths = mlm.prepare_mlm_shards(corpus, vocab, out, seq_len=seq_len,
                                   rows_per_shard=64, segments=segments)
    return out, paths, vocab


def test_prep_layout_single_segment(tmp_path):
    out, paths, vocab = _prep(tmp_path, seq_len=16)
    meta = mlm.read_meta(out)
    assert meta["vocab_size"] == mlm.N_SPECIAL + vocab.vocab_size
    assert meta["seq_len"] == 16 and not meta["segments"]
    toks = np.load(paths["tokens"][0])
    typs = np.load(paths["token_types"][0])
    assert toks.shape[1] == 16 and toks.dtype == np.int32
    # Row layout: [CLS] 14 words [SEP]; full rows, no padding.
    assert (toks[:, 0] == mlm.CLS_ID).all()
    assert (toks[:, -1] == mlm.SEP_ID).all()
    body = toks[:, 1:-1]
    assert (body >= mlm.N_SPECIAL).all()
    assert (toks < meta["vocab_size"]).all()
    assert (typs == 0).all()
    # Rows count matches the word budget: n_words // 14 full rows.
    assert meta["rows"] == sum(len(np.load(p)) for p in paths["tokens"])


def test_prep_layout_segment_pairs(tmp_path):
    out, paths, _ = _prep(tmp_path, seq_len=16, segments=True)
    toks = np.load(paths["tokens"][0])
    typs = np.load(paths["token_types"][0])
    for row, typ in zip(toks[:20], typs[:20]):
        assert row[0] == mlm.CLS_ID and row[-1] == mlm.SEP_ID
        (seps,) = np.where(row == mlm.SEP_ID)
        assert len(seps) == 2  # mid + final
        mid = seps[0]
        # types: 0 through the first SEP, 1 after it.
        assert (typ[:mid + 1] == 0).all() and (typ[mid + 1:] == 1).all()
        # both segments non-empty
        assert mid >= 2 and mid <= len(row) - 3


def test_mask_batch_recipe():
    rng = np.random.Generator(np.random.PCG64(0))
    L, B, P = 64, 512, 10
    vocab_size = 100
    tokens = np.full((B, L), mlm.CLS_ID, np.int32)
    tokens[:, 1:-1] = np.random.RandomState(1).randint(
        mlm.N_SPECIAL, vocab_size, (B, L - 2))
    tokens[:, -1] = mlm.SEP_ID
    out = mlm.mask_batch(tokens, rng, vocab_size=vocab_size, max_predictions=P)

    assert out["tokens"].shape == (B, L)
    assert out["mlm_positions"].shape == (B, P)
    live = out["mlm_weights"] > 0
    # 15% of 62 maskable ~ 9.3 -> min(P, 9) = 9 live slots per row.
    assert live.sum(axis=1).min() >= 8 and live.sum(axis=1).max() <= P
    rows = np.arange(B)[:, None]
    # No special position is ever masked.
    assert (out["mlm_positions"][live] != 0).all()
    assert (tokens[rows, out["mlm_positions"]][live] >= mlm.N_SPECIAL).all()
    # Targets are the ORIGINAL tokens at the chosen positions.
    np.testing.assert_array_equal(out["mlm_targets"],
                                  tokens[rows, out["mlm_positions"]])
    # Off-position tokens are untouched.
    untouched = np.ones((B, L), bool)
    untouched[rows, out["mlm_positions"]] = False
    np.testing.assert_array_equal(out["tokens"][untouched], tokens[untouched])
    # 80/10/10 over the live slots (binomial bounds, ~4.6k draws).
    vals = out["tokens"][rows, out["mlm_positions"]][live]
    orig = out["mlm_targets"][live]
    frac_mask = (vals == mlm.MASK_ID).mean()
    frac_keep = (vals == orig).mean()
    assert 0.75 < frac_mask < 0.85, frac_mask
    assert 0.06 < frac_keep < 0.15, frac_keep


def test_masking_is_deterministic_and_fresh_per_batch(tmp_path):
    out, paths, _ = _prep(tmp_path)
    meta = mlm.read_meta(out)

    def stream(n):
        loader = DataLoader(files=paths, batch_size=8, shuffle=True, seed=3,
                            native=False)
        b = mlm.MLMBatcher(loader, vocab_size=meta["vocab_size"],
                           max_predictions=4, seed=11)
        return [b.next() for _ in range(n)]

    a, b = stream(5), stream(5)
    for x, y in zip(a, b):
        for key in x:
            np.testing.assert_array_equal(x[key], y[key])
    # Dynamic masking: successive epochs over the same rows draw different
    # masks (the RoBERTa property static tfrecord masking lacks).
    assert not np.array_equal(a[0]["mlm_positions"], a[1]["mlm_positions"])


@requires_shard_map
def test_bert_trains_from_disk(tmp_path):
    from autodist_tpu import AutoDist
    from autodist_tpu.models import bert
    from autodist_tpu.models.common import jit_init
    from autodist_tpu.strategy import AllReduce

    out, paths, _ = _prep(tmp_path, seq_len=16, n_words=8000)
    meta = mlm.read_meta(out)
    cfg = bert.BertConfig(vocab_size=meta["vocab_size"], d_model=32, n_heads=2,
                          n_layers=2, d_ff=64, max_len=16, dtype=jnp.float32)
    model = bert.Bert(cfg)
    loader = DataLoader(files=paths, batch_size=16, shuffle=True, seed=0,
                        native=False)
    batcher = mlm.MLMBatcher(loader, vocab_size=meta["vocab_size"],
                             max_predictions=4, seed=0)
    example = batcher.next()
    params = jit_init(model, jnp.asarray(example["tokens"]),
                      jnp.asarray(example["token_types"]))
    ad = AutoDist(strategy_builder=AllReduce())
    step = ad.function(bert.make_mlm_loss_fn(model), params,
                       optax.adam(1e-2), example_batch=example)
    losses = [float(step(batcher.next())) for _ in range(30)]
    assert np.isfinite(losses).all()
    # The corpus is uniform-random (entropy floor ~log(40) = 3.7): training
    # should descend clearly from the initial loss toward that floor.
    assert np.mean(losses[-5:]) < losses[0] - 0.5, losses


@requires_shard_map
def test_bert_eval_restores_and_scores(tmp_path, monkeypatch):
    """Train -> checkpoint -> `bert.py --eval --restore`: masked-LM accuracy
    on a cyclic (fully predictable) corpus is far above chance with the
    restored params and ~chance with a fresh init — the reference's
    masked_lm_accuracy metric driven through the benchmark CLI."""
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.checkpoint import Saver
    from autodist_tpu.models import bert
    from autodist_tpu.models.common import jit_init
    from autodist_tpu.strategy import AllReduce

    # Cyclic corpus: word i = w{i % 8} — every masked slot is inferable from
    # its neighbors, so a trained model should approach 100%.
    corpus = str(tmp_path / "cyclic.txt")
    with open(corpus, "w") as f:
        for _ in range(400):
            f.write(" ".join(f"w{i % 8}" for i in range(40)) + "\n")

    import examples.benchmark.bert as bench

    bench.main(["--tokenize_corpus", corpus, "--data_dir",
                str(tmp_path / "shards"), "--seq_len", "16",
                "--vocab_size", "16"])

    from autodist_tpu.data import mlm
    meta = mlm.read_meta(str(tmp_path / "shards"))
    tiny = dict(d_model=32, n_heads=2, n_layers=2, d_ff=64)
    monkeypatch.setitem(bench.SIZES, "tiny", tiny)

    loader, _ = mlm.open_mlm_loader(str(tmp_path / "shards"), batch_size=16,
                                    shuffle=True)
    batcher = mlm.MLMBatcher(loader, vocab_size=meta["vocab_size"],
                             max_predictions=3, seed=0)
    cfg = bert.BertConfig(vocab_size=meta["vocab_size"], max_len=16,
                          dtype=jnp.float32, **tiny)
    model = bert.Bert(cfg)
    example = batcher.next()
    params = jit_init(model, jnp.asarray(example["tokens"]),
                      jnp.asarray(example["token_types"]))
    ad = AutoDist(strategy_builder=AllReduce())
    step = ad.function(bert.make_mlm_loss_fn(model), params,
                       optax.adam(3e-3), example_batch=example)
    for _ in range(60):
        step(batcher.next())
    loader.close()
    prefix = Saver().save(step.get_state(), str(tmp_path / "ckpt"))

    common = ["--size", "tiny", "--eval", "--data_dir",
              str(tmp_path / "shards"), "--seq_len", "16",
              "--batch_size", "16", "--max_predictions", "3"]
    # 60 tiny-model steps reach ~0.55 (10% of masked slots are random-replaced
    # and neighbors can be masked too, so 1.0 is not the ceiling); fresh init
    # sits at ~1/vocab. The GAP is what proves restore carried the learning.
    acc = bench.main(common + ["--restore", prefix])
    assert acc > 0.4, acc
    chance = bench.main(common)
    assert chance < 0.2, chance


def test_prep_validates(tmp_path):
    corpus = str(tmp_path / "tiny.txt")
    with open(corpus, "w") as f:
        f.write("a b c\n")
    vocab = Vocabulary(["a", "b", "c"])
    with pytest.raises(ValueError, match="too short"):
        mlm.prepare_mlm_shards(corpus, vocab, str(tmp_path / "x"), seq_len=2)
    with pytest.raises(ValueError, match="no MLM rows"):
        mlm.prepare_mlm_shards(corpus, vocab, str(tmp_path / "x"), seq_len=32)
