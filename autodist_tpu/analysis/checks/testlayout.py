"""GL008 — tier-1 test-window conventions.

The tier-1 suite runs ``pytest tests/ -m 'not slow'`` under an 870-second
budget and collects files in alphabetical order; the budget historically
expires inside ``test_multiprocess.py``. Two conventions keep that window
stable (CHANGES.md records both): new test files must be NAMED so they sort
where they intend to run (in-window, or deliberately last like
``test_unrolled.py``), and known-slow tests — anything spawning real
subprocesses — must either carry ``@pytest.mark.slow`` or live at/after the
window edge so a new subprocess-heavy file cannot silently push existing
in-window tests past the budget.
"""

import ast
import re
from typing import List

from autodist_tpu.analysis import callgraph
from autodist_tpu.analysis.core import Context, Finding, Module, register

_NAME_RE = re.compile(r"^test_[a-z0-9_]+\.py$")
# The alphabetical point where the 870s tier-1 budget historically expires
# (see CHANGES.md PR 2 note): files sorting at/after it are outside the
# guaranteed window, so their wall-clock cost cannot displace in-window tests.
WINDOW_EDGE = "test_multiprocess.py"

_SPAWN_ATTRS = {"Popen", "run", "check_call", "check_output", "call"}


def _basename(relpath: str) -> str:
    return relpath.rsplit("/", 1)[-1]


@register("GL008", "test file violates the tier-1 window conventions")
def check_test_layout(module: Module, ctx: Context) -> List[Finding]:
    """GL008 — test-window ordering.

    For ``tests/test_*.py`` files:

    - The filename must match ``test_[a-z0-9_]+.py`` — the suite's ordering
      IS its schedule (files collect alphabetically against the 870s tier-1
      budget), so a stray uppercase/hyphen name lands at an unintended
      position.
    - A file sorting BEFORE the window edge (``test_multiprocess.py``) that
      spawns real subprocesses (``subprocess.Popen/run/...`` or the
      ``mp_env`` multi-process harness) must mark those tests
      ``@pytest.mark.slow``: subprocess tests cost tens of seconds each,
      and an unmarked one inside the window displaces existing in-window
      tests past the budget. (Pre-existing files are grandfathered via the
      committed baseline — marking them slow NOW would remove them from
      tier-1 and change the pass count.)
    - ``pytest.mark.slow`` requires the ``slow`` marker registered in
      pyproject.toml — an unregistered marker is a typo trap (``-m 'not
      slow'`` silently matches nothing).
    """
    base = _basename(module.relpath)
    if module.tree is None or not module.relpath.startswith("tests/") \
            or not base.startswith("test"):
        return []
    findings: List[Finding] = []

    if not _NAME_RE.match(base):
        findings.append(Finding(
            "GL008", module.relpath, 1, 0,
            f"test filename {base!r} does not match test_[a-z0-9_]+.py; "
            f"alphabetical position decides whether it runs inside the "
            f"870s tier-1 window — name it deliberately"))

    spawn_line = None
    imports_mp_env = False
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "mp_env" or alias.name.endswith(".mp_env"):
                    imports_mp_env = True
                    spawn_line = spawn_line or node.lineno
        elif isinstance(node, ast.ImportFrom):
            # Both import forms the repo uses: `from mp_env import ...` and
            # `from tests.mp_env import ...`.
            mod = node.module or ""
            if mod == "mp_env" or mod.endswith(".mp_env"):
                imports_mp_env = True
                spawn_line = spawn_line or node.lineno
        elif isinstance(node, ast.Call):
            dotted = callgraph.dotted_name(node.func) or ""
            if dotted.startswith("subprocess.") \
                    and dotted.rsplit(".", 1)[-1] in _SPAWN_ATTRS:
                spawn_line = spawn_line or node.lineno

    has_slow = any(
        callgraph.dotted_name(node) == "pytest.mark.slow"
        for node in ast.walk(module.tree))

    if spawn_line is not None and base < WINDOW_EDGE and not has_slow:
        kind = "the mp_env multi-process harness" if imports_mp_env \
            else "subprocess"
        findings.append(Finding(
            "GL008", module.relpath, spawn_line, 0,
            f"file sorts inside the tier-1 window (before {WINDOW_EDGE}) "
            f"and spawns {kind} without @pytest.mark.slow; subprocess "
            f"tests displace in-window tests past the 870s budget"))

    if has_slow and "slow" not in ctx.pyproject_markers():
        line = next((n.lineno for n in ast.walk(module.tree)
                     if callgraph.dotted_name(n) == "pytest.mark.slow"), 1)
        findings.append(Finding(
            "GL008", module.relpath, line, 0,
            "pytest.mark.slow used but the `slow` marker is not registered "
            "in pyproject.toml [tool.pytest.ini_options] markers"))
    return findings
