"""GL012 guarded-field consistency — the static half of the race detector.

The classic lockset argument, scoped the way GL010 scopes closeables:
DISCOVERED from the code, never listed. For every class in the checked tree,
each instance attribute's guard is *inferred* from the writes that happen
inside ``with self._lock:`` (or ``with rep._lock:`` — any receiver whose
type resolves) blocks; an attribute the code bothers to guard in one method
but reads or writes bare in another method that can run on a different
thread is a finding. "Can run on a different thread" means: the bare access
(or the guarded write) sits in a method reachable from a thread entry point
— ``Thread(target=self.m)`` anywhere in the class family — or the access
crosses a class boundary through a typed receiver (a ``Replica`` attribute
touched from ``Router`` methods is shared state by construction; the writer
taking a lock is the admission that it races).

What keeps the false-positive rate workable:

- **single-guard inference**: an attribute is only checked when ALL its
  guarded writes agree on one lock attribute; ambiguous disciplines are
  skipped, not guessed.
- **locked helpers**: a method whose every intra-family call site sits
  under a guard (``_inflight_locked`` called only from ``with self._lock``
  blocks) has its accesses credited with that guard — the
  lock-held-helper idiom this codebase uses deliberately.
- **class families**: base classes resolvable through the ProgramIndex are
  folded in, so a scheduling loop defined on ``_BatcherBase`` makes the
  subclass's methods thread-reachable and the base's ``with self._lock:``
  call sites guard the subclass's hooks.
- **deferred code is skipped** (``callgraph.walk_executed`` semantics): a
  closure body under a ``with`` is not guarded by it, and is not walked.
- ``__init__`` self-writes are construction, not publication; attributes
  holding synchronization objects themselves (locks, conditions, events,
  queues, threads) are exempt — they are the discipline, not the data.

Like every program check its results are never file-cached (two-layer cache
semantics); suppression needs the mandatory reason
(``# graftlint: disable=GL012(why the bare access is safe)``).
"""

import ast
from typing import Dict, List, Optional, Set, Tuple

from autodist_tpu.analysis import callgraph
from autodist_tpu.analysis.core import Context, Finding, register_program
from autodist_tpu.analysis.checks.concurrency import (_LOCK_CTORS,
                                                      _LOCK_TOKENS)

# Constructors whose instances are internally synchronized (or are the
# synchronization): attributes bound to them are exempt from the guarded-
# field rule. The san_* factories are the sanitizer's lock-producing twins.
_SYNC_CTORS = _LOCK_CTORS | {
    "Event", "Barrier", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "BoundedQueue", "deque", "count", "local", "Thread",
    "san_lock", "san_rlock", "san_condition", "san_event",
}
_LOCKY_CTORS = _LOCK_CTORS | {"san_lock", "san_rlock", "san_condition"}

_CHECKED_PREFIXES = ("autodist_tpu/", "examples/", "tools/")


def _checked_path(relpath: str) -> bool:
    return relpath.startswith(_CHECKED_PREFIXES) or "/" not in relpath


_LIST_HEADS = {"List", "list", "Sequence", "Iterable", "Iterator", "Tuple",
               "tuple", "Set", "set", "FrozenSet", "frozenset"}


def _annotation_class(ann) -> Optional[Tuple[str, bool]]:
    """``(class name, is_element_type)`` for an annotation that names one
    class — ``C``, ``"C"``, ``Optional[C]`` -> (C, False); ``List[C]`` and
    friends -> (C, True); anything else None."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, (ast.Name, ast.Attribute)):
        name = callgraph.dotted_name(ann)
        return (name, False) if name else None
    if isinstance(ann, ast.Subscript):
        head = callgraph.last_attr(ann.value)
        inner = ann.slice
        if head == "Optional":
            hit = _annotation_class(inner)
            return (hit[0], False) if hit and not hit[1] else None
        if head in _LIST_HEADS:
            if isinstance(inner, ast.Tuple) and inner.elts:
                inner = inner.elts[0]
            hit = _annotation_class(inner)
            return (hit[0], True) if hit and not hit[1] else None
    return None


class _ClassFacts:
    """Per-class harvest: lock attrs, sync-object attrs, thread entries."""

    def __init__(self):
        self.lock_attrs: Set[str] = set()     # self.X = Lock()/san_lock()...
        self.sync_attrs: Set[str] = set()     # exempt attribute names
        self.entries: Set[str] = set()        # Thread(target=self.m) methods
        self.bases: List[str] = []            # base-class dotted names
        self.methods: Dict[str, ast.FunctionDef] = {}


class _Access:
    __slots__ = ("attr", "cls_key", "is_write", "guards", "relpath", "line",
                 "col", "scope", "method_key", "cross_class", "in_init")

    def __init__(self, attr, cls_key, is_write, guards, relpath, line, col,
                 scope, method_key, cross_class, in_init):
        self.attr = attr              # attribute name
        self.cls_key = cls_key        # (owner relpath, class name)
        self.is_write = is_write
        self.guards = guards          # frozenset of lock-attr names held
        self.relpath = relpath        # module containing the ACCESS
        self.line = line
        self.col = col
        self.scope = scope
        self.method_key = method_key  # (relpath, cls, method) or None
        self.cross_class = cross_class
        self.in_init = in_init


def _class_facts(program) -> Dict[Tuple[str, str], _ClassFacts]:
    facts: Dict[Tuple[str, str], _ClassFacts] = {}
    for info in program.modules():
        for cls_name, cls in info.classes.items():
            f = _ClassFacts()
            f.bases = [callgraph.dotted_name(b) for b in cls.bases
                       if callgraph.dotted_name(b)]
            for item in cls.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    f.methods[item.name] = item
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    ctor = callgraph.last_attr(node.value.func)
                    for target in node.targets:
                        if isinstance(target, ast.Attribute) \
                                and isinstance(target.value, ast.Name) \
                                and target.value.id == "self":
                            if ctor in _LOCKY_CTORS:
                                f.lock_attrs.add(target.attr)
                            if ctor in _SYNC_CTORS:
                                f.sync_attrs.add(target.attr)
                elif isinstance(node, ast.Call) \
                        and callgraph.last_attr(node.func) == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target" \
                                and isinstance(kw.value, ast.Attribute) \
                                and isinstance(kw.value.value, ast.Name) \
                                and kw.value.value.id == "self":
                            f.entries.add(kw.value.attr)
            facts[(info.relpath, cls_name)] = f
    return facts


def _family(program, info, cls_name, facts, depth=3) \
        -> List[Tuple[str, str]]:
    """``[(relpath, class)]`` for a class and its resolvable bases, most
    derived first."""
    out, seen = [], set()

    def visit(inf, name, d):
        key = (inf.relpath, name)
        if key in seen or key not in facts or d < 0:
            return
        seen.add(key)
        out.append(key)
        for base in facts[key].bases:
            hit = program.resolve_class(inf, base)
            if hit is not None:
                visit(hit[0], hit[1].name, d - 1)

    visit(info, cls_name, depth)
    return out


def _is_locky(attr: str, recv_cls_key, facts) -> bool:
    if callgraph.name_tokens(attr) & _LOCK_TOKENS:
        return True
    f = facts.get(recv_cls_key) if recv_cls_key else None
    return f is not None and attr in f.lock_attrs


def _guard_items(items, recv_types, facts) -> Set[Tuple[str, str]]:
    """``(receiver name, lock attr)`` pairs a with-statement acquires."""
    out = set()
    for item in items:
        expr = item.context_expr
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            recv = expr.value.id
            if _is_locky(expr.attr, recv_types.get(recv), facts):
                out.add((recv, expr.attr))
    return out


def _walk_function(info, cls_name, cls_key, fn, program, facts, accesses,
                   helper_sites, scope_name):
    """One pass over a function/method: typed receivers, guard nesting,
    attribute accesses, intra-family self-call sites."""
    # Receiver typing: self, annotated params, ctor locals, and locals /
    # loop targets drawn from calls with class-valued return annotations.
    recv_types: Dict[str, Tuple[str, str]] = {}
    if cls_name is not None:
        recv_types["self"] = cls_key
    args = fn.args
    for a in (list(args.posonlyargs) + list(args.args)
              + list(args.kwonlyargs)):
        if a.annotation is None or a.arg == "self":
            continue
        hit = _annotation_class(a.annotation)
        if hit and not hit[1]:
            r = program.resolve_class(info, hit[0])
            if r is not None:
                recv_types[a.arg] = (r[0].relpath, r[1].name)
    for name, (owner, c) in program.local_types(info, fn).items():
        recv_types.setdefault(name, (owner.relpath, c))

    def returns_class(call) -> Optional[Tuple[Tuple[str, str], bool]]:
        resolved = program.resolve_call(info, call, cls_name,
                                        program.local_types(info, fn))
        if resolved is None or getattr(resolved.fn, "returns", None) is None:
            return None
        hit = _annotation_class(resolved.fn.returns)
        if hit is None:
            return None
        r = program.resolve_class(resolved.info, hit[0])
        if r is None:
            return None
        return (r[0].relpath, r[1].name), hit[1]

    for stmt in fn.body:
        for node in callgraph.walk_executed(stmt):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                typed = returns_class(node.value)
                if typed and not typed[1]:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            recv_types.setdefault(t.id, typed[0])
            elif isinstance(node, ast.For) and isinstance(node.iter,
                                                          ast.Call) \
                    and isinstance(node.target, ast.Name):
                typed = returns_class(node.iter)
                if typed and typed[1]:
                    recv_types.setdefault(node.target.id, typed[0])

    in_init = cls_name is not None and fn.name == "__init__"
    method_key = (info.relpath, cls_name, fn.name) if cls_name else None
    module = info.module

    def visit(node, guards):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # deferred code: neither guarded by, nor walked under
        if isinstance(node, (ast.With, ast.AsyncWith)):
            added = _guard_items(node.items, recv_types, facts)
            for item in node.items:
                visit(item, guards)
            for body_stmt in node.body:
                visit(body_stmt, guards | added)
            return
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name):
            recv = node.value.id
            recv_key = recv_types.get(recv)
            if recv_key is not None \
                    and not _is_locky(node.attr, recv_key, facts):
                f = facts.get(recv_key)
                if f is None or node.attr not in f.sync_attrs:
                    held = frozenset(g for r, g in guards if r == recv)
                    accesses.append(_Access(
                        node.attr, recv_key,
                        isinstance(node.ctx, (ast.Store, ast.Del)),
                        held, info.relpath, node.lineno, node.col_offset,
                        module.scope_at(node), method_key,
                        cross_class=(recv_key != cls_key
                                     or cls_name is None),
                        in_init=(in_init and recv == "self")))
        if isinstance(node, ast.Call) and cls_name is not None \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            held = frozenset(g for r, g in guards if r == "self")
            helper_sites.setdefault(
                (info.relpath, cls_name, node.func.attr), []).append(held)
        for child in ast.iter_child_nodes(node):
            visit(child, guards)

    for stmt in fn.body:
        visit(stmt, frozenset())


@register_program("GL012", "guarded-field consistency (static race detector)")
def check_guarded_fields(program, ctx: Context) -> List[Finding]:
    """GL012 — attribute guarded in one method, bare in another.

    For each class, each instance attribute's guard is inferred from writes
    under ``with <receiver>._lock:`` blocks (single-guard agreement
    required). A bare read/write of the same attribute is a finding when the
    race is reachable: the bare site or the guarded writer runs on a spawned
    thread (``Thread(target=self.m)`` reachability over the class family's
    self-calls), or the access crosses a class boundary through a typed
    receiver (annotated params, constructor locals, class-valued return
    annotations). Locked helpers — methods only ever called under the guard
    — are credited with it; ``__init__`` writes and synchronization-object
    attributes are exempt. Suppress a deliberate lock-free read with
    ``# graftlint: disable=GL012(reason)`` on the access line — e.g. a
    monotonic flag read where one-round staleness is harmless.
    """
    facts = _class_facts(program)
    accesses: List[_Access] = []
    helper_sites: Dict[Tuple[str, str, str], List[frozenset]] = {}

    for info in program.modules():
        if not _checked_path(info.relpath):
            continue
        for name, fn in info.index.module_funcs.items():
            _walk_function(info, None, None, fn, program, facts, accesses,
                           helper_sites, name)
        for (cls_name, mname), fn in info.index.methods.items():
            _walk_function(info, cls_name, (info.relpath, cls_name), fn,
                           program, facts, accesses, helper_sites,
                           f"{cls_name}.{mname}")

    # Locked-helper credit: a method whose every intra-family call site
    # holds guard g is itself under g. Call sites recorded per defining
    # class; a subclass family's call into a base method (or vice versa)
    # credits the method wherever it is defined.
    family_of: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
    for info in program.modules():
        for cls_name in info.classes:
            key = (info.relpath, cls_name)
            family_of[key] = _family(program, info, cls_name, facts)

    def resolve_method(family, mname) -> Optional[Tuple[str, str, str]]:
        for rel, cname in family:
            if mname in facts[(rel, cname)].methods:
                return (rel, cname, mname)
        return None

    # A call site in a base class dispatches to subclass overrides at
    # runtime (`_BatcherBase.close` calling `self._inflight_locked()` runs
    # `Batcher._inflight_locked`) — credit the resolved method in every
    # class whose family contains the call site's class.
    descendants: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
    for key, family in family_of.items():
        for fam_key in family:
            descendants.setdefault(fam_key, set()).add(key)

    helper_guards: Dict[Tuple[str, str, str], frozenset] = {}
    site_lists: Dict[Tuple[str, str, str], List[frozenset]] = {}
    for (rel, cname, mname), held_list in helper_sites.items():
        for dkey in descendants.get((rel, cname), {(rel, cname)}):
            target = resolve_method(family_of.get(dkey, []), mname)
            if target is not None:
                site_lists.setdefault(target, []).extend(held_list)
    for target, held_list in site_lists.items():
        common = frozenset.intersection(*held_list) if held_list \
            else frozenset()
        if common:
            helper_guards[target] = common

    # Thread-reachable methods: BFS from each family's Thread entries over
    # intra-family self-calls.
    caller_edges: Dict[Tuple[str, str, str], Set[str]] = {}
    for key, f in facts.items():
        rel, cname = key
        for mname, fn in f.methods.items():
            callees = set()
            for stmt in fn.body:
                for call in callgraph.calls_executed(stmt):
                    if isinstance(call.func, ast.Attribute) \
                            and isinstance(call.func.value, ast.Name) \
                            and call.func.value.id == "self":
                        callees.add(call.func.attr)
            caller_edges[(rel, cname, mname)] = callees

    threaded: Set[Tuple[str, str, str]] = set()
    for key, family in family_of.items():
        entries = set()
        for fam_key in family:
            entries |= facts[fam_key].entries
        if not entries:
            continue
        queue = [m for m in entries]
        seen_m: Set[str] = set()
        while queue:
            mname = queue.pop()
            if mname in seen_m:
                continue
            seen_m.add(mname)
            target = resolve_method(family, mname)
            if target is None:
                continue
            threaded.add(target)
            queue.extend(caller_edges.get(target, ()))

    # Group accesses by (class, attr); infer guards; emit findings.
    by_attr: Dict[Tuple[Tuple[str, str], str], List[_Access]] = {}
    for acc in accesses:
        if acc.in_init:
            continue
        eff = acc.guards
        if acc.method_key is not None and not acc.cross_class:
            eff = eff | helper_guards.get(acc.method_key, frozenset())
        acc.guards = eff
        by_attr.setdefault((acc.cls_key, acc.attr), []).append(acc)

    findings: List[Finding] = []
    for (cls_key, attr), accs in sorted(
            by_attr.items(), key=lambda kv: (kv[0][0][0], kv[0][0][1],
                                             kv[0][1])):
        if not _checked_path(cls_key[0]):
            continue
        guarded_writes = [a for a in accs if a.is_write and a.guards]
        if not guarded_writes:
            continue
        guards_used = set()
        for a in guarded_writes:
            guards_used |= a.guards
        lock_attrs = facts.get(cls_key, _ClassFacts()).lock_attrs
        preferred = guards_used & lock_attrs
        candidates = preferred or guards_used
        if len(candidates) != 1:
            continue  # ambiguous discipline: skip, don't guess
        guard = next(iter(candidates))
        if not all(guard in a.guards for a in guarded_writes):
            continue
        bare = [a for a in accs if guard not in a.guards]
        if not bare:
            continue

        def hot(a):
            return a.cross_class or (a.method_key in threaded)

        if not any(hot(a) for a in bare) \
                and not any(hot(a) for a in guarded_writes):
            continue
        bare.sort(key=lambda a: (a.relpath, a.line, a.col))
        first = bare[0]
        writer = guarded_writes[0]
        writer_where = writer.scope or writer.relpath
        kinds = ("written" if all(a.is_write for a in bare) else
                 "read" if not any(a.is_write for a in bare) else
                 "read/written")
        others = len(bare) - 1
        findings.append(Finding(
            "GL012", first.relpath, first.line, first.col,
            f"attribute `{cls_key[1]}.{attr}` is written under "
            f"`{guard}` (in {writer_where}) but {kinds} bare here"
            + (f" (+{others} more bare site{'s' if others > 1 else ''})"
               if others else "")
            + "; a thread holding the lock and this access race — take "
              f"`{guard}` here or suppress with the reason the lock-free "
              "access is safe",
            scope=first.scope))
    return findings
