"""Pipeline parallelism: GPipe + 1F1B loop correctness, gradients, memory,
strategy, e2e training."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu import AutoDist, ResourceSpec
from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.models import pipeline_lm
from autodist_tpu.parallel.pipeline import pipelined, pipelined_value_and_grad
from autodist_tpu.parallel.plan import ShardingPlan
from autodist_tpu.strategy import Pipeline, StrategyCompiler
from shardmap_compat import requires_shard_map

TINY = pipeline_lm.PipelineLMConfig(
    vocab_size=64, d_model=16, n_heads=2, n_layers=4, d_ff=32, max_len=32,
    n_stages=4, num_microbatches=4, dtype=jnp.float32)


def _spec_for(n_devices=8, mesh=None):
    return ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "tpus": n_devices, "chief": True}],
        **({"mesh": mesh} if mesh else {}),
    })


def _pipe_mesh(n_stages=4):
    from autodist_tpu.parallel.mesh import build_mesh
    return build_mesh(axes={"pipe": n_stages, "data": -1})


@requires_shard_map
def test_gpipe_loop_matches_sequential_forward_and_grad():
    rng = np.random.RandomState(0)
    d, s, m = 8, 4, 6
    w = (rng.randn(s, d, d) * 0.3).astype(np.float32)
    x_mb = rng.randn(m, 4, d).astype(np.float32)
    mesh = _pipe_mesh(s)

    def stage_fn(p, x):
        return jnp.tanh(x @ p[0])

    f = pipelined(stage_fn, s, mesh=mesh)

    def loss_pipe(w, x):
        return (f(w, x) ** 2).sum()

    def loss_seq(w, x):
        h = x
        for i in range(s):
            h = jnp.tanh(h @ w[i])
        return (h ** 2).sum()

    with mesh:
        lp, gp = jax.jit(jax.value_and_grad(loss_pipe))(w, x_mb)
        ls, gs = jax.jit(jax.value_and_grad(loss_seq))(w, x_mb)
    np.testing.assert_allclose(float(lp), float(ls), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gs), rtol=1e-4, atol=1e-5)


def _onef_oneb_setup(s=4, m=6, d=8, seed=0):
    rng = np.random.RandomState(seed)
    w = (rng.randn(s, d, d) * 0.3).astype(np.float32)
    head = (rng.randn(d, 3) * 0.3).astype(np.float32)
    x_mb = rng.randn(m, 4, d).astype(np.float32)
    t_mb = rng.randn(m, 4, 3).astype(np.float32)

    def stage_fn(p, x):
        return jnp.tanh(x @ p[0])

    def tail_fn(tp, y, tgt):
        return jnp.mean((y @ tp - tgt) ** 2)

    return w, head, x_mb, t_mb, stage_fn, tail_fn


@requires_shard_map
def test_onef_oneb_matches_gpipe_loss_and_grads():
    """1F1B returns the SAME mean loss and gradients (stage, tail, input) as
    GPipe + autodiff on the same stages — only the schedule differs."""
    s, m = 4, 6
    w, head, x_mb, t_mb, stage_fn, tail_fn = _onef_oneb_setup(s, m)
    mesh = _pipe_mesh(s)

    f_1f1b = pipelined_value_and_grad(stage_fn, tail_fn, s, mesh=mesh)
    gpipe = pipelined(stage_fn, s, mesh=mesh)

    def gpipe_loss(w, head, x, tgt):
        y = gpipe(w, x)
        losses = jax.vmap(lambda yk, tk: tail_fn(head, yk, tk))(y, tgt)
        return losses.mean()

    with mesh:
        loss_b, gs_b, gt_b, gx_b = jax.jit(f_1f1b)(w, head, x_mb, t_mb)
        loss_a, (gs_a, gt_a, gx_a) = jax.jit(jax.value_and_grad(
            gpipe_loss, argnums=(0, 1, 2)))(w, head, x_mb, t_mb)
    np.testing.assert_allclose(float(loss_b), float(loss_a), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gs_b), np.asarray(gs_a),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gt_b), np.asarray(gt_a),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gx_b), np.asarray(gx_a),
                               rtol=1e-4, atol=1e-6)


@requires_shard_map
def test_onef_oneb_single_stage_degenerate():
    w, head, x_mb, t_mb, stage_fn, tail_fn = _onef_oneb_setup(s=1, m=4)
    from autodist_tpu.parallel.mesh import build_mesh
    mesh = build_mesh(axes={"pipe": 1, "data": -1})
    f = pipelined_value_and_grad(stage_fn, tail_fn, 1, mesh=mesh)

    def ref(w, head, x, tgt):
        y = jax.vmap(lambda xk: stage_fn(w, xk))(x)
        return jax.vmap(lambda yk, tk: tail_fn(head, yk, tk))(y, tgt).mean()

    with mesh:
        loss, gs, gt, gx = jax.jit(f)(w, head, x_mb, t_mb)
        l_ref, (gs_r, gt_r, gx_r) = jax.jit(jax.value_and_grad(
            ref, argnums=(0, 1, 2)))(w, head, x_mb, t_mb)
    np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_r), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gt), np.asarray(gt_r), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_r), rtol=1e-4)


@requires_shard_map
def test_onef_oneb_memory_flat_in_microbatches():
    """The point of 1F1B: compiled temp memory stays ~flat as num_microbatches
    grows (live set O(n_stages)), while GPipe+autodiff's grows linearly
    (residuals for every tick)."""
    s, d = 4, 64
    mesh = _pipe_mesh(s)

    def measure(m):
        w, head, x_mb, t_mb, stage_fn, tail_fn = _onef_oneb_setup(s, m, d)
        f_1f1b = pipelined_value_and_grad(stage_fn, tail_fn, s, mesh=mesh)
        gpipe = pipelined(stage_fn, s, mesh=mesh)

        def gpipe_loss(w, head, x, tgt):
            y = gpipe(w, x)
            return jax.vmap(lambda yk, tk: tail_fn(head, yk, tk))(y, tgt).mean()

        with mesh:
            mem_b = jax.jit(f_1f1b).lower(w, head, x_mb, t_mb).compile() \
                .memory_analysis().temp_size_in_bytes
            mem_a = jax.jit(jax.value_and_grad(gpipe_loss, argnums=(0, 1))) \
                .lower(w, head, x_mb, t_mb).compile() \
                .memory_analysis().temp_size_in_bytes
        return mem_a, mem_b

    gpipe_4, onef_4 = measure(4)
    gpipe_32, onef_32 = measure(32)
    # GPipe's residual storage scales with the microbatch count (measured on
    # this config: 49.7 KB -> 193.2 KB over 4 -> 32 microbatches)...
    assert gpipe_32 > 3 * gpipe_4, (gpipe_4, gpipe_32)
    # ...1F1B's live set does not (measured ~30.4 KB -> ~33.8 KB: the ring is
    # sized by n_stages; slack covers the [M, ...] input-grad buffer).
    assert onef_32 < 1.5 * onef_4, (onef_4, onef_32)
    assert onef_32 < gpipe_32 / 4, (onef_32, gpipe_32)


@requires_shard_map
def test_pipeline_lm_onef_oneb_full_model_grads():
    """The full-model 1F1B step returns the SAME loss and gradients — for
    embedding, positions, every block, final norm, and head — as
    jax.value_and_grad over the GPipe loss."""
    model, params = pipeline_lm.init_params(TINY)
    batch = pipeline_lm.synthetic_batch(TINY, batch_size=8, seq_len=16)
    mesh = _pipe_mesh(TINY.n_stages)

    f_1f1b = pipeline_lm.make_onef_oneb_value_and_grad(model)
    loss_fn = pipeline_lm.make_loss_fn(model)
    with mesh:
        loss_b, grads_b = jax.jit(f_1f1b)(params, batch)
        loss_a, grads_a = jax.jit(jax.value_and_grad(loss_fn))(params, batch)
    np.testing.assert_allclose(float(loss_b), float(loss_a), rtol=1e-5)
    flat_a = jax.tree_util.tree_leaves_with_path(grads_a)
    flat_b = dict(jax.tree_util.tree_leaves_with_path(grads_b))
    assert len(flat_a) == len(flat_b)
    for path, g in flat_a:
        np.testing.assert_allclose(
            np.asarray(flat_b[path]), np.asarray(g), rtol=2e-4, atol=1e-6,
            err_msg=jax.tree_util.keystr(path))
    # And a few SGD steps actually train.
    import optax
    opt = optax.sgd(0.1)
    state = opt.init(params)
    losses = []
    with mesh:
        for _ in range(5):
            loss, grads = jax.jit(f_1f1b)(params, batch)
            updates, state = opt.update(grads, state, params)
            params = optax.apply_updates(params, updates)
            losses.append(float(loss))
    assert losses[-1] < losses[0]


@requires_shard_map
def test_pipeline_lm_matches_sequential_apply():
    model, params = pipeline_lm.init_params(TINY)
    batch = pipeline_lm.synthetic_batch(TINY, batch_size=8, seq_len=16)
    tokens = jnp.asarray(batch["tokens"][:, :-1])
    mesh = _pipe_mesh(TINY.n_stages)
    with mesh:
        piped = jax.jit(model.apply)(params, tokens)
    seq = pipeline_lm.sequential_apply(model, params, tokens)
    np.testing.assert_allclose(np.asarray(piped), np.asarray(seq),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_strategy_shards_block_stacks():
    model, params = pipeline_lm.init_params(TINY)
    model_spec = ModelSpec.from_params(params)
    rs = _spec_for(8)
    strategy = StrategyCompiler(model_spec, rs).compile(
        Pipeline(n_stages=4).build(model_spec, rs))
    assert strategy.mesh_axes()["pipe"] == 4
    assert strategy.mesh_axes()["data"] == 2

    plan = ShardingPlan.from_strategy(strategy, model_spec)
    block_plans = [p for n, p in plan.params.items() if "blocks" in n]
    assert len(block_plans) == 8
    for p in block_plans:
        assert p.partition_mesh_axis == "pipe"
        assert p.pspec[0] == "pipe"
    assert plan.params["embed"].pspec == jax.sharding.PartitionSpec()


@requires_shard_map
def test_pipeline_lm_trains_end_to_end():
    model, params = pipeline_lm.init_params(TINY)
    loss_fn = pipeline_lm.make_loss_fn(model)
    batch = pipeline_lm.synthetic_batch(TINY, batch_size=8, seq_len=16)
    ad = AutoDist(_spec_for(8), strategy_builder=Pipeline(n_stages=4))
    step = ad.function(loss_fn, params, optax.adam(1e-2), example_batch=batch)
    losses = [float(step(batch)) for _ in range(4)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]

    # Block stacks live sharded over the pipe axis.
    state = step.get_state()
    spec = state.params["blocks"]["wqkv"].sharding.spec
    assert spec and spec[0] == "pipe"


@requires_shard_map
def test_pipeline_e2e_loss_matches_unsharded():
    model, params = pipeline_lm.init_params(TINY)
    loss_fn = pipeline_lm.make_loss_fn(model)
    batch = pipeline_lm.synthetic_batch(TINY, batch_size=8, seq_len=16)

    def seq_loss(params, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits = pipeline_lm.sequential_apply(model, params, inputs)
        logprobs = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(
            logprobs, targets[..., None], axis=-1)[..., 0].mean()

    expected = float(seq_loss(params, {k: jnp.asarray(v) for k, v in batch.items()}))
    ad = AutoDist(_spec_for(8), strategy_builder=Pipeline(n_stages=4))
    step = ad.function(loss_fn, params, optax.sgd(0.0), example_batch=batch)
    np.testing.assert_allclose(float(step(batch)), expected, rtol=2e-5)


def test_pipelined_rejects_mesh_stage_mismatch():
    import pytest
    mesh = _pipe_mesh(2)
    f = pipelined(lambda p, x: x, n_stages=4, mesh=mesh)
    with mesh, pytest.raises(ValueError, match="pipe"):
        jax.jit(lambda w, x: f(w, x))(jnp.zeros((4, 2, 2)), jnp.zeros((2, 2, 2)))


@requires_shard_map
def test_interleaved_matches_plain_1f1b():
    """Interleaved 1F1B (v chunks per device) returns the SAME loss and
    gradients as plain 1F1B run with one device per virtual stage — only the
    device mapping and schedule differ."""
    from autodist_tpu.parallel.mesh import build_mesh
    from autodist_tpu.parallel.pipeline import (interleave_chunk_layout,
                                                interleaved_value_and_grad)
    s, v, m = 2, 2, 6
    V = s * v
    w, head, x_mb, t_mb, stage_fn, tail_fn = _onef_oneb_setup(V, m, seed=2)

    plain_mesh = build_mesh(axes={"pipe": V, "data": -1})
    f_plain = pipelined_value_and_grad(stage_fn, tail_fn, V, mesh=plain_mesh)
    with plain_mesh:
        loss_p, gs_p, gt_p, gx_p = jax.jit(f_plain)(w, head, x_mb, t_mb)

    il_mesh = build_mesh(axes={"pipe": s, "data": -1})
    f_il = interleaved_value_and_grad(stage_fn, tail_fn, s, v, mesh=il_mesh)
    w_dev = interleave_chunk_layout(w, s, v)          # virtual -> device-major
    with il_mesh:
        loss_i, gs_i, gt_i, gx_i = jax.jit(f_il)(w_dev, head, x_mb, t_mb)
    gs_i = interleave_chunk_layout(gs_i, s, v, inverse=True)

    np.testing.assert_allclose(float(loss_i), float(loss_p), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gs_i), np.asarray(gs_p),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gt_i), np.asarray(gt_p),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gx_i), np.asarray(gx_p),
                               rtol=1e-4, atol=1e-6)


@requires_shard_map
def test_interleaved_deeper_and_chunks_one_degenerates():
    """v=4 chunks on 2 devices (8 virtual stages); and n_chunks=1 must equal
    plain 1F1B exactly (same schedule by construction)."""
    from autodist_tpu.parallel.mesh import build_mesh
    from autodist_tpu.parallel.pipeline import (interleave_chunk_layout,
                                                interleaved_value_and_grad)
    s, v, m = 2, 4, 4
    V = s * v
    w, head, x_mb, t_mb, stage_fn, tail_fn = _onef_oneb_setup(V, m, seed=5)
    mesh = build_mesh(axes={"pipe": s, "data": -1})
    f_il = interleaved_value_and_grad(stage_fn, tail_fn, s, v, mesh=mesh)
    with mesh:
        loss_i, gs_i, _, gx_i = jax.jit(f_il)(
            interleave_chunk_layout(w, s, v), head, x_mb, t_mb)
    gs_i = interleave_chunk_layout(gs_i, s, v, inverse=True)

    # Sequential oracle over all V stages.
    def ref(w, head, x, tgt):
        def one(xk, tk):
            h = xk
            for i in range(V):
                h = stage_fn(w[i:i + 1], h)   # stage_fn takes a [1, ...] block
            return tail_fn(head, h, tk)
        return jax.vmap(one)(x, tgt).mean()
    l_ref, (gs_r, gx_r) = jax.jit(jax.value_and_grad(
        ref, argnums=(0, 2)))(w, head, x_mb, t_mb)
    np.testing.assert_allclose(float(loss_i), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gs_i), np.asarray(gs_r),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gx_i), np.asarray(gx_r),
                               rtol=1e-4, atol=1e-6)

    # n_chunks=1: identical schedule to plain 1F1B.
    s4 = 4
    w4, head4, x4, t4, stage_fn, tail_fn = _onef_oneb_setup(s4, 4, seed=7)
    mesh4 = build_mesh(axes={"pipe": s4, "data": -1})
    f_plain = pipelined_value_and_grad(stage_fn, tail_fn, s4, mesh=mesh4)
    f_one = interleaved_value_and_grad(stage_fn, tail_fn, s4, 1, mesh=mesh4)
    with mesh4:
        loss_p, gs_p, gt_p, gx_p = jax.jit(f_plain)(w4, head4, x4, t4)
        loss_o, gs_o, gt_o, gx_o = jax.jit(f_one)(w4, head4, x4, t4)
    np.testing.assert_allclose(float(loss_o), float(loss_p), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(gs_o), np.asarray(gs_p), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gt_o), np.asarray(gt_p), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gx_o), np.asarray(gx_p), rtol=1e-5)


@requires_shard_map
def test_interleaved_wide_mesh_and_validation():
    """S=4 with v=2 (wide mesh x chunks); non-divisible microbatch counts are
    refused (a ragged final group would silently skip/double-process pairs);
    scalar stage-params leaves get the clear leading-dim error."""
    import pytest

    from autodist_tpu.parallel.mesh import build_mesh
    from autodist_tpu.parallel.pipeline import (interleave_chunk_layout,
                                                interleaved_value_and_grad)
    s, v, m = 4, 2, 8
    V = s * v
    w, head, x_mb, t_mb, stage_fn, tail_fn = _onef_oneb_setup(V, m, seed=9)
    mesh = build_mesh(axes={"pipe": s, "data": -1})
    f_il = interleaved_value_and_grad(stage_fn, tail_fn, s, v, mesh=mesh)
    with mesh:
        loss_i, gs_i, _, gx_i = jax.jit(f_il)(
            interleave_chunk_layout(w, s, v), head, x_mb, t_mb)
    gs_i = interleave_chunk_layout(gs_i, s, v, inverse=True)

    def ref(w, head, x, tgt):
        def one(xk, tk):
            h = xk
            for i in range(V):
                h = stage_fn(w[i:i + 1], h)
            return tail_fn(head, h, tk)
        return jax.vmap(one)(x, tgt).mean()
    l_ref, (gs_r, gx_r) = jax.jit(jax.value_and_grad(
        ref, argnums=(0, 2)))(w, head, x_mb, t_mb)
    np.testing.assert_allclose(float(loss_i), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gs_i), np.asarray(gs_r),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gx_i), np.asarray(gx_r),
                               rtol=1e-4, atol=1e-6)

    with mesh, pytest.raises(ValueError, match="divisible by n_stages"):
        jax.jit(f_il)(interleave_chunk_layout(w, s, v), head,
                      x_mb[:5], t_mb[:5])
    with mesh, pytest.raises(ValueError, match="leading dim"):
        jax.jit(f_il)({"w": interleave_chunk_layout(w, s, v),
                       "gain": jnp.ones(())}, head, x_mb, t_mb)


@requires_shard_map
def test_blocks_execution_order_roundtrip():
    """Stored (device-major) <-> execution-order conversion round-trips, and
    sequential_apply(interleaved cfg) equals the n_chunks=1 model applied to
    the execution-order blocks — the checkpoint-migration contract."""
    cfg = pipeline_lm.PipelineLMConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=4, d_ff=32, max_len=32,
        n_stages=2, n_chunks=2, num_microbatches=2, dtype=jnp.float32)
    model, params = pipeline_lm.init_params(cfg)
    exe = pipeline_lm.blocks_to_execution_order(cfg, params["blocks"])
    back = pipeline_lm.blocks_from_execution_order(cfg, exe)
    for path, a in jax.tree_util.tree_leaves_with_path(params["blocks"]):
        b = dict(jax.tree_util.tree_leaves_with_path(back))[path]
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    import dataclasses
    plain_model = pipeline_lm.PipelineLM(dataclasses.replace(cfg, n_chunks=1))
    plain_params = dict(params, blocks=exe)
    toks = jnp.asarray(pipeline_lm.synthetic_batch(cfg, 4, 8)["tokens"][:, :-1])
    a = pipeline_lm.sequential_apply(model, params, toks)
    b = pipeline_lm.sequential_apply(plain_model, plain_params, toks)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    # And the GPipe pipeline forward (model.apply) honors the stored layout:
    # it must equal sequential_apply on the SAME interleaved config.
    mesh = _pipe_mesh(cfg.n_stages)
    with mesh:
        c = jax.jit(lambda p, t: model.apply(p, t))(params, toks)
    np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                               rtol=1e-4, atol=1e-5)



def test_interleave_chunk_layout_roundtrip():
    from autodist_tpu.parallel.pipeline import interleave_chunk_layout
    x = jnp.arange(6 * 3).reshape(6, 3)           # V=6 rows
    fwd = interleave_chunk_layout(x, n_stages=3, n_chunks=2)
    # Device-major: row r*v + j = virtual j*S + r.
    expect = [0 * 3 + 0, 1 * 3 + 0, 0 * 3 + 1, 1 * 3 + 1, 0 * 3 + 2, 1 * 3 + 2]
    np.testing.assert_array_equal(np.asarray(fwd[:, 0]) // 3, expect)
    back = interleave_chunk_layout(fwd, n_stages=3, n_chunks=2, inverse=True)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))


@requires_shard_map
def test_pipeline_lm_interleaved_full_model_grads():
    """The full-model INTERLEAVED step (n_chunks=2: 4 layers as 4 virtual
    stages on 2 devices) returns the same loss and gradients as autodiff
    over the sequential forward — same surface, thinner-tick schedule."""
    cfg = pipeline_lm.PipelineLMConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=4, d_ff=32, max_len=32,
        n_stages=2, n_chunks=2, num_microbatches=4, dtype=jnp.float32)
    model, params = pipeline_lm.init_params(cfg)
    batch = pipeline_lm.synthetic_batch(cfg, batch_size=8, seq_len=16)
    mesh = _pipe_mesh(cfg.n_stages)

    f_il = pipeline_lm.make_onef_oneb_value_and_grad(model)

    def seq_loss(params, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits = pipeline_lm.sequential_apply(model, params, inputs)
        logprobs = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(
            logprobs, targets[..., None], axis=-1)[..., 0].mean()

    with mesh:
        loss_i, grads_i = jax.jit(f_il)(params, batch)
    loss_s, grads_s = jax.jit(jax.value_and_grad(seq_loss))(params, batch)
    np.testing.assert_allclose(float(loss_i), float(loss_s), rtol=1e-5)
    flat_s = jax.tree_util.tree_leaves_with_path(grads_s)
    flat_i = dict(jax.tree_util.tree_leaves_with_path(grads_i))
    for path, g in flat_s:
        np.testing.assert_allclose(
            np.asarray(flat_i[path]), np.asarray(g), rtol=2e-4, atol=1e-6,
            err_msg=jax.tree_util.keystr(path))

    import pytest
    with pytest.raises(ValueError, match="num_microbatches"):
        pipeline_lm.PipelineLMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=4, d_ff=32,
            n_stages=2, n_chunks=2, num_microbatches=3, dtype=jnp.float32)
