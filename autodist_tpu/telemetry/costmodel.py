"""Calibrated step-time cost model over static program costs.

Automap-style strategy search (ROADMAP item 3, arXiv 2112.02958) needs to
rank candidate distribution plans WITHOUT running each one to steady state.
The two ingredients are both shipped by the attribution plane
(:mod:`autodist_tpu.telemetry.profiling`):

- **static costs** — per-program flops / bytes-accessed from XLA's cost
  analysis, cached per shape signature at compile time;
- **a calibration record** — the machine's ACHIEVED rates (flops/s, bytes/s,
  host seconds per dispatch, wire bytes/s), fitted from a short real run's
  profile rather than spec sheets, so systematic model error (padding,
  rematerialization, dispatch overhead) cancels between candidates.

:func:`predict` is the interface the search calls: roofline per program
(``max(flops/flops_per_s, bytes/bytes_per_s)``), plus per-dispatch host
overhead (what ``unroll=K`` amortizes) and a bytes/bandwidth wire term for
plans that cross the PS transport. Shipped here as observability —
``adprof predict`` surfaces it and tests pin prediction-vs-measured
agreement on the CPU micro-model — with the search itself left for the
strategy PR.
"""

import dataclasses
from typing import Any, Dict, Iterable, Optional, Union

__all__ = ["Calibration", "calibrate", "predict", "predict_from_profile"]


@dataclasses.dataclass
class Calibration:
    """Achieved machine rates fitted from one profile (see :func:`calibrate`).

    ``flops_per_s``/``bytes_per_s`` are the rates the device actually
    sustained during the profiled run's compute phase — NOT hardware peaks;
    ``host_s_per_dispatch`` is the host-side cost of one program launch
    (feed sharding + enqueue); ``wire_bytes_per_s`` is the measured PS-wire
    bandwidth (None for collective-only runs); ``quantize_bytes_per_s`` is
    the host's achieved gradient quantize rate (dense bytes in per second
    of ``wire.quantize_s``), fitted like ``host_s_per_dispatch`` — the cost
    side of wire compression, so :func:`predict` can refuse a wire_dtype
    whose quantize seconds exceed the wire seconds it saves (None until a
    compressed run has been profiled)."""

    flops_per_s: Optional[float] = None
    bytes_per_s: Optional[float] = None
    host_s_per_dispatch: float = 0.0
    wire_bytes_per_s: Optional[float] = None
    quantize_bytes_per_s: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Calibration":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})


def _wire_bytes_per_s(profile: Dict[str, Any]) -> Optional[float]:
    """Measured PS-wire bandwidth: the profile's ``wire`` block (the
    ``ps.wire.*`` registry counters ``profile_document`` attaches when the
    run mirrored any transport traffic) over the comm phase's wall seconds;
    None for collective-only runs, which cross no wire.

    SYMMETRIC-RATE ASSUMPTION, deliberate: ``bytes_sent + bytes_received``
    are lumped over ONE comm window, i.e. the fitted rate models a
    full-duplex link whose send and receive directions achieve the same
    bandwidth (true of the loopback and NIC fabrics this transport runs
    on; the overlapped client moves pull traffic off the comm window's
    critical path anyway). Callers that price an ASYMMETRICALLY compressed
    plan — a quantized push against an uncompressed pull — must therefore
    scale the per-DIRECTION byte counts before dividing by this rate
    (``strategy/autotune._wire_terms`` prices push and pull separately for
    exactly this reason); scaling the lumped total by the push ratio would
    skew the prediction by the pull share."""
    wire = profile.get("wire") or {}
    total_bytes = (wire.get("bytes_sent", 0) or 0) \
        + (wire.get("bytes_received", 0) or 0)
    summary = profile.get("summary") or {}
    shares = summary.get("shares") or {}
    comm_s = (shares.get("comm") or 0.0) * (summary.get("wall_s") or 0.0)
    if total_bytes and comm_s > 0:
        return total_bytes / comm_s
    return None


def _quantize_bytes_per_s(profile: Dict[str, Any]) -> Optional[float]:
    """Achieved host quantize rate: dense gradient bytes the compressor
    consumed (``ps.wire.bytes_quantized``) over its cumulative
    ``wire.quantize_s``; None when the profiled run never compressed."""
    wire = profile.get("wire") or {}
    qbytes = wire.get("bytes_quantized", 0) or 0
    qs = wire.get("quantize_s", 0.0) or 0.0
    if qbytes and qs > 0:
        return qbytes / qs
    return None


def calibrate(profile: Dict[str, Any]) -> Calibration:
    """Fit a :class:`Calibration` from one profile document (the dict
    :func:`telemetry.write_profile` wrote / ``profile_document`` returned).

    The compute phase's wall seconds anchor the achieved rates: the profiled
    run dispatched ``flops_per_step * steps`` flops and its loop sat parked
    behind the device for ``compute_share * wall_s`` seconds, so the
    sustained rate is their quotient (same for bytes). Degenerate profiles
    (no compute residual — a fully host-bound run) fall back to whole-wall
    rates, which keeps predictions conservative rather than infinite."""
    summary = profile.get("summary") or {}
    shares = summary.get("shares") or {}
    wall_s = summary.get("wall_s") or 0.0
    steps = summary.get("steps") or 0
    compute_s = (shares.get("compute") or 0.0) * wall_s
    if compute_s <= 0:
        compute_s = wall_s
    flops_step = summary.get("flops_per_step")
    bytes_step = summary.get("bytes_per_step")
    return Calibration(
        flops_per_s=(flops_step * steps / compute_s)
        if flops_step and steps and compute_s > 0 else None,
        bytes_per_s=(bytes_step * steps / compute_s)
        if bytes_step and steps and compute_s > 0 else None,
        host_s_per_dispatch=summary.get("host_s_per_dispatch") or 0.0,
        wire_bytes_per_s=_wire_bytes_per_s(profile),
        quantize_bytes_per_s=_quantize_bytes_per_s(profile),
    )


def predict(plan_costs: Union[Dict[str, Any], Iterable[Dict[str, Any]]],
            calib: Calibration,
            comm_bytes_per_step: float = 0.0,
            loader_s_per_step: float = 0.0,
            prefetch_depth: int = 0,
            quantize_bytes_per_step: float = 0.0,
            resident_bytes: float = 0.0) -> Dict[str, Any]:
    """Predict per-step time for a candidate plan's program set.

    ``plan_costs``: one program-cost dict or an iterable of them — the
    ``{"flops", "bytes_accessed", "steps", "dispatches"}`` records a
    profile's ``programs`` table holds, flops/bytes PER DISPATCH (a
    ``steps=K`` fused block counts as one dispatch advancing K steps;
    ``dispatches`` defaults to 1 and weights the program's contribution).
    Per dispatch the device time is the roofline ``max(flops/flops_per_s,
    bytes/bytes_per_s)`` — whichever resource binds — plus
    ``calib.host_s_per_dispatch`` for the launch; ``comm_bytes_per_step``
    over the calibrated wire bandwidth adds the PS transfer term.

    ``comm_bytes_per_step`` must already reflect any wire compression (the
    caller scales the push direction by its compression ratio — see the
    ``_wire_bytes_per_s`` direction note); ``quantize_bytes_per_step`` is
    the DENSE bytes the compressor must quantize per step, priced over
    ``calib.quantize_bytes_per_s`` as host seconds — the cost side of the
    trade, so compression only predicts faster when the wire seconds saved
    exceed the quantize seconds added.

    ``loader_s_per_step`` prices the input pipeline: with
    ``prefetch_depth == 0`` (the synchronous feed) the loader's full
    per-step seconds land in the step; with ``prefetch_depth >= 1`` the
    async producer overlaps loading with the rest of the step, so only
    the RESIDUAL ``max(0, loader_s - hidden_s)`` remains, where
    ``hidden_s`` is everything the pipeline can hide behind (device +
    host + comm per step) — the steady-state bound: a pipeline of any
    depth >= 1 sustains ``max(rest_s, loader_s)`` per step.

    ``resident_bytes`` is the plan's per-device resident state (params +
    optimizer state + whatever else stays allocated across steps); with it
    the prediction also carries ``peak_hbm_bytes`` — resident plus the
    WORST program's transient working set (``max`` over the records'
    ``temp_bytes``, falling back to ``argument_bytes + output_bytes`` when
    the backend reported no temp ledger) — the fit estimate the autotuner's
    OOM pre-flight prices against the device budget before spending a
    compile probe.

    Returns ``{"step_s", "steps_per_s", "bound", "peak_hbm_bytes",
    "breakdown": {compute_s, memory_s, host_s, comm_s, quantize_s,
    data_wait_s per step}}`` — ``bound`` names the binding resource, the
    MLPerf-style "what do I fix first" answer (``peak_hbm_bytes`` is None
    when neither resident bytes nor any memory ledger was given)."""
    if isinstance(plan_costs, dict):
        plan_costs = [plan_costs]
    compute_s = memory_s = device_s = 0.0
    host_s = 0.0
    total_steps = 0
    peak_temp: Optional[float] = None
    for rec in plan_costs:
        n = max(1, int(rec.get("dispatches") or 1))
        steps = int(rec.get("steps") or 1)
        total_steps += n * steps
        temp = rec.get("temp_bytes")
        if temp is None and (rec.get("argument_bytes") is not None
                             or rec.get("output_bytes") is not None):
            temp = (rec.get("argument_bytes") or 0) \
                + (rec.get("output_bytes") or 0)
        if temp is not None:
            peak_temp = max(peak_temp or 0.0, float(temp))
        c = (rec.get("flops") or 0.0) / calib.flops_per_s \
            if calib.flops_per_s else 0.0
        m = (rec.get("bytes_accessed") or 0.0) / calib.bytes_per_s \
            if calib.bytes_per_s else 0.0
        compute_s += n * c
        memory_s += n * m
        device_s += n * max(c, m)
        host_s += n * calib.host_s_per_dispatch
    total_steps = max(1, total_steps)
    comm_s = 0.0
    if comm_bytes_per_step and calib.wire_bytes_per_s:
        comm_s = comm_bytes_per_step / calib.wire_bytes_per_s
    quantize_s = 0.0
    if quantize_bytes_per_step and calib.quantize_bytes_per_s:
        quantize_s = quantize_bytes_per_step / calib.quantize_bytes_per_s
    hidden_s = device_s / total_steps + host_s / total_steps + comm_s \
        + quantize_s
    data_s = 0.0
    if loader_s_per_step > 0:
        data_s = max(0.0, loader_s_per_step - hidden_s) \
            if prefetch_depth >= 1 else float(loader_s_per_step)
    step_s = hidden_s + data_s
    breakdown = {"compute_s": compute_s / total_steps,
                 "memory_s": memory_s / total_steps,
                 "host_s": host_s / total_steps,
                 "comm_s": comm_s,
                 "quantize_s": quantize_s,
                 "data_wait_s": data_s}
    bound = max(("compute", breakdown["compute_s"]),
                ("memory", breakdown["memory_s"]),
                ("host", breakdown["host_s"]),
                ("comm", breakdown["comm_s"]),
                ("quantize", breakdown["quantize_s"]),
                ("data_wait", breakdown["data_wait_s"]),
                key=lambda kv: kv[1])[0] if step_s > 0 else "unknown"
    peak_hbm = None
    if resident_bytes or peak_temp is not None:
        peak_hbm = int(float(resident_bytes or 0.0) + (peak_temp or 0.0))
    return {"step_s": step_s,
            "steps_per_s": (1.0 / step_s) if step_s > 0 else None,
            "bound": bound,
            "peak_hbm_bytes": peak_hbm,
            "breakdown": breakdown}


def predict_from_profile(profile: Dict[str, Any],
                         calib: Optional[Calibration] = None) -> Dict[str, Any]:
    """Self-consistency probe: calibrate from ``profile`` (unless given) and
    predict ITS OWN program mix, weighting each program by its dispatch
    count. Returns the prediction plus ``measured_step_s`` and ``ratio``
    (predicted/measured) — the agreement the tests pin within a generous
    band, and the sanity check to run before trusting cross-plan ranking."""
    calib = calib if calib is not None else calibrate(profile)
    programs = profile.get("programs") or {}
    summary = profile.get("summary") or {}
    # One "plan unit" = every program, dispatch-weighted (predict() honors
    # the records' own dispatch counts) so rare programs — a one-off eval
    # signature — don't outvote the hot step.
    out = predict(list(programs.values()), calib)
    measured = summary.get("step_s")
    out["measured_step_s"] = measured
    out["ratio"] = (out["step_s"] / measured) \
        if measured and out["step_s"] else None
    out["calibration"] = calib.to_dict()
    return out
