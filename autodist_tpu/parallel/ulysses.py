"""Ulysses-style all-to-all sequence parallelism.

The second sequence-parallel scheme (alongside ring attention,
:mod:`autodist_tpu.parallel.ring_attention`), after DeepSpeed-Ulysses: instead of
rotating K/V shards around a ring, one ``all_to_all`` re-shards activations from
sequence-sharded to head-sharded — each device then holds the FULL sequence for
``H / seq_size`` heads, runs ordinary (flash) attention locally, and a second
``all_to_all`` restores sequence sharding. Communication is two all-to-alls of the
activations per attention call (vs ``seq_size - 1`` K/V rotations for ring); ring
wins when ``seq_size`` is small or K/V are much smaller than activations, Ulysses
wins at large ``seq_size`` since its volume is topology-constant.

Requires ``n_heads % seq_size == 0``. Runs inside the same sequence-parallel
shard_map as ring attention (``parallel/sequence.py``); local attention uses the
pallas flash kernel, so the [L, L] score matrix never materializes either.
"""

import jax
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu import const


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      causal: bool = True,
                      axis_name: str = const.MESH_AXIS_SEQ) -> jax.Array:
    """Attention over seq-sharded [B, L_local, H, D] via head re-sharding.

    Must run inside a ``shard_map`` binding ``axis_name``, with axis 1 the local
    shard of the global sequence in axis-index order (same contract as
    :func:`~autodist_tpu.parallel.ring_attention.ring_attention`).
    """
    seq_size = jax.lax.axis_size(axis_name)
    if seq_size == 1:
        from autodist_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal)
    n_heads = q.shape[2]
    if n_heads % seq_size:
        raise ValueError(
            f"Ulysses attention needs n_heads ({n_heads}) divisible by the seq "
            f"axis ({seq_size}); use ring attention otherwise")

    def to_heads(x):
        # [B, L/s, H, D] -> [B, L, H/s, D]: split heads across the axis, gather
        # the sequence (axis-index order == global sequence order).
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    from autodist_tpu.ops.flash_attention import flash_attention
    out = flash_attention(qh, kh, vh, causal=causal)     # full L, H/s heads
    # [B, L, H/s, D] -> [B, L/s, H, D]
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def make_ulysses_attention_fn(mesh: Mesh, *, causal: bool = True):
    """Wrap :func:`ulysses_attention` in a shard_map over (data, seq) — the
    standalone counterpart of ``make_ring_attention_fn``."""
    spec = P((const.MESH_AXIS_DATA, const.MESH_AXIS_REDUCE),
             const.MESH_AXIS_SEQ, None, None)

    def fn(q, k, v):
        return ulysses_attention(q, k, v, causal=causal)

    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)
