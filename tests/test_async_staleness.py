"""Async / bounded-staleness PS mode (parallel/staleness.py).

Mirrors the reference's staleness semantics test (``tests/integration/cases/
c9.py:92-126``: a fast worker can run exactly ``staleness`` steps ahead of the
slowest before blocking) plus value checks for the fully-async regime.
"""

import threading

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist
from autodist_tpu.parallel.staleness import (AsyncPSRunner, StalenessController,
                                             StalenessTimeout)
from autodist_tpu.runner import DistributedRunner
from autodist_tpu.strategy import PS

LR = 0.1
BATCH = 16


def _data(seed=123):
    rng = np.random.RandomState(seed)
    x = rng.randn(BATCH).astype(np.float32)
    y = (3.0 * x + 2.0 + 0.1 * rng.randn(BATCH)).astype(np.float32)
    return {"x": x, "y": y}


def _loss(p, batch):
    pred = batch["x"] * p["w"] + p["b"]
    return jnp.mean((batch["y"] - pred) ** 2)


def _params():
    return {"w": jnp.zeros(()), "b": jnp.zeros(())}


# ------------------------------------------------------------------ controller unit

def test_controller_allows_exactly_staleness_steps_ahead():
    c = StalenessController(num_workers=2, staleness=3)
    for _ in range(3):
        c.start_step(0, timeout=1)
        c.finish_step(0)
    # 3 ahead of worker 1 (at 0): the 4th start must block.
    with pytest.raises(StalenessTimeout):
        c.start_step(0, timeout=0.1)
    # Slow worker completes one step -> exactly one more step opens up.
    c.start_step(1, timeout=1)
    c.finish_step(1)
    c.start_step(0, timeout=1)
    c.finish_step(0)
    with pytest.raises(StalenessTimeout):
        c.start_step(0, timeout=0.1)
    assert c.steps == [4, 1]


def test_controller_unbounded_when_staleness_zero():
    c = StalenessController(num_workers=2, staleness=0)
    for _ in range(100):
        c.start_step(0, timeout=0.1)
        c.finish_step(0)
    assert c.steps == [100, 0]


def test_controller_validates_args():
    with pytest.raises(ValueError):
        StalenessController(num_workers=0)
    with pytest.raises(ValueError):
        StalenessController(num_workers=1, staleness=-1)


# ------------------------------------------------------------------- runner dispatch

def test_autodist_dispatches_async_runner():
    batch = _data()
    ad = AutoDist(strategy_builder=PS(sync=False))
    runner = ad.create_distributed_session(_loss, _params(), optax.sgd(LR),
                                           example_batch=batch)
    assert isinstance(runner, AsyncPSRunner)


def test_autodist_dispatches_async_runner_for_staleness():
    batch = _data()
    ad = AutoDist(strategy_builder=PS(sync=True, staleness=2))
    runner = ad.create_distributed_session(_loss, _params(), optax.sgd(LR),
                                           example_batch=batch, num_workers=2)
    assert isinstance(runner, AsyncPSRunner)
    assert runner.staleness == 2
    assert runner.num_workers == 2


def test_sync_ps_still_uses_spmd_runner():
    batch = _data()
    ad = AutoDist(strategy_builder=PS())
    runner = ad.create_distributed_session(_loss, _params(), optax.sgd(LR),
                                           example_batch=batch)
    assert isinstance(runner, DistributedRunner)
    assert not isinstance(runner, AsyncPSRunner)


# --------------------------------------------------------------------- value checks

def test_async_single_worker_matches_sequential_sgd():
    """One async worker is plain sequential SGD: value-exact vs numpy (c0-style)."""
    batch = _data()
    ad = AutoDist(strategy_builder=PS(sync=False))
    step = ad.function(_loss, _params(), optax.sgd(LR), example_batch=batch)

    w = b = 0.0
    for _ in range(5):
        step(batch)
        x, y = batch["x"], batch["y"]
        resid = y - (w * x + b)
        w, b = w - LR * np.mean(-2.0 * x * resid), b - LR * np.mean(-2.0 * resid)

    got = step.runner.service.state.params
    np.testing.assert_allclose(float(got["w"]), w, rtol=1e-5)
    np.testing.assert_allclose(float(got["b"]), b, rtol=1e-5)


def test_bounded_staleness_worker_gate_c9_parity():
    """Fast worker runs exactly ``staleness`` steps ahead, blocks, then resumes one
    step per slow-worker step (reference c9.py:92-126 asserted this by wall-clock)."""
    staleness = 3
    batch = _data()
    ad = AutoDist(strategy_builder=PS(staleness=staleness))
    runner = ad.create_distributed_session(_loss, _params(), optax.sgd(LR),
                                           example_batch=batch, num_workers=2)
    runner.init(_params())
    fast, slow = runner.worker(0), runner.worker(1)

    for _ in range(staleness):
        fast.step(batch, timeout=5)
    with pytest.raises(StalenessTimeout):
        fast.step(batch, timeout=0.2)
    assert fast.steps_completed == staleness

    slow.step(batch, timeout=5)
    fast.step(batch, timeout=5)
    with pytest.raises(StalenessTimeout):
        fast.step(batch, timeout=0.2)
    assert fast.steps_completed == staleness + 1
    assert runner.service.version == fast.steps_completed + slow.steps_completed


def test_concurrent_async_workers_apply_all_updates():
    """Two threaded workers, unbounded async: every pushed gradient is applied and
    the model still converges."""
    n_steps = 8
    batch = _data()
    ad = AutoDist(strategy_builder=PS(sync=False))
    runner = ad.create_distributed_session(_loss, _params(), optax.sgd(0.05),
                                           example_batch=batch, num_workers=2)
    runner.init(_params())
    l0 = float(_loss(runner.service.state.params, batch))

    def drive(worker_id):
        w = runner.worker(worker_id)
        for _ in range(n_steps):
            w.step(batch, timeout=30)

    threads = [threading.Thread(target=drive, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive()

    assert runner.service.version == 2 * n_steps
    l1 = float(_loss(runner.service.state.params, batch))
    assert l1 < l0


def test_async_aux_metrics_pass_through():
    """has_aux losses return their real aux in async mode (not a dropped stub)."""
    batch = _data()

    def loss_aux(p, b):
        pred = b["x"] * p["w"] + p["b"]
        loss = jnp.mean((b["y"] - pred) ** 2)
        return loss, {"mean_pred": jnp.mean(pred)}

    ad = AutoDist(strategy_builder=PS(sync=False))
    step = ad.function(loss_aux, _params(), optax.sgd(LR), example_batch=batch,
                       has_aux=True)
    loss, aux = step(batch)
    assert float(loss) > 0
    assert "mean_pred" in aux


def test_async_restore_reseeds_before_updates_and_raises_after():
    batch = _data()
    ad = AutoDist(strategy_builder=PS(sync=False))
    runner = ad.create_distributed_session(_loss, _params(), optax.sgd(LR),
                                           example_batch=batch)
    state0 = runner.init(_params())
    # A foreign (e.g. checkpoint-restored) state before any update re-seeds the PS.
    import dataclasses
    restored = dataclasses.replace(state0, params={"w": jnp.ones(()), "b": jnp.ones(())})
    new_state, _ = runner.run(restored, batch)
    assert runner.service.updates_applied == 1
    # The adoption itself opened a new generation (so any cached conditional
    # pull refetches), then the step's apply advanced it again.
    assert runner.service.version == 2
    # After updates, a foreign state is ambiguous -> explicit restore required.
    with pytest.raises(RuntimeError, match="restore"):
        runner.run(restored, batch)
    runner.restore(restored)
    assert float(runner.service.state.params["w"]) == 1.0


def test_stale_snapshot_is_immutable():
    """A worker's stale params reference survives later applies (no donation)."""
    batch = _data()
    ad = AutoDist(strategy_builder=PS(sync=False))
    runner = ad.create_distributed_session(_loss, _params(), optax.sgd(LR),
                                           example_batch=batch)
    runner.init(_params())
    snap, _ef, v0 = runner.service.read()
    w0 = float(snap["w"])
    runner.worker(0).step(batch)
    runner.worker(0).step(batch)
    assert runner.service.version == v0 + 2
    assert float(snap["w"]) == w0  # old version still readable, unchanged
