"""SavedModel-equivalent export for serving.

Counterpart of reference ``checkpoint/saved_model_builder.py:24-64`` (a
SavedModelBuilder that exported the transformed graph's variables under original
names for vanilla-TF serving; proven there by reloading the artifact and serving
it in plain TF, ``tests/checkpoint/test_saved_model.py:26-40``). The TPU-native
serving artifact is a directory with:

- ``params.npz`` — full unsharded parameters under original names (via Saver),
- ``model_config.json`` — user-provided model metadata (enough to rebuild the
  apply function),
- ``apply.export`` — the EXECUTABLE serving graph: a serialized ``jax.export``
  artifact (versioned StableHLO bytes). :meth:`load_serving_fn` deserializes
  and runs it with no model code imported — the TPU analogue of serving a
  SavedModel's GraphDef in vanilla TF. Exported for both ``cpu`` and ``tpu``
  so one artifact serves on a host or a chip.
- ``apply.hlo`` — the same graph as StableHLO *text*, for human inspection and
  non-JAX toolchains (kept alongside the executable form).
"""

import json
import os
from typing import Any, Callable, Optional, Sequence

import jax

from autodist_tpu.checkpoint.saver import Saver
from autodist_tpu.utils import logging


class SavedModelBuilder:
    def __init__(self, export_dir: str):
        self._export_dir = export_dir
        os.makedirs(export_dir, exist_ok=True)

    def save(self, params: Any, model_config: Optional[dict] = None,
             apply_fn: Optional[Callable] = None, example_args: tuple = (),
             platforms: Optional[Sequence[str]] = None,
             polymorphic_batch: bool = False) -> str:
        """Write the serving artifact.

        ``apply_fn(params, *example_args)`` is traced once and exported as an
        executable, framework-closed graph. ``platforms`` lowers the one
        artifact for every listed backend; the default is the current backend
        plus ``cpu``, so an artifact exported on a chip also serves on a host.
        A function that only lowers on one backend (e.g. one calling pallas
        TPU kernels) should pass ``platforms=("tpu",)`` explicitly.
        ``polymorphic_batch=True`` exports with a symbolic leading dimension
        on every array arg of rank >= 1 (scalars stay concrete), so the
        served function accepts any batch size (otherwise the example shapes
        are baked in, the fastest and most predictable form).
        """
        saver = Saver(max_to_keep=1)
        saver.save(params, os.path.join(self._export_dir, "params"), global_step=0)
        # Rename to the stable serving name (no step suffix) and drop the Saver's
        # latest-pointer state file, which would point at the renamed-away prefix.
        for suffix in (".npz", ".json"):
            src = os.path.join(self._export_dir, "params-0" + suffix)
            dst = os.path.join(self._export_dir, "params" + suffix)
            if os.path.exists(src):
                os.replace(src, dst)
        state_file = os.path.join(self._export_dir, "checkpoint")
        if os.path.exists(state_file):
            os.remove(state_file)

        with open(os.path.join(self._export_dir, "model_config.json"), "w") as f:
            json.dump(model_config or {}, f, indent=1, sort_keys=True)

        if apply_fn is not None:
            from jax import export as jax_export
            if platforms is None:
                current = jax.default_backend()
                platforms = (current,) if current == "cpu" else (current, "cpu")
            args = example_args
            if polymorphic_batch:
                (b,) = jax_export.symbolic_shape("b")

                def _poly(a):
                    arr = jax.numpy.asarray(a)
                    if arr.ndim == 0:
                        return a  # scalars have no batch dim; keep concrete
                    return jax.ShapeDtypeStruct((b,) + arr.shape[1:], arr.dtype)

                args = tuple(_poly(a) for a in example_args)
            exported = jax_export.export(
                jax.jit(apply_fn), platforms=tuple(platforms))(params, *args)
            with open(os.path.join(self._export_dir, "apply.export"), "wb") as f:
                f.write(exported.serialize())
            # Inspectable text form of the same graph.
            with open(os.path.join(self._export_dir, "apply.hlo"), "w") as f:
                f.write(exported.mlir_module())
        else:
            # A re-save without apply_fn must not leave a previous export's
            # graph behind: apply.export is EXECUTABLE, and serving a stale
            # graph against new params is silent wrong output.
            for name in ("apply.export", "apply.hlo"):
                stale = os.path.join(self._export_dir, name)
                if os.path.exists(stale):
                    os.remove(stale)

        logging.info("Exported serving artifact to %s", self._export_dir)
        return self._export_dir

    @staticmethod
    def load_params(export_dir: str):
        return Saver().restore_params(os.path.join(export_dir, "params"))

    @staticmethod
    def load_serving_fn(export_dir: str) -> Callable:
        """Deserialize ``apply.export`` into a callable ``fn(params, *args)``.

        Pure artifact execution: nothing here imports or rebuilds model code —
        the returned callable runs the serialized StableHLO through XLA, the
        same contract as reference ``test_saved_model.py:26-40`` serving the
        exported GraphDef in vanilla TF.
        """
        from jax import export as jax_export
        path = os.path.join(export_dir, "apply.export")
        with open(path, "rb") as f:
            exported = jax_export.deserialize(f.read())
        return exported.call
