"""Deterministic test/chaos harnesses shipped WITH the package.

The self-healing runtime (``parallel/recovery.py``) is only trustworthy if
its chaos paths are driven by REAL failures, not mocks: :mod:`faults`
provides deterministic, env/arg-keyed fault points (worker crash at step N,
worker hang, NaN-in-grads, wire connect refusal) that the product code
consults at a handful of instrumented sites. Un-armed, every site costs one
module-global read.

:mod:`sanitizer` is the same philosophy for the threaded plane (graftsan):
env-armed wrappers around ``threading`` primitives that detect lock-order
cycles, unbounded waits and leaked threads at runtime, and export observed
lock-order edges for ``graftlint --crosscheck``. Un-armed, its factories
return bare primitives — one module-global check at creation time.
"""

from autodist_tpu.testing import sanitizer  # before faults: faults uses it
from autodist_tpu.testing import faults

__all__ = ["faults", "sanitizer"]
