"""Host transport for the async/bounded-stale parameter service.

The reference's non-synchronous PS regimes spanned worker *processes*: each
re-executed user script pushed gradients to PS-device accumulators over TF's
grpc session plane and the chief-side token queues gated staleness
(``ps_synchronizer.py:387-458``, ``:556-633``). The TPU-native async design
keeps the regimes host-driven (``parallel/staleness.py``); this module puts the
chief-owned :class:`ParameterService` + :class:`StalenessController` behind a
small TCP transport so workers in OTHER processes (launched by the Coordinator)
pull parameters and push gradients exactly like the reference's PS plane:

- :class:`PSServer` — runs on the chief next to its AsyncPSRunner; each request
  is handled on its own thread so a blocking ``start_step`` gate (the token
  queue) does not stall other workers.
- :class:`RemotePSWorker` — a worker process's handle: ``step(batch)`` gates on
  the chief's staleness bound, pulls the current parameters, computes local
  gradients on its own devices, and pushes them back.

Wire format: length-prefixed TYPED messages (``parallel/wire.py`` — tag-based
scalars/containers + dtype/shape-headed raw tensor bytes). Nothing on the
socket is ever unpickled, so a hostile peer gets no code execution — the same
property the reference's protobuf-over-grpc plane had (its servers were
unauthenticated but typed). The SPMD data plane is untouched — this is the
host-side control/parameter plane that has no XLA equivalent.

The bytes-on-the-wire hot path is ZERO-COPY in both directions:

- Send: ``wire.encode_parts`` frames ndarrays as borrowed views of their own
  memory and ``_send_payload`` hands the scatter-gather list straight to
  ``socket.sendmsg`` (one syscall, no ``tobytes()``/concat copies), with a
  chunked ``sendall`` fallback where ``sendmsg`` is unavailable.
- Receive: the payload lands in a per-connection recycled buffer
  (``_RecvBuffer`` — reused only once every alias from the previous message
  has been dropped, checked by refcount) and ``wire.decode(..., copy=False)``
  aliases tensors into it, so the PSServer apply path and the client pull
  path never copy tensor bytes on the host.

Framing is 8 bytes big-endian ahead of the payload; the TOP byte is the
frame VERSION (0 for this format — the payload length spans the low 56
bits), so pre-zero-copy endpoints — whose lengths never reached 2^56 —
interoperate bit-for-bit and a future incompatible framing is detectable
instead of being misparsed as an absurd length. Sockets carrying a timeout
always use the Python path to keep timeout semantics.
"""

# The client's per-connection exchange lock nests the fault-injection
# registry's module lock (testing/faults.py `_LOCK`, taken inside
# `_faults.armed()`/`should_fire()`), never the reverse:
# graftlint: lock-order=_lock->_LOCK
import math
import os
import socket
import socketserver
import struct
import sys
import threading
import time
from typing import Any, List, Optional, Tuple, Union

import jax
import numpy as np

from autodist_tpu import telemetry
from autodist_tpu.parallel import wire
from autodist_tpu.testing import faults as _faults
from autodist_tpu.utils import logging
from autodist_tpu.utils.metrics import WireCounters
from autodist_tpu.testing.sanitizer import san_lock, san_event

PyTree = Any

_HDR = struct.Struct("!Q")
# Top header byte = frame version; low 56 bits = payload length.
_FRAME_VERSION = 0
_FRAME_LEN_MAX = (1 << 56) - 1
# sendmsg batches at most this many iovecs per syscall (safely under any
# platform's IOV_MAX); longer part lists loop.
_IOV_BATCH = 64

# ---------------------------------------------------------------- native plane
# native/transport.cc (writev send, one-buffer recv, GIL released during the
# syscalls) — the reference's PS plane was likewise native (TF's C++ grpc,
# SURVEY.md §2.4). The zero-copy plane SUPERSEDED it on the production hot
# paths (scatter-gather sendmsg sends, pooled recv_into receives — measured
# faster in `bench.py --wire` because it removes the codec copies, which
# dominated, not just the framing ones). The lib is retained as the
# send/receive plane for external single-`bytes`-payload and pool-less
# callers of _send_payload/_recv_msg, and the mixed-pairing tests keep both
# planes byte-interoperable so old and new endpoints can coexist in one
# cluster.
_TR_LIB = None
_TR_FAILED = False
_TR_LOCK = san_lock()


def _native_transport():
    global _TR_LIB, _TR_FAILED
    if _TR_LIB is not None or _TR_FAILED:
        return _TR_LIB
    # graftlint: disable=GL001(this lock EXISTS to serialize the one-time native compile — concurrent cc1 invocations over the same .so path corrupt the artifact; no device program or socket runs under it)
    with _TR_LOCK:
        if _TR_LIB is not None or _TR_FAILED:
            return _TR_LIB
        import ctypes

        from autodist_tpu.utils.native_build import build_native_lib
        from autodist_tpu import const
        if not const.ENV.AUTODIST_NATIVE_TRANSPORT.val:
            _TR_FAILED = True
            return None
        src = os.path.join(os.path.dirname(__file__), "native", "transport.cc")
        lib = build_native_lib(src, "transport")
        if lib is None:
            _TR_FAILED = True
            return None
        lib.tr_send.restype = ctypes.c_int
        lib.tr_send.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64]
        lib.tr_recv.restype = ctypes.c_int64
        lib.tr_recv.argtypes = [ctypes.c_int, ctypes.POINTER(ctypes.c_void_p)]
        lib.tr_free.restype = None
        lib.tr_free.argtypes = [ctypes.c_void_p]
        lib.tr_last_errno.restype = ctypes.c_int
        lib.tr_last_errno.argtypes = []
        _TR_LIB = lib
        return _TR_LIB


def _native_error(lib, what: str) -> ConnectionError:
    """ConnectionError carrying the native layer's errno (the C functions
    collapse failures to -1; tr_last_errno() preserves the diagnostic the
    Python fallback's OSError would have shown)."""
    err = lib.tr_last_errno()
    if err == 0:
        return ConnectionError(f"PS transport {what}: connection closed by peer")
    return ConnectionError(
        f"PS transport {what} failed (errno {err}: {os.strerror(err)})")


def _send_msg(sock: socket.socket, obj,
              counters: Optional[WireCounters] = None) -> int:
    """Send one framed message (scatter-gather encode, no serialization
    copies); returns the payload byte count for the caller's accounting."""
    t0 = time.perf_counter() if counters is not None else 0.0
    parts = wire.encode_parts(obj)
    enc_s = time.perf_counter() - t0 if counters is not None else 0.0
    n = _send_payload(sock, parts)
    if counters is not None:
        counters.add_sent(n, enc_s)
    return n


def _sendmsg_all(sock: socket.socket, buffers: List[Any]) -> None:
    """sendall for a scatter-gather buffer list: one ``sendmsg`` syscall per
    <= _IOV_BATCH parts, resuming mid-part after short writes."""
    queue = [memoryview(b) for b in buffers if len(b)]
    while queue:
        sent = sock.sendmsg(queue[:_IOV_BATCH])
        while queue and sent >= len(queue[0]):
            sent -= len(queue[0])
            queue.pop(0)
        if sent and queue:
            queue[0] = queue[0][sent:]


def _send_payload(sock: socket.socket,
                  payload: Union[bytes, bytearray, List[Any]]) -> int:
    """Send an already-encoded payload — one buffer or an ``encode_parts``
    list — with framing (the server pre-encodes replies so an encode failure
    can be reported instead of dropping the connection)."""
    if isinstance(payload, (bytes, bytearray, memoryview)):
        parts, total = [payload], len(payload)
    else:
        parts = payload
        total = sum(len(p) for p in parts)
    if total > _FRAME_LEN_MAX:
        raise wire.WireError(
            f"message of {total} bytes exceeds the 56-bit frame length")
    if _faults.armed():
        # Injected slow wire (bench.py --wire-compress): charge the payload
        # at the installed bandwidth before it moves, both directions — the
        # loopback stand-in for a congested pod fabric.
        delay = _faults.throttle_s(total)
        if delay > 0.0:
            time.sleep(delay)   # bounded by the installed bytes_per_s
    # Native path only for plain blocking sockets (a socket timeout must keep
    # Python's timeout semantics, which raw-fd syscalls would bypass) and
    # single contiguous bytes payloads (the ctypes surface takes one buffer;
    # scatter-gather lists go through sendmsg below, which is its own
    # single-syscall writev).
    lib = _native_transport() if sock.gettimeout() is None else None
    if lib is not None and len(parts) == 1 and type(parts[0]) is bytes:
        data = parts[0]
        while True:
            rc = lib.tr_send(sock.fileno(), data, total)
            if rc == 0:
                return total
            if rc == -2:
                # Signal before any byte moved: the ctypes-call boundary has
                # run pending Python signal handlers (KeyboardInterrupt raises
                # here); otherwise retry the send.
                continue
            raise _native_error(lib, "send")
    header = _HDR.pack(total)  # top byte 0 == _FRAME_VERSION
    if hasattr(sock, "sendmsg"):
        _sendmsg_all(sock, [header, *parts])
    else:  # very old/exotic platforms: chunked sendall, still no concat copy
        sock.sendall(header)
        for p in parts:
            sock.sendall(p)
    return total


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("PS transport connection closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    got, n = 0, len(view)
    while got < n:
        r = sock.recv_into(view[got:])
        if not r:
            raise ConnectionError("PS transport connection closed")
        got += r


def _frame_len(header: bytes) -> int:
    """Validate the 8-byte frame header; returns the payload length.
    Raises :class:`wire.WireError` for an unknown frame version (the top
    header byte) so the server treats it like any other malformed peer."""
    (word,) = _HDR.unpack(header)
    version = word >> 56
    if version != _FRAME_VERSION:
        raise wire.WireError(
            f"unsupported PS frame version {version} (header {header!r})")
    return word & _FRAME_LEN_MAX


_RECVBUF_TEL = None


def _recvbuf_counters():
    """Cached (fresh, recycled) registry counters, ``None`` while telemetry
    is disabled — one enabled-check per message instead of a registry
    get-or-create lookup (same pattern as ``metrics._wire_registry``)."""
    if not telemetry.enabled():
        return None
    global _RECVBUF_TEL
    if _RECVBUF_TEL is None:
        _RECVBUF_TEL = (telemetry.counter("ps.recvbuf.fresh"),
                        telemetry.counter("ps.recvbuf.recycled"))
    return _RECVBUF_TEL


class _RecvBuffer:
    """Per-connection recycled receive buffer for the zero-copy plane.

    ``take(n)`` returns a writable view of an owned buffer. The buffer is
    REUSED only when nothing else references it (``sys.getrefcount == 2``:
    this object's slot + the refcount argument) — arrays aliased out of the
    previous message by ``wire.decode(copy=False)`` hold references through
    their ``.base`` chain, so a consumer that kept the tree (e.g. the
    client's conditional-pull cache, or jax buffers still pinned by an
    in-flight dispatch) silently gets a FRESH buffer instead of having its
    data overwritten. Consume-then-drop callers pay zero copies; holders pay
    one allocation, never corruption.

    ``fresh_allocs``/``recycles`` count the two outcomes (mirrored into the
    telemetry registry as ``ps.recvbuf.fresh``/``ps.recvbuf.recycled`` when
    enabled): a recycle ratio near zero on a hot connection means some
    consumer is holding decoded trees and the zero-copy receive path is
    paying an allocation per message."""

    __slots__ = ("_buf", "fresh_allocs", "recycles")
    _MIN_BYTES = 1 << 16

    def __init__(self):
        self._buf: Optional[bytearray] = None
        self.fresh_allocs = 0
        self.recycles = 0

    def take(self, n: int) -> memoryview:
        tel = _recvbuf_counters()
        if (self._buf is None or len(self._buf) < n
                or sys.getrefcount(self._buf) != 2):
            self._buf = bytearray(max(n, self._MIN_BYTES))
            self.fresh_allocs += 1
            if tel is not None:
                tel[0].inc()
        else:
            self.recycles += 1
            if tel is not None:
                tel[1].inc()
        return memoryview(self._buf)[:n]


def _recv_msg(sock: socket.socket, pool: Optional[_RecvBuffer] = None,
              counters: Optional[WireCounters] = None):
    """Receive one framed message; returns ``(obj, payload_bytes)``.

    With ``pool`` the payload is received straight into the pool's recycled
    buffer and decoded with ``copy=False`` — tensors alias the buffer (see
    :class:`_RecvBuffer` for the reuse contract). Without it, the payload is
    decoded with copies (native single-buffer receive when available)."""
    if pool is not None:
        n = _frame_len(_recv_exact(sock, _HDR.size))
        view = pool.take(n)
        _recv_exact_into(sock, view)
        t0 = time.perf_counter() if counters is not None else 0.0
        obj = wire.decode(view, copy=False)
        if counters is not None:
            counters.add_received(n, time.perf_counter() - t0)
        return obj, n
    lib = _native_transport() if sock.gettimeout() is None else None
    if lib is not None:
        import ctypes
        out = ctypes.c_void_p()
        while True:
            n = lib.tr_recv(sock.fileno(), ctypes.byref(out))
            if n != -2:  # -2 = signal at a message boundary -> handlers ran; retry
                break
        if n < 0:
            raise _native_error(lib, "recv")
        try:
            # Zero-copy view over the malloc'd buffer; wire.decode copies
            # tensor data out, so freeing right after is safe.
            view = memoryview((ctypes.c_char * n).from_address(out.value or 0))
            obj = wire.decode(view)
        finally:
            lib.tr_free(out)
        if counters is not None:
            counters.add_received(n)
        return obj, n
    n = _frame_len(_recv_exact(sock, _HDR.size))
    obj = wire.decode(_recv_exact(sock, n))
    if counters is not None:
        counters.add_received(n)
    return obj, n


def _to_host(tree: PyTree) -> PyTree:
    return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)), tree)


class _WorkerStats:
    """Server-side per-worker accounting: the wire traffic of every
    connection bound to one worker id (``mirror=False`` — the server's
    aggregate ``PSServer.wire`` already mirrors these bytes into the
    telemetry registry, and one byte must not be registry-counted twice),
    plus the monotonic stamp of the worker's last completed exchange
    (the watchdog's stall signal and the ``last_seen_s`` field in
    ``stats_snapshot``)."""

    __slots__ = ("wire", "last_seen")

    def __init__(self):
        self.wire = WireCounters(mirror=False)
        self.last_seen = time.monotonic()


class _StragglerWatchdog:
    """Background straggler/stall monitor for a :class:`PSServer`.

    Every ``interval`` seconds (a BOUNDED ``Event.wait`` — GL005's rule) it
    samples, per registered worker, (a) the age of the last completed
    exchange and (b) the instantaneous staleness lag from the gate
    (:meth:`StalenessController.live_lags`), then:

    - sets ``ps.worker.last_seen_s.w<id>`` registry gauges,
    - flags a worker STALLED when it has been silent longer than
      ``stall_after`` (default 3x the interval),
    - flags a worker a STRAGGLER when some peer is parked AT the staleness
      bound while this worker sits at lag 0 — it is the one everyone is
      waiting for (a merely-stalled worker is often the gate's *victim*;
      the straggler flag names the culprit),
    - bumps the ``ps.straggler.flags`` counter, records a structured
      ``ps.anomaly.{stall,straggler}`` event in the registry, and emits a
      rate-limited ``train:`` warning naming the worker.

    ``flagged`` is the most recent tick's flagged-worker set (tests and
    dashboards read it); anomalies persist in ``telemetry.events()``.
    """

    # A worker silent for this many intervals is considered stalled.
    STALL_INTERVALS = 3.0
    # Per-worker floor between repeated warnings about the same condition.
    WARN_EVERY_S = 60.0

    def __init__(self, server: "PSServer", interval: float,
                 warn_every: Optional[float] = None,
                 evict_after: Optional[float] = None):
        """``evict_after`` arms auto-eviction: a worker silent longer than
        this many seconds is RETIRED from the staleness gate (the recovery
        plane's close-the-loop action), not just flagged. Default: the
        ``AUTODIST_EVICT_AFTER_S`` flag (0/unset = detect-and-warn only,
        the pre-recovery behavior)."""
        from autodist_tpu.parallel import recovery as _recovery
        self._server = server
        self._interval = max(0.01, float(interval))
        self._stall_after = self.STALL_INTERVALS * self._interval
        self._evict_after = _recovery.evict_after_s() \
            if evict_after is None else (float(evict_after) or None)
        self._warn_every = self.WARN_EVERY_S if warn_every is None \
            else float(warn_every)
        self._last_warn: dict = {}
        # Consecutive ticks each worker has satisfied the straggler
        # condition: a fast worker parked AT the bound for a moment is
        # NORMAL steady-state gating, so the flag needs persistence (the
        # same STALL_INTERVALS the silence check uses) before it fires.
        self._straggler_ticks: dict = {}
        self._stop = san_event()
        self.flagged: set = set()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ps-watchdog")
        self._thread.start()

    def close(self):
        self._stop.set()
        self._thread.join(timeout=self._interval + 5.0)

    def _run(self):
        while not self._stop.wait(self._interval):  # bounded: GL005-clean
            try:
                self._sample()
            except Exception as e:  # monitoring must never take down serving
                logging.debug("PS watchdog sample failed: %s", e)

    def _sample(self):
        now = time.monotonic()
        server = self._server
        with server._worker_stats_lock:
            ages = {wid: now - ws.last_seen
                    for wid, ws in server._worker_stats.items()}
        controller = getattr(server._runner, "controller", None)
        lags = controller.live_lags() if controller is not None else {}
        bound = controller.bound if controller is not None else math.inf
        if controller is not None:
            # A worker absent from live_lags was retired (clean close or
            # disconnect): its frozen last-seen age would otherwise flag it
            # stalled forever, drowning real anomalies.
            ages = {wid: age for wid, age in ages.items() if wid in lags}
        reg = telemetry.registry()
        for wid, age in ages.items():
            reg.gauge(f"ps.worker.last_seen_s.w{wid}").set(round(age, 3))
        flagged = {}
        for wid, age in ages.items():
            if age > self._stall_after:
                flagged[wid] = ("stall", age)
        straggling = set()
        if math.isfinite(bound) and len(lags) >= 2 \
                and max(lags.values()) >= bound:
            # Someone is parked at the bound: the lag-0 worker(s) hold the
            # min step count everyone else is gated on.
            straggling = {wid for wid, lag in lags.items() if lag == 0}
        # Persistence gate: flag only after STALL_INTERVALS consecutive
        # ticks — a healthy bounded-staleness run has workers momentarily
        # at the bound every step, and a single sampled instant is noise.
        self._straggler_ticks = {wid: self._straggler_ticks.get(wid, 0) + 1
                                 for wid in straggling}
        for wid in sorted(straggling, key=str):
            if self._straggler_ticks[wid] >= self.STALL_INTERVALS \
                    and wid not in flagged:
                flagged[wid] = ("straggler", ages.get(wid, 0.0))
        for wid, (kind, age) in sorted(flagged.items(), key=lambda kv:
                                       str(kv[0])):
            reg.counter("ps.straggler.flags").inc()
            reg.event(f"ps.anomaly.{kind}", worker=wid,
                      last_seen_s=round(age, 3))
            # Flight recorder: an armed recorder (AUTODIST_RECORDER=1 or
            # telemetry.set_recorder) snapshots the cluster trace + metrics
            # at the anomaly, debounced; un-armed it is a no-op.
            from autodist_tpu.telemetry import recorder as _recorder
            _recorder.maybe_record(f"ps.{kind}.w{wid}", server=self._server)
            # Auto-eviction (AUTODIST_EVICT_AFTER_S): a sustained STALL past
            # the policy threshold RETIRES the worker — live workers parked
            # at the staleness bound resume instead of waiting forever, the
            # evicted worker's parked gate RPC fails typed (WorkerEvicted),
            # and its client rejoins on its own if it was merely slow. Once
            # retired the worker leaves live_lags, so the eviction cannot
            # re-fire on the next tick. STRAGGLER flags never evict: that
            # worker is actively completing exchanges, just slowly —
            # evicting it would churn evict/rejoin every long step and
            # throw its compute away.
            if (kind == "stall" and self._evict_after is not None
                    and age > self._evict_after and controller is not None):
                from autodist_tpu.parallel import recovery as _recovery
                _recovery.evict(controller, wid, kind="stall", age_s=age,
                                server=self._server)
            if now - self._last_warn.get(wid, -math.inf) >= self._warn_every:
                self._last_warn[wid] = now
                if kind == "stall":
                    logging.warning(
                        "train: PS watchdog: worker %s looks STALLED — no "
                        "completed exchange for %.1fs (threshold %.1fs)",
                        wid, age, self._stall_after)
                else:
                    logging.warning(
                        "train: PS watchdog: worker %s is the STRAGGLER — "
                        "peers are parked at the staleness bound (%s) "
                        "waiting for it (last seen %.1fs ago)",
                        wid, int(bound), age)
        self.flagged = set(flagged)


class PSServer:
    """Serve a chief AsyncPSRunner's service + controller to remote workers.

    ``host`` defaults to loopback; pass the coordinator address for real
    multi-node runs. The wire is typed (no unpickling — a hostile peer gets
    data parsing, not code execution), but the protocol is unauthenticated
    like the reference's tf.Servers, so binding wider than the cluster's
    trust domain is still the caller's explicit choice."""

    def __init__(self, runner, host: str = "127.0.0.1", port: int = 0,
                 listen_sock: Optional[socket.socket] = None,
                 watchdog: Optional[bool] = None,
                 watchdog_interval: Optional[float] = None):
        """``listen_sock``: an already-bound listening socket to adopt — the
        launcher binds it BEFORE shipping the address to workers, so the port is
        reserved rather than guessed (no bind race at init time).

        ``watchdog``/``watchdog_interval`` override the
        ``AUTODIST_WATCHDOG``/``AUTODIST_WATCHDOG_SEC`` defaults for the
        straggler/stall monitor (:class:`_StragglerWatchdog`)."""
        if runner.service is None:
            raise RuntimeError("Call runner.init(params) before serving")
        self._runner = runner
        self._t_started = time.monotonic()
        # Span rings workers deposited over the `push_trace` opcode, keyed by
        # worker id — the chief-side half of telemetry.collect_cluster_trace.
        self._worker_traces: dict = {}
        self._trace_lock = san_lock()
        # Aggregate wire accounting across every connection this server has
        # handled (payload bytes, message counts, encode/decode time) —
        # surfaced in the async-PS log line and summarized at close().
        self.wire = WireCounters()
        # Per-worker breakdown of the same traffic, keyed by the worker id a
        # connection binds to (gate/register messages); shipped over the
        # `stats` opcode and printed at close() next to each worker's
        # staleness histogram.
        self._worker_stats: dict = {}
        self._worker_stats_lock = san_lock()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # The worker id this connection drives (from its gate or
                # register messages) + the slot generation it observed:
                # needed to free the gate if the worker dies mid-step, and to
                # make that retire a no-op if a replacement has re-registered
                # the slot since (a stale socket's death must not retire the
                # live occupant).
                self.worker_id = None
                self.worker_gen = 0
                controller = outer._runner.controller
                # Zero-copy receive plane: requests land in this connection's
                # recycled buffer; decoded tensors (the apply path's gradient
                # trees) alias it and are consumed by the dispatch before the
                # next recv can touch the buffer.
                pool = _RecvBuffer()
                try:
                    while True:
                        msg, nrecv = _recv_msg(self.request, pool=pool,
                                               counters=outer.wire)
                        reply = outer._dispatch(msg)
                        is_protocol = isinstance(msg, tuple) and bool(msg)
                        op = msg[0] if is_protocol else "<malformed>"
                        t0 = time.perf_counter()
                        try:
                            payload = wire.encode_parts(reply)
                        except wire.WireError as e:
                            # OUR reply is unencodable (e.g. the user's params
                            # tree contains an unregistered pytree node) —
                            # a server-side limitation, not a hostile peer:
                            # tell the worker instead of dropping it.
                            logging.warning(
                                "PS transport: reply to %r is not "
                                "wire-encodable (%s)", op, e)
                            payload = wire.encode_parts((
                                "error", "WireError",
                                f"server reply to {op!r} is not "
                                f"wire-encodable: {e}"))
                        enc_s = time.perf_counter() - t0
                        # The generation token rides in the dispatch reply,
                        # read inside the controller's own critical section —
                        # a separate generation() read here could race a
                        # concurrent re-registration and adopt the REPLACEMENT
                        # occupant's token (whose retire would then kill the
                        # live worker when this connection dies).
                        if op in ("start_step", "finish_step") \
                                and reply[0] == "ok":
                            # Capture ONCE, at the connection's first bind to
                            # this worker id. Refreshing on every message would
                            # let a zombie connection that sends one more gate
                            # message AFTER a replacement re-registered the
                            # slot adopt the new generation.
                            if self.worker_id != msg[1]:
                                self.worker_id = msg[1]
                                self.worker_gen = reply[1]
                        elif op == "register" and reply[0] == "ok":
                            # register DOES refresh: this connection's own
                            # registration bumped the slot's generation, so the
                            # old token is stale by construction.
                            # Covers a replacement that registers and dies
                            # before its first step (and worker_id=None
                            # allocations, whose id only the reply knows).
                            self.worker_id = reply[1]
                            self.worker_gen = reply[2]
                        nsent = _send_payload(self.request, payload)
                        outer.wire.add_sent(nsent, enc_s)
                        if self.worker_id is not None:
                            # Once the connection is bound to a worker, its
                            # traffic also lands in that worker's breakdown
                            # (the codec-time split stays aggregate-only),
                            # and the exchange refreshes the worker's
                            # last-seen stamp (the watchdog's stall signal).
                            ws = outer._stats_for(self.worker_id)
                            ws.wire.add_received(nrecv)
                            ws.wire.add_sent(nsent)
                            ws.last_seen = time.monotonic()
                        # Drop this message's decoded tree (it aliases the
                        # recv buffer) BEFORE the next recv, or the loop
                        # variable itself would pin the buffer and defeat
                        # recycling for every message.
                        msg = reply = payload = None
                except wire.WireError as e:
                    # Malformed/out-of-vocabulary bytes (a broken or hostile
                    # peer): drop the connection. Decoding allocates data only
                    # — nothing on the socket can execute — so the worst such
                    # a peer achieves is its own disconnect.
                    logging.warning("PS transport: dropping connection with "
                                    "malformed payload (%s)", e)
                    if self.worker_id is not None:
                        controller.retire(self.worker_id,
                                          generation=self.worker_gen)
                except (ConnectionError, OSError):
                    # A vanished worker must not freeze the staleness gate for
                    # everyone else (its step count would pin min(steps) forever).
                    if self.worker_id is not None:
                        logging.warning(
                            "PS worker %s disconnected; retiring it from the "
                            "staleness gate", self.worker_id)
                        # Recovery bookkeeping only when the retire ACTED: a
                        # stale-generation no-op (the slot's live replacement
                        # re-registered first) must not book an eviction of a
                        # worker that never left the gate. A disconnect
                        # retire IS a membership eviction (crash and clean
                        # close are indistinguishable here) — the rejoin
                        # records tell the rest of the story.
                        if controller.retire(self.worker_id,
                                             generation=self.worker_gen):
                            from autodist_tpu.parallel import recovery \
                                as _recovery
                            _recovery.log_eviction(self.worker_id,
                                                   kind="disconnect")

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        if listen_sock is not None:
            self._server = Server(listen_sock.getsockname(), Handler,
                                  bind_and_activate=False)
            self._server.socket.close()
            self._server.socket = listen_sock
            self._server.server_activate()
        else:
            self._server = Server((host, port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        from autodist_tpu import const
        if watchdog is None:
            watchdog = const.ENV.AUTODIST_WATCHDOG.val
        if watchdog_interval is None:
            watchdog_interval = const.ENV.AUTODIST_WATCHDOG_SEC.val
        self._watchdog = _StragglerWatchdog(self, watchdog_interval) \
            if watchdog else None
        # Scrape endpoint: AUTODIST_METRICS_PORT attaches /metrics+/healthz
        # to this process (process-global: one bind even when a train loop
        # or InferenceServer shares the process; no-op when the flag is off).
        from autodist_tpu.telemetry import history as _history
        from autodist_tpu.telemetry import openmetrics as _openmetrics
        _openmetrics.maybe_serve()
        # Metric history: a PS chief may have NO train-loop boundary or
        # scheduler round (applies arrive over the wire), so its only
        # sampling beat is the wall-clock thread — arm it here so the
        # worker_stalled rule actually watches the last-seen gauges this
        # very process books. No-op when the metrics flags are off.
        _history.maybe_arm()
        logging.info("PSServer listening on %s:%d", *self._server.server_address)

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def _stats_for(self, worker_id) -> _WorkerStats:
        with self._worker_stats_lock:
            ws = self._worker_stats.get(worker_id)
            if ws is None:
                ws = self._worker_stats[worker_id] = _WorkerStats()
            return ws

    def stats_snapshot(self) -> dict:
        """The server's observability snapshot, wire-encodable (the ``stats``
        opcode's reply): the process-global telemetry registry, the server's
        aggregate wire counters, its uptime, structured anomaly events (the
        watchdog's straggler/stall records), and a per-worker breakdown of
        wire traffic, last-seen age, and staleness-lag histograms from the
        gate."""
        now = time.monotonic()
        with self._worker_stats_lock:
            ws_items = sorted(self._worker_stats.items())
        per_worker: dict = {
            wid: {"wire": ws.wire.snapshot(),
                  "last_seen_s": round(now - ws.last_seen, 3)}
            for wid, ws in ws_items}
        controller = getattr(self._runner, "controller", None)
        if controller is not None:
            for wid, snap in controller.staleness_snapshot().items():
                per_worker.setdefault(wid, {})["staleness"] = snap
        snap = {"registry": telemetry.snapshot(),
                "wire": self.wire.snapshot(),
                "uptime_s": round(now - self._t_started, 3),
                "anomalies": telemetry.events(),
                "per_worker": per_worker}
        # ZeRO-sharded PS apply: per-shard apply counters (the breakdown of
        # the aggregate service version the staleness protocol rides on).
        service = getattr(self._runner, "service", None)
        shard_versions = getattr(service, "shard_versions", None)
        if shard_versions is not None:
            snap["shard_versions"] = list(shard_versions)
        return snap

    def status_snapshot(self) -> dict:
        """The live-ops view the ``status`` opcode ships (``tools/adtop.py``
        polls it): :meth:`stats_snapshot` plus the gate's INSTANTANEOUS
        per-worker lags and bound, the recent structured events, and a
        ``kind`` discriminator so one console renders PS and serving
        endpoints alike."""
        snap = self.stats_snapshot()
        snap["kind"] = "ps"
        # Rename, don't alias: `status` replies ship the bounded event ring
        # ONCE (adtop reads `events`, falling back to the stats plane's
        # `anomalies` key) — an aliased copy doubles the poll payload.
        snap["events"] = snap.pop("anomalies", [])
        # Alert plane: active + recently-resolved rule firings (a stable
        # empty shell when alerting never armed — pollers keep one schema).
        from autodist_tpu.telemetry import alerts as _alerts
        snap["alerts"] = _alerts.alerts_snapshot()
        # Recovery plane: evictions/rejoins/rollbacks/respawns + per-worker
        # membership generations (same stable-shell contract as alerts).
        from autodist_tpu.parallel import recovery as _recovery
        snap["recovery"] = _recovery.recovery_snapshot()
        # Memory plane: owner census + budget + pressure (stable empty
        # shell until the plane arms — same contract as the two above).
        from autodist_tpu.telemetry import memplane as _memplane
        snap["memory"] = _memplane.memory_snapshot()
        controller = getattr(self._runner, "controller", None)
        if controller is not None:
            bound = controller.bound
            snap["staleness_bound"] = None if math.isinf(bound) else int(bound)
            for wid, lag in controller.live_lags().items():
                snap["per_worker"].setdefault(wid, {})["lag"] = int(lag)
        service = getattr(self._runner, "service", None)
        version = getattr(service, "version", None)
        if version is not None:
            snap["version"] = int(version)
        return snap

    def _store_worker_trace(self, worker_id, state):
        """The ``push_trace`` arm's sink: keep a worker's deposited span ring
        (latest wins) for :func:`telemetry.collect_cluster_trace`.

        Array columns are DEEP-COPIED out of the message: the zero-copy
        receive path decodes them as aliases into the connection's recycled
        buffer, and retaining those aliases for the server's lifetime would
        pin a largest-message-sized buffer (a multi-MiB gradient push) per
        worker to keep ~1 MiB of trace data."""
        if not isinstance(state, dict) or "t0_ns" not in state:
            raise TypeError("push_trace payload is not a trace-state dict")
        state = {k: (np.array(v) if isinstance(v, np.ndarray) else v)
                 for k, v in state.items()}
        with self._trace_lock:
            self._worker_traces[worker_id] = state

    def worker_traces(self) -> dict:
        """``{worker_id: trace-state}`` for every ring workers have pushed
        (``RemotePSWorker.push_trace``) — the chief-side input of
        :func:`telemetry.collect_cluster_trace`."""
        with self._trace_lock:
            return dict(self._worker_traces)

    def _dispatch(self, msg):
        # The wire codec's vocabulary is wider than the protocol's: a peer
        # can legally encode a bare dict/int/None, which would raise at
        # msg[0] OUTSIDE the per-op try below and skip the gate retire.
        if not isinstance(msg, tuple) or not msg \
                or not isinstance(msg[0], str):
            return ("error", "PSClientError",
                    f"malformed protocol message: expected (op, ...) tuple, "
                    f"got {type(msg).__name__}")
        op = msg[0]
        r = self._runner
        try:
            if op == "start_step":
                _, worker_id, timeout = msg
                # A client-requested timeout is honored exactly (a finite
                # wait re-raises StalenessTimeout to that client only). The
                # wait-indefinitely default (None) is bounded at 24h purely
                # so a vanished peer cannot park this handler thread forever
                # — the recv loop shares this thread, so a dead socket never
                # wakes a parked wait (graftlint GL005's rule at the trust
                # boundary); a staleness stall that long is operationally
                # dead anyway.
                gen = r.controller.start_step(
                    worker_id, 86400.0 if timeout is None else float(timeout))
                return ("ok", gen)
            if op == "read":
                params, ef_state, version = r.service.read()
                return ("ok", _to_host(params), _to_host(ef_state), version)
            if op == "read_if_newer":
                params, ef_state, version = r.service.read_if_newer(msg[1])
                if params is None:  # not modified: version-only reply, no tree
                    return ("ok", None, None, version)
                return ("ok", _to_host(params), _to_host(ef_state), version)
            if op == "read_min":
                # Overlapped-client prefetch: wait (bounded) until the service
                # reaches min_version — normally the caller's own in-flight
                # apply on its other connection — then conditional-read. The
                # wait runs on this connection's own handler thread, so it
                # stalls nobody else (the same property the start_step gate
                # relies on). The timeout is clamped: a hostile peer must not
                # park threads indefinitely.
                _, min_version, have_version, timeout = msg
                timeout = min(float(timeout), 600.0) if timeout else 0.0
                params, ef_state, version = r.service.read_min(
                    min_version, have_version, timeout)
                if params is None:
                    return ("ok", None, None, version)
                return ("ok", _to_host(params), _to_host(ef_state), version)
            if op == "apply":
                version = r.service.apply(msg[1])
                return ("ok", version)
            if op == "apply_sparse":
                # Sparse-push apply: the wire codec already dequantized any
                # quantized leaves; expand the SparseRows frames to dense
                # (scatter rows into zeros — exact for the gather-only
                # params the plan marks sparse) and run the ordinary apply.
                from autodist_tpu.parallel.synchronization import \
                    densify_sparse_rows
                version = r.service.apply(densify_sparse_rows(msg[1]))
                return ("ok", version)
            if op == "wire_caps":
                # Compression-capability probe: a pure read the compressing
                # client sends once per connection; an old server answers
                # "unknown op" and the client degrades to exact pushes.
                return ("ok", {"quantized": True, "sparse_push": True})
            if op == "finish_step":
                gen = r.controller.finish_step(msg[1])
                return ("ok", gen)
            if op == "register":
                # Through add_worker, not the bare controller: the chief-side
                # runner's num_workers / handle table must track the gate.
                # with_generation captures the retire token atomically with
                # the registration (see register_with_generation).
                worker, gen = r.add_worker(msg[1], with_generation=True)
                return ("ok", worker.worker_id, gen)
            if op == "version":
                return ("ok", r.service.version)
            if op == "stats":
                # Cross-worker stats plane: ship this process's registry
                # snapshot + per-worker wire/staleness breakdown to whoever
                # asks (RemotePSWorker.stats(), dashboards, tests).
                return ("ok", self.stats_snapshot())
            if op == "status":
                # Live-ops console plane (tools/adtop.py): stats plus the
                # gate's instantaneous lags/bound and recent anomaly events.
                return ("ok", self.status_snapshot())
            if op == "record":
                # Manual flight-recorder trigger: capture a snapshot NOW
                # (bypasses the debounce — a human asked) and return its
                # path. Arms a default recorder when none is installed.
                from autodist_tpu.telemetry import recorder as _recorder
                reason = str(msg[1]) if len(msg) > 1 and msg[1] else "manual"
                path = _recorder.get_or_create().record(reason, server=self)
                return ("ok", path)
            if op == "ping":
                # Clock-offset probe: echo the client's send stamp with this
                # process's wall clock. No locks, no device work — the reply
                # must be fast for the NTP midpoint assumption to hold.
                return ("ok", msg[1], time.time_ns())
            if op == "trace":
                # Cluster trace plane: drain this process's span ring to the
                # caller as a columnar blob (RemotePSWorker.trace()).
                since = msg[1] if len(msg) > 1 else None
                return ("ok", telemetry.local_trace_state(since_ns=since))
            if op == "push_trace":
                # A worker depositing its own ring (already clock-offset
                # stamped) for the chief's collect_cluster_trace.
                self._store_worker_trace(msg[1], msg[2])
                return ("ok", True)
            return ("error", "PSClientError", f"unknown op {op!r}")
        except Exception as e:  # ship the failure to the worker, keep serving
            return ("error", type(e).__name__, str(e))

    def close(self):
        if self._watchdog is not None:
            self._watchdog.close()
            self._watchdog = None
        self._server.shutdown()
        self._server.server_close()
        if self.wire.msgs_received:
            # Aggregate first, then one line per worker: wire traffic next to
            # the staleness-lag distribution its gate entries observed and
            # the worker's last-seen age, so a skewed worker (all lag at the
            # bound, 10x the bytes, or long silent) is visible in the close
            # summary without grepping its own log.
            now = time.monotonic()
            logging.info("PSServer closed: %s | up %.1fs",
                         self.wire.format_line(), now - self._t_started)
            controller = getattr(self._runner, "controller", None)
            stal = controller.staleness_histograms() \
                if controller is not None else {}
            with self._worker_stats_lock:
                ws_items = dict(self._worker_stats)
            for wid in sorted(set(ws_items) | set(stal), key=str):
                parts = []
                ws = ws_items.get(wid)
                if ws is not None:
                    parts.append(ws.wire.format_line())
                    parts.append(f"last seen {now - ws.last_seen:.1f}s ago")
                hist = stal.get(wid)
                if hist is not None and hist.count:
                    parts.append(f"staleness {hist.format_compact()}")
                if parts:
                    logging.info("PSServer closed:   worker %s: %s",
                                 wid, " | ".join(parts))


class PSClientError(RuntimeError):
    """A server-side failure reported over the transport."""


# Per-opcode idempotency contract — the wire-retry policy's ground truth.
# IDEMPOTENT: repeating the request after a transport failure cannot change
# server state a second time, so the client may transparently reconnect and
# retry (AUTODIST_WIRE_RETRIES budget, jittered exponential backoff):
#   read / read_if_newer / read_min / version / stats / status / trace /
#     reqtrace — pure reads; ping — stateless echo; push_trace —
#     latest-ring-wins sink;
#   register — idempotent ONLY with an explicit worker_id (a live slot keeps
#     its count); register(None) ALLOCATES a fresh slot per request, so a
#     replay would leave a phantom live slot pinning min(steps) forever —
#     _retry_safe carves it out;
#   start_step — re-entering the gate wait moves no counters;
#   wire_caps — a pure capability read (no state touched).
# NOT idempotent (a failure mid-exchange surfaces to the caller — the
# request may or may not have landed, and replaying it would double-apply):
#   apply / apply_sparse (one gradient update each — apply_sparse is apply
#   with a densify prologue, same double-apply hazard), finish_step
#   (advances the step count), record (writes a snapshot dir per request).
IDEMPOTENT_OPS = frozenset({
    "read", "read_if_newer", "read_min", "version", "stats", "status",
    "ping", "trace", "reqtrace", "push_trace", "register", "start_step",
    "wire_caps"})


def _retry_safe(msg) -> bool:
    """True when replaying this exact request after a transport failure is
    safe (see :data:`IDEMPOTENT_OPS` and the register(None) carve-out)."""
    op = msg[0] if isinstance(msg, tuple) and msg else None
    if op not in IDEMPOTENT_OPS:
        return False
    if op == "register" and (len(msg) < 2 or msg[1] is None):
        return False   # each replay would allocate another slot
    return True


class _PSClient:
    def __init__(self, address, connect_timeout: float = 60.0,
                 read_timeout: Optional[float] = None):
        """``connect_timeout`` bounds the whole retry-until-up loop AND each
        attempt (a SYN-dropping peer must not park one attempt for longer
        than the caller's total budget — the adfleet liveness-probe case);
        ``read_timeout`` optionally bounds each reply wait (default None:
        workers park on the gate for as long as the protocol says)."""
        if isinstance(address, str):
            host, _, port = address.rpartition(":")
            address = (host, int(port))
        from autodist_tpu import const
        self._address = address
        self._connect_timeout = float(connect_timeout)
        self._read_timeout = read_timeout
        self._retries = max(0, int(const.ENV.AUTODIST_WIRE_RETRIES.val))
        self._backoff_s = max(0.0,
                              float(const.ENV.AUTODIST_WIRE_BACKOFF_S.val))
        self._sock = self._connect(self._connect_timeout)
        self._lock = san_lock()
        self._pool = _RecvBuffer()
        # Wire accounting (payload bytes/messages both directions + codec
        # time) — lets callers and tests measure what a protocol change
        # (e.g. read_if_newer) saves.
        self.wire = WireCounters()

    def _connect(self, budget: float) -> socket.socket:
        """Connect with jittered exponential backoff under a total-deadline
        budget — the chief serves only after its runner.init(), so a worker
        process that starts faster (or reconnects through a chief restart)
        retries refused/reset attempts instead of surfacing the first one."""
        from autodist_tpu.parallel import recovery as _recovery
        deadline = time.monotonic() + budget
        attempt = 0
        while True:
            try:
                if _faults.armed() and _faults.should_fire("wire_refuse"):
                    raise ConnectionRefusedError(
                        "injected wire_refuse fault point")
                per_try = min(10.0, max(0.1, deadline - time.monotonic()))
                sock = socket.create_connection(self._address,
                                                timeout=per_try)
                sock.settimeout(self._read_timeout)
                return sock
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                # Bounded jittered backoff (recovery.backoff_s caps at 2s
                # here: a liveness probe's 2s budget must fit retries).
                time.sleep(min(
                    max(0.0, deadline - time.monotonic()),
                    _recovery.backoff_s(attempt, self._backoff_s or 0.2,
                                        cap_s=2.0)))
                attempt += 1

    @property
    def bytes_sent(self) -> int:
        return self.wire.bytes_sent

    @property
    def bytes_received(self) -> int:
        return self.wire.bytes_received

    def call_raw(self, msg: tuple, counters: WireCounters):
        """One request/reply exchange accounted into ``counters`` (NOT this
        client's own) and returned unchecked — the overlapped prefetch path,
        whose bytes are attributed only when the result is consumed so
        ``wire_bytes`` reads stay deterministic while a pull is in flight.

        Transient transport failures (refused/reset connections) on
        IDEMPOTENT opcodes reconnect and retry under the
        ``AUTODIST_WIRE_RETRIES``/``AUTODIST_WIRE_BACKOFF_S`` budget; a
        non-idempotent op's failure surfaces immediately (the request may
        have committed — see :data:`IDEMPOTENT_OPS`). A reply-wait TIMEOUT
        never retries: the reply may still be in flight, and a resend would
        desync the request/reply pairing."""
        op = msg[0] if isinstance(msg, tuple) and msg else None
        attempt = 0
        # graftlint: disable=GL001(the lock IS the request/reply pairing — one in-flight exchange per connection; the server replies promptly per-op and close/shutdown unblocks a parked recv; the retry's bounded backoff sleeps under it so a concurrent caller cannot interleave on a half-reconnected socket)
        with self._lock:
            while True:
                try:
                    if _faults.armed() \
                            and _faults.should_fire("wire_reset", op=op):
                        # Tear the connection down for real so the retry
                        # exercises the genuine reconnect path.
                        try:
                            self._sock.shutdown(socket.SHUT_RDWR)
                        except OSError:
                            pass
                        self._sock.close()
                        raise ConnectionResetError(
                            "injected wire_reset fault point")
                    _send_msg(self._sock, msg, counters)
                    reply, _ = _recv_msg(self._sock, pool=self._pool,
                                         counters=counters)
                    return reply
                except (socket.timeout, TimeoutError):
                    raise
                except (ConnectionError, OSError) as e:
                    if not _retry_safe(msg) or attempt >= self._retries:
                        raise
                    attempt += 1
                    from autodist_tpu.parallel import recovery as _recovery
                    delay = _recovery.backoff_s(attempt - 1,
                                                self._backoff_s, cap_s=5.0)
                    logging.warning(
                        "PS transport: %r failed (%s); reconnecting and "
                        "retrying idempotent op in %.2fs (attempt %d/%d)",
                        op, e, delay, attempt, self._retries)
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    time.sleep(delay)   # bounded: cap_s
                    self._sock = self._connect(self._connect_timeout)
                    # Fresh buffer: the old one may hold a half-received
                    # payload aliased by nothing we can trust.
                    self._pool = _RecvBuffer()

    def call(self, *msg):
        reply = self.call_raw(msg, self.wire)
        if reply[0] != "ok":
            # Re-raise gate timeouts and evictions under their real types so
            # callers written against the AsyncWorker contract (`except
            # StalenessTimeout` / the rejoin-on-WorkerEvicted path) keep
            # working across the transport.
            kind, detail = reply[1], reply[2]
            if kind == "StalenessTimeout":
                from autodist_tpu.parallel.staleness import StalenessTimeout
                raise StalenessTimeout(detail)
            if kind == "WorkerEvicted":
                from autodist_tpu.parallel.staleness import WorkerEvicted
                raise WorkerEvicted(detail)
            raise PSClientError(f"{kind}: {detail}")
        return reply[1:]

    def close(self):
        # shutdown() before close(): closing an fd does NOT wake a thread
        # blocked inside recv(2) on Linux — the overlapped worker's prefetch
        # thread may be parked exactly there, and it must observe EOF at
        # close time, not after the server-side read_min wait expires.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # never connected / peer already gone
        self._sock.close()


class _Prefetch:
    """An in-flight overlapped parameter pull (result, error, accounting)."""
    __slots__ = ("thread", "result", "error", "counters")

    def __init__(self):
        self.thread = None
        self.result = None
        self.error = None
        self.counters = WireCounters()


class RemotePSWorker:
    """A worker process's handle onto the chief's parameter service.

    Mirrors :class:`~autodist_tpu.parallel.staleness.AsyncWorker` but with the
    service/controller calls crossing the transport; gradient computation runs on
    this process's own devices through the runner's jitted grad fn.

    The client is OVERLAPPED by default (``overlap=None`` reads
    ``AUTODIST_PS_OVERLAP``, default on): a second connection carries a
    background parameter pull that is kicked off just before step k's
    gradient push, using the ``read_min`` op so the server replies once the
    worker's own apply has landed — the step-k+1 download streams while the
    step-k upload, ``finish_step``/``start_step`` round trips, and batch
    sharding proceed, hiding one RTT plus a full parameter transfer per step.
    The staleness gate is untouched (``finish_step`` is still sent only after
    the apply is acknowledged), and the prefetched tree is used only after a
    post-gate revalidation (``read_if_newer``) confirms it is the CURRENT
    version — if other workers applied in between, the client re-pulls, so
    every value and version a step observes is identical to the serial
    client's. Old servers without ``read_min`` degrade gracefully (the
    prefetch falls back to a plain conditional read)."""

    # Bound on the server-side read_min wait and on joining a prefetch; a
    # wedged pull connection disables overlap rather than wedging the step.
    PREFETCH_TIMEOUT = 30.0

    # Ping round-trips per clock-offset estimate (median across rounds; odd
    # count so the median is a real sample).
    CLOCK_PING_ROUNDS = 7

    def __init__(self, address, runner, worker_id: int,
                 overlap: Optional[bool] = None,
                 wire_dtype: Optional[str] = None,
                 compressor=None):
        self._client = _PSClient(address)
        self._runner = runner
        self.worker_id = worker_id
        self.steps_completed = 0
        self.last_version_read = -1
        from autodist_tpu import const
        if overlap is None:
            overlap = const.ENV.AUTODIST_PS_OVERLAP.val
        self._pull_client = _PSClient(address) if overlap else None
        self._prefetch: Optional[_Prefetch] = None
        self._server_has_read_min = True  # optimistic; cleared on unknown-op
        # Wire-push compression: tuned plan's wire_dtype knob wins, then the
        # env flag; sparse push rides for any plan that marks row-sparse
        # params (lossless — framing only). ``compressor`` overrides
        # everything (tests inject an EF-disabled one as negative control).
        if compressor is None:
            if wire_dtype is None:
                wire_dtype = getattr(getattr(runner, "tuned_plan", None),
                                     "wire_dtype", "") \
                    or const.ENV.AUTODIST_WIRE_DTYPE.val
            sparse_params = {}
            plan = getattr(runner, "plan", None)
            if const.ENV.AUTODIST_SPARSE_PUSH.val and plan is not None:
                sparse_params = {
                    name: p.index_leaf
                    for name, p in plan.sparse_wire_params.items()}
            from autodist_tpu.parallel.synchronization import \
                WirePushCompressor
            compressor = WirePushCompressor(wire_dtype,
                                            sparse_params=sparse_params)
        self._compressor = compressor if compressor.active else None
        # Chief-clock offset for this worker's main connection (estimated by
        # estimate_clock_offset; None until then). ADD to this process's
        # wall-clock ns to land on the chief's timeline.
        self.clock_offset_ns: Optional[int] = None
        self.clock_offset_err_ns: Optional[int] = None
        # Register up front: idempotent for a live slot (the server keeps its
        # count), and for a RETIRED slot — e.g. a Coordinator-relaunched worker
        # reusing its AUTODIST_PROCESS_ID — it re-admits the slot so stepping
        # is gated again. Without this, a relaunched process would step a
        # retired slot the live workers no longer wait for, silently making
        # the staleness bound one-sided.
        self.register()
        if self._compressor is not None:
            self._probe_wire_caps()
        # Cache of the last pulled (params, ef_state): the conditional pull in
        # step() reuses it when the service version is unchanged, so a worker
        # whose gate opened with no intervening applies ships no parameter
        # bytes (the reference's proxy-variable cache served the same purpose,
        # proxy_variable.py:74-114).
        self._cached_pull = None

    @property
    def wire_bytes(self) -> Tuple[int, int]:
        """(sent, received) payload bytes over this worker's transport.

        Deterministic under overlap: a background pull's bytes are attributed
        when its result is CONSUMED (the next step's pull), not while it
        streams, so two reads bracketing a step measure exactly that step."""
        return self._client.bytes_sent, self._client.bytes_received

    @property
    def wire_counters(self) -> WireCounters:
        """Full wire accounting (bytes/messages/codec time), consumed-basis."""
        return self._client.wire

    def _probe_wire_caps(self):
        """One ``wire_caps`` round trip: drop whichever compression regimes
        the server cannot decode. An old server answers "unknown op" and
        this worker degrades to exact pushes for its lifetime — the same
        optimistic-capability pattern as ``_server_has_read_min``, probed
        eagerly because a compressed frame an old server CAN'T decode would
        fail its apply, not just fall back."""
        try:
            caps = self._client.call("wire_caps")[0] or {}
        except PSClientError as e:
            if "unknown op" not in str(e):
                raise
            caps = {}
            logging.warning(
                "PS worker %s: server has no wire_caps op; pushing exact "
                "uncompressed gradients", self.worker_id)
        comp = self._compressor
        if not caps.get("quantized"):
            comp.wire_dtype = ""
        if not caps.get("sparse_push"):
            comp.sparse_params = {}
        if not comp.active:
            self._compressor = None

    def register(self) -> int:
        """(Re-)admit this worker to the chief's staleness gate — the elastic
        rejoin for a replacement process after the original disconnected and
        was retired. Seeds the gate at the slowest live worker's step count;
        returns the admitted id (may differ when ``worker_id`` was None)."""
        wid = self._client.call("register", self.worker_id)[0]
        self.worker_id = wid
        return wid

    def rejoin(self) -> int:
        """Recover from an eviction WITHOUT a checkpoint: re-register (the
        gate seeds this worker at the slowest LIVE step count — neither
        wedging the bound nor surging past it) and catch up to the chief's
        LIVE parameters over the ``read_min`` path, seeding the conditional-
        pull cache so the next :meth:`step` revalidates instead of
        re-downloading. Called automatically when a gate RPC fails with
        :class:`~autodist_tpu.parallel.staleness.WorkerEvicted`; safe to
        call manually after any suspected membership loss."""
        # The eviction may span many service versions: drop the stale
        # prefetch/cache so nothing pre-eviction can be revalidated.
        self._prefetch = None
        self._cached_pull = None
        self.last_version_read = -1
        wid = self.register()
        with telemetry.span("ps.rejoin", worker=wid):
            try:
                # read_min(0, -1): released immediately at the service's
                # CURRENT version — the catch-up pull, one round trip.
                params, ef_state, version = self._client.call(
                    "read_min", 0, -1, self.PREFETCH_TIMEOUT)
            except PSClientError as e:
                if "unknown op" not in str(e):
                    raise
                # Pre-read_min chief: a plain read is the same catch-up.
                params, ef_state, version = self._client.call("read")
        if params is not None:
            self._cached_pull = (params, ef_state)
            self.last_version_read = version
        logging.warning(
            "PS worker %s rejoined the staleness gate and caught up to "
            "chief version %s (checkpoint-free restart)", wid, version)
        return wid

    def warmup(self, batch: PyTree) -> None:
        """Compile this worker's gradient program without applying an update
        (pull params, compile, discard) — keeps process-startup compile time out
        of the staleness-gated stepping. The pull seeds the conditional-read
        cache, so the first step() skips re-downloading an unchanged tree."""
        params, ef_state, _ = self._pull()
        sharded = self._runner.shard_batch(batch)
        with self._runner.mesh:
            jax.block_until_ready(self._runner.grad_fn(params, sharded, ef_state)[0])

    def _start_prefetch(self):
        """Kick the step-k+1 parameter pull onto the second connection, just
        before step k's gradient push: ``read_min(last+1)`` parks on the
        server until the in-flight apply lands, then streams the new tree
        while this thread pushes/finishes/gates. Bytes are accounted at join
        (:meth:`wire_bytes`)."""
        if self._pull_client is None or self._prefetch is not None:
            return
        pf = _Prefetch()
        have = self.last_version_read
        use_read_min = self._server_has_read_min
        client = self._pull_client

        def run():
            try:
                with telemetry.span("ps.prefetch", worker=self.worker_id):
                    self._prefetch_exchange(pf, client, have, use_read_min)
            except BaseException as e:  # surfaced (or discarded) at join
                pf.error = e
        pf.thread = threading.Thread(target=run, daemon=True,
                                     name="ps-pull-prefetch")
        pf.thread.start()
        self._prefetch = pf

    def _prefetch_exchange(self, pf: _Prefetch, client: _PSClient, have: int,
                           use_read_min: bool):
        """The background pull's request/reply exchange (the body of the
        prefetch thread, spanned as ``ps.prefetch``)."""
        if use_read_min:
            reply = client.call_raw(
                ("read_min", have + 1, have, self.PREFETCH_TIMEOUT),
                pf.counters)
            if (reply[0] == "error" and len(reply) > 2
                    and "unknown op" in str(reply[2])):
                # Pre-read_min server: degrade to a plain conditional
                # read for this and every later prefetch. ONLY the
                # unknown-op reply downgrades — any other server-side
                # error is transient (this prefetch is simply
                # discarded at join) and must not cost the overlap
                # for the worker's whole life.
                self._server_has_read_min = False
                logging.info(
                    "PS overlap: server has no read_min op; "
                    "prefetching with plain conditional reads")
                reply = client.call_raw(("read_if_newer", have),
                                        pf.counters)
        else:
            reply = client.call_raw(("read_if_newer", have),
                                    pf.counters)
        pf.result = reply

    def _take_prefetch(self):
        """Join the in-flight pull; returns ``(params, ef_state, version)`` or
        ``None``. A failed/wedged pull connection disables overlap for the
        rest of this worker's life — the serial path is always correct."""
        pf, self._prefetch = self._prefetch, None
        if pf is None:
            return None
        pf.thread.join(timeout=self.PREFETCH_TIMEOUT + 30.0)
        if pf.thread.is_alive() or pf.error is not None:
            logging.warning(
                "PS overlap: background pull failed (%s); falling back to "
                "serial pulls", pf.error or "join timeout")
            if self._pull_client is not None:
                try:
                    self._pull_client.close()
                except OSError:
                    pass
                self._pull_client = None
            return None
        # Consumed now: fold the pull's bytes into the visible accounting.
        self._client.wire.merge(pf.counters)
        if pf.result[0] != "ok":
            return None
        return pf.result[1:]

    def _pull(self):
        """Current (params, ef_state, version), skipping the parameter payload
        when the service hasn't advanced past the cached version. A completed
        background pull pre-seeds the cache; the conditional read below then
        REVALIDATES it against the live version, so the returned tree is
        byte-identical to what a serial pull at this moment would see."""
        pf = self._take_prefetch()
        if pf is not None:
            p_params, p_ef, p_version = pf
            if p_params is not None and p_version > self.last_version_read:
                self._cached_pull = (p_params, p_ef)
                self.last_version_read = p_version
        if self._cached_pull is None:
            params, ef_state, version = self._client.call("read")
        else:
            params, ef_state, version = self._client.call(
                "read_if_newer", self.last_version_read)
            if params is None:  # not modified: the cached tree IS current
                params, ef_state = self._cached_pull
        self._cached_pull = (params, ef_state)
        self.last_version_read = version
        return params, ef_state, version

    def step(self, batch: PyTree, timeout: Optional[float] = None):
        from autodist_tpu.parallel.staleness import WorkerEvicted
        r = self._runner
        if _faults.armed():
            # Chaos harness: deterministic hang (the watchdog/eviction
            # driver) and crash (abrupt socket teardown — the server sees
            # exactly what a killed process produces) fault points.
            _faults.maybe_hang(step=self.steps_completed,
                               worker=self.worker_id)
            if _faults.should_fire("worker_crash", step=self.steps_completed,
                                   worker=self.worker_id):
                self._crash()
                raise _faults.WorkerCrashed(
                    f"remote worker {self.worker_id} crashed by fault "
                    f"injection at step {self.steps_completed}")
        try:
            with telemetry.span("ps.gate", worker=self.worker_id):
                self._client.call("start_step", self.worker_id, timeout)
        except WorkerEvicted:
            # Auto-eviction hit this worker (sustained stall — possibly as
            # the gate's victim, not its culprit): rejoin seeded at the
            # slowest live count, catch up on live params, and take the
            # gate again. One retry: a second eviction inside one step
            # means the chief really wants us gone.
            logging.warning(
                "PS worker %s was evicted from the staleness gate; "
                "rejoining with live-param catch-up", self.worker_id)
            self.rejoin()
            with telemetry.span("ps.gate", worker=self.worker_id):
                self._client.call("start_step", self.worker_id, timeout)
        with telemetry.span("ps.pull", worker=self.worker_id):
            params, ef_state, _ = self._pull()
        with telemetry.span("ps.shard"):
            sharded = r.shard_batch(batch)
        with telemetry.span("ps.grad"):
            with r.mesh:
                grads, loss, aux, _ef = r.grad_fn(params, sharded, ef_state)
            grads = _to_host(grads)
        # Overlap: next step's parameter download streams on the second
        # socket while this one pushes the gradients and runs the
        # finish/start gate round trips. The gate ordering is unchanged —
        # finish_step goes out only after the apply is acknowledged.
        self._start_prefetch()
        push_op = "apply"
        if self._compressor is not None:
            # Host-side compression between grad materialization and the
            # push: quantize (+ error-feedback residual), sparse-frame any
            # row-sparse params. The server's decode dequantizes and
            # apply_sparse densifies, so its apply path sees a dense tree.
            with telemetry.span("ps.compress", worker=self.worker_id):
                grads, has_sparse = self._compressor.compress(grads,
                                                              batch=batch)
            if has_sparse:
                push_op = "apply_sparse"
        with telemetry.span("ps.push", worker=self.worker_id):
            self._client.call(push_op, grads)
            self._client.call("finish_step", self.worker_id)
        self.steps_completed += 1
        if r.has_aux:
            return loss, aux
        return loss

    def stats(self) -> dict:
        """Pull the chief's stats snapshot over the transport: the server
        process's telemetry-registry snapshot, its aggregate wire counters,
        and the per-worker wire/staleness breakdown
        (:meth:`PSServer.stats_snapshot`) — remote observability without
        grepping the chief's log."""
        return self._client.call("stats")[0]

    def status(self) -> dict:
        """Pull the chief's live-ops status (:meth:`PSServer.status_snapshot`
        — stats plus instantaneous gate lags and recent anomaly events); the
        payload ``tools/adtop.py`` renders."""
        return self._client.call("status")[0]

    def record(self, reason: str = "manual") -> Optional[str]:
        """Trigger a flight-recorder snapshot ON THE CHIEF (the ``record``
        opcode; bypasses the debounce) and return the chief-side snapshot
        dir path — the remote 'capture the cluster's state now' button."""
        return self._client.call("record", reason)[0]

    def estimate_clock_offset(self, rounds: Optional[int] = None):
        """Estimate the chief-clock offset for this worker: ``rounds`` ping
        exchanges on the main connection, each yielding an NTP midpoint
        sample; the median offset and its RTT-bounded uncertainty are stored
        on the worker (``clock_offset_ns``/``clock_offset_err_ns``) and
        returned. The cluster trace plane uses the offset to rebase this
        process's spans onto the chief's timeline
        (:func:`autodist_tpu.telemetry.cluster.ntp_offset`)."""
        from autodist_tpu.telemetry import cluster as _cluster
        samples = []
        for _ in range(rounds or self.CLOCK_PING_ROUNDS):
            t0 = time.time_ns()
            _, server_ns = self._client.call("ping", t0)
            samples.append((t0, server_ns, time.time_ns()))
        self.clock_offset_ns, self.clock_offset_err_ns = \
            _cluster.ntp_offset(samples)
        return self.clock_offset_ns, self.clock_offset_err_ns

    def trace(self, since_ns: Optional[int] = None) -> dict:
        """Pull the CHIEF's span ring over the transport (the ``trace``
        opcode): a columnar trace-state blob
        (:func:`autodist_tpu.telemetry.cluster.local_trace_state`) ready for
        ``telemetry.merge_trace_states`` / ``collect_cluster_trace``."""
        return self._client.call("trace", since_ns)[0]

    def push_trace(self, since_ns: Optional[int] = None) -> int:
        """Deposit this process's span ring on the chief (the ``push_trace``
        opcode) so the chief's ``collect_cluster_trace`` can lay it out as
        this worker's ``pid`` lane. Estimates the clock offset first (once
        per worker) and stamps it into the blob; returns the span count
        pushed. Automatic at :meth:`close` under ``AUTODIST_TRACE_PULL=1``."""
        if self.clock_offset_ns is None:
            self.estimate_clock_offset()
        from autodist_tpu.telemetry import cluster as _cluster
        state = _cluster.local_trace_state(
            since_ns=since_ns, worker_id=self.worker_id,
            clock_offset_ns=self.clock_offset_ns)
        self._client.call("push_trace", self.worker_id, state)
        return len(state["name_idx"])

    @property
    def version(self) -> int:
        return self._client.call("version")[0]

    def _crash(self):
        """Abrupt transport teardown (the ``worker_crash`` fault point): no
        trace push, no goodbye — the server's recv observes EOF exactly as
        it would for a killed process and retires the slot."""
        pf, self._prefetch = self._prefetch, None
        if self._pull_client is not None:
            try:
                self._pull_client.close()
            except OSError:
                pass
            self._pull_client = None
        if pf is not None and pf.thread is not None:
            pf.thread.join(timeout=5.0)
        try:
            self._client.close()
        except OSError:
            pass

    def close(self):
        from autodist_tpu import const
        if const.ENV.AUTODIST_TRACE_PULL.val and telemetry.enabled():
            # Last act on the live connection: leave this worker's timeline
            # with the chief so the cluster trace has a lane for it even
            # after the process is gone.
            try:
                self.push_trace()
            except (ConnectionError, OSError, PSClientError) as e:
                logging.debug("trace push at close failed: %s", e)
        pf, self._prefetch = self._prefetch, None
        if self._pull_client is not None:
            # Closing the socket unblocks an in-flight background pull.
            self._pull_client.close()
            self._pull_client = None
        if pf is not None and pf.thread is not None:
            pf.thread.join(timeout=5.0)
        self._client.close()
