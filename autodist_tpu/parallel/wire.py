"""Typed binary wire codec for the PS transport — the pickle replacement.

The reference's PS plane spoke protobuf over grpc: typed messages, no code
execution on decode (``SURVEY.md`` §2.4). The first TPU-native transport
pickled pytrees, which made every socket byte a potential
``pickle.loads`` RCE. This codec closes that: a small tag-based binary
format covering exactly the protocol's value vocabulary —

- ``None``/bool/int/float/str/bytes,
- tuple/list/dict (the protocol messages and pytree containers),
- numpy ndarrays as ``dtype name + shape + raw C-order bytes`` (the typed
  tensor framing; custom float dtypes like bfloat16 ride as their true dtype
  name, decoded via ml_dtypes),
- QUANTIZED tensors (:class:`QuantizedArray`, tag ``q``) as ``original dtype
  + payload dtype + shape + float32 scale section + raw low-precision
  bytes`` — the compressed gradient-push framing. The scale section holds
  one per-tensor scale or one scale PER ROW (int8 2-D grads). Decode
  DEQUANTIZES: the receiver gets a plain dense ndarray of the original
  dtype, so a server's apply path never learns the push was compressed,
- REGISTERED dataclass pytree nodes (compressor state such as ``EFState``),
  encoded as a registry key + field dict and reconstructed only through the
  registry — never by importing attacker-chosen names.

Decoding allocates plain Python/numpy objects; there is no reduce protocol,
no module import, no callable evaluation. Unknown tags or registry keys
raise :class:`WireError`. By default arrays are copied out of the input
buffer so the caller may free it; ``decode(buf, copy=False)`` instead
aliases array payloads into ``buf`` (read-only views) for receive paths
that keep the buffer alive — see :func:`decode`.

The encoder has two faces over one code path: :func:`encode` returns one
``bytes`` object, and :func:`encode_parts` returns a scatter-gather list of
buffers whose concatenation is byte-identical to ``encode``'s output — large
C-contiguous ndarrays ride as BORROWED views of their own memory (no
``tobytes()`` copy, no concat copy), so a multi-MB gradient push serializes
without touching the tensor bytes. Old and new endpoints therefore
interoperate freely: the bytes on the wire are the same either way.

Ints use a fixed 8-byte signed encoding with a decimal-string escape for
arbitrary precision; dict keys may be any encodable value (the protocol uses
str keys, but pytrees may legally carry int keys).
"""

import struct
from typing import Any, Callable, Dict, List, Tuple

import numpy as np

__all__ = ["encode", "encode_parts", "decode", "register_wire_dataclass",
           "WireError", "QuantizedArray", "quantize", "dequantize",
           "WIRE_DTYPES"]


class WireError(ValueError):
    """Malformed or out-of-vocabulary wire data."""


_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1
_u32 = struct.Struct("!I")
_u64 = struct.Struct("!Q")
_i64 = struct.Struct("!q")
_f64 = struct.Struct("!d")

# Registered dataclass nodes: key -> (cls, field_names). The key is the
# class's registration name, agreed by both endpoints at import time; decode
# can only ever construct classes something in THIS process registered.
_REGISTRY: Dict[str, Tuple[type, Tuple[str, ...]]] = {}
_CLS_KEY: Dict[type, str] = {}


def register_wire_dataclass(cls: type, key: str = None) -> type:
    """Allow ``cls`` (a field-constructible dataclass used as a pytree node)
    across the wire. Both endpoints must register it — which they do by
    importing the defining module. Returns ``cls`` (decorator-friendly)."""
    import dataclasses
    if not dataclasses.is_dataclass(cls):
        raise TypeError(f"{cls!r} is not a dataclass")
    key = key or f"{cls.__module__}:{cls.__qualname__}"
    _REGISTRY[key] = (cls, tuple(f.name for f in dataclasses.fields(cls)))
    _CLS_KEY[cls] = key
    return cls


# ------------------------------------------------------------------- quantized

# The wire dtypes the compression plane speaks. "fp16"/"bf16" halve the
# payload; "int8" quarters it (plus a 4-byte scale per row for 2-D grads).
WIRE_DTYPES = ("fp16", "bf16", "int8")


def _wire_np_dtype(wire_dtype: str) -> np.dtype:
    if wire_dtype == "fp16":
        return np.dtype(np.float16)
    if wire_dtype == "bf16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    if wire_dtype == "int8":
        return np.dtype(np.int8)
    raise ValueError(f"unknown wire dtype {wire_dtype!r}; valid: "
                     f"{', '.join(WIRE_DTYPES)}")


class QuantizedArray:
    """A host tensor carried in low precision on the wire (tag ``q``).

    ``qdata`` is the low-precision payload in the ORIGINAL shape; ``scale``
    is a float32 vector of dequantization multipliers — size 1 (per-tensor)
    or size ``shape[0]`` (per-row, the int8 framing for 2-D+ gradients,
    where one outlier row must not crush every other row's resolution);
    ``dtype`` is the original dtype the decoder restores. Built by
    :func:`quantize`; the decoder never sees this class — ``decode``
    dequantizes in place of constructing it."""

    __slots__ = ("qdata", "scale", "dtype")

    def __init__(self, qdata, scale, dtype):
        self.qdata = np.asarray(qdata)
        self.scale = np.ascontiguousarray(
            np.asarray(scale, np.float32).reshape(-1))
        self.dtype = np.dtype(dtype)
        rows = self.qdata.shape[0] if self.qdata.ndim else 1
        if self.scale.size not in (1, rows):
            raise WireError(
                f"quantized array: {self.scale.size} scales for {rows} rows "
                f"(want 1 or {rows})")

    @property
    def shape(self):
        return self.qdata.shape

    @property
    def wire_nbytes(self) -> int:
        """Payload bytes this frame ships (scales + quantized data) — what
        ``ps.wire.bytes_saved`` accounting compares against the dense size."""
        return self.qdata.nbytes + self.scale.nbytes


def quantize(arr, wire_dtype: str) -> QuantizedArray:
    """Quantize a float host array for the wire.

    int8 is symmetric: ``q = round(x / s)`` with the stored ``s`` the
    DEQUANT multiplier ``amax / 127`` — per row (axis 0) for 2-D+ arrays
    whose rows span >= 8 elements, per tensor otherwise (narrower rows
    cannot amortize a 4-byte f32 scale each: a (N, 1) grad would grow past
    its own float32 encoding); an all-zero row stores scale 0 and payload 0.
    fp16 stores a per-tensor scale that is 1.0 unless the tensor overflows
    float16's range (then ``amax / 65504``); bf16 is a pure cast (same
    exponent range as float32, scale stays 1.0)."""
    arr = np.asarray(arr)
    if not np.issubdtype(arr.dtype, np.floating):
        raise WireError(f"cannot quantize non-float dtype {arr.dtype}")
    x = arr.astype(np.float32, copy=False)
    if wire_dtype == "int8":
        if x.size == 0:
            q = np.zeros(x.shape, np.int8)
            nrows = x.shape[0] if x.ndim else 1
            return QuantizedArray(q, np.zeros(max(1, nrows), np.float32),
                                  arr.dtype)
        if x.ndim >= 2 and x.size // x.shape[0] >= 8:
            amax = np.max(np.abs(x), axis=tuple(range(1, x.ndim)),
                          keepdims=True)
        else:
            amax = np.max(np.abs(x)).reshape((1,) * x.ndim)
        scale = (amax / 127.0).astype(np.float32)
        safe = np.where(scale > 0.0, scale, np.float32(1.0))
        q = np.clip(np.rint(x / safe), -127.0, 127.0).astype(np.int8)
        return QuantizedArray(q, scale.reshape(-1), arr.dtype)
    qdtype = _wire_np_dtype(wire_dtype)
    amax = float(np.max(np.abs(x))) if x.size else 0.0
    scale = 1.0
    if wire_dtype == "fp16" and amax > 65504.0:
        scale = amax / 65504.0
    q = (x / np.float32(scale)).astype(qdtype) if scale != 1.0 \
        else x.astype(qdtype)
    return QuantizedArray(q, np.array([scale], np.float32), arr.dtype)


def _dequantize_raw(q, scale, dtype: np.dtype) -> np.ndarray:
    """Shared dequant core: always returns a FRESH writable dense array of
    ``dtype`` (``q.astype`` copies — the q payload may alias a recycled
    receive buffer, the result never does)."""
    x = np.asarray(q).astype(np.float32)
    scale = np.asarray(scale, np.float32).reshape(-1)
    if scale.size == 1:
        if scale[0] != 1.0:
            x *= scale[0]
    else:
        x *= scale.reshape((-1,) + (1,) * max(0, x.ndim - 1))
    return np.ascontiguousarray(x.astype(dtype, copy=False))


def dequantize(qa: QuantizedArray) -> np.ndarray:
    """Reconstruct the dense array a :func:`quantize` frame represents —
    the exact values a peer's ``decode`` would hand its apply path (the
    error-feedback residual is ``x - dequantize(quantize(x))``)."""
    return _dequantize_raw(qa.qdata, qa.scale, qa.dtype)


# ---------------------------------------------------------------------- encode

# Arrays at or above this many bytes are emitted as borrowed buffers by
# encode_parts; smaller ones are inlined into the adjacent header segment
# (a dedicated iovec per 8-byte scalar would cost more than the copy saves).
_BORROW_MIN_BYTES = 1024


class _PartSink:
    """bytearray-compatible accumulator that can split out borrowed buffers.

    ``_enc`` only ever does ``out += <bytes-like>``, so the same encoder body
    serves both faces: with a plain ``bytearray`` it produces one contiguous
    message (:func:`encode`); with a ``_PartSink`` large array payloads are
    appended as zero-copy views between the accumulated header segments
    (:func:`encode_parts`)."""

    __slots__ = ("parts", "tail")

    def __init__(self):
        self.parts: List[Any] = []
        self.tail = bytearray()

    def __iadd__(self, data):
        self.tail += data
        return self

    def borrow(self, view):
        """Append ``view`` (a memoryview over caller-owned memory) without
        copying; the caller must keep the backing memory unchanged until the
        parts have been sent."""
        if self.tail:
            self.parts.append(self.tail)
            self.tail = bytearray()
        self.parts.append(view)

    def finish(self) -> List[Any]:
        if self.tail:
            self.parts.append(self.tail)
            self.tail = bytearray()
        return self.parts


def _enc_str(out, s: str):
    b = s.encode("utf-8")
    out += _u32.pack(len(b))
    out += b


def _enc(out, obj: Any):
    if obj is None:
        out += b"N"
    elif obj is True:
        out += b"T"
    elif obj is False:
        out += b"F"
    elif type(obj) is int:  # exact: bool is handled above, np ints below
        if _I64_MIN <= obj <= _I64_MAX:
            out += b"i"
            out += _i64.pack(obj)
        else:
            out += b"I"
            _enc_str(out, str(obj))
    elif type(obj) is float:
        out += b"f"
        out += _f64.pack(obj)
    elif type(obj) is str:
        out += b"s"
        _enc_str(out, obj)
    elif type(obj) is bytes:
        out += b"b"
        out += _u64.pack(len(obj))
        out += obj
    elif type(obj) is QuantizedArray:
        # Quantized frame: orig dtype + payload dtype + shape + scale
        # section + raw low-precision bytes. Same borrow rule as tag "a":
        # the (large) payload rides as a zero-copy view under encode_parts.
        q = obj.qdata
        out += b"q"
        _enc_str(out, str(obj.dtype))
        _enc_str(out, str(q.dtype))
        out += bytes([q.ndim])
        for d in q.shape:
            out += _u64.pack(d)
        out += _u32.pack(obj.scale.size)
        out += obj.scale.tobytes()
        if (type(out) is _PartSink and q.nbytes >= _BORROW_MIN_BYTES
                and q.flags.c_contiguous):
            out += _u64.pack(q.nbytes)
            out.borrow(memoryview(q.reshape(-1).view(np.uint8)))
        else:
            raw = q.tobytes()
            out += _u64.pack(len(raw))
            out += raw
    elif isinstance(obj, (np.ndarray, np.generic)):
        # asarray, NOT ascontiguousarray: the latter promotes 0-d to 1-d,
        # silently reshaping scalar gradients. tobytes() below serializes in
        # C order whatever the memory layout.
        arr = np.asarray(obj)
        if arr.dtype.hasobject:
            # tobytes() on an object array would serialize raw heap POINTERS
            # — a memory-address leak the peer cannot decode anyway. Refuse
            # at encode time so the server's reply-encode error path reports
            # it as a server-side limitation.
            raise WireError("object-dtype arrays are not wire-encodable")
        out += b"a"
        _enc_str(out, str(arr.dtype))
        out += bytes([arr.ndim])
        for d in arr.shape:
            out += _u64.pack(d)
        if (type(out) is _PartSink and arr.nbytes >= _BORROW_MIN_BYTES
                and arr.flags.c_contiguous):
            # Zero-copy: the payload is the array's own memory. A C-contiguous
            # buffer viewed as flat uint8 is exactly tobytes()'s C-order
            # output, so the concatenated parts stay byte-identical to
            # encode(). (reshape(-1)/view are views here, never copies.)
            out += _u64.pack(arr.nbytes)
            out.borrow(memoryview(arr.reshape(-1).view(np.uint8)))
        else:
            raw = arr.tobytes()  # C-order buffer; works for custom dtypes too
            out += _u64.pack(len(raw))
            out += raw
    elif type(obj) is tuple:
        out += b"t"
        out += _u32.pack(len(obj))
        for item in obj:
            _enc(out, item)
    elif type(obj) is list:
        out += b"l"
        out += _u32.pack(len(obj))
        for item in obj:
            _enc(out, item)
    elif type(obj) is dict:
        out += b"d"
        out += _u32.pack(len(obj))
        for k, v in obj.items():
            _enc(out, k)
            _enc(out, v)
    elif type(obj) in _CLS_KEY:
        out += b"o"
        _enc_str(out, _CLS_KEY[type(obj)])
        fields = _REGISTRY[_CLS_KEY[type(obj)]][1]
        out += _u32.pack(len(fields))
        for name in fields:
            _enc_str(out, name)
            _enc(out, getattr(obj, name))
    else:
        # jax Arrays must be host-converted (_to_host) before sending; any
        # other type is outside the protocol vocabulary by design.
        raise WireError(
            f"type {type(obj).__name__} is not wire-encodable; convert device "
            f"arrays to numpy first or register the dataclass")


def encode(obj: Any) -> bytes:
    """Serialize a protocol message to bytes."""
    out = bytearray()
    _enc(out, obj)
    return bytes(out)


def encode_parts(obj: Any) -> List[Any]:
    """Serialize a protocol message as a scatter-gather buffer list.

    ``b"".join(encode_parts(obj)) == encode(obj)`` always holds — the parts
    are the SAME wire bytes, merely not concatenated. Large C-contiguous
    ndarray payloads come back as borrowed read-views of the arrays' own
    memory, so the caller (``ps_transport._send_payload``) can hand the list
    to ``socket.sendmsg`` and ship a multi-MB pytree with zero serialization
    copies. The views borrow: do not mutate the source arrays until the
    parts have been fully sent."""
    sink = _PartSink()
    _enc(sink, obj)
    return sink.finish()


# ---------------------------------------------------------------------- decode

class _Reader:
    __slots__ = ("buf", "pos", "copy")

    def __init__(self, buf, copy: bool = True):
        self.buf = memoryview(buf)
        self.pos = 0
        self.copy = copy

    def take(self, n: int) -> memoryview:
        if self.pos + n > len(self.buf):
            raise WireError("truncated wire message")
        v = self.buf[self.pos:self.pos + n]
        self.pos += n
        return v

    def u32(self) -> int:
        return _u32.unpack(self.take(4))[0]

    def u64(self) -> int:
        return _u64.unpack(self.take(8))[0]

    def str_(self) -> str:
        return str(self.take(self.u32()), "utf-8")


def dtype_from_name(name: str) -> np.dtype:
    """Resolve a dtype by its string name, including ml_dtypes customs
    (bfloat16, float8_*). Raises ValueError for unknown names — the single
    resolver shared by the wire codec and the checkpoint manifest reader."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        try:
            return np.dtype(getattr(ml_dtypes, name))
        except (AttributeError, TypeError):
            raise ValueError(f"unknown array dtype {name!r}") from None


def _np_dtype(name: str):
    try:
        return dtype_from_name(name)
    except ValueError as e:
        raise WireError(str(e)) from None


def _dec(r: _Reader) -> Any:
    tag = bytes(r.take(1))
    if tag == b"N":
        return None
    if tag == b"T":
        return True
    if tag == b"F":
        return False
    if tag == b"i":
        return _i64.unpack(r.take(8))[0]
    if tag == b"I":
        return int(r.str_())
    if tag == b"f":
        return _f64.unpack(r.take(8))[0]
    if tag == b"s":
        return r.str_()
    if tag == b"b":
        return bytes(r.take(r.u64()))
    if tag == b"a":
        dtype = _np_dtype(r.str_())
        ndim = bytes(r.take(1))[0]
        shape = tuple(r.u64() for _ in range(ndim))
        nbytes = r.u64()
        want = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if nbytes != want:
            raise WireError(f"array payload {nbytes}B != shape/dtype {want}B")
        flat = np.frombuffer(r.take(nbytes), np.uint8)
        if r.copy:
            # Copy: the caller may free the receive buffer after decode.
            flat = flat.copy()
        else:
            # Alias: the array keeps the receive buffer alive through its
            # .base chain; mark it read-only so a caller mutating a pulled
            # tree cannot scribble over a recycled buffer.
            flat.flags.writeable = False
        return flat.view(dtype).reshape(shape)
    if tag == b"q":
        dtype = _np_dtype(r.str_())
        qdtype = _np_dtype(r.str_())
        ndim = bytes(r.take(1))[0]
        shape = tuple(r.u64() for _ in range(ndim))
        nscales = r.u32()
        rows = shape[0] if ndim else 1
        if nscales not in (1, rows):
            raise WireError(f"quantized frame: {nscales} scales for {rows} "
                            f"row(s) (want 1 or {rows})")
        scale = np.frombuffer(r.take(4 * nscales), np.float32)
        nbytes = r.u64()
        want = int(np.prod(shape, dtype=np.int64)) * qdtype.itemsize
        if nbytes != want:
            raise WireError(
                f"quantized payload {nbytes}B != shape/dtype {want}B")
        q = np.frombuffer(r.take(nbytes), np.uint8).view(qdtype).reshape(shape)
        # Dequantize-on-decode: the apply path receives a plain dense array
        # of the original dtype. Dequantization allocates fresh memory, so
        # this frame never aliases the receive buffer in EITHER copy mode —
        # the copy flag only governs tag "a".
        return _dequantize_raw(q, scale, dtype)
    if tag == b"t":
        return tuple(_dec(r) for _ in range(r.u32()))
    if tag == b"l":
        return [_dec(r) for _ in range(r.u32())]
    if tag == b"d":
        n = r.u32()
        out = {}
        for _ in range(n):
            k = _dec(r)
            out[k] = _dec(r)
        return out
    if tag == b"o":
        key = r.str_()
        entry = _REGISTRY.get(key)
        if entry is None:
            raise WireError(f"unregistered wire dataclass {key!r}")
        cls, known = entry
        fields = {}
        for _ in range(r.u32()):
            name = r.str_()
            value = _dec(r)
            if name not in known:
                raise WireError(f"{key}: unexpected field {name!r}")
            fields[name] = value
        return cls(**fields)
    raise WireError(f"unknown wire tag {tag!r}")


def decode(buf, copy: bool = True) -> Any:
    """Deserialize one message (bytes/memoryview).

    ``copy=True`` (default): array data is copied out of ``buf``; the caller
    may free/reuse the buffer afterwards. ``copy=False``: arrays come back as
    READ-ONLY views aliasing ``buf`` — zero decode copies. The views keep the
    buffer alive (refcount), but a transport recycling the buffer (see
    ``ps_transport._RecvBuffer``) will overwrite it once every alias has been
    dropped, so only callers that consume the tree — e.g. feed it to a jitted
    function and drop it — before releasing their references should pass
    ``copy=False``.

    EVERY malformed-input failure surfaces as :class:`WireError` — including
    bad UTF-8, overflowing dims, unhashable dict keys, wrong dataclass
    fields, or absurd nesting — so a server can catch one exception type and
    treat it as 'broken peer' (anything else escaping decode is a server-side
    bug, not bad input)."""
    r = _Reader(buf, copy=copy)
    try:
        obj = _dec(r)
    except WireError:
        raise
    except Exception as e:
        raise WireError(f"malformed wire message: {type(e).__name__}: {e}") \
            from e
    if r.pos != len(r.buf):
        raise WireError(f"{len(r.buf) - r.pos} trailing bytes after message")
    return obj
