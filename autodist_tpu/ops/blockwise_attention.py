"""Blockwise (memory-efficient) attention in pure JAX.

Online-softmax attention scanned over key/value blocks: peak memory is
O(L * block) instead of O(L^2), fully differentiable (XLA differentiates the
scan), and runs on any backend. This is the reference semantics for the pallas
flash kernel, the backward path of :func:`flash_attention`, and the per-step local
operation of ring attention (the online-softmax merge is exactly the ring
accumulation rule).

The reference framework has no attention machinery at all (SURVEY.md §5.7); this
is part of the long-context capability the TPU build adds as first-class.
"""

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _merge(acc, m, l, scores, v_blk):
    """One online-softmax update (all f32).

    acc: [..., q, d] unnormalized output; m: [..., q] running max;
    l: [..., q] running denominator; scores: [..., q, k]; v_blk: [..., k, d].
    """
    m_new = jnp.maximum(m, scores.max(axis=-1))
    correction = jnp.exp(m - m_new)
    # Zero fully-masked entries explicitly: when a whole row is masked both scores
    # and m_new sit at NEG_INF and exp(0)=1 would poison the denominator.
    p = jnp.where(scores <= NEG_INF * 0.5, 0.0, jnp.exp(scores - m_new[..., None]))
    l_new = l * correction + p.sum(axis=-1)
    acc_new = acc * correction[..., None] + jnp.einsum("...qk,...kd->...qd", p, v_blk)
    return acc_new, m_new, l_new


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        causal: bool = True, block_size: int = 256,
                        q_offset: int = 0, k_offset: int = 0) -> jax.Array:
    """Memory-efficient attention. q/k/v: [B, L, H, D] (L may differ for q vs k/v).

    ``q_offset``/``k_offset`` are the global positions of the first query/key —
    ring attention passes the ring-shifted key offset so causal masking stays
    globally correct.
    """
    out, _, _ = _blockwise_inner(q, k, v, causal, block_size, q_offset, k_offset,
                                 init_carry=None)
    return out


def blockwise_attention_with_carry(q, k, v, carry, *, causal=True, block_size=256,
                                   q_offset=0, k_offset=0):
    """Ring-attention building block: same scan, but accepting and returning the
    (acc, m, l) carry so partial results merge across ring steps. Returns
    ((acc, m, l)); normalize with :func:`finalize` after the last step."""
    _, (acc, m, l), _ = _blockwise_inner(q, k, v, causal, block_size, q_offset,
                                         k_offset, init_carry=carry,
                                         return_carry=True)
    return acc, m, l


def finalize(acc, m, l):
    """Normalize an online-softmax carry into the attention output."""
    safe_l = jnp.maximum(l, 1e-30)
    return acc / safe_l[..., None]


def _blockwise_inner(q, k, v, causal, block_size, q_offset, k_offset, init_carry,
                     return_carry: bool = False):
    orig_dtype = q.dtype
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = 1.0 / (d ** 0.5)

    # [B, H, L, D] in f32 for the accumulation.
    qt = q.transpose(0, 2, 1, 3).astype(jnp.float32) * scale
    kt = k.transpose(0, 2, 1, 3).astype(jnp.float32)
    vt = v.transpose(0, 2, 1, 3).astype(jnp.float32)

    block = min(block_size, lk)
    n_blocks = (lk + block - 1) // block
    pad = n_blocks * block - lk
    if pad:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad), (0, 0)))

    if init_carry is None:
        acc0 = jnp.zeros((b, h, lq, d), jnp.float32)
        m0 = jnp.full((b, h, lq), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, lq), jnp.float32)
    else:
        acc0, m0, l0 = init_carry

    q_pos = q_offset + jnp.arange(lq)

    def body(carry, j):
        acc, m, l = carry
        k_blk = jax.lax.dynamic_slice_in_dim(kt, j * block, block, axis=2)
        v_blk = jax.lax.dynamic_slice_in_dim(vt, j * block, block, axis=2)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qt, k_blk)
        k_pos = k_offset + j * block + jnp.arange(block)
        invalid = k_pos >= (k_offset + lk)          # padding keys
        if causal:
            invalid = invalid[None, :] | (k_pos[None, :] > q_pos[:, None])
            scores = jnp.where(invalid[None, None], NEG_INF, scores)
        else:
            scores = jnp.where(invalid[None, None, None, :], NEG_INF, scores)
        return _merge(acc, m, l, scores, v_blk), None

    (acc, m, l), _ = jax.lax.scan(body, (acc0, m0, l0), jnp.arange(n_blocks))

    if return_carry:
        return None, (acc, m, l), None
    out = finalize(acc, m, l)                       # [B, H, Lq, D]
    return out.transpose(0, 2, 1, 3).astype(orig_dtype), None, None
