"""Cross-process bounded-staleness PS script (driver in test_multiprocess.py).

Role-split on AUTODIST_WORKER like any Coordinator-launched script. The chief
owns the AsyncPSRunner and serves it over the PS transport; it drives worker 0
SLOWLY (sleeping before each step). The worker process connects a
RemotePSWorker and steps FAST, recording per-step wall times. With
staleness=2 the fast worker must complete exactly 2 steps ahead, then block on
the chief's gate until the slow worker advances — the reference's c9 timing
assertion (``tests/integration/cases/c9.py:92-126``) across a real process
boundary. No jax.distributed here: async PS processes are independent JAX
programs joined only by the host transport, as the reference's were joined only
by grpc.
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from autodist_tpu import AutoDist, const  # noqa: E402
from autodist_tpu.strategy import PS  # noqa: E402

# AutoDist sees a single-node spec (no jax.distributed bootstrap); the 2-process
# launch runs over the Cluster/Coordinator with the transport address in env.
SINGLE_NODE = "nodes: [{address: localhost, tpus: 1, chief: true}]"
STALENESS = 2
SLOW_STEPS = 4
FAST_STEPS = 6
SLOW_SLEEP = 0.5
LR = 0.05


def make_batch():
    rng = np.random.RandomState(0)
    x = rng.randn(16).astype(np.float32)
    return {"x": x, "y": (3.0 * x + 2.0).astype(np.float32)}


def loss_fn(p, b):
    return jnp.mean((b["y"] - (b["x"] * p["w"] + p["b"])) ** 2)


def _make_runner():
    ad = AutoDist(SINGLE_NODE, PS(sync=True, staleness=STALENESS))
    params = {"w": np.zeros((), np.float32), "b": np.zeros((), np.float32)}
    runner = ad.create_distributed_session(
        loss_fn, params, optax.sgd(LR), example_batch=make_batch(),
        num_workers=2)
    return runner, params, ad


def chief_main(out_path: str):
    from autodist_tpu.cluster import Cluster
    from autodist_tpu.coordinator import Coordinator
    from autodist_tpu.parallel.ps_transport import PSServer
    from autodist_tpu.resource_spec import ResourceSpec

    runner, params, ad = _make_runner()
    state = runner.init(params)
    server = PSServer(runner, host="127.0.0.1")
    host, port = server.address

    cluster = Cluster(ResourceSpec(
        "nodes: [{address: localhost, tpus: 1, chief: true}, "
        "{address: 127.0.0.1, tpus: 1}]"))
    coordinator = Coordinator(ad._strategy, cluster,
                              argv=[os.path.abspath(__file__), out_path])
    coordinator.launch_clients(extra_env={"AUTODIST_PS_ADDR": f"{host}:{port}"})

    batch = make_batch()
    slow = runner.worker(0)
    # Compile the chief-side worker too, then wait for the remote's readiness
    # handshake so both sides enter the timed phase together.
    params_now, ef_now, _ = runner.service.read()
    with runner.mesh:
        jax.block_until_ready(
            runner.grad_fn(params_now, runner.shard_batch(batch), ef_now)[0])
    deadline = time.time() + 120
    while not os.path.exists(out_path + ".ready"):
        if time.time() > deadline:
            raise RuntimeError("remote worker never became ready")
        time.sleep(0.05)
    for _ in range(SLOW_STEPS):
        time.sleep(SLOW_SLEEP)
        slow.step(batch, timeout=60.0)

    if not coordinator.join(timeout=120.0):
        raise RuntimeError("worker process did not finish")
    # Total applied updates = both workers' steps.
    result = json.loads(open(out_path + ".worker").read())
    result["final_version"] = runner.service.version
    result["slow_steps"] = slow.steps_completed
    with open(out_path, "w") as f:
        json.dump(result, f)
    server.close()
    cluster.terminate()


def worker_main(out_path: str):
    from autodist_tpu.parallel.ps_transport import RemotePSWorker

    runner, _, _ad = _make_runner()  # loads the shipped strategy (AUTODIST_STRATEGY_ID)
    remote = RemotePSWorker(os.environ["AUTODIST_PS_ADDR"], runner, worker_id=1)
    batch = make_batch()
    # Compile before the timed loop, then tell the chief we're ready — process
    # startup must not eat the slow worker's head start.
    remote.warmup(batch)
    with open(out_path + ".ready", "w") as f:
        f.write("1")
    durations = []
    versions = []
    for _ in range(FAST_STEPS):
        t0 = time.perf_counter()
        remote.step(batch, timeout=60.0)
        durations.append(time.perf_counter() - t0)
        versions.append(remote.last_version_read)
    with open(out_path + ".worker", "w") as f:
        json.dump({"durations": durations, "versions_read": versions,
                   "fast_steps": remote.steps_completed}, f)
    remote.close()


if __name__ == "__main__":
    out = sys.argv[1]
    if const.is_worker():
        worker_main(out)
    else:
        chief_main(out)
