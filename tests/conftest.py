"""Test backend: an 8-device virtual CPU mesh.

The reference's CI needs real GPUs and two real machines (SURVEY.md §4); the TPU build
tests sharding semantics on a faked multi-chip backend instead:
``--xla_force_host_platform_device_count=8`` gives every test a deterministic 8-device
mesh with real XLA collectives. Must run before the first ``import jax``.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the image presets JAX_PLATFORMS=axon (real TPU)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("AUTODIST_IS_TESTING", "1")

import jax  # noqa: E402  (sitecustomize may have imported jax already — env alone is too late)

jax.config.update("jax_platforms", "cpu")
# Pin the backend NOW: initialization is otherwise lazy, and a test module
# that adjusts XLA_FLAGS for its own subprocesses (imported before the first
# device touch) would silently re-shape every later test's "8-device" mesh.
assert len(jax.devices()) == 8, jax.devices()

import pytest  # noqa: E402


def pytest_addoption(parser):
    # Reference conftest.py:4-17 gates integration tests behind --run-integration; kept
    # for workflow parity, though our integration tier runs fine on the CPU mesh.
    parser.addoption("--run-integration", action="store_true", default=False,
                     help="run tests marked integration")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--run-integration"):
        return
    skip = pytest.mark.skip(reason="needs --run-integration")
    for item in items:
        if "integration" in item.keywords and item.get_closest_marker("integration"):
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _graftsan_thread_fence():
    """graftsan ``threads`` fence: with ``AUTODIST_SANITIZE=threads`` armed,
    a test leaking a live non-daemon thread past its own teardown fails with
    every survivor's name and current stack (testing/sanitizer.py). Disarmed
    (the default), the fixture is a no-op yield."""
    from autodist_tpu.testing import sanitizer
    if "threads" not in sanitizer.modes():
        yield
        return
    with sanitizer.thread_fence(grace_s=2.0):
        yield
