"""Serving plane (autodist_tpu.serving): batcher, engines, wire, SLO metrics.

NAMED to sort inside the tier-1 alphabetical window (after test_aux, before
test_image_data). No subprocesses: the loopback legs run server + client in
THIS process over 127.0.0.1, the same pattern the PS transport tests use.

Coverage per the PR 7 contract:
- packing/bucketing units (jax-free, driven by a fake engine);
- early-exit slot reuse at decode-step granularity (continuous) vs wave
  admission (static);
- batch-1 served output is BIT-IDENTICAL to direct ``generate()`` /
  model ``apply`` for a fixed key (greedy and sampled);
- multi-slot continuous decode matches each request's batch-1 reference;
- wire round-trip including malformed-request rejection;
- ``serve.*`` SLO metric families present in ``telemetry.snapshot()`` with
  ms-scale bucket edges resolved via ``metrics.BUCKET_FAMILIES``.
"""

import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from autodist_tpu import serving, telemetry  # noqa: E402
from autodist_tpu.models import transformer_lm  # noqa: E402
from autodist_tpu.models.transformer_lm import (TransformerLMConfig,  # noqa: E402
                                                generate)
from autodist_tpu.serving import (Batcher, LMEngine, ServeConfig,  # noqa: E402
                                  ServeError, bucket_for, default_buckets,
                                  pad_prompt)


# ------------------------------------------------------------------ fixtures

def _small_cfg(**kw):
    kw.setdefault("vocab_size", 97)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("d_ff", 64)
    kw.setdefault("max_len", 32)
    kw.setdefault("dtype", jnp.float32)   # exact-comparison friendly
    return TransformerLMConfig(**kw)


@pytest.fixture(scope="module")
def lm():
    cfg = _small_cfg()
    model, params = transformer_lm.init_params(cfg)
    return model, params


@pytest.fixture(scope="module")
def greedy_engine(lm):
    """One shared greedy engine (capacity 2) — jit programs compile once for
    the whole module; tests free every slot they use."""
    model, params = lm
    return LMEngine(model, params, ServeConfig(max_batch=2))


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(1, 97, size=n).astype(np.int32)


def _drive(batcher, reqs, rounds=80):
    for _ in range(rounds):
        if all(r.done.is_set() for r in reqs):
            break
        batcher.run_once()
    assert all(r.done.is_set() for r in reqs), "batcher did not converge"


# ------------------------------------------------- bucketing / packing units

def test_default_buckets_power_of_two_with_max_cap():
    assert default_buckets(32) == (8, 16, 32)
    assert default_buckets(48) == (8, 16, 32, 48)   # non-pow2 max included
    assert default_buckets(8) == (8,)


def test_bucket_for_picks_smallest_fit_and_rejects_oversize():
    assert bucket_for(3, (8, 16)) == 8
    assert bucket_for(8, (8, 16)) == 8              # boundary lands in-bucket
    assert bucket_for(9, (8, 16)) == 16
    with pytest.raises(ServeError):
        bucket_for(17, (8, 16))


def test_pad_prompt_right_pads_to_bucket():
    p = np.array([5, 6, 7], np.int32)
    out = pad_prompt(p, 8)
    assert out.shape == (1, 8) and out.dtype == np.int32
    assert list(out[0]) == [5, 6, 7, 0, 0, 0, 0, 0]


def test_serve_config_validates_and_reads_env(monkeypatch):
    with pytest.raises(ValueError):
        ServeConfig(mode="adaptive")
    with pytest.raises(ValueError):
        ServeConfig(max_batch=0)
    with pytest.raises(ValueError):
        ServeConfig(buckets=(16, 8))
    monkeypatch.setenv("AUTODIST_SERVE_MAX_BATCH", "5")
    monkeypatch.setenv("AUTODIST_SERVE_MODE", "static")
    cfg = ServeConfig.from_env(max_queue=7)
    assert cfg.max_batch == 5 and cfg.mode == "static" and cfg.max_queue == 7


def test_transport_env_address_default(monkeypatch):
    """AUTODIST_SERVE_ADDR is the shared server-bind / client-target default;
    unset means loopback on an ephemeral port."""
    from autodist_tpu.serving import transport
    monkeypatch.delenv("AUTODIST_SERVE_ADDR", raising=False)
    assert transport._env_address() == ("127.0.0.1", 0)
    monkeypatch.setenv("AUTODIST_SERVE_ADDR", "10.0.0.5:7701")
    assert transport._env_address() == ("10.0.0.5", 7701)


# --------------------------------------------- fake-engine batcher semantics

class FakeEngine:
    """Deterministic jax-free engine: token = 100*slot + step index. Records
    every admit/free so tests can assert slot-reuse order."""

    def __init__(self, capacity=2, max_len=32):
        self.capacity = capacity
        self.max_len = max_len
        self.buckets = default_buckets(max_len)
        self.admits = []                  # (slot, prompt_len) in admit order
        self.freed = []
        self._steps = np.zeros(capacity, np.int64)

    def make_keys(self, seed, n):
        return None

    def admit(self, slot, prompt, key):
        self.admits.append((slot, int(prompt.size)))
        self._steps[slot] = 0
        return 100 * slot

    def step(self, keys):
        self._steps += 1
        return (100 * np.arange(self.capacity) + self._steps).astype(np.int32)

    def free(self, slot):
        self.freed.append(slot)


def test_continuous_early_exit_frees_slot_for_waiter():
    """Capacity 2, three requests; the SHORT one exits early and its slot is
    reused by the waiter at decode-step granularity — the long request never
    drains first (no convoy)."""
    eng = FakeEngine(capacity=2)
    b = Batcher(eng, ServeConfig(max_batch=2), start=False)
    r_long = b.submit(_prompt(4), 8)
    r_short = b.submit(_prompt(3), 2)
    r_wait = b.submit(_prompt(5), 2)
    b.run_once()                  # admits long+short; short: 2 tokens after 1 step
    assert [s for s, _ in eng.admits] == [0, 1]
    assert b.queue_depth() == 1   # waiter still queued
    b.run_once()                  # short finished last round -> waiter admitted
    assert r_short.done.is_set() and not r_long.done.is_set()
    assert eng.admits[2][0] == eng.freed[0]      # reused the freed slot
    _drive(b, [r_long, r_wait])
    assert not r_long.error and not r_wait.error
    # Token streams: admit token then per-step tokens for the request's slot.
    assert r_short.tokens[0] == 100 * r_short.slot
    assert len(r_long.tokens) == 8 and len(r_wait.tokens) == 2


def test_static_mode_admits_only_full_waves():
    eng = FakeEngine(capacity=2)
    b = Batcher(eng, ServeConfig(max_batch=2, mode="static"), start=False)
    reqs = [b.submit(_prompt(3), n) for n in (2, 4, 2)]
    b.run_once()
    assert len(eng.admits) == 2          # wave of 2 admitted
    b.run_once()                         # first short request done; one slot free
    assert len(eng.admits) == 2          # static: NO mid-wave admission
    _drive(b, reqs)
    assert len(eng.admits) == 3          # third admitted only after the drain


def test_queue_full_rejects_instantly():
    eng = FakeEngine(capacity=1)
    b = Batcher(eng, ServeConfig(max_batch=1, max_queue=1), start=False)
    b.submit(_prompt(3), 2)
    with pytest.raises(ServeError, match="queue is full"):
        b.submit(_prompt(3), 2)


def test_submit_validation_rejects_malformed_requests():
    eng = FakeEngine(capacity=1, max_len=32)
    b = Batcher(eng, ServeConfig(max_batch=1), start=False)
    with pytest.raises(ServeError, match="1-D integer"):
        b.submit(np.zeros((2, 3), np.int32), 2)           # wrong rank
    with pytest.raises(ServeError, match="1-D integer"):
        b.submit(np.zeros(3, np.float32), 2)              # wrong dtype
    with pytest.raises(ServeError, match="positive int"):
        b.submit(_prompt(3), 0)                           # no tokens asked
    with pytest.raises(ServeError, match="exceeds"):
        b.submit(_prompt(3), 64)                          # cache overflow
    with pytest.raises(ServeError, match="pad bucket"):
        b.submit(_prompt(33), 1)                          # oversize prompt


def test_eos_stops_generation_early(lm):
    """A fake engine emitting the configured EOS id ends the request before
    its token budget; the EOS is the last emitted token."""

    class EosEngine(FakeEngine):
        def step(self, keys):
            toks = super().step(keys)
            return np.where(self._steps == 2, 7, toks).astype(np.int32)

    eng = EosEngine(capacity=1)
    b = Batcher(eng, ServeConfig(max_batch=1, eos_id=7), start=False)
    req = b.submit(_prompt(3), 10)
    _drive(b, [req])
    assert req.tokens[-1] == 7 and len(req.tokens) == 3   # admit + 2 steps


def test_close_fails_pending_requests():
    eng = FakeEngine(capacity=1)
    b = Batcher(eng, ServeConfig(max_batch=1), start=False)
    req = b.submit(_prompt(3), 4)
    b.close()
    assert req.done.is_set() and "shutting down" in req.error


def test_abandoned_queued_request_never_reaches_the_device():
    """A waiter whose client gave up (transport timeout -> abandon()) is
    dropped at the admission pop: no prefill, no decode, slot goes to the
    next live waiter."""
    eng = FakeEngine(capacity=1)
    b = Batcher(eng, ServeConfig(max_batch=1), start=False)
    r_active = b.submit(_prompt(3), 3)
    r_dead = b.submit(_prompt(4), 3)
    r_live = b.submit(_prompt(5), 2)
    r_dead.abandon()
    _drive(b, [r_active, r_dead, r_live])
    assert "abandoned" in r_dead.error and not r_dead.tokens
    assert r_live.error is None and len(r_live.tokens) == 2
    # Only the two live requests were ever admitted (prompt lens 3 and 5).
    assert [n for _, n in eng.admits] == [3, 5]


def test_abandoned_inflight_request_frees_its_slot_early():
    """An active request whose client gave up leaves the batch at the next
    scheduling round — its remaining decode budget goes to the waiter."""
    eng = FakeEngine(capacity=1)
    b = Batcher(eng, ServeConfig(max_batch=1), start=False)
    r_dead = b.submit(_prompt(3), 20)
    r_live = b.submit(_prompt(4), 2)
    b.run_once()                  # admits r_dead, one decode step
    assert r_dead.slot == 0 and not r_dead.done.is_set()
    r_dead.abandon()
    b.run_once()                  # drop r_dead, slot refilled by r_live
    assert r_dead.done.is_set() and "abandoned" in r_dead.error
    assert len(r_dead.tokens) < 20
    _drive(b, [r_live])
    assert r_live.error is None and eng.freed[0] == 0


def test_expired_inflight_request_is_dropped_mid_generation():
    """Deadline expiry applies to ADMITTED requests too (no transport, so
    nothing calls abandon()): a slow generation past request_timeout_s frees
    its slot at the next decode round."""
    import time as _time
    eng = FakeEngine(capacity=1)
    b = Batcher(eng, ServeConfig(max_batch=1, request_timeout_s=0.05),
                start=False)
    req = b.submit(_prompt(3), 20)
    b.run_once()                  # admitted before the deadline check matters
    assert req.slot == 0 and not req.done.is_set()
    _time.sleep(0.1)              # deadline passes mid-generation
    b.run_once()
    assert req.done.is_set() and "timed out" in req.error
    assert len(req.tokens) < 20 and eng.freed == [0]


def test_submit_after_close_rejects_instantly():
    """A request arriving after close() gets an immediate rejection, not a
    full-timeout park on a queue nobody drains."""
    eng = FakeEngine(capacity=1)
    b = Batcher(eng, ServeConfig(max_batch=1), start=False)
    b.close()
    with pytest.raises(ServeError, match="shutting down"):
        b.submit(_prompt(3), 2)


def test_expired_queued_request_is_dropped_at_admission():
    """A waiter that outlived request_timeout_s in the queue is dropped at
    the pop instead of burning decode on a reply nobody is waiting for."""
    import time as _time
    eng = FakeEngine(capacity=1)
    b = Batcher(eng, ServeConfig(max_batch=1, request_timeout_s=0.005),
                start=False)
    r1 = b.submit(_prompt(3), 2)
    r2 = b.submit(_prompt(4), 2)
    _time.sleep(0.02)             # both deadlines pass while queued
    b.run_once()
    assert "timed out" in r1.error and "timed out" in r2.error
    assert not eng.admits


# ----------------------------------------------------- LM engine parity legs

def test_batch1_greedy_parity_vs_generate(lm, greedy_engine):
    """Served greedy output == direct generate() bit for bit (the KV-cache
    slot path, padded prefill and per-row decode positions included)."""
    model, params = lm
    b = Batcher(greedy_engine, greedy_engine.config, start=False)
    prompt = _prompt(7, seed=1)
    ref = np.asarray(generate(model, params, jnp.asarray(prompt[None]), 8))[0]
    req = b.submit(prompt, 8)
    _drive(b, [req])
    assert req.error is None
    np.testing.assert_array_equal(ref, np.asarray(req.tokens, np.int32))


def test_batch1_sampled_parity_vs_generate(lm):
    """Sampled path: the engine replays generate()'s exact per-step key
    schedule for the request's seed, so the served stream is bit-identical
    even though other requests share the decode batch."""
    model, params = lm
    scfg = ServeConfig(max_batch=2, temperature=0.8, top_k=5)
    eng = LMEngine(model, params, scfg)
    b = Batcher(eng, scfg, start=False)
    prompt = _prompt(6, seed=2)
    ref = np.asarray(generate(model, params, jnp.asarray(prompt[None]), 6,
                              temperature=0.8, top_k=5,
                              rng=jax.random.PRNGKey(3)))[0]
    req = b.submit(prompt, 6, seed=3)
    _drive(b, [req])
    assert req.error is None
    np.testing.assert_array_equal(ref, np.asarray(req.tokens, np.int32))


def test_concurrent_slots_match_batch1_references(lm, greedy_engine):
    """Three requests with different prompt lengths and budgets through a
    2-slot continuous batch — every stream equals its own batch-1 generate()
    (per-row decode positions keep slots independent; early exits reuse
    slots mid-flight)."""
    model, params = lm
    b = Batcher(greedy_engine, greedy_engine.config, start=False)
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, 97, size=n).astype(np.int32) for n in (3, 11, 6)]
    news = [9, 4, 7]
    refs = [np.asarray(generate(model, params, jnp.asarray(p[None]), n))[0]
            for p, n in zip(prompts, news)]
    reqs = [b.submit(p, n) for p, n in zip(prompts, news)]
    _drive(b, reqs)
    for ref, req in zip(refs, reqs):
        assert req.error is None
        np.testing.assert_array_equal(ref, np.asarray(req.tokens, np.int32))


def test_jit_cache_is_bounded_by_buckets(lm, greedy_engine):
    """Admissions at many prompt lengths compile one prefill per BUCKET, not
    per length — the batcher's churn never compiles."""
    n_prefill, _ = greedy_engine.compiled_programs()
    assert n_prefill <= len(greedy_engine.buckets)


# ------------------------------------------------------- apply (stateless)

def test_apply_engine_parity_and_padding(lm):
    """Served stateless outputs == direct apply at batch 1; a 3-request batch
    pads to 4 and the pad outputs are dropped."""
    rng = np.random.RandomState(0)
    W = rng.randn(5, 3).astype(np.float32)

    def apply_fn(params, x):
        return x @ params["w"]

    params = {"w": W}
    eng = serving.ApplyEngine(apply_fn, params, ServeConfig(max_batch=4))
    b = serving.ApplyBatcher(eng, ServeConfig(max_batch=4), start=False)
    xs = [rng.randn(5).astype(np.float32) for _ in range(3)]
    reqs = [b.submit(x) for x in xs]
    _drive(b, reqs)
    # Bit-identity reference: the SAME jitted program at the padded batch
    # shape (an eager numpy matmul can differ in the last ulp from XLA's).
    stacked = np.stack(xs + [xs[-1]], axis=0)           # padded to 4
    refs = np.asarray(jax.jit(apply_fn)(params, stacked))
    for i, req in enumerate(reqs):
        assert req.error is None
        np.testing.assert_array_equal(refs[i], req.output)


# ------------------------------------------------------------ wire loopback

def test_loopback_server_client_end_to_end(lm, greedy_engine):
    """Concurrent clients against a live continuous-batching server: every
    stream equals its batch-1 generate() reference, timings are populated,
    stats and ping work, malformed requests are rejected with typed errors
    and the connection survives them. Reuses the module engine so this leg
    adds no compiles beyond its generate() references."""
    model, params = lm
    server = serving.InferenceServer(
        Batcher(greedy_engine, greedy_engine.config))
    try:
        rng = np.random.RandomState(11)
        prompts = [rng.randint(1, 97, size=n).astype(np.int32)
                   for n in (5, 9)]
        refs = [np.asarray(generate(model, params,
                                    jnp.asarray(p[None]), 5))[0]
                for p in prompts]
        results = [None] * len(prompts)

        def hit(i):
            c = serving.ServeClient(server.address)
            try:
                results[i] = c.generate(prompts[i], 5)
            finally:
                c.close()

        threads = [threading.Thread(target=hit, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        for ref, res in zip(refs, results):
            assert res is not None, "client thread did not finish"
            toks, timing = res
            np.testing.assert_array_equal(ref, toks)
            assert set(timing) == {"request_id", "queue_s", "prefill_s",
                                   "decode_s", "total_s"}
            assert timing["total_s"] >= 0.0
            assert isinstance(timing["request_id"], int)

        c = serving.ServeClient(server.address)
        try:
            assert c.ping() < 60.0
            st = c.stats()
            assert st["kind"] == "lm" and st["mode"] == "continuous"
            assert st["registry"]["serve.requests.completed"] >= 3
            # Malformed requests: typed rejections, connection stays usable.
            with pytest.raises(ServeError, match="pad bucket"):
                c.generate(np.arange(100, dtype=np.int32), 5)
            with pytest.raises(ServeError, match="positive int"):
                c.generate(prompts[0], 0)
            with pytest.raises(ServeError, match="'infer' op|LM batcher"):
                c.infer({"x": np.zeros(3, np.float32)})
            # A protocol-shaped-but-bogus message gets an error reply, not a
            # dropped server.
            reply = c._client.call_raw((123, "nope"), c._client.wire)
            assert reply[0] == "error" and "malformed" in reply[2]
            reply = c._client.call_raw(("warp", 1), c._client.wire)
            assert reply[0] == "error" and "unknown op" in reply[2]
            # ...and the same connection still serves real requests.
            toks, _ = c.generate(prompts[0], 5)
            np.testing.assert_array_equal(refs[0], toks)
        finally:
            c.close()
    finally:
        server.close()


def test_loopback_apply_server(lm):
    rng = np.random.RandomState(3)
    W = rng.randn(4, 2).astype(np.float32)
    params = {"w": W}

    def apply_fn(params, x):
        return x @ params["w"]

    scfg = ServeConfig(max_batch=4)
    server = serving.InferenceServer(
        serving.ApplyBatcher(serving.ApplyEngine(apply_fn, params, scfg),
                             scfg))
    try:
        c = serving.ServeClient(server.address)
        try:
            x = rng.randn(4).astype(np.float32)
            out, timing = c.infer(x)
            # Same jitted program, same batch shape -> bit-identical.
            ref = np.asarray(jax.jit(apply_fn)(params, x[None]))[0]
            np.testing.assert_array_equal(ref, out)
            with pytest.raises(ServeError, match="'generate' op|apply"):
                c.generate(np.arange(3, dtype=np.int32), 2)
            assert c.stats()["kind"] == "apply"
        finally:
            c.close()
    finally:
        server.close()


# ------------------------------------------------------------- SLO metrics

def test_slo_metric_families_in_snapshot(lm, greedy_engine):
    """The serve.* families land in the process-global telemetry snapshot,
    and the latency histograms carry the ms-scale family buckets (not the
    step-time defaults) so a loopback distribution actually resolves."""
    b = Batcher(greedy_engine, greedy_engine.config, start=False)
    req = b.submit(_prompt(4, seed=5), 2)
    _drive(b, [req])
    snap = telemetry.snapshot()
    for fam in ("queue", "prefill", "decode", "total"):
        h = snap[f"serve.latency_s.{fam}"]
        assert h["count"] >= 1
        from autodist_tpu.telemetry import metrics as tmetrics
        for edge in tmetrics.MS_BUCKETS:
            assert f"le:{edge:g}" in h
    for name in ("serve.requests.submitted", "serve.requests.completed",
                 "serve.requests.rejected", "serve.queue_depth",
                 "serve.batch_fill"):
        assert name in snap
