"""Version-conditional PS pulls (read_if_newer): the transport's bandwidth valve.

The reference cached parameter reads in proxy variables
(``kernel/common/proxy_variable.py:74-114``) so a worker never re-fetched
unchanged values; here the equivalent is a version-conditional pull on the PS
transport. These tests assert the semantics at the service layer and measure
the wire saving end-to-end over a real loopback PSServer with a ~10M-param
model: a pull at an unchanged version ships bytes(version reply) instead of
bytes(parameter tree).
"""

import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu import AutoDist
from autodist_tpu.strategy import PS

PARAM_ROWS, PARAM_COLS = 2500, 1000  # 10 MB of f32 -> 10M bytes on the wire
BATCH = 16


def _params():
    rng = np.random.RandomState(0)
    return {"w": rng.randn(PARAM_ROWS, PARAM_COLS).astype(np.float32) * 0.01,
            "b": np.zeros((PARAM_COLS,), np.float32)}


def _data(seed=1):
    rng = np.random.RandomState(seed)
    x = rng.randn(BATCH, PARAM_ROWS).astype(np.float32)
    y = rng.randn(BATCH, PARAM_COLS).astype(np.float32)
    return {"x": x, "y": y}


def _loss(p, b):
    return jnp.mean((b["y"] - b["x"] @ p["w"] - p["b"]) ** 2)


# --------------------------------------------------------------- service unit

def test_read_if_newer_semantics():
    from autodist_tpu.parallel.staleness import ParameterService
    from autodist_tpu.runner import TrainState

    state = TrainState(step=np.zeros((), np.int32), params={"w": jnp.ones((2,))},
                       opt_state=(), ef_state=())
    calls = []

    def apply_fn(s, grads):
        calls.append(grads)
        return TrainState(step=s.step + 1,
                          params={"w": s.params["w"] - grads["w"]},
                          opt_state=(), ef_state=())

    svc = ParameterService(state, apply_fn)
    params, ef, version = svc.read_if_newer(-1)
    assert version == 0 and params is not None

    # Unchanged version: no tree, same version back.
    params2, ef2, version2 = svc.read_if_newer(0)
    assert params2 is None and ef2 is None and version2 == 0

    svc.apply({"w": jnp.ones((2,)) * 0.5})
    params3, _, version3 = svc.read_if_newer(0)
    assert version3 == 1
    np.testing.assert_allclose(np.asarray(params3["w"]), 0.5)


# ----------------------------------------------------- loopback wire accounting

def test_conditional_pull_saves_wire_bytes():
    """Over a real PSServer: a pull at an unchanged version must cost ~0
    parameter bytes, while a post-apply pull ships the full ~10 MB tree; and
    stepping through the conditional path stays value-identical to the
    service's own state."""
    from autodist_tpu.parallel.ps_transport import PSServer, RemotePSWorker

    ad = AutoDist(strategy_builder=PS(sync=False))
    runner = ad.create_distributed_session(
        _loss, _params(), optax.sgd(0.01), example_batch=_data(), num_workers=1)
    state = runner.init(_params())
    server = PSServer(runner, host="127.0.0.1")
    host, port = server.address
    remote = RemotePSWorker(f"{host}:{port}", runner, worker_id=0)
    try:
        batch = _data()
        param_bytes = (PARAM_ROWS * PARAM_COLS + PARAM_COLS) * 4

        remote.warmup(batch)  # full pull: seeds the conditional-read cache
        _, received_after_warmup = remote.wire_bytes
        assert received_after_warmup >= param_bytes

        # First step: gate opens with no intervening applies -> the read is
        # version-only. The step's OWN apply then advances the version.
        remote.step(batch, timeout=30.0)
        sent_1, received_1 = remote.wire_bytes
        read_cost_step1 = received_1 - received_after_warmup
        assert read_cost_step1 < 64 * 1024, (
            f"conditional pull shipped {read_cost_step1} bytes at an "
            f"unchanged version (expected ~0 of the {param_bytes}-byte tree)")

        # Second step: the previous apply changed the params -> full pull.
        remote.step(batch, timeout=30.0)
        _, received_2 = remote.wire_bytes
        assert received_2 - received_1 >= param_bytes

        # The worker's cached tree tracks the service exactly.
        pulled, _, version = remote._pull()  # monitoring pull: not modified
        assert version == runner.service.version
        np.testing.assert_allclose(
            np.asarray(pulled["w"]),
            np.asarray(runner.service.state.params["w"]), rtol=1e-6)
    finally:
        remote.close()
        server.close()


def test_conditional_pull_sees_checkpoint_restore():
    """restore()/adopt() must defeat the conditional-pull cache: the version is
    a never-reused generation counter, so a worker that cached params at some
    version can never be told 'not modified' about a restored state."""
    import dataclasses

    from autodist_tpu.parallel.ps_transport import PSServer, RemotePSWorker

    ad = AutoDist(strategy_builder=PS(sync=False))
    runner = ad.create_distributed_session(
        _loss, _params(), optax.sgd(0.01), example_batch=_data(), num_workers=1)
    state = runner.init(_params())
    server = PSServer(runner, host="127.0.0.1")
    host, port = server.address
    remote = RemotePSWorker(f"{host}:{port}", runner, worker_id=0)
    try:
        remote.warmup(_data())  # caches params at the initial version
        restored = dataclasses.replace(
            state, params={"w": jnp.ones((PARAM_ROWS, PARAM_COLS)),
                           "b": jnp.ones((PARAM_COLS,))})
        runner.restore(restored)
        params, _, version = remote._pull()
        assert version == 1  # reset opened generation 1, it did not restart at 0
        np.testing.assert_allclose(np.asarray(params["w"]), 1.0)
    finally:
        remote.close()
        server.close()


def test_conditional_pull_concurrent_writer_still_fresh():
    """A second writer applying between a worker's pulls must defeat the cache:
    read_if_newer returns the NEW tree, never a stale cached one."""
    from autodist_tpu.parallel.ps_transport import PSServer, RemotePSWorker

    ad = AutoDist(strategy_builder=PS(sync=False))
    runner = ad.create_distributed_session(
        _loss, _params(), optax.sgd(0.01), example_batch=_data(), num_workers=2)
    runner.init(_params())
    server = PSServer(runner, host="127.0.0.1")
    host, port = server.address
    remote = RemotePSWorker(f"{host}:{port}", runner, worker_id=0)
    try:
        batch = _data()
        remote.warmup(batch)
        v0 = remote.last_version_read
        # In-process worker 1 applies an update behind the remote's back.
        runner.worker(1).step(batch, timeout=30.0)
        params, _, v1 = remote._pull()
        assert v1 == v0 + 1
        np.testing.assert_allclose(
            np.asarray(params["w"]),
            np.asarray(runner.service.state.params["w"]), rtol=1e-6)
    finally:
        remote.close()
        server.close()
