"""ResNet for ImageNet-class benchmarks.

Counterpart of the reference's ImageNet CNN benchmark models
(``examples/benchmark/imagenet.py:150-170`` ran Keras ResNet101/VGG16/DenseNet121/
InceptionV3; the driver's north-star config is ResNet-50). TPU-first choices:
NHWC layout, bfloat16 activations with float32 params, and GroupNorm instead of
BatchNorm so the train step stays a pure function of (params, batch) — no mutable
running statistics to thread through the distributed state (cross-replica BatchNorm
would otherwise need its own sync path).
"""

import dataclasses
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.models.common import num_groups


@dataclasses.dataclass(frozen=True)
class ResNet50Config:
    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)      # ResNet-50
    width: int = 64
    dtype: Any = jnp.bfloat16
    norm_groups: int = 32
    # "group" (default; pure function, no running stats) or "batch" —
    # cross-replica sync-BN: statistics are computed in-graph over the batch
    # axes, so under pjit with a batch-sharded input the mean/var reduce over
    # the GLOBAL batch (GSPMD inserts the cross-replica collectives). Matches
    # the reference Keras models' train-time normalization; running averages
    # for eval are intentionally not tracked DURING TRAINING (the train step
    # stays pure). For inference parity with reference BatchNorm, a post-hoc
    # calibration pass (:func:`calibrate_bn_ema`) EMAs (mean, var) per norm
    # site into a ``bn_ema`` collection carried OUTSIDE params, and
    # ``bn_ema=True`` makes every SyncBatchNorm normalize with those stored
    # statistics instead of the eval batch's own.
    norm: str = "group"
    bn_ema: bool = False


class SyncBatchNorm(nn.Module):
    """Train-mode BatchNorm as a pure function: normalize by THIS batch's
    statistics (no mutable running averages). Under a data-sharded ``pjit``
    the reductions below span the global batch — this is sync-BN, the
    distributed-framework capability the reference delegated to
    ``CollectiveReduce`` in TF's BN layers.

    Inference parity (flag-gated, default off): with ``use_ema=True`` the
    layer normalizes with stored (mean, var) read from the ``bn_ema``
    collection — reference BatchNorm's inference mode — instead of the eval
    batch's own moments. The stored statistics live OUTSIDE params (the train
    step stays a pure function of (params, batch)); :func:`calibrate_bn_ema`
    produces them post hoc. In batch-stats mode the layer additionally sows
    its per-batch (mean, var) into a ``bn_stats`` collection — a no-op unless
    the caller asks for it with ``mutable=["bn_stats"]`` (the calibration
    pass does; training never does)."""
    dtype: Any = jnp.bfloat16
    epsilon: float = 1e-5
    use_ema: bool = False

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        # f32 statistics regardless of activation dtype (bf16 mean/var over a
        # global batch loses too much precision).
        xf = x.astype(jnp.float32)
        if self.use_ema:
            stats = self.variable("bn_ema", "stats",
                                  lambda: jnp.zeros((2, c), jnp.float32))
            mean, var = stats.value[0], stats.value[1]
        else:
            mean = xf.mean(axis=(0, 1, 2))
            var = ((xf - mean) ** 2).mean(axis=(0, 1, 2))
            self.sow("bn_stats", "batch", jnp.stack([mean, var]))
        y = (xf - mean) * jax.lax.rsqrt(var + self.epsilon)
        return (y * scale + bias).astype(self.dtype)


def _make_norm(cfg: ResNet50Config, channels: int, name: str):
    if cfg.norm == "batch":
        return SyncBatchNorm(dtype=cfg.dtype, use_ema=cfg.bn_ema, name=name)
    return nn.GroupNorm(num_groups=num_groups(channels, cfg.norm_groups),
                        dtype=cfg.dtype, name=name)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    config: ResNet50Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        norm = lambda name: _make_norm(cfg, self.filters, name)  # noqa: E731
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=cfg.dtype,
                    param_dtype=jnp.float32, name="conv1")(x)
        y = nn.relu(norm("norm1")(y))
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                    use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32,
                    name="conv2")(y)
        y = nn.relu(norm("norm2")(y))
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False, dtype=cfg.dtype,
                    param_dtype=jnp.float32, name="conv3")(y)
        y = _make_norm(cfg, self.filters * 4, "norm3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters * 4, (1, 1),
                               strides=(self.strides, self.strides), use_bias=False,
                               dtype=cfg.dtype, param_dtype=jnp.float32,
                               name="proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    config: ResNet50Config

    @nn.compact
    def __call__(self, images):
        cfg = self.config
        x = images.astype(cfg.dtype)
        x = nn.Conv(cfg.width, (7, 7), strides=(2, 2), use_bias=False,
                    dtype=cfg.dtype, param_dtype=jnp.float32, name="conv_init")(x)
        x = nn.relu(_make_norm(cfg, cfg.width, "norm_init")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(cfg.stage_sizes):
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(cfg.width * 2 ** stage, strides, cfg,
                                    name=f"stage{stage}_block{block}")(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(x)


def calibrate_bn_ema(model: "ResNet", params, image_batches,
                     momentum: float = 0.9):
    """EMA of every SyncBatchNorm site's (mean, var) over calibration batches.

    The classic BN-recalibration pass: forward-only passes (no label use, no
    updates) through a ``norm="batch"`` model in batch-stats mode, folding
    each batch's per-site moments into an exponential moving average. Returns
    the ``bn_ema`` collection pytree — statistics carried OUTSIDE params —
    that ``ResNet50Config(bn_ema=True)`` models read at inference, restoring
    the reference BatchNorm's eval behavior (accuracy independent of eval
    batch size/composition). ``image_batches`` yields preprocessed image
    arrays ``[B, H, W, C]``."""
    if model.config.bn_ema:
        raise ValueError("calibrate with a batch-stats model "
                         "(ResNet50Config(bn_ema=False)); the EMA-reading "
                         "model is for inference")

    @jax.jit
    def batch_stats(p, images):
        _, muts = model.apply({"params": p}, images, mutable=["bn_stats"])
        return muts["bn_stats"]

    def to_ema(tree):
        # sow() wraps each sown value in a 1-tuple under leaf key "batch";
        # the bn_ema collection stores the same [2, C] stack under "stats".
        if isinstance(tree, dict):
            return {("stats" if k == "batch" else k): to_ema(v)
                    for k, v in tree.items()}
        return tree[0] if isinstance(tree, tuple) else tree

    ema = None
    for images in image_batches:
        stats = to_ema(jax.device_get(batch_stats(params, images)))
        if ema is None:
            ema = stats
        else:
            ema = jax.tree_util.tree_map(
                lambda e, s: momentum * e + (1.0 - momentum) * s, ema, stats)
    if ema is None:
        raise ValueError("calibrate_bn_ema needs at least one batch")
    return ema


def make_loss_fn(model: ResNet) -> Callable:
    from autodist_tpu.models.common import make_classification_loss_fn
    return make_classification_loss_fn(model)


def init_params(config: ResNet50Config, rng=None, image_size: int = 224):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    model = ResNet(config)
    images = jnp.zeros((2, image_size, image_size, 3), jnp.float32)
    from autodist_tpu.models.common import jit_init
    return model, jit_init(model, images, rng=rng)


def synthetic_batch(config: ResNet50Config, batch_size: int, image_size: int = 224,
                    seed: int = 0):
    rng = np.random.RandomState(seed)
    return {
        "images": rng.randn(batch_size, image_size, image_size, 3).astype(np.float32),
        "labels": rng.randint(0, config.num_classes, size=(batch_size,)).astype(np.int32),
    }
