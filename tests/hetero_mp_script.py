"""Heterogeneous 2+1-device, 2-process training script (driver in
test_multiprocess.py; not collected by pytest).

The reference supported clusters with unequal per-node GPU counts and asserted
weighted-mean gradient correctness (``resource_specs/r4.yml``,
``tests/integration/cases/c0.py:110-120``). The SPMD equivalent: the chief
contributes 2 CPU devices, the worker 1, the global mesh has 3 equal batch
shards, and the per-node weighting falls out of equal per-device shards.
"""

import json
import os
import sys

# Per-role local device count BEFORE the backend initializes: chief 2, worker 1.
# ONLY when running as the script — mutating XLA_FLAGS on a mere import would
# poison the importing pytest process's own (lazy) backend init, flipping its
# 8-device mesh to 2 for every later test in that process.
if __name__ == "__main__":
    _worker = bool(os.environ.get("AUTODIST_WORKER"))
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={1 if _worker else 2}")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from autodist_tpu import AutoDist  # noqa: E402
from autodist_tpu.strategy import AllReduce  # noqa: E402

SPEC = ("nodes: [{address: localhost, tpus: 2, chief: true}, "
        "{address: 127.0.0.1, tpus: 1}]")
BATCH = 15  # 5 examples per device over 3 devices
LR = 0.1
STEPS = 3


def make_batch(step: int):
    rng = np.random.RandomState(2000 + step)
    x = rng.randn(BATCH).astype(np.float32)
    y = (3.0 * x + 2.0 + 0.1 * rng.randn(BATCH)).astype(np.float32)
    return {"x": x, "y": y}


def loss_fn(p, b):
    pred = b["x"] * p["w"] + p["b"]
    return jnp.mean((b["y"] - pred) ** 2)


def main(out_path: str):
    ad = AutoDist(SPEC, AllReduce())
    params = {"w": np.zeros((), np.float32), "b": np.zeros((), np.float32)}
    runner = ad.create_distributed_session(
        loss_fn, params, optax.sgd(LR), example_batch=make_batch(0))
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 3, jax.device_count()

    state = runner.init(params)
    losses = []
    for step in range(STEPS):
        state, loss = runner.run(state, make_batch(step))
        losses.append(float(loss))

    if jax.process_index() == 0:
        with open(out_path, "w") as f:
            json.dump({"w": float(state.params["w"]), "b": float(state.params["b"]),
                       "losses": losses, "device_count": jax.device_count()}, f)


if __name__ == "__main__":
    main(sys.argv[1])
