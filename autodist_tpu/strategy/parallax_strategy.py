"""Parallax hybrid strategy: dense gradients AllReduce, sparse gradients PS.

Port of reference ``autodist/strategy/parallax_strategy.py:24-71`` (after the Parallax
paper): dense parameters use gradient all-reduce; embedding-style parameters with
row-sparse gradients use load-balanced PS placement, which on TPU compiles to sharded
embedding storage with row-local updates.
"""

from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.all_reduce_strategy import parse_ar_options
from autodist_tpu.strategy.base import AR_DEFAULT_AXES, Strategy
from autodist_tpu.strategy.ps_lb_strategy import PSLoadBalancing


class Parallax(PSLoadBalancing):
    # Data parallelism stays primary in the hybrid; PS destinations are computed
    # against this same axis default, so they always fit the recorded mesh.
    _default_axes = AR_DEFAULT_AXES

    def __init__(self, chunk_size: int = 128, all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor", local_proxy_variable: bool = False,
                 sync: bool = True, staleness: int = 0):
        super().__init__(local_proxy_variable=local_proxy_variable, sync=sync,
                         staleness=staleness)
        self._chunk_size, self._spec, self._compressor = parse_ar_options(
            chunk_size, all_reduce_spec, compressor)

    def build(self, model_spec: ModelSpec, resource_spec: ResourceSpec) -> Strategy:
        strategy = Strategy()
        n_dest = self._num_destinations(resource_spec)
        loads = [0] * n_dest
        dense_idx = 0
        for spec in model_spec.trainable.values():
            node = strategy.proto.node_config.add(var_name=spec.name)
            node.sparse = spec.sparse
            if spec.sparse:
                dest = min(range(n_dest), key=loads.__getitem__)
                loads[dest] += self._load_fn(spec)
                node.ps_synchronizer.reduction_destination = f"reduce:{dest}"
                node.ps_synchronizer.local_replication = self._local_proxy_variable
                node.ps_synchronizer.sync = self._sync
                node.ps_synchronizer.staleness = self._staleness
            else:
                ar = node.all_reduce_synchronizer
                ar.spec = self._spec
                ar.compressor = self._compressor
                ar.group = dense_idx // self._chunk_size
                dense_idx += 1
        self._fill_mesh_config(strategy, resource_spec,
                               self._resolved_axes(resource_spec, self._default_axes))
        return strategy
