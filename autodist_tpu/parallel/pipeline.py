"""Pipeline parallelism over the mesh ``pipe`` axis (GPipe microbatch schedule).

Beyond reference parity: the reference explicitly scoped pipeline parallelism out
(``docs/design/architecture.rst:49-51``, SURVEY.md §2.2). The TPU-native design is
the collective-permute formulation: stage parameters are sharded ``P("pipe", ...)``
on their leading stage dimension, and inside a ``jax.shard_map`` manual region over
the ``pipe`` axis each device runs its stage on a stream of microbatches, handing
activations to the next stage with ``lax.ppermute``. The schedule is a single
``lax.scan`` of ``num_microbatches + n_stages - 1`` ticks (fill + steady state +
drain). Reverse-mode autodiff through the scan/ppermute yields the backward
pipeline automatically — no hand-written backward schedule.

The loop is written for the *partial-manual* shard_map mode (``axis_names=
{"pipe"}``): every other mesh axis stays under automatic SPMD partitioning, so
pipeline composes with data parallelism (batch stays sharded on ``data``) and the
other strategies.
"""

from typing import Callable

import jax
import jax.numpy as jnp

from autodist_tpu import const

PyTree = object


def pipeline_apply(stage_fn: Callable, stage_params: PyTree, x_mb: jax.Array,
                   axis: str = const.MESH_AXIS_PIPE) -> jax.Array:
    """GPipe loop body — must run inside a shard_map manual over ``axis``.

    stage_fn(stage_params, x) -> y applies one pipeline stage to one microbatch
    (``stage_params`` is this device's shard: leading stage dim of size 1).
    x_mb: [num_microbatches, mb_batch, ...] activations entering stage 0,
    replicated along ``axis`` (only rank 0 reads them; the transpose of that read
    routes the input gradient back correctly). Returns the last stage's outputs,
    [num_microbatches, mb_batch, ...], replicated along ``axis``.
    """
    n_stages = jax.lax.psum(1, axis)
    rank = jax.lax.axis_index(axis)
    n_mb = x_mb.shape[0]

    if n_stages == 1:
        # Degenerate single-stage pipeline: no schedule needed.
        def apply_one(carry, x):
            return carry, stage_fn(stage_params, x)
        _, out = jax.lax.scan(apply_one, 0, x_mb)
        return out

    shift_pairs = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, outputs = carry
        mb = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, n_mb - 1), 0, keepdims=False)
        x = jnp.where(rank == 0, mb, state)
        y = stage_fn(stage_params, x)
        # The last stage starts emitting results at tick n_stages-1.
        take = (t >= n_stages - 1) & (rank == n_stages - 1)
        idx = jnp.clip(t - (n_stages - 1), 0, n_mb - 1)
        prev = jax.lax.dynamic_index_in_dim(outputs, idx, 0, keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(take, y, prev), idx, 0)
        nxt = jax.lax.ppermute(y, axis, shift_pairs)
        return (nxt, outputs), None

    init = (jnp.zeros_like(x_mb[0]), jnp.zeros_like(x_mb))
    (_, outputs), _ = jax.lax.scan(tick, init, jnp.arange(n_mb + n_stages - 1))
    # Broadcast the last stage's results to every pipe rank so downstream
    # (replicated) computation — the LM head, the loss — sees them everywhere.
    mask = (rank == n_stages - 1).astype(outputs.dtype)
    return jax.lax.psum(outputs * mask, axis)


def _ambient_mesh():
    """The mesh in effect at trace time: the abstract-mesh context if set, else the
    ``with mesh:`` physical-mesh context the runner steps under."""
    abstract = jax.sharding.get_abstract_mesh()
    if abstract is not None and not abstract.empty:
        return abstract
    try:
        # No public accessor for the `with mesh:` context; degrade to the
        # explicit-mesh error if a jax upgrade moves this.
        from jax._src import mesh as mesh_lib
        physical = mesh_lib.thread_resources.env.physical_mesh
    except (ImportError, AttributeError):
        physical = None
    if physical is not None and not physical.empty:
        return physical
    raise RuntimeError(
        "pipelined() needs a mesh: pass one explicitly or call inside a "
        "`with mesh:` block (DistributedRunner.run steps under one)")


def pipelined(stage_fn: Callable, n_stages: int, axis: str = const.MESH_AXIS_PIPE,
              mesh=None) -> Callable:
    """Wrap :func:`pipeline_apply` in the partial-manual shard_map.

    Returns ``f(stage_params, x_mb) -> y_mb`` where ``stage_params`` leaves carry a
    leading stage dimension of size ``n_stages`` (sharded over ``axis``) and all
    other mesh axes remain automatic. ``mesh`` defaults to the ambient mesh
    context (the runner steps inside ``with self.mesh``). Must run under ``jit``
    (partial-manual shard_map is trace-time only).
    """
    from jax.sharding import PartitionSpec as P

    def f(stage_params, x_mb):
        m = mesh if mesh is not None else _ambient_mesh()
        mesh_stages = dict(m.shape).get(axis, 1)
        if mesh_stages != n_stages:
            # Without this check a mismatched mesh silently runs only the stage
            # groups the pipe axis covers — finite loss, most layers skipped.
            raise ValueError(
                f"pipelined(n_stages={n_stages}) needs mesh axis {axis!r} of that "
                f"size, but the mesh has {axis}={mesh_stages}; size the mesh with "
                f"the Pipeline strategy or a matching resource-spec mesh")
        specs = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
        return jax.shard_map(
            lambda p, x: pipeline_apply(stage_fn, p, x, axis=axis),
            mesh=m, in_specs=(specs, P()), out_specs=P(),
            axis_names={axis}, check_vma=False,
        )(stage_params, x_mb)

    return f
