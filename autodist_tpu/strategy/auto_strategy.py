"""AutoStrategy: analytic cost-model selection of a per-parameter strategy.

The reference ships only fixed-policy builders and frames strategy auto-selection
as the project's aspiration (its tutorial closes with "auto-learning a strategy",
``docs/usage/tutorials/customize-strategy.md``; the default is simply
PSLoadBalancing, ``autodist.py:70``). This builder is the analytic version: it
reads the same inputs every builder gets — parameter metadata (bytes, shapes,
sparse-gradient flags) and the resource spec (device count, node count, per-node
``network_bandwidth``) — and derives the per-parameter choice the fixed builders
would have to be hand-picked for:

1. **Regime** — if resident train state (params + the optimizer's EXACT state
   bytes, computed with ``jax.eval_shape(optimizer.init, params)``; 3x
   Adam-class assumed only when no optimizer is visible) exceeds the
   per-device memory budget, dense parameters use the PS/ZeRO regime (state
   sharded along ``reduce``); otherwise plain AllReduce (lowest latency on
   ICI). ``create_distributed_session`` hands the builder its optimizer
   automatically (:meth:`AutoStrategy.observe_optimizer`), so SGD vs Adam vs
   Adafactor on the same model legitimately flip this decision.
2. **Sparse** — embedding-style parameters always go to load-balanced PS so their
   gradients ride the sparse wire path (the Parallax rule).
3. **Partitioning** — any dense parameter above ``partition_threshold_bytes``
   with a partitionable axis is sharded (smallest divisor >= 2, capped), so no
   single logical tensor dominates one shard's storage.
4. **Wire codec** — on multi-node specs the AllReduce spec becomes DCN
   (hierarchical intra-slice reduce first) and, when the slowest node link is
   below ``bf16_bandwidth_gbps`` / ``ef_bandwidth_gbps``, gradients are cast to
   bf16 / bf16 with error feedback for the cross-node hop.

Every decision is logged; ``explain()`` returns the decision table for the last
``build()``.
"""

from typing import Optional

from autodist_tpu import const
from autodist_tpu.model_spec import ModelSpec, ParamSpec
from autodist_tpu.proto import strategy_pb2
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.all_reduce_strategy import (fill_ar_synchronizer,
                                                       parse_ar_options)
from autodist_tpu.strategy.base import (AR_DEFAULT_AXES, PS_DEFAULT_AXES, Strategy,
                                        StrategyBuilder, num_devices)
from autodist_tpu.strategy.partition_utils import make_num_shards, partitionable_axis
from autodist_tpu.strategy.ps_lb_strategy import byte_size_load_fn
from autodist_tpu.utils import logging

_ADAM_STATE_MULTIPLIER = 3          # params + two moments — no-optimizer fallback
_DEFAULT_BUDGET_BYTES = 8 << 30     # conservative HBM fallback when undiscoverable


def _device_memory_budget() -> int:
    """Usable per-device memory through the memory plane
    (:func:`autodist_tpu.telemetry.memplane.device_budget`): 80% of the
    measured allocator limit, else the ``AUTODIST_MEM_BUDGET`` override,
    else a WARNED 8 GiB default — with the winning source booked as
    ``mem.budget_source``, so the async-PS memory rule never again runs on
    a budget nobody saw (the old ``memory_stats() or {}`` silently fell
    through to the default on every CPU/sim backend)."""
    try:
        from autodist_tpu.telemetry import memplane
        budget, _source = memplane.device_budget()
        return budget
    except Exception as e:  # noqa: BLE001 — strategy choice must not die
        logging.debug("memory-plane budget unavailable (%s); using the "
                      "%d GiB default", e, _DEFAULT_BUDGET_BYTES >> 30)
        return _DEFAULT_BUDGET_BYTES


def _fmt_bytes(n: int) -> str:
    """Human units that never round a nonzero count to zero (three significant
    digits) — a threshold comparison printed as '0 MiB >= 0 MiB' reads as a
    contradiction at small scales."""
    value, unit = float(n), "B"
    for next_unit in ("KiB", "MiB", "GiB", "TiB"):
        if value < 1024:
            break
        value, unit = value / 1024, next_unit
    if unit == "B":
        return f"{int(value)} B"
    # Fixed-point, never scientific ('{:.3g}' turns 1023.9 into '1.02e+03').
    if value >= 100:
        return f"{value:.0f} {unit}"
    if value >= 10:
        return f"{value:.1f} {unit}"
    return f"{value:.2f} {unit}"


def _shape_dtype_tree(model_spec: ModelSpec):
    """The params pytree as ShapeDtypeStructs (for eval_shape, no allocation)."""
    import jax
    return model_spec.unflatten([
        jax.ShapeDtypeStruct(tuple(model_spec.params[n].shape),
                             model_spec.params[n].dtype)
        for n in model_spec.names])


def _opt_state_bytes(optimizer, model_spec: ModelSpec,
                     dense_names) -> Optional[int]:
    """EXACT optimizer-state bytes attributable to the dense parameters,
    via ``jax.eval_shape(optimizer.init, params)`` — no arrays materialize.
    Leaves are attributed to parameters by path-suffix (the same rule the
    sharding plan uses); unmatched leaves (step counters, sparse-param
    moments) are excluded from the dense figure. None when the optimizer
    cannot be shape-evaluated (custom non-optax object)."""
    import jax

    from autodist_tpu.model_spec import _path_name
    from autodist_tpu.parallel.plan import _suffix_matcher
    try:
        state = jax.eval_shape(optimizer.init, _shape_dtype_tree(model_spec))
    except Exception as e:  # noqa: BLE001 — fall back to the heuristic
        logging.warning(
            "AutoStrategy: could not shape-evaluate optimizer.init (%s); "
            "falling back to the Adam-class 3x heuristic", e)
        return None
    match = _suffix_matcher(dense_names)
    total = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        if match(_path_name(path)) is not None and hasattr(leaf, "shape"):
            import numpy as _np
            total += int(_np.prod(leaf.shape, dtype=_np.int64)
                         * _np.dtype(leaf.dtype).itemsize)
    return total


class OptimizerChoice:
    """Result of :func:`choose_optimizer`: the optimizer plus the decision."""

    def __init__(self, optimizer, factored: bool, reason: str):
        self.optimizer = optimizer
        self.factored = factored       # True = memory-tight, factored moments
        self.reason = reason

    def __repr__(self):
        return f"OptimizerChoice(factored={self.factored}, {self.reason!r})"


def choose_optimizer(params, learning_rate: float = 1e-3,
                     memory_budget_bytes: Optional[int] = None) -> OptimizerChoice:
    """Pick Adam when its full moments fit the per-device budget next to the
    params and gradients; Adafactor (factored second moment, state ~= a few %
    of params) when they do not — the decision lm1b's giant-vocab config
    previously hand-coded (examples/lm1b/lm1b_train.py), now owned by the
    strategy layer with exact state bytes from ``jax.eval_shape``.

    The residency model is params + gradients (~param bytes) + optimizer
    state vs the budget; activations are workload-dependent and covered by
    the budget's 20% headroom."""
    import jax
    import numpy as np
    import optax

    budget = memory_budget_bytes if memory_budget_bytes is not None \
        else _device_memory_budget()
    param_bytes = sum(
        int(np.prod(l.shape, dtype=np.int64)) * np.dtype(l.dtype).itemsize
        for l in jax.tree_util.tree_leaves(params) if hasattr(l, "shape"))
    adam = optax.adam(learning_rate)
    adam_state = jax.eval_shape(
        adam.init, jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), params))
    adam_bytes = sum(
        int(np.prod(l.shape, dtype=np.int64)) * np.dtype(l.dtype).itemsize
        for l in jax.tree_util.tree_leaves(adam_state) if hasattr(l, "shape"))
    resident = 2 * param_bytes + adam_bytes   # params + grads + moments
    if resident <= budget:
        return OptimizerChoice(adam, False, (
            f"adam: params+grads+moments {_fmt_bytes(resident)} "
            f"<= budget {_fmt_bytes(budget)}"))
    return OptimizerChoice(optax.adafactor(learning_rate), True, (
        f"adafactor: adam residency {_fmt_bytes(resident)} exceeds budget "
        f"{_fmt_bytes(budget)}; factored second moment fits"))


class AutoStrategy(StrategyBuilder):
    """Pick per-parameter synchronization from an analytic cost model."""

    def __init__(self, memory_budget_bytes: Optional[int] = None,
                 partition_threshold_bytes: int = 64 << 20,
                 bf16_bandwidth_gbps: int = 100, ef_bandwidth_gbps: int = 25,
                 chunk_size: int = 128, optimizer=None):
        self._budget = memory_budget_bytes
        self._partition_threshold = partition_threshold_bytes
        self._bf16_gbps = bf16_bandwidth_gbps
        self._ef_gbps = ef_bandwidth_gbps
        self._chunk_size, _, _ = parse_ar_options(chunk_size, "AUTO", "NoneCompressor")
        self._optimizer = optimizer
        self._optimizer_explicit = optimizer is not None
        self._decisions: list = []

    def observe_optimizer(self, optimizer) -> None:
        """Called by ``create_distributed_session`` with the session's
        optimizer, so the memory model uses EXACT state bytes instead of the
        Adam-class guess. An optimizer passed to the constructor wins (the
        user pinned the assumption deliberately)."""
        if not self._optimizer_explicit:
            self._optimizer = optimizer

    # ------------------------------------------------------------------ model
    def _pick_codec(self, resource_spec: ResourceSpec):
        """(spec, compressor) for AllReduce nodes, from the slowest network tier.

        Lossy codecs (bf16 / error feedback) change numerics, so they are only
        chosen from bandwidth the user actually stated: a spec that leaves
        ``network_bandwidth`` unset gets the lossless hierarchical reduce, not a
        compression decision inferred from the YAML parser's 1 GBE default."""
        AR = strategy_pb2.AllReduceSynchronizer
        if resource_spec.num_nodes <= 1:
            return AR.AUTO, AR.NONE, "single node: ICI, dense bf16-free wire"
        if not all(n.bandwidth_specified for n in resource_spec.nodes):
            logging.warning(
                "AutoStrategy: multi-node spec without explicit network_bandwidth;"
                " keeping the lossless wire (set network_bandwidth per node to "
                "enable bf16/error-feedback compression)")
            return AR.DCN, AR.NONE, (
                "multi-node, bandwidth unspecified: hierarchical DCN reduce, "
                "lossless wire (declare network_bandwidth to opt into bf16/EF)")
        slowest = min(n.network_bandwidth for n in resource_spec.nodes)
        if slowest <= self._ef_gbps:
            return AR.DCN, AR.BF16_EF, (
                f"multi-node, slowest link {slowest} Gbps <= {self._ef_gbps}: "
                f"hierarchical DCN reduce + bf16 with error feedback")
        if slowest <= self._bf16_gbps:
            return AR.DCN, AR.BF16, (
                f"multi-node, slowest link {slowest} Gbps <= {self._bf16_gbps}: "
                f"hierarchical DCN reduce + bf16 wire")
        return AR.DCN, AR.NONE, (
            f"multi-node, slowest link {slowest} Gbps: hierarchical DCN reduce")

    def build(self, model_spec: ModelSpec, resource_spec: ResourceSpec) -> Strategy:
        self._decisions = []
        n_dev = num_devices(resource_spec)
        budget = self._budget if self._budget is not None else _device_memory_budget()
        dense = {n: s for n, s in model_spec.trainable.items() if not s.sparse}
        dense_bytes = sum(s.byte_size for s in dense.values())
        opt_bytes = None
        if self._optimizer is not None:
            opt_bytes = _opt_state_bytes(self._optimizer, model_spec, dense)
        if opt_bytes is not None:
            state_bytes = dense_bytes + opt_bytes
            state_how = (f"params {_fmt_bytes(dense_bytes)} + exact optimizer "
                         f"state {_fmt_bytes(opt_bytes)} (eval_shape)")
        else:
            state_bytes = _ADAM_STATE_MULTIPLIER * dense_bytes
            state_how = (f"{_ADAM_STATE_MULTIPLIER}x params "
                         f"{_fmt_bytes(dense_bytes)} (Adam-class assumption; "
                         f"pass the optimizer for exact bytes)")
        memory_bound = state_bytes > budget
        if (memory_bound and opt_bytes is not None
                and opt_bytes >= 1.5 * dense_bytes
                and dense_bytes + int(0.1 * dense_bytes) <= budget):
            # Full moments are what broke the budget, not the params: factored
            # second moments (adafactor-class, state ~= a few % of params)
            # would fit without sharding the weight update at all.
            self._decisions.append((
                "<recommend>",
                f"optimizer state {_fmt_bytes(opt_bytes)} dominates the "
                f"memory pressure (params only {_fmt_bytes(dense_bytes)}): a "
                f"factored-moment optimizer (optax.adafactor / "
                f"strategy.choose_optimizer) would fit the "
                f"{_fmt_bytes(budget)} budget without the PS/ZeRO regime"))

        # Size a `model` mesh axis for physical tensor sharding: large enough that
        # the biggest partitioned parameter's shard drops below the threshold,
        # constrained to a divisor of the device count (XLA needs an even mesh).
        partitioned = [s for s in model_spec.trainable.values()
                       if not s.sparse and s.byte_size >= self._partition_threshold
                       and partitionable_axis(s) is not None]
        model_axis = 1
        if partitioned and n_dev > 1:
            need = max(-(-s.byte_size // self._partition_threshold)
                       for s in partitioned)
            divisors = [d for d in range(2, n_dev + 1) if n_dev % d == 0]
            model_axis = next((d for d in divisors if d >= need),
                              divisors[-1] if divisors else 1)

        ar_spec, ar_compressor, codec_reason = self._pick_codec(resource_spec)
        axes = dict(PS_DEFAULT_AXES if memory_bound else AR_DEFAULT_AXES)
        if (not memory_bound
                and ar_spec == strategy_pb2.AllReduceSynchronizer.DCN):
            # The DCN knob requests a two-phase reduce, which needs BOTH data-
            # parallel mesh axes populated (inner = intra-node ICI tier). Carve
            # the inner axis from the per-node chip count so the knob this
            # builder emits is actually honored by the lowering, instead of
            # silently collapsing to a single-phase reduce on {data: -1}.
            counts = [max(1, len(n.accelerator_devices))
                      for n in resource_spec.nodes]
            inner = counts[0] if len(set(counts)) == 1 else 0
            if model_axis > 1:
                # Partitioned parameters take the implicit SPMD lowering, where
                # XLA owns the reduction schedule — the two-phase knob cannot
                # be honored there, so say so rather than pretending.
                logging.warning(
                    "AutoStrategy: hierarchical DCN reduce downgraded — "
                    "partitioned parameters use the implicit lowering (XLA "
                    "schedules the cross-node reduction)")
            elif inner > 1 and n_dev % inner == 0:
                axes = {const.MESH_AXIS_REDUCE: inner,
                        const.MESH_AXIS_DATA: -1}
                self._decisions.append(
                    ("<mesh>", f"DCN hierarchical reduce: inner ICI axis = "
                               f"{inner} chips/node x {n_dev // inner} nodes"))
            else:
                logging.warning(
                    "AutoStrategy: hierarchical DCN reduce downgraded to a "
                    "single-phase reduce — per-node chip counts %s do not form "
                    "an even inner mesh axis", counts)
        if model_axis > 1:
            axes[const.MESH_AXIS_MODEL] = model_axis
        resolved = self._resolved_axes(resource_spec, axes)
        n_dest = resolved.get(const.MESH_AXIS_REDUCE, 1)

        self._decisions.append(
            ("<regime>",
             f"{'PS/ZeRO' if memory_bound else 'AllReduce'}: resident state "
             f"{_fmt_bytes(state_bytes)} ({state_how}) "
             f"{'>' if memory_bound else '<='} budget {_fmt_bytes(budget)} "
             f"on {n_dev} devices"))
        self._decisions.append(("<codec>", codec_reason))

        strategy = Strategy()
        loads = [0] * n_dest
        dense_idx = 0

        def fill_ps(node, spec_load):
            dest = min(range(n_dest), key=loads.__getitem__)
            loads[dest] += spec_load
            node.ps_synchronizer.reduction_destination = f"reduce:{dest}"
            node.ps_synchronizer.sync = True
            return dest

        def fill_ar(node):
            nonlocal dense_idx
            fill_ar_synchronizer(node, spec=ar_spec, compressor=ar_compressor,
                                 group=dense_idx // self._chunk_size)
            dense_idx += 1

        for spec in model_spec.trainable.values():
            node = strategy.proto.node_config.add(var_name=spec.name)
            node.sparse = spec.sparse
            if spec.sparse:
                dest = fill_ps(node, byte_size_load_fn(spec))
                self._log(spec, f"sparse grads -> PS reduce:{dest} (sparse wire)")
                continue
            axis = partitionable_axis(spec)
            if (model_axis > 1 and axis is not None
                    and spec.byte_size >= self._partition_threshold):
                # Shard count == the model axis size so the proto's partitioning and
                # the physical storage sharding coincide (non-divisible dims get
                # padded storage in the plan).
                self._fill_partitioned(node, spec, axis, model_axis, memory_bound,
                                       fill_ps, fill_ar)
                continue
            if memory_bound:
                dest = fill_ps(node, byte_size_load_fn(spec))
                self._log(spec, f"dense -> PS/ZeRO reduce:{dest} (memory-bound)")
            else:
                fill_ar(node)
                self._log(spec, "dense -> AllReduce")

        self._fill_mesh_config(strategy, resource_spec, resolved)
        for name, why in self._decisions:
            logging.info("AutoStrategy %s: %s", name, why)
        return strategy

    def _fill_partitioned(self, node, spec: ParamSpec, axis: int, k: int,
                          memory_bound: bool, fill_ps, fill_ar):
        node.partitioner.num_shards.extend(make_num_shards(len(spec.shape), axis, k))
        node.partitioner.mesh_axis = const.MESH_AXIS_MODEL
        for i in range(k):
            part = node.part_config.add(var_name=f"{spec.name}/part_{i}")
            part.sparse = spec.sparse
            if memory_bound:
                fill_ps(part, max(byte_size_load_fn(spec) // k, 1))
            else:
                fill_ar(part)
        self._log(spec, f"{_fmt_bytes(spec.byte_size)} >= partition threshold "
                        f"{_fmt_bytes(self._partition_threshold)}: "
                        f"{k} shards on axis {axis} "
                        f"({'PS' if memory_bound else 'AllReduce'} per shard)")

    def _log(self, spec: ParamSpec, why: str):
        self._decisions.append((spec.name, why))

    def explain(self) -> str:
        """Human-readable decision table for the last ``build()``."""
        if not self._decisions:
            return "AutoStrategy: no build yet"
        width = max(len(n) for n, _ in self._decisions)
        return "\n".join(f"{n:<{width}}  {w}" for n, w in self._decisions)
