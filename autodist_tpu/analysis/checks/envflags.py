"""GL007 — AUTODIST_* env flags must resolve through const.py's registry.

Scattered ``os.environ.get("AUTODIST_...")`` reads made the flag surface
unenumerable: nothing could list the knobs, docs drifted, and a typo'd flag
name (``AUTODIST_PS_OVERLAP`` misspellings were the motivating near-miss)
silently fell back to the default instead of erroring. ``const.KNOWN_FLAGS``
is now the single registry; this check keeps it exhaustive.
"""

import ast
import re
from typing import List

from autodist_tpu.analysis import callgraph
from autodist_tpu.analysis.core import Context, Finding, Module, register

_FLAG_RE = re.compile(r"^AUTODIST_[A-Z0-9_]+$")
_CONST_PATH = "autodist_tpu/const.py"
_ENV_READ_CALLS = {"os.environ.get", "os.getenv", "environ.get",
                   "os.environ.setdefault", "environ.setdefault"}


@register("GL007", "env flag read outside const.py / unregistered "
                   "AUTODIST_* name")
def check_env_flags(module: Module, ctx: Context) -> List[Finding]:
    """GL007 — env-flag registry.

    Two rules keeping the flag surface enumerable and typo-proof:

    - Package code (``autodist_tpu/``, except ``const.py`` itself) must not
      read ``os.environ`` / ``os.getenv`` directly — add an ``ENV`` member
      (typed, defaulted, documented) and read ``const.ENV.X.val``. Passing
      the whole environment through (``dict(os.environ)`` for child
      processes) is fine; per-key reads are not.
    - Anywhere in the linted tree, a string literal that IS an AUTODIST_*
      name must appear in ``const.KNOWN_FLAGS`` — this catches typo'd flags
      in tests' spawned-process env dicts, where a misspelling silently
      tests the default behavior instead of the intended one.
      ``const.warn_unknown_autodist_flags()`` enforces the same registry at
      runtime for flags set (not read) with a typo.
    """
    if module.tree is None or module.relpath == _CONST_PATH:
        return []
    findings: List[Finding] = []

    if module.relpath.startswith("autodist_tpu/"):
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                dotted = callgraph.dotted_name(node.func)
                if dotted in _ENV_READ_CALLS:
                    findings.append(Finding(
                        "GL007", module.relpath, node.lineno, node.col_offset,
                        f"direct env read `{dotted}(...)` in package code; "
                        f"add the flag to const.ENV/_ENV_DEFAULTS and read "
                        f"const.ENV.<NAME>.val so flags stay enumerable and "
                        f"typed", scope=module.scope_at(node)))
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and callgraph.dotted_name(node.value) in (
                        "os.environ", "environ"):
                findings.append(Finding(
                    "GL007", module.relpath, node.lineno, node.col_offset,
                    "direct `os.environ[...]` read in package code; resolve "
                    "through const.ENV instead",
                    scope=module.scope_at(node)))

    known = ctx.known_flags()
    if known:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                    and _FLAG_RE.match(node.value) \
                    and node.value not in known:
                findings.append(Finding(
                    "GL007", module.relpath, node.lineno, node.col_offset,
                    f"unknown flag {node.value!r} — not in const.KNOWN_FLAGS "
                    f"(typo? if intentional, register it there with a doc "
                    f"line)", scope=module.scope_at(node)))
    return findings
