"""Shared skip-guard for tests that need ``jax.shard_map``.

Some jax builds (including this box's — see ROADMAP "Known environment
caveats") ship no ``jax.shard_map``; every package path written against it
(the explicit compressed/sparse-wire gradient lowering, sequence/context
parallelism, ring/Ulysses attention, the pipeline-parallel loops) raises
``AttributeError`` at trace time there. Those are ENVIRONMENT limitations,
not regressions: this helper turns them into skips so tier-1 reports signal,
not ~100 known-env failures.

Usage::

    from shardmap_compat import requires_shard_map, skip_unless_shard_map

    pytestmark = requires_shard_map            # whole module needs it
    @requires_shard_map                        # ...or one test
    def test_ring_attention(): ...

    def test_matrix(builder, case):            # data-dependent lowering:
        step = ad.function(...)
        skip_unless_shard_map(step.runner)     # skips iff THIS plan compiled
                                               # to the shard_map lowering
"""

import jax
import pytest

HAS_SHARD_MAP = hasattr(jax, "shard_map")

SKIP_REASON = ("this jax build has no jax.shard_map (known environment "
               "caveat, see ROADMAP.md); the path under test cannot lower")

requires_shard_map = pytest.mark.skipif(not HAS_SHARD_MAP,
                                        reason=SKIP_REASON)


def skip_unless_shard_map(runner) -> None:
    """Skip the calling test when ``runner``'s gradient function compiled to
    the explicit (``jax.shard_map``) lowering on a build without it.

    Parametrized matrices (strategy x case x mesh) take the explicit path only
    for some combinations (a compressor, a sparse-wire embedding, an honored
    DCN hint — ``make_grad_fn`` tags the decision as ``uses_shard_map``), so a
    blanket file marker would skip healthy combos; this guard skips exactly
    the ones that cannot run."""
    if HAS_SHARD_MAP:
        return
    grad_fn = getattr(runner, "_grad_fn", None)
    if getattr(grad_fn, "uses_shard_map", False):
        pytest.skip(SKIP_REASON)
