"""Telemetry-calibrated plan autotuner: predict, prune, probe, persist.

ROADMAP item 3 (Automap, arXiv 2112.02958; weight-update sharding, arXiv
2004.13336): the win at scale comes from *searching* the joint
strategy x execution-knob space with a calibrated cost model, not from
hand-picking one builder. This module unifies the repo's three previously
disconnected pieces into one two-stage search:

- :class:`AutoStrategy`'s analytic regime/partition rules **generate
  candidates** (PS vs collective vs partitioned variants) instead of one
  answer, jointly with the execution knobs the runtime already ships:
  ``unroll=K`` (PR 1), ``zero`` weight-update sharding (PR 6),
  ``accumulation_steps``, and the async-PS client's ``overlap``;
- **Stage 1 (predict + prune)** ranks every candidate with
  :func:`telemetry.costmodel.predict` fed by compile-only static costs from
  the runner's :meth:`DistributedRunner.plan_costs` probe (lower + XLA
  ``cost_analysis()`` — **no step executes**), using a
  :class:`~autodist_tpu.telemetry.costmodel.Calibration` loaded from an
  ``AUTODIST_PROFILE_DIR`` profile or the bundled default; candidates whose
  predicted step time exceeds the frontrunner by a margin are pruned without
  ever being measured.
- **Stage 2 (probe)** runs a few real steps for the top-k survivors through
  the tuner's shared :func:`~autodist_tpu.strategy.tuner.measure_candidate`
  loop (failure-skip semantics preserved), and the measured winner persists
  to the on-disk **plan cache** (``AUTODIST_PLAN_CACHE``, schema-versioned
  JSON keyed by model/shape signature + device topology + package version)
  so later launches of the same job apply the tuned plan with zero search
  cost.

``AutoDist.create_distributed_session(..., tune=True)`` /
``AutoDist(strategy_builder="autotune")`` is the user entry; every search
emits ``tune.*`` telemetry spans/gauges and an :meth:`TunedPlan.explain`
table so adprof/adtop can show why a plan won.
"""

import dataclasses
import gc
import hashlib
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from autodist_tpu import const, telemetry
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import StrategyBuilder, num_devices
from autodist_tpu.strategy.tuner import CandidateResult, measure_candidate
from autodist_tpu.telemetry import costmodel
from autodist_tpu.utils import logging

__all__ = ["Candidate", "TunedPlan", "autotune", "enumerate_candidates",
           "plan_cache_key", "load_cached_plan", "store_plan",
           "DEFAULT_CALIBRATION", "PLAN_SCHEMA", "PLAN_SCHEMA_VERSION"]

# Plan/plan-cache JSON identity, pinned by tests. Bump on breaking change.
PLAN_SCHEMA = "autodist-plan-cache"
PLAN_SCHEMA_VERSION = 1

# The unroll factors stage 1 ranks by default: the PR 1 sweep's grid (the
# measured curve flattens at 8 on host-bound models, PERF_BASELINE
# unroll_curve).
DEFAULT_UNROLLS = (1, 2, 4, 8)

# The prefetch depths ranked when the tuning problem declares a loader cost
# (``loader_s_per_step > 0``). Two points suffice: the cost model prices
# the steady-state pipeline (any depth >= 1 sustains max(rest_s, loader_s)
# — depth beyond that only smooths jitter), so 0-vs-on is the real
# decision; 2 is the shipped on-value (double buffering). Without a loader
# cost the knob collapses to (0,) and the space is unchanged.
DEFAULT_PREFETCH_DEPTHS = (0, 2)

# Stage-1 prune margin: a candidate predicted more than this fraction slower
# than the frontrunner is dropped without measurement. Wide by design — the
# calibrated model ranks, it does not referee photo finishes; anything
# within 35% of the leader deserves a real probe (subject to top-k).
PRUNE_MARGIN = 0.35

# Bundled default calibration, used when no AUTODIST_PROFILE_DIR profile is
# available. Provenance (this matters: the ABSOLUTE numbers are generic, the
# STRUCTURE — host cost per dispatch >> 0, finite device rates, a measured
# wire — is what makes the ranking sane):
# - host_s_per_dispatch 2e-3: the dev box's host-bound CPU micro-step is
#   ~9 ms (PERF_BASELINE attr_overhead) of which the host share dominates;
#   2 ms/dispatch is the order profiling measured — this term is what makes
#   unroll=K amortization win on host-bound models.
# - flops_per_s 5e10 / bytes_per_s 5e9: CPU-class achieved rates (a few
#   GFLOP/s/core x a few cores), so big programs still cost more than small
#   ones; on TPU, calibrate from a real profile instead.
# - wire_bytes_per_s 400e6: the measured zero-copy PS wire rate
#   (PERF_BASELINE ps_wire zero_copy, MB/s) — the comm term for async-PS
#   candidates.
# - quantize_bytes_per_s 2e9: numpy's per-row int8 quantize rate on a
#   CPU-class host (abs-max reduce + scale + round over the dense bytes) —
#   the host-cost term that makes wire_dtype a priced trade instead of a
#   free win; calibrate() refits it from any compressed run's profile.
DEFAULT_CALIBRATION = costmodel.Calibration(
    flops_per_s=5e10, bytes_per_s=5e9, host_s_per_dispatch=2e-3,
    wire_bytes_per_s=400e6, quantize_bytes_per_s=2e9)

# The wire_dtype knob's enumeration axis for async-PS candidates, and each
# value's push-byte compression ratio (int8 is 1/4 payload + ~2% per-row
# float32 scales). The ratio prices bytes only; the host quantize seconds
# are priced separately over quantize_bytes_per_s.
DEFAULT_WIRE_DTYPES = ("", "fp16", "int8")
_WIRE_RATIO = {"": 1.0, "fp16": 0.5, "bf16": 0.5, "int8": 0.26}

# Builders the autotuner may emit, by name — the reconstructible subset a
# cached plan can name (cache entries store a spec, not a pickle).
_BUILDERS: Dict[str, Callable[..., StrategyBuilder]] = {}


def _builder_registry() -> Dict[str, Callable[..., StrategyBuilder]]:
    if not _BUILDERS:
        from autodist_tpu.strategy import (AllReduce, AutoStrategy, Parallax,
                                           PartitionedAR, PartitionedPS, PS,
                                           PSLoadBalancing)
        _BUILDERS.update({
            "AllReduce": AllReduce, "PSLoadBalancing": PSLoadBalancing,
            "AutoStrategy": AutoStrategy, "Parallax": Parallax,
            "PartitionedAR": PartitionedAR, "PartitionedPS": PartitionedPS,
            "PS": PS,
        })
    return _BUILDERS


def builder_from_spec(spec: Dict[str, Any]) -> StrategyBuilder:
    """Reconstruct a builder from its cacheable ``{"name", "kwargs"}`` spec."""
    reg = _builder_registry()
    name = spec.get("name")
    if name not in reg:
        raise ValueError(f"unknown builder {name!r} in plan spec (known: "
                         f"{sorted(reg)}); the plan cache may predate this "
                         f"package version")
    return reg[name](**(spec.get("kwargs") or {}))


@dataclasses.dataclass
class Candidate:
    """One point of the joint strategy x knob space."""

    builder_spec: Dict[str, Any]          # {"name": ..., "kwargs": {...}}
    unroll: int = 1
    accumulation_steps: int = 1
    zero: int = 0
    overlap: bool = True                  # async-PS prefetch client knob
    prefetch_depth: int = 0               # input-pipeline prefetch knob
    wire_dtype: str = ""                  # quantized-push knob ("" = exact)
    asynchronous: bool = False            # async regime: predicted, not probed
    why: str = ""                         # enumeration reason
    predicted: Optional[Dict[str, Any]] = None   # costmodel.predict output
    pruned: Optional[str] = None          # prune reason, None = survivor
    probe: Optional[CandidateResult] = None      # stage-2 measurement
    # Analytic per-device resident bytes (params + effective opt state +
    # accumulation buffer) — the memory pre-flight's refusal basis and
    # costmodel.predict's ``resident_bytes`` term (-> peak_hbm_bytes).
    resident_bytes: Optional[int] = None

    @property
    def name(self) -> str:
        knobs = []
        if self.unroll != 1:
            knobs.append(f"unroll={self.unroll}")
        if self.accumulation_steps != 1:
            knobs.append(f"accum={self.accumulation_steps}")
        if self.zero:
            knobs.append(f"zero={self.zero}")
        if self.prefetch_depth:
            knobs.append(f"pf={self.prefetch_depth}")
        if self.wire_dtype:
            knobs.append(f"wire={self.wire_dtype}")
        if self.asynchronous:
            knobs.append("async" + ("" if self.overlap else ",overlap=0"))
        base = self.builder_spec["name"]
        return f"{base}[{','.join(knobs)}]" if knobs else base

    def make_builder(self) -> StrategyBuilder:
        return builder_from_spec(self.builder_spec)

    def base_key(self) -> Tuple:
        """The compile-probe grouping key: candidates differing only in
        ``unroll``/``overlap``/``prefetch_depth``/``wire_dtype`` share one
        probed base program (the fused block's cost is the scanned body's
        x K — the same scaling rule the runner's cost extraction already
        applies — and the prefetch producer and the wire-push compressor
        both change the host pipeline, not the compiled program)."""
        return (self.builder_spec["name"],
                tuple(sorted((self.builder_spec.get("kwargs") or {}).items())),
                self.accumulation_steps, self.zero, self.asynchronous)


@dataclasses.dataclass
class TunedPlan:
    """The autotuner's product: winning knobs + the evidence.

    ``to_dict()``/``from_dict()`` round-trip through the plan cache;
    ``candidates`` (search runs only) carries the full enumerated record
    behind :meth:`explain`."""

    builder_spec: Dict[str, Any]
    unroll: int = 1
    accumulation_steps: int = 1
    zero: int = 0
    overlap: bool = True
    prefetch_depth: int = 0
    wire_dtype: str = ""
    predicted: Optional[Dict[str, Any]] = None
    measured_steps_per_s: Optional[float] = None
    cache_key: str = ""
    from_cache: bool = False
    search_s: float = 0.0
    enumerated: int = 0
    probed: int = 0
    candidates: List[Candidate] = dataclasses.field(default_factory=list)

    def make_builder(self) -> StrategyBuilder:
        return builder_from_spec(self.builder_spec)

    @property
    def name(self) -> str:
        c = Candidate(self.builder_spec, unroll=self.unroll,
                      accumulation_steps=self.accumulation_steps,
                      zero=self.zero, overlap=self.overlap,
                      prefetch_depth=self.prefetch_depth,
                      wire_dtype=self.wire_dtype)
        return c.name

    def knobs_dict(self) -> Dict[str, Any]:
        return {"builder": self.builder_spec, "unroll": self.unroll,
                "accumulation_steps": self.accumulation_steps,
                "zero": self.zero, "overlap": self.overlap,
                "prefetch_depth": self.prefetch_depth,
                "wire_dtype": self.wire_dtype}

    def to_dict(self) -> Dict[str, Any]:
        """The cache entry / profile-manifest record: knobs + prediction +
        measurement + provenance (schema-versioned at the cache file level)."""
        return {
            "cache_key": self.cache_key,
            "knobs": self.knobs_dict(),
            "predicted": self.predicted,
            "measured_steps_per_s": self.measured_steps_per_s,
            "search_s": round(self.search_s, 3),
            "enumerated": self.enumerated,
            "probed": self.probed,
            "from_cache": self.from_cache,
            "created": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TunedPlan":
        knobs = d.get("knobs") or {}
        return cls(builder_spec=knobs.get("builder") or {"name": "AllReduce"},
                   unroll=int(knobs.get("unroll") or 1),
                   accumulation_steps=int(knobs.get("accumulation_steps") or 1),
                   zero=int(knobs.get("zero") or 0),
                   overlap=bool(knobs.get("overlap", True)),
                   prefetch_depth=int(knobs.get("prefetch_depth") or 0),
                   wire_dtype=str(knobs.get("wire_dtype") or ""),
                   predicted=d.get("predicted"),
                   measured_steps_per_s=d.get("measured_steps_per_s"),
                   cache_key=d.get("cache_key") or "",
                   search_s=float(d.get("search_s") or 0.0),
                   enumerated=int(d.get("enumerated") or 0),
                   probed=int(d.get("probed") or 0))

    def explain(self) -> str:
        """Why this plan won: one row per enumerated candidate — predicted
        step time + binding resource from stage 1, measured steps/s or the
        prune/skip reason from stage 2 — ranked by prediction, winner
        marked. A cache-hit plan explains itself from the stored record."""
        if not self.candidates:
            src = "plan cache" if self.from_cache else "search"
            pred = (f"predicted {self.predicted['step_s'] * 1e3:.3f} ms/step "
                    f"({self.predicted.get('bound')}-bound)"
                    if self.predicted else "no prediction")
            meas = (f"measured {self.measured_steps_per_s:.2f} steps/s"
                    if self.measured_steps_per_s else "unmeasured")
            return (f"{self.name}  <- applied from {src} "
                    f"[{self.cache_key}]\n  {pred}; {meas}")
        rows = sorted(self.candidates,
                      key=lambda c: (c.predicted or {}).get("step_s")
                      or float("inf"))
        width = max(len(c.name) for c in rows)
        lines = [f"autotune [{self.cache_key}]: {self.enumerated} candidates, "
                 f"{self.probed} probed, {self.search_s:.2f}s search"]
        for c in rows:
            pred = c.predicted or {}
            p = (f"{pred['step_s'] * 1e3:9.3f} ms/step {pred['bound']:>7}"
                 if pred.get("step_s") is not None else
                 f"{'?':>9} ms/step {'?':>7}")
            if c.probe is not None and c.probe.steps_per_sec is not None:
                tail = f"measured {c.probe.steps_per_sec:8.2f} steps/s"
                if (c.builder_spec == self.builder_spec
                        and c.unroll == self.unroll
                        and c.accumulation_steps == self.accumulation_steps
                        and c.zero == self.zero
                        and c.prefetch_depth == self.prefetch_depth
                        and c.wire_dtype == self.wire_dtype):
                    tail += "  <- winner"
            elif c.probe is not None:
                tail = f"probe: {c.probe.error}"
            elif c.pruned:
                tail = f"pruned: {c.pruned}"
            else:
                tail = "not probed"
            lines.append(f"  {c.name:<{width}}  {p}  {tail}")
        return "\n".join(lines)


# ------------------------------------------------------------------ cache key

def plan_cache_key(model_spec, example_batch: Any = None,
                   resource_spec: Optional[ResourceSpec] = None) -> str:
    """The cache identity of one tuning problem: model/shape signature
    (trainable param names, shapes, dtypes, sparsity) + batch leaf
    shapes/dtypes + device topology (platform, device kind, local device
    count, process count, resource-spec node count) + package version.
    Any of these changing invalidates by MISS — old entries stay valid for
    the jobs they were tuned for."""
    import numpy as np
    from autodist_tpu.version import __version__
    parts: List[str] = [f"v{__version__}"]
    try:
        import jax
        dev = jax.devices()[0]
        parts.append(f"{dev.platform}:{getattr(dev, 'device_kind', '')}"
                     f":d{len(jax.devices())}:p{jax.process_count()}")
    except Exception:  # noqa: BLE001 — key must be computable backend-less
        parts.append("nojax")
    if resource_spec is not None:
        parts.append(f"nodes{resource_spec.num_nodes}")
    for name, p in sorted(model_spec.trainable.items()):
        parts.append(f"{name}:{tuple(p.shape)}:{p.dtype}"
                     f":{'s' if p.sparse else 'd'}")
    if example_batch is not None:
        try:
            import jax
            leaves = jax.tree_util.tree_leaves(example_batch)
        except Exception:  # noqa: BLE001 — same backend-less contract as above
            leaves = []
            parts.append("nobatch")
        for leaf in leaves:
            arr = leaf if hasattr(leaf, "shape") else np.asarray(leaf)
            parts.append(f"b{tuple(arr.shape)}:{arr.dtype}")
    return hashlib.sha1("|".join(parts).encode()).hexdigest()[:16]


def _read_cache_file(path: str) -> Dict[str, Any]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    if (doc.get("schema") != PLAN_SCHEMA
            or doc.get("schema_version") != PLAN_SCHEMA_VERSION):
        logging.warning("plan cache %s has schema %r v%r (want %s v%d); "
                        "ignoring it", path, doc.get("schema"),
                        doc.get("schema_version"), PLAN_SCHEMA,
                        PLAN_SCHEMA_VERSION)
        return {}
    return doc if isinstance(doc.get("plans"), dict) else {}


def load_cached_plan(path: str, key: str) -> Optional[TunedPlan]:
    """The cached :class:`TunedPlan` for ``key``, or None (missing file,
    wrong schema, unknown key, or an entry naming a builder this version
    cannot reconstruct — all misses, never errors)."""
    if not path:
        return None
    entry = _read_cache_file(path).get("plans", {}).get(key)
    if not entry:
        return None
    plan = TunedPlan.from_dict(entry)
    plan.cache_key = key
    plan.from_cache = True
    try:
        plan.make_builder()   # entry must be reconstructible to count as a hit
    except ValueError as e:
        logging.warning("plan cache %s[%s]: %s; treating as a miss", path,
                        key, e)
        return None
    return plan


def store_plan(path: str, plan: TunedPlan) -> bool:
    """Persist ``plan`` under its key (read-modify-write; a fresh or corrupt
    file is recreated). Returns True on success — a failed write logs and
    returns False, a broken disk never takes down the run being tuned.

    The read-modify-write runs under an ``flock`` on a sidecar lock file, so
    two jobs finishing searches against a shared cache merge their entries
    instead of the later ``os.replace`` silently erasing the earlier job's
    plan (which would re-run its full search on every warm launch). The
    rename stays atomic for lock-less readers."""
    if not path:
        return False
    try:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(f"{path}.lock", "a") as lock:
            try:
                import fcntl
                fcntl.flock(lock, fcntl.LOCK_EX)
            except (ImportError, OSError):
                pass   # no flock (non-POSIX / odd fs): best-effort write
            doc = _read_cache_file(path)
            if not doc:
                doc = {"schema": PLAN_SCHEMA,
                       "schema_version": PLAN_SCHEMA_VERSION, "plans": {}}
            doc["plans"][plan.cache_key] = plan.to_dict()
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, path)  # atomic: concurrent readers see old or new
        return True
    except OSError as e:
        logging.warning("plan cache write to %s failed: %s", path, e)
        return False


# ---------------------------------------------------------------- enumeration

def enumerate_candidates(model_spec, resource_spec: ResourceSpec,
                         optimizer=None, *,
                         unrolls: Sequence[int] = DEFAULT_UNROLLS,
                         accums: Sequence[int] = (1,),
                         include_async: Optional[bool] = None,
                         budget: Optional[int] = None,
                         prefetch_depths: Optional[Sequence[int]] = None,
                         loader_s_per_step: float = 0.0,
                         wire_dtypes: Optional[Sequence[str]] = None,
                         ) -> List[Candidate]:
    """The joint candidate space, generated from :class:`AutoStrategy`'s
    analytic rules instead of collapsed to its one answer:

    - **regime**: AllReduce and (sync) PSLoadBalancing always compete;
      memory pressure (resident params + exact optimizer-state bytes vs the
      per-device budget — AutoStrategy's rule) additionally admits the
      async-PS regime with the ``overlap`` knob on/off;
    - **sparse**: any sparse parameter admits Parallax (the sparse-wire
      rule); **partitioning**: any dense parameter above the partition
      threshold with a partitionable axis admits PartitionedAR (and
      PartitionedPS when memory-bound);
    - **knobs**: each builder crosses ``unroll`` (sync only — the async
      regime has no fused block), ``accumulation_steps``, ``zero``
      (only where the mesh has a data-parallel extent to shard over), and
      ``prefetch_depth`` (sync only; enumerated only when the tuning
      problem declares a loader cost — ``loader_s_per_step > 0`` — since
      without one every depth predicts identically), and ``wire_dtype``
      (async only — the quantized-push knob prices wire bytes against
      host quantize seconds, a trade that exists only across the PS wire).

    Deterministic order (builder priority, then unroll/accum/zero
    ascending), capped at ``budget`` (``AUTODIST_TUNE_BUDGET``) with a log
    line naming how many were dropped — a silent cap would read as
    "searched everything" when it didn't."""
    from autodist_tpu.strategy.auto_strategy import (_device_memory_budget,
                                                     _fmt_bytes,
                                                     _opt_state_bytes)
    from autodist_tpu.strategy.partition_utils import partitionable_axis

    if budget is None:
        budget = int(const.ENV.AUTODIST_TUNE_BUDGET.val)
    n_dev = num_devices(resource_spec)
    dense = {n: s for n, s in model_spec.trainable.items() if not s.sparse}
    has_sparse = len(dense) != len(model_spec.trainable)
    dense_bytes = sum(s.byte_size for s in dense.values())
    opt_bytes = _opt_state_bytes(optimizer, model_spec, dense) \
        if optimizer is not None else None
    state_bytes = (dense_bytes + opt_bytes) if opt_bytes is not None \
        else 3 * dense_bytes
    budget_bytes = _device_memory_budget()
    memory_bound = state_bytes > budget_bytes
    partitioned = [s for s in dense.values()
                   if s.byte_size >= 64 << 20
                   and partitionable_axis(s) is not None]
    if include_async is None:
        include_async = memory_bound

    bases: List[Tuple[Dict[str, Any], bool, str]] = [
        ({"name": "AllReduce"}, False, "dense collective baseline"),
        ({"name": "PSLoadBalancing"}, False, "sync PS (the session default)"),
    ]
    if has_sparse:
        bases.append(({"name": "Parallax"}, False,
                      "sparse params ride the sparse wire"))
    if partitioned and n_dev > 1:
        bases.append(({"name": "PartitionedAR"}, False,
                      f"{len(partitioned)} param(s) above the partition "
                      f"threshold"))
        if memory_bound:
            bases.append(({"name": "PartitionedPS"}, False,
                          "partitioned + memory-bound"))
    if include_async:
        why = ("resident state exceeds the per-device budget"
               if memory_bound else "async regime requested")
        bases.append(({"name": "PS", "kwargs": {"sync": False}}, True, why))

    # The zero knob only changes the program where the spec's mesh has a
    # data-parallel extent to shard over — gated on the SAME device count
    # the partition gate reads, so a spec pinning one device never wastes
    # compile probes (or top-k slots) on zero=1 twins of zero=0 programs.
    zeros = [0, 1] if n_dev > 1 else [0]
    # The prefetch knob only differentiates predictions when the problem
    # declares a loader cost; without one, every depth prices identically
    # and enumerating it would only burn budget on twins.
    if prefetch_depths is None:
        prefetch_depths = DEFAULT_PREFETCH_DEPTHS \
            if loader_s_per_step > 0 else (0,)
    if wire_dtypes is None:
        wire_dtypes = DEFAULT_WIRE_DTYPES
    out: List[Candidate] = []
    for spec, is_async, why in bases:
        for accum in accums:
            for zero in zeros:
                if is_async:
                    # The async regime has no fused block and its ZeRO knob
                    # (server-side apply shards) changes no device program;
                    # the client overlap knob is its execution dimension.
                    # (Its per-step train loop can still prefetch, but the
                    # knob is not enumerated: async candidates are
                    # predicted, never measured.)
                    if zero:
                        continue
                    for overlap in (True, False):
                        for wire_dtype in wire_dtypes:
                            out.append(Candidate(
                                spec, unroll=1, accumulation_steps=accum,
                                zero=0, overlap=overlap,
                                wire_dtype=wire_dtype, asynchronous=True,
                                why=why))
                    continue
                for unroll in unrolls:
                    for depth in prefetch_depths:
                        out.append(Candidate(
                            spec, unroll=int(unroll),
                            accumulation_steps=accum, zero=zero,
                            prefetch_depth=int(depth), why=why))
    if len(out) > budget:
        logging.warning(
            "autotune: enumerated %d candidates, keeping the first %d "
            "(AUTODIST_TUNE_BUDGET) — raise the budget to rank the rest",
            len(out), budget)
        out = out[:budget]
    # ---- memory pre-flight: refuse never-fit candidates HERE, before any
    # stage-1 compile probe spends a compile (and possibly an allocator
    # OOM) on a program whose resident state alone exceeds the budget.
    # The budget is the memory plane's (measured x 0.8 / env / warned
    # default); the refusal reason renders as ``pruned: oom: ...`` in
    # TunedPlan.explain().
    part_bytes = sum(s.byte_size for s in partitioned)
    for c in out:
        d_bytes = dense_bytes
        if c.builder_spec["name"].startswith("Partitioned") and n_dev > 1:
            # Partition-eligible params live sharded 1/n_dev per device.
            d_bytes = dense_bytes - part_bytes + part_bytes // n_dev
        c.resident_bytes = _predicted_resident_bytes(
            c, d_bytes, opt_bytes, n_dev)
        if c.resident_bytes > budget_bytes:
            c.pruned = (
                f"oom: predicted resident {_fmt_bytes(c.resident_bytes)} "
                f"exceeds the per-device budget {_fmt_bytes(budget_bytes)}"
                f" — refused before the compile probe")
    return out


def _predicted_resident_bytes(cand: Candidate, dense_bytes: int,
                              opt_bytes: Optional[int], n_dev: int) -> int:
    """A candidate's analytic per-device resident bytes: params + the
    optimizer state its knobs leave on-device (ZeRO shards it ``1/n_dev``;
    the async regime moves it to the PS servers entirely, leaving params +
    the pushed gradient) + one dense gradient buffer when accumulating.
    ``opt_bytes`` is the exact eval_shape footprint when known, else the
    Adam-shaped 2x-params fallback. Program temporaries are NOT included —
    they come from the compiled ledger (``costmodel.predict``'s
    ``peak_hbm_bytes``), which this pre-flight deliberately precedes."""
    opt_eff = opt_bytes if opt_bytes is not None else 2 * dense_bytes
    if cand.asynchronous:
        return int(2 * dense_bytes)
    if cand.zero:
        opt_eff = opt_eff // max(1, n_dev)
    resident = dense_bytes + opt_eff
    if cand.accumulation_steps > 1:
        resident += dense_bytes
    return int(resident)


# ------------------------------------------------------------------ stage 1

def _load_calibration(
        calibration: Optional[costmodel.Calibration]) -> Tuple[
            costmodel.Calibration, str]:
    """The prediction calibration, by preference: an explicit object, the
    newest ``AUTODIST_PROFILE_DIR`` profile (the machine's own achieved
    rates), else the bundled default."""
    if calibration is not None:
        return calibration, "explicit"
    prof_dir = str(const.ENV.AUTODIST_PROFILE_DIR.val)
    if prof_dir and os.path.isdir(prof_dir):
        def mtime(path):
            # The dir may belong to a concurrently-profiling job (the normal
            # way to keep calibration fresh): a file rotated away between
            # listdir and this stat sorts first and is skipped below.
            try:
                return os.path.getmtime(path)
            except OSError:
                return 0.0

        profiles = sorted(
            (os.path.join(prof_dir, f) for f in os.listdir(prof_dir)
             if f.startswith("profile-") and f.endswith(".json")),
            key=mtime)
        for path in reversed(profiles):
            try:
                with open(path) as f:
                    calib = costmodel.calibrate(json.load(f))
                if calib.flops_per_s or calib.host_s_per_dispatch:
                    return calib, f"profile:{os.path.basename(path)}"
            except (OSError, ValueError, TypeError):
                continue
    return DEFAULT_CALIBRATION, "bundled-default"


def _wire_terms(model_spec, cand: Candidate) -> Tuple[float, float]:
    """``(comm_bytes, quantize_bytes)`` one optimizer step charges an async
    candidate. The two wire DIRECTIONS are priced separately because only
    the push compresses: push = dense gradient bytes x the candidate's
    ``wire_dtype`` ratio; pull = dense param bytes, exact, hidden entirely
    when the overlapped client prefetches it behind compute. (The
    calibrated rate is per-direction-symmetric — see
    ``costmodel._wire_bytes_per_s`` — so scaling each direction's byte
    count before summing is the correct composition; scaling the lumped
    2x total by the push ratio would discount the incompressible pull.)
    ``quantize_bytes`` is the DENSE bytes the host must quantize per step
    (the cost side of the trade), zero for exact pushes. Sync candidates
    cross no host wire — their collectives live inside the compiled
    program's own cost analysis."""
    if not cand.asynchronous:
        return 0.0, 0.0
    dense_bytes = float(sum(s.byte_size for s in model_spec.trainable.values()
                            if not s.sparse))
    push = dense_bytes * _WIRE_RATIO.get(cand.wire_dtype, 1.0)
    pull = 0.0 if cand.overlap else dense_bytes
    quantize = dense_bytes if cand.wire_dtype else 0.0
    return push + pull, quantize


def _derive_record(base: Dict[str, Any], unroll: int) -> Dict[str, Any]:
    """A unroll=K candidate's cost record from its base (unroll=1) probe:
    the fused block is the same body scanned K times, so flops/bytes scale
    by K while the dispatch count stays 1 — the amortization
    ``costmodel.predict`` prices via its per-dispatch host term. (The same
    rule the runner's cost extraction applies to real fused programs;
    verified there against a compiled K=4 block.)"""
    return {"flops": (base.get("flops") or 0.0) * unroll or None,
            "bytes_accessed": (base.get("bytes_accessed") or 0.0) * unroll
            or None,
            "steps": unroll * max(1, int(base.get("steps") or 1)),
            "dispatches": 1}


def _probe_base_costs(cands: List[Candidate], loss_fn, params, optimizer,
                      example_batch, resource_spec, sparse_names, has_aux):
    """One compile-only :meth:`plan_costs` probe per distinct base program
    (builder x accum x zero); async bases borrow the sync PS probe's program
    costs (their per-worker grad step is the same math minus the collective
    — the wire term is added separately). Returns ``{base_key: record}``;
    a failed probe records an error string instead."""
    from autodist_tpu.autodist import (AutoDist, get_default_autodist,
                                       set_default_autodist)

    base_costs: Dict[Tuple, Any] = {}
    sync_ps_cost = None
    for cand in cands:
        if cand.pruned:
            # Memory pre-flight refusal: spend ZERO compile probes on a
            # base every surviving candidate has already walked away from.
            continue
        key = cand.base_key()
        if key in base_costs:
            continue
        if cand.asynchronous:
            base_costs[key] = None   # filled from the sync PS probe below
            continue
        prior = get_default_autodist()
        ad = runner = None
        try:
            with telemetry.span("tune.compile_probe", candidate=cand.name):
                ad = AutoDist(resource_spec, cand.make_builder())
                runner = ad.create_distributed_session(
                    loss_fn, params, optimizer, example_batch=example_batch,
                    sparse_names=sparse_names, has_aux=has_aux,
                    accumulation_steps=cand.accumulation_steps,
                    zero=cand.zero, tune=False)
                cost = runner.plan_costs(params, example_batch, unroll=1)
            base_costs[key] = cost if cost is not None else \
                "probe: backend reported no cost analysis"
            if cand.builder_spec["name"] == "PSLoadBalancing" \
                    and isinstance(cost, dict):
                sync_ps_cost = cost
        except Exception as e:  # noqa: BLE001 — a candidate failing to build
            base_costs[key] = f"{type(e).__name__}: {e}"   # must not abort
            logging.warning("autotune compile probe %s failed: %s",
                            cand.name, e)
        finally:
            # Tear each probe session down before the NEXT probe (and long
            # before stage 2's timed measurements): a pile of live probe
            # runners holding compiled executables would skew the very
            # measurements that pick the winner — measure_candidate's
            # teardown-before-timing invariant, kept here too.
            if ad is not None:
                try:
                    ad._teardown()
                except Exception as e:  # noqa: BLE001
                    logging.warning("autotune probe %s teardown: %s",
                                    cand.name, e)
            ad = runner = None  # noqa: F841
            gc.collect()
            set_default_autodist(prior)
    for key, val in base_costs.items():
        if val is None:   # async base: approximate with the sync PS program
            base_costs[key] = sync_ps_cost if sync_ps_cost is not None else \
                "probe: no sync PS base to approximate the async program from"
    return base_costs


# ------------------------------------------------------------------- search

def autotune(loss_fn: Callable, params: Any, optimizer, example_batch: Any, *,
             resource_spec: Optional[ResourceSpec] = None,
             sparse_names: Optional[Sequence[str]] = None,
             has_aux: bool = False,
             unrolls: Sequence[int] = DEFAULT_UNROLLS,
             accumulation_steps: Sequence[int] = (1,),
             top_k: Optional[int] = None,
             budget: Optional[int] = None,
             margin: float = PRUNE_MARGIN,
             calibration: Optional[costmodel.Calibration] = None,
             plan_cache: Optional[str] = None,
             warmup_steps: int = 2, measure_steps: int = 8,
             include_async: Optional[bool] = None,
             prefetch_depths: Optional[Sequence[int]] = None,
             loader_s_per_step: float = 0.0) -> TunedPlan:
    """The two-stage plan search. Returns the winning :class:`TunedPlan`.

    ``loader_s_per_step`` declares the input pipeline's measured per-step
    host-loader seconds (e.g. a timed ``loader.next()``); stage 1 then
    also enumerates ``prefetch_depth`` (``DEFAULT_PREFETCH_DEPTHS``,
    override with ``prefetch_depths=``) and prices each candidate's
    residual data wait as ``max(0, loader_s - hidden_s)`` — the winner's
    depth rides the plan (``train(prefetch_depth=None)`` adopts it, and
    the applied-plan manifest records it).

    A warm ``plan_cache`` entry (``AUTODIST_PLAN_CACHE`` when None) for this
    (model, batch, topology, version) returns immediately — zero compile
    probes, zero measured steps. Otherwise stage 1 compile-probes one base
    program per (builder, accum, zero), derives the unroll grid analytically,
    ranks everything with the calibrated cost model, and prunes; stage 2
    measures at most ``top_k`` (``AUTODIST_TUNE_TOPK``) survivors with
    ``measure_steps`` real steps each through the tuner's shared loop. The
    measured winner is persisted to the cache and returned. Raises
    RuntimeError when every stage-2 probe fails (same contract as
    ``tune_strategy``)."""
    from autodist_tpu.model_spec import ModelSpec

    t_start = time.perf_counter()
    if plan_cache is None:
        plan_cache = str(const.ENV.AUTODIST_PLAN_CACHE.val)
    if top_k is None:
        top_k = int(const.ENV.AUTODIST_TUNE_TOPK.val)
    if top_k < 1:
        raise ValueError("top_k must be >= 1 (stage 2 needs at least one "
                         "measured candidate)")
    resource_spec = resource_spec if resource_spec is not None \
        else ResourceSpec(None)
    if resource_spec.num_nodes > 1:
        raise ValueError(
            "autotune probes candidates on THIS process's local devices; a "
            "multi-node resource spec would be ranked by a measurement that "
            "ignores the cross-node wire (same contract as tune_strategy)")
    model_spec = (ModelSpec(params, sparse_names=sparse_names)
                  if sparse_names is not None
                  else ModelSpec.from_loss_fn(loss_fn, params, example_batch))
    key = plan_cache_key(model_spec, example_batch, resource_spec)

    cached = load_cached_plan(plan_cache, key)
    if cached is not None:
        telemetry.counter("tune.cache_hit").inc()
        logging.info("autotune: plan cache hit [%s] -> %s (predicted %s, "
                     "measured %s steps/s) — zero probe steps", key,
                     cached.name,
                     (cached.predicted or {}).get("step_s"),
                     cached.measured_steps_per_s)
        return cached
    telemetry.counter("tune.cache_miss").inc()

    with telemetry.span("tune.search", key=key):
        # ---- stage 1: enumerate, compile-probe bases, predict, prune
        cands = enumerate_candidates(
            model_spec, resource_spec, optimizer, unrolls=unrolls,
            accums=tuple(accumulation_steps), include_async=include_async,
            budget=budget, prefetch_depths=prefetch_depths,
            loader_s_per_step=loader_s_per_step)
        calib, calib_src = _load_calibration(calibration)
        logging.info("autotune [%s]: %d candidates, calibration %s", key,
                     len(cands), calib_src)
        with telemetry.span("tune.predict", candidates=len(cands)):
            base_costs = _probe_base_costs(
                cands, loss_fn, params, optimizer, example_batch,
                resource_spec, sparse_names, has_aux)
            for c in cands:
                if c.pruned:
                    continue   # memory pre-flight refusal: keep its reason
                base = base_costs.get(c.base_key())
                if not isinstance(base, dict):
                    c.pruned = str(base)
                    continue
                rec = _derive_record(base, c.unroll)
                comm_bytes, quantize_bytes = _wire_terms(model_spec, c)
                c.predicted = costmodel.predict(
                    rec, calib,
                    comm_bytes_per_step=comm_bytes,
                    loader_s_per_step=loader_s_per_step,
                    prefetch_depth=c.prefetch_depth,
                    quantize_bytes_per_step=quantize_bytes,
                    resident_bytes=float(c.resident_bytes or 0))
        predicted = [c for c in cands if c.predicted is not None]
        if not predicted:
            raise RuntimeError(
                "autotune: no candidate could be compile-probed:\n" +
                "\n".join(f"  {c.name}: {c.pruned}" for c in cands))
        best_pred = min(c.predicted["step_s"] for c in predicted)
        ranked = sorted(predicted, key=lambda c: c.predicted["step_s"])
        survivors: List[Candidate] = []
        # prefetch_depth changes the host pipeline, not the compiled
        # program — a depth twin of an already-selected survivor shares
        # that survivor's stage-2 measurement instead of burning a scarce
        # top-k probe slot on a bit-identical program.
        probe_sharers: List[Tuple[Candidate, Candidate]] = []
        probed_programs: Dict[Tuple, Candidate] = {}
        for c in ranked:
            if c.asynchronous:
                c.pruned = ("skipped: async candidate — predicted only, "
                            "not measurable by the synchronous probe loop")
            elif c.predicted["step_s"] > (1.0 + margin) * best_pred:
                c.pruned = (f"predicted {c.predicted['step_s'] * 1e3:.3f} "
                            f"ms/step, > {1.0 + margin:.2f}x the frontrunner"
                            f" ({best_pred * 1e3:.3f} ms)")
            else:
                program = (c.base_key(), c.unroll, c.overlap)
                twin = probed_programs.get(program)
                if twin is not None:
                    probe_sharers.append((c, twin))
                elif len(survivors) >= top_k:
                    c.pruned = f"beyond top-k={top_k}"
                else:
                    survivors.append(c)
                    probed_programs[program] = c
        telemetry.gauge("tune.candidates").set(len(cands))
        # Gauges must reconcile: candidates = pruned + measured-directly
        # (survivors) + measured-via-twin (probe sharers). The oom subset
        # of pruned gets its own gauge — pre-flight refusals are the
        # memory plane's work, not the cost ranking's.
        telemetry.gauge("tune.pruned").set(
            len(cands) - len(survivors) - len(probe_sharers))
        telemetry.gauge("tune.pruned_oom").set(
            sum(1 for c in cands if (c.pruned or "").startswith("oom")))

        # ---- stage 2: measure the survivors with real steps
        for c in survivors:
            with telemetry.span("tune.probe", candidate=c.name):
                c.probe = measure_candidate(
                    c.make_builder(), loss_fn, params, optimizer,
                    example_batch, name=c.name, resource_spec=resource_spec,
                    warmup_steps=warmup_steps, measure_steps=measure_steps,
                    sparse_names=sparse_names, has_aux=has_aux,
                    accumulation_steps=c.accumulation_steps,
                    unroll=c.unroll, zero=c.zero)
        telemetry.gauge("tune.probed").set(len(survivors))
        for c, twin in probe_sharers:
            c.probe = twin.probe   # same compiled program, one measurement
        measured = [c for c in survivors + [s for s, _ in probe_sharers]
                    if c.probe is not None
                    and c.probe.steps_per_sec is not None]
        if not measured:
            raise RuntimeError(
                "autotune: every stage-2 probe failed or was skipped:\n" +
                "\n".join(f"  {c.name}: {c.probe.error}" for c in survivors))

        def effective_steps_per_s(c: Candidate) -> float:
            # The probe loop feeds a resident synthetic batch — it measures
            # the PROGRAM, not the loader — so a declared loader cost is
            # added back as the candidate's priced residual data wait
            # (max(0, loader_s - hidden_s), 0 for depth >= 1 pipelines that
            # hide it). Without this, prefetch-depth twins would tie on
            # measurement and load noise would pick the knob.
            sps = c.probe.steps_per_sec
            data_s = (((c.predicted or {}).get("breakdown") or {})
                      .get("data_wait_s") or 0.0)
            return 1.0 / (1.0 / sps + data_s) if data_s > 0 else sps

        winner = max(measured, key=effective_steps_per_s)

    plan = TunedPlan(
        builder_spec=winner.builder_spec, unroll=winner.unroll,
        accumulation_steps=winner.accumulation_steps, zero=winner.zero,
        overlap=winner.overlap, prefetch_depth=winner.prefetch_depth,
        wire_dtype=winner.wire_dtype,
        predicted=winner.predicted,
        measured_steps_per_s=winner.probe.steps_per_sec, cache_key=key,
        search_s=time.perf_counter() - t_start, enumerated=len(cands),
        probed=len(survivors), candidates=cands)
    telemetry.gauge("tune.best_steps_per_s").set(plan.measured_steps_per_s)
    telemetry.gauge("tune.search_s").set(plan.search_s)
    if plan_cache:
        store_plan(plan_cache, plan)
    logging.info("autotune winner [%s]: %s (%.2f steps/s measured, %.2f ms "
                 "predicted, %d/%d probed, %.2fs search)", key, plan.name,
                 plan.measured_steps_per_s,
                 (plan.predicted or {}).get("step_s", 0.0) * 1e3,
                 plan.probed, plan.enumerated, plan.search_s)
    return plan
