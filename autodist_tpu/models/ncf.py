"""NeuMF recommender — the sparse-gradient-heavy benchmark.

Counterpart of the reference NCF benchmark (``examples/benchmark/ncf.py`` +
``utils/recommendation``): two embedding pairs (GMF + MLP towers) whose gradients
are row-sparse, exercising the PS/Parallax sparse path the same way the reference's
``SparseConditionalAccumulator`` did.
"""

import dataclasses
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class NeuMFConfig:
    num_users: int = 138_000
    num_items: int = 27_000
    mf_dim: int = 64
    mlp_dims: Sequence[int] = (256, 128, 64)
    dtype: Any = jnp.float32


class NeuMF(nn.Module):
    config: NeuMFConfig

    @nn.compact
    def __call__(self, users, items):
        cfg = self.config
        embed = lambda n, d, name: nn.Embed(  # noqa: E731
            n, d, dtype=cfg.dtype, param_dtype=jnp.float32, name=name)
        mf_u = embed(cfg.num_users, cfg.mf_dim, "mf_user_embed")(users)
        mf_i = embed(cfg.num_items, cfg.mf_dim, "mf_item_embed")(items)
        mlp_u = embed(cfg.num_users, cfg.mlp_dims[0] // 2, "mlp_user_embed")(users)
        mlp_i = embed(cfg.num_items, cfg.mlp_dims[0] // 2, "mlp_item_embed")(items)

        gmf = mf_u * mf_i
        x = jnp.concatenate([mlp_u, mlp_i], axis=-1)
        for i, d in enumerate(cfg.mlp_dims[1:]):
            x = nn.relu(nn.Dense(d, dtype=cfg.dtype, param_dtype=jnp.float32,
                                 name=f"mlp_{i}")(x))
        both = jnp.concatenate([gmf, x], axis=-1)
        return nn.Dense(1, dtype=jnp.float32, name="head")(both)[..., 0]


def make_loss_fn(model: NeuMF) -> Callable:
    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["users"], batch["items"])
        labels = batch["labels"].astype(jnp.float32)
        # Numerically stable sigmoid cross entropy.
        return jnp.mean(jnp.maximum(logits, 0) - logits * labels
                        + jnp.log1p(jnp.exp(-jnp.abs(logits))))
    return loss_fn


def synthetic_batch(config: NeuMFConfig, batch_size: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    return {
        "users": rng.randint(0, config.num_users, size=(batch_size,)).astype(np.int32),
        "items": rng.randint(0, config.num_items, size=(batch_size,)).astype(np.int32),
        "labels": rng.randint(0, 2, size=(batch_size,)).astype(np.float32),
    }
