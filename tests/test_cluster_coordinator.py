"""Cluster/Coordinator launch protocol — reference coordinator.py / cluster.py parity
tested with local (loopback) worker addresses so no real SSH is needed."""

import json
import os
import sys
import time

import jax.numpy as jnp

from autodist_tpu import const
from autodist_tpu.cluster import Cluster, is_local_address
from autodist_tpu.coordinator import Coordinator
from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import AllReduce

TWO_NODE = ResourceSpec(
    "nodes: [{address: localhost, tpus: 4, chief: true}, {address: 127.0.0.1, tpus: 4}]")


def _strategy():
    model = ModelSpec({"w": jnp.zeros((4, 2))})
    return AllReduce().build(model, TWO_NODE)


def test_cluster_spec_deterministic_ids():
    c = Cluster(TWO_NODE)
    assert c.num_processes == 2
    assert c.cluster_spec["processes"][0]["address"] == "localhost"  # chief first
    assert c.process_id_of("127.0.0.1") == 1
    assert c.cluster_spec["coordinator"].startswith("localhost:")


def test_cluster_start_writes_spec_file(tmp_path, monkeypatch):
    monkeypatch.setattr(const, "DEFAULT_WORKING_DIR", str(tmp_path))
    c = Cluster(TWO_NODE)
    c.start()
    with open(tmp_path / "cluster_spec.json") as f:
        spec = json.load(f)
    assert spec == c.cluster_spec


def test_remote_exec_local_runs_with_env(tmp_path):
    c = Cluster(TWO_NODE)
    out = tmp_path / "envdump"
    proc = c.remote_exec(
        [sys.executable, "-c",
         f"import os; open({str(out)!r}, 'w').write(os.environ.get('AUTODIST_WORKER',''))"],
        "localhost", env={"AUTODIST_WORKER": "127.0.0.1"})
    assert proc.wait() == 0
    assert out.read_text() == "127.0.0.1"


def test_remote_file_write_and_copy_local(tmp_path):
    c = Cluster(TWO_NODE)
    target = tmp_path / "sub" / "f.txt"
    c.remote_file_write(str(target), "hello", "localhost")
    assert target.read_text() == "hello"
    src = tmp_path / "src.bin"
    src.write_bytes(b"abc")
    c.remote_copy(str(src), str(tmp_path / "dest"), "127.0.0.1")
    assert (tmp_path / "dest" / "src.bin").read_bytes() == b"abc"


def test_coordinator_launches_worker_with_role_env(tmp_path):
    """The worker re-runs 'the user script' with AUTODIST_WORKER/STRATEGY_ID/
    process-id env set (reference coordinator.py:66-90)."""
    strategy = _strategy()
    cluster = Cluster(TWO_NODE)
    out = tmp_path / "worker_env.json"
    script = tmp_path / "user_script.py"
    script.write_text(
        "import json, os\n"
        "keys = ['AUTODIST_WORKER', 'AUTODIST_STRATEGY_ID',\n"
        "        'AUTODIST_COORDINATOR_ADDR', 'AUTODIST_NUM_PROCESSES',\n"
        "        'AUTODIST_PROCESS_ID']\n"
        f"json.dump({{k: os.environ.get(k) for k in keys}}, open({str(out)!r}, 'w'))\n")
    coord = Coordinator(strategy, cluster, argv=[str(script)])
    coord.launch_clients()
    coord.join()
    env = json.loads(out.read_text())
    assert env["AUTODIST_WORKER"] == "127.0.0.1"
    assert env["AUTODIST_STRATEGY_ID"] == strategy.id
    assert env["AUTODIST_NUM_PROCESSES"] == "2"
    assert env["AUTODIST_PROCESS_ID"] == "1"
    assert env["AUTODIST_COORDINATOR_ADDR"].startswith("localhost:")
    # strategy file exists where the worker will load it
    assert os.path.exists(os.path.join(const.DEFAULT_SERIALIZATION_DIR, strategy.id))


def test_watchdog_fires_on_nonzero_worker_exit(tmp_path):
    strategy = _strategy()
    cluster = Cluster(TWO_NODE)
    script = tmp_path / "bad_script.py"
    script.write_text("import sys; sys.exit(3)\n")
    failures = []

    class TestCoordinator(Coordinator):
        def _on_worker_failure(self, address, code):
            failures.append((address, code))

    coord = TestCoordinator(strategy, cluster, argv=[str(script)])
    coord.launch_clients()
    deadline = time.time() + 10
    while not failures and time.time() < deadline:
        time.sleep(0.05)
    assert failures == [("127.0.0.1", 3)]


def test_cluster_terminate_kills_processes():
    c = Cluster(TWO_NODE)
    proc = c.remote_exec([sys.executable, "-c", "import time; time.sleep(60)"],
                         "localhost")
    assert proc.poll() is None
    c.terminate()
    deadline = time.time() + 5
    while proc.poll() is None and time.time() < deadline:
        time.sleep(0.05)
    assert proc.poll() is not None


def test_is_local_address():
    assert is_local_address("localhost")
    assert is_local_address("127.0.0.1")
    assert not is_local_address("10.0.0.5")


def test_is_local_address_own_ip():
    """A resource spec listing the chief's real IP/hostname must take the local
    fast path, not SSH to itself (reference utils/network.py:21-75)."""
    import socket
    hostname = socket.gethostname()
    assert is_local_address(hostname)
    try:
        own_ip = socket.gethostbyname(hostname)
    except OSError:
        own_ip = None
    if own_ip:
        assert is_local_address(own_ip)
    assert not is_local_address("203.0.113.7")  # TEST-NET-3: never a real host
