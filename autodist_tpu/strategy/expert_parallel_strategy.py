"""Expert-parallel strategy: MoE expert weights sharded over the ``expert`` axis.

Beyond reference parity (the reference's strategies cover data parallelism and
per-variable placement only, SURVEY.md §2.2); this builder targets MoE models
(``models/moe.py``). Parameters identified as expert-banked — leading dimension
equal to ``num_experts`` and matching the ``expert_filter`` name test — get a
partitioner on tensor axis 0 mapped onto the ``expert`` mesh axis, so each device
stores only its experts and XLA inserts the dispatch/return ``all_to_all``s.
Every other parameter falls back to AllReduce data parallelism (replicated +
implicit gradient psum).
"""

from typing import Callable, Optional

from autodist_tpu import const
from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.all_reduce_strategy import parse_ar_options
from autodist_tpu.strategy.base import Strategy, StrategyBuilder, num_devices


def _default_expert_filter(name: str) -> bool:
    return "expert" in name.lower()


class ExpertParallel(StrategyBuilder):
    """AllReduce everywhere + expert-axis sharding for expert-banked parameters.

    ``expert_axis_size`` sizes the mesh ``expert`` axis (-1 = one expert shard per
    device group; must divide both the device count and ``num_experts``); the
    remaining devices fill the ``data`` axis.
    """

    def __init__(self, num_experts: int, expert_axis_size: int = -1,
                 expert_filter: Optional[Callable[[str], bool]] = None,
                 chunk_size: int = 128, all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor"):
        if num_experts < 2:
            raise ValueError("num_experts must be >= 2")
        self._num_experts = num_experts
        self._expert_axis_size = expert_axis_size
        self._expert_filter = expert_filter or _default_expert_filter
        self._chunk_size, self._spec, self._compressor = parse_ar_options(
            chunk_size, all_reduce_spec, compressor)

    def _resolve_expert_axis(self, resource_spec: ResourceSpec) -> int:
        n = num_devices(resource_spec)
        size = self._expert_axis_size
        if size == -1:
            # Largest divisor of both the device count and the expert count: every
            # expert shard holds num_experts/size whole experts.
            size = next(s for s in range(min(n, self._num_experts), 0, -1)
                        if n % s == 0 and self._num_experts % s == 0)
        if n % size != 0:
            raise ValueError(
                f"expert_axis_size={size} does not divide {n} devices")
        if self._num_experts % size != 0:
            raise ValueError(
                f"expert_axis_size={size} does not divide num_experts="
                f"{self._num_experts}")
        return size

    def build(self, model_spec: ModelSpec, resource_spec: ResourceSpec) -> Strategy:
        expert_size = self._resolve_expert_axis(resource_spec)

        def is_expert(spec):
            return (self._expert_filter(spec.name) and len(spec.shape) >= 1
                    and spec.shape[0] == self._num_experts)

        return self._build_axis0_sharded(
            model_spec, resource_spec, const.MESH_AXIS_EXPERT, expert_size,
            is_expert, self._spec, self._compressor, self._chunk_size)
