"""Disk-fed vs device-resident flagship throughput.

The reference's input layer read real datasets from disk (lm1b corpus files,
``examples/lm1b/lm1b_train.py:30-50``; ImageNet with a synthetic option,
``examples/benchmark/imagenet.py``), so its throughput numbers included input
cost. This script measures that cost here: the flagship Transformer LM config
(bench.py) trained from (a) one device-resident synthetic batch and (b) a
token corpus streamed from memory-mapped ``.npy`` shards through the native
prefetch ring + ``device_prefetch``. A healthy pipeline keeps (b) within a few
percent of (a): the gather/page-fault work rides the C++ worker thread and the
host->HBM transfer overlaps the running step.

    python examples/benchmark/disk_input.py [--rows 100000] [--steps 30]
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--rows", type=int, default=100_000,
                        help="corpus rows (each seq_len+1 int32 tokens)")
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--batch_size", type=int, default=0)
    parser.add_argument("--data_dir", type=str, default=None,
                        help="reuse an existing corpus (else a synthetic one "
                             "is written to a temp dir)")
    args = parser.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.data import DataLoader, device_prefetch, save_shards
    from autodist_tpu.models import transformer_lm
    from autodist_tpu.ops import mosaic_compiles
    from autodist_tpu.strategy import AllReduce

    on_accel = jax.default_backend() != "cpu"
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=32_000, d_model=512 if on_accel else 64, n_heads=8,
        n_layers=6 if on_accel else 2, d_ff=2048 if on_accel else 256,
        max_len=512, dtype=jnp.bfloat16 if on_accel else jnp.float32,
        tied_output=False, fused_head=mosaic_compiles())
    seq_len = 256 if on_accel else 32
    batch_size = args.batch_size or ((384 if on_accel else 8)
                                     * len(jax.devices()))

    model, params = transformer_lm.init_params(cfg)
    loss_fn = transformer_lm.make_loss_fn(model)
    example = transformer_lm.synthetic_batch(cfg, batch_size, seq_len)

    ad = AutoDist(strategy_builder=AllReduce())
    step = ad.function(loss_fn, params, optax.adam(1e-3), example_batch=example)

    def timed(get_batch, label):
        for _ in range(3):
            loss = step(get_batch())
        _ = float(loss)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            loss = step(get_batch())
        _ = float(loss)  # host read = completion fence
        rate = batch_size * seq_len * args.steps / (time.perf_counter() - t0)
        print(f"{label}: {rate:,.0f} tokens/s")
        return rate

    # (a) device-resident synthetic batch — the chip-only ceiling.
    resident = step.runner.shard_batch(example)
    rate_resident = timed(lambda: resident, "device-resident synthetic")

    # (b) disk-fed: mmap'd shards -> native gather -> device_prefetch.
    data_dir = args.data_dir
    tmp = None
    if data_dir is None:
        tmp = tempfile.mkdtemp(prefix="adtpu_corpus_")
        rng = np.random.RandomState(0)
        tokens = rng.randint(0, cfg.vocab_size,
                             size=(args.rows, seq_len + 1)).astype(np.int32)
        save_shards({"tokens": tokens}, tmp,
                    rows_per_shard=max(1, args.rows // 8))
        del tokens
        data_dir = tmp
    import glob
    shards = sorted(glob.glob(os.path.join(data_dir, "tokens-*.npy")))
    loader = DataLoader(files={"tokens": shards}, batch_size=batch_size,
                        shuffle=True, prefetch=4)
    feed = device_prefetch(loader, step.runner, depth=2)
    try:
        rate_disk = timed(lambda: next(feed), "disk-fed (mmap shards)")
        native = loader.is_native
    finally:
        feed.close()     # stop the producer before its loader goes away
        loader.close()

    print(json.dumps({
        "resident_tokens_per_sec": round(rate_resident),
        "disk_tokens_per_sec": round(rate_disk),
        "disk_vs_resident": round(rate_disk / rate_resident, 4),
        "corpus_rows": args.rows,
        "shards": len(shards),
        "native_loader": native,
    }))
    if tmp is not None:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return rate_disk / rate_resident


if __name__ == "__main__":
    main()
