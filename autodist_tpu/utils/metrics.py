"""Throughput instrumentation.

Counterparts of the reference's benchmark-side observability (SURVEY.md §5.1):
``TimeHistory`` (``examples/benchmark/imagenet.py:84-133``, examples/sec per log
period + run average) and ``ExamplesPerSecondHook``
(``examples/benchmark/utils/logs/hooks.py:28-130``). These live in the framework
here (the reference kept them in examples) so every example/benchmark shares one
implementation.
"""

import threading
import time
from typing import Dict, List, Optional, Union

from autodist_tpu import telemetry
from autodist_tpu.utils import logging
from autodist_tpu.testing.sanitizer import san_lock

_WIRE_TEL = None


def _wire_registry():
    """The telemetry registry's process-aggregate wire counters, or ``None``
    while telemetry is disabled (the common case — one ``enabled`` check per
    increment). Cached after first use; the registry is get-or-create so the
    cache can never race."""
    if not telemetry.enabled():
        return None
    global _WIRE_TEL
    if _WIRE_TEL is None:
        reg = telemetry.registry()
        _WIRE_TEL = (reg.counter("ps.wire.bytes_sent"),
                     reg.counter("ps.wire.msgs_sent"),
                     reg.counter("ps.wire.encode_s"),
                     reg.counter("ps.wire.bytes_received"),
                     reg.counter("ps.wire.msgs_received"),
                     reg.counter("ps.wire.decode_s"))
    return _WIRE_TEL


class WireCounters:
    """Per-connection PS-transport accounting: payload bytes and message
    counts in both directions plus cumulative encode/decode seconds.

    The transport's counterpart of the reference's grpc channel stats: one
    instance per socket (client side) or aggregated across connections
    (server side — increments are locked so concurrent handler threads
    cannot lose counts). ``format_line()`` is the compact rendering the
    async-PS log line carries.

    With telemetry enabled, primary instances (``mirror=True``, the default)
    additionally fold every increment into the process-global registry's
    ``ps.wire.*`` counters; secondary views over the same traffic (the PS
    server's per-worker breakdown) pass ``mirror=False`` so bytes are never
    registry-counted twice. :meth:`merge` never mirrors for the same reason —
    the folded counters already mirrored when they streamed."""

    __slots__ = ("bytes_sent", "bytes_received", "msgs_sent", "msgs_received",
                 "encode_s", "decode_s", "_lock", "_mirror")

    def __init__(self, mirror: bool = True):
        self.bytes_sent = 0
        self.bytes_received = 0
        self.msgs_sent = 0
        self.msgs_received = 0
        self.encode_s = 0.0
        self.decode_s = 0.0
        self._lock = san_lock()
        self._mirror = mirror

    def add_sent(self, nbytes: int, encode_s: float = 0.0):
        with self._lock:
            self.bytes_sent += nbytes
            self.msgs_sent += 1
            self.encode_s += encode_s
        tel = _wire_registry() if self._mirror else None
        if tel is not None:
            tel[0].inc(nbytes)
            tel[1].inc()
            tel[2].inc(encode_s)

    def add_received(self, nbytes: int, decode_s: float = 0.0):
        with self._lock:
            self.bytes_received += nbytes
            self.msgs_received += 1
            self.decode_s += decode_s
        tel = _wire_registry() if self._mirror else None
        if tel is not None:
            tel[3].inc(nbytes)
            tel[4].inc()
            tel[5].inc(decode_s)

    def snapshot(self) -> Dict[str, Union[int, float]]:
        """Wire-encodable dict of all six counters under one lock hold (the
        ``stats`` opcode's per-connection payload)."""
        with self._lock:
            return {"bytes_sent": self.bytes_sent,
                    "bytes_received": self.bytes_received,
                    "msgs_sent": self.msgs_sent,
                    "msgs_received": self.msgs_received,
                    "encode_s": self.encode_s,
                    "decode_s": self.decode_s}

    def merge(self, other: "WireCounters"):
        """Fold another counter set into this one (prefetch-join accounting:
        bytes pulled by a background prefetch are attributed when consumed,
        keeping ``wire_bytes`` reads deterministic)."""
        with self._lock:
            self.bytes_sent += other.bytes_sent
            self.bytes_received += other.bytes_received
            self.msgs_sent += other.msgs_sent
            self.msgs_received += other.msgs_received
            self.encode_s += other.encode_s
            self.decode_s += other.decode_s

    def format_line(self) -> str:
        """``wire tx 12.3MB/45 rx 67.8MB/46 enc 1.2ms/msg dec 3.4ms/msg``."""
        def mb(n):
            return f"{n / 1e6:.1f}MB"
        enc = 1e3 * self.encode_s / max(self.msgs_sent, 1)
        dec = 1e3 * self.decode_s / max(self.msgs_received, 1)
        return (f"wire tx {mb(self.bytes_sent)}/{self.msgs_sent} "
                f"rx {mb(self.bytes_received)}/{self.msgs_received} "
                f"enc {enc:.2f}ms/msg dec {dec:.2f}ms/msg")


def _sync(value) -> float:
    """Force a device->host read of ``value`` (a completion fence for the
    asynchronously dispatched step it came from); a no-op when jax is absent
    or the value is host-side already. Returns the seconds spent blocked on
    the readback (0.0 when skipped) and records them as the
    ``train.readback_wait_s`` counter / ``train.readback_wait`` span when
    telemetry is on."""
    if value is None:
        return 0.0
    try:
        import jax
    except ImportError:  # meter used from a jax-less tool: rates become
        return 0.0       # dispatch rates, which is all that exists there
    t0 = time.perf_counter()
    try:
        with telemetry.span("train.readback_wait"):
            jax.device_get(value)
    except (RuntimeError, ValueError, TypeError) as e:
        # Narrow on purpose: a failed readback must not crash metering, but
        # the old bare `except Exception: pass` silently turned the meter
        # into a dispatch-rate meter — leave a diagnosable trace instead.
        logging.debug("metrics._sync: device readback failed (%s: %s); the "
                      "period rate will measure dispatch, not compute",
                      type(e).__name__, e)
    elapsed = time.perf_counter() - t0
    if telemetry.enabled():
        telemetry.counter("train.readback_wait_s").inc(elapsed)
    return elapsed


class ThroughputMeter:
    """examples/sec (or tokens/sec) per log period plus a run average."""

    def __init__(self, batch_size: int, log_every: int = 100,
                 unit: str = "examples", warmup_steps: int = 1,
                 log: bool = True):
        self._batch_size = batch_size
        self._log_every = log_every
        self._unit = unit
        self._warmup = warmup_steps
        self._log = log  # False when the caller emits its own period log line
        self._step = 0
        now = time.perf_counter()
        # warmup_steps=0 means "count from construction"; otherwise these restart
        # when the last warmup step lands.
        self._period_start: float = now
        self._run_start: float = now
        self._run_end: Optional[float] = None   # frozen by finish()
        self._run_steps = 0
        self._period_steps = 0   # block-mode (step_many) period accounting
        self._period_readback_s = 0.0
        # Seconds the LAST CLOSED period spent blocked on device->host
        # readback — the `rb` field on the train: log line.
        self.last_readback_s = 0.0
        self.history: List[float] = []

    @property
    def batch_size(self) -> int:
        """Examples per step — what divides a period's examples/s rate back
        into the steps/s the fleet console compares across processes."""
        return self._batch_size

    def step(self, sync=None) -> Optional[float]:
        """Record one completed step; returns the period rate when a period ends.

        Pass the step's fetched value (e.g. the loss array) as ``sync``: dispatch is
        asynchronous, so at period boundaries the meter forces a device->host read
        of it before taking the clock — otherwise rates measure dispatch, not
        compute."""
        self._step += 1
        self._run_end = None   # stepping again unfreezes a finish()ed clock
        at_boundary = (self._step > self._warmup
                       and (self._run_steps + 1) % self._log_every == 0)
        if at_boundary or self._step == self._warmup:
            self._period_readback_s += _sync(sync)
        now = time.perf_counter()
        if self._step <= self._warmup:
            # Exclude compile/warmup from rates (reference TimeHistory did the same
            # by starting timers on_batch_begin after the first epoch).
            self._period_start = now
            self._run_start = now
            self._run_steps = 0
            return None
        self._run_steps += 1
        if self._run_steps % self._log_every == 0:
            rate = self._log_every * self._batch_size / (now - self._period_start)
            self.history.append(rate)
            if self._log:
                logging.info("step %d: %.1f %s/sec", self._step, rate, self._unit)
            self._period_start = now
            self.last_readback_s = self._period_readback_s
            self._period_readback_s = 0.0
            return rate
        return None

    def step_many(self, n: int, sync=None) -> Optional[float]:
        """Record ``n`` steps completed as ONE fused dispatch
        (``runner.run_many`` block mode); returns the period rate when one or
        more ``log_every`` periods closed inside this block.

        The block analogue of :meth:`step`: the first call is wholly warmup
        (it carries the block compile), a period closes at the first block
        boundary with >= ``log_every`` post-warmup steps since the last
        period, and the rate uses the actual step count — block-granular
        logging stays unbiased even when cadence-clipped blocks make periods
        ragged. ``sync`` is read back (device->host) only when a period
        closes."""
        if n < 1:
            return None
        first = self._step == 0
        self._step += n
        self._run_end = None   # stepping again unfreezes a finish()ed clock
        if first and self._warmup:
            _sync(sync)
            now = time.perf_counter()
            self._period_start = now
            self._run_start = now
            self._run_steps = 0
            self._period_steps = 0
            return None
        self._run_steps += n
        self._period_steps += n
        if self._period_steps < self._log_every:
            return None
        self._period_readback_s += _sync(sync)
        now = time.perf_counter()
        rate = self._period_steps * self._batch_size / (now - self._period_start)
        self.history.append(rate)
        if self._log:
            logging.info("step %d: %.1f %s/sec", self._step, rate, self._unit)
        self._period_start = now
        self._period_steps = 0
        self.last_readback_s = self._period_readback_s
        self._period_readback_s = 0.0
        return rate

    def finish(self) -> Optional[float]:
        """Freeze the run clock at training end; returns the final average.

        :attr:`average` reads the clock at CALL time, so querying it after
        the run — post-eval, teardown, a summary printed minutes later —
        silently diluted the rate with non-training wall time. ``train()``
        calls this when its loop exits; idempotent, and a subsequent
        ``step()`` unfreezes (the meter is training again)."""
        if self._run_end is None:
            self._run_end = time.perf_counter()
        return self.average

    @property
    def average(self) -> Optional[float]:
        """Run-average rate excluding warmup (reference logged the same).
        Uses the clock frozen by :meth:`finish` when the run has ended."""
        if not self._run_steps:
            return None
        end = self._run_end if self._run_end is not None else time.perf_counter()
        return self._run_steps * self._batch_size / (end - self._run_start)
