// Native framed-message data plane for the PS transport.
//
// The reference delegated its PS plane to TensorFlow's C++ grpc runtime
// (SURVEY.md §2.4); here the Python protocol layer (typed wire codec,
// staleness gate) stays Python and this library owns the bytes-on-the-wire
// hot path: one writev for header+payload (the Python fallback concatenates,
// copying the whole multi-MB payload), and one malloc + full-read loop for
// receive (the fallback accumulates chunks through a Python loop). Calls run
// with the GIL released (ctypes).
//
// Framing matches the Python fallback exactly — 8-byte big-endian length then
// payload — so native and fallback endpoints interoperate freely.
//
// Build: g++ -O2 -shared -fPIC transport.cc -o transport.so  (done lazily by
// ps_transport.py, like data/native/loader.cc).

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>

#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

namespace {

// Last failure's errno, per thread (0 = orderly EOF). The -1 return code
// collapses all failures; Python reads this back via tr_last_errno() so a
// native-path ConnectionError carries the same diagnostic the fallback's
// OSError would.
thread_local int g_last_errno = 0;

uint64_t to_be64(uint64_t v) {
  const uint16_t probe = 1;
  if (*reinterpret_cast<const uint8_t*>(&probe) == 0) return v;  // big-endian
  uint64_t r = 0;
  for (int i = 0; i < 8; ++i) r = (r << 8) | ((v >> (8 * i)) & 0xff);
  return r;
}

// Full-read loop; returns 0 on success, -1 on EOF/error, -2 when interrupted
// by a signal BEFORE any byte moved (so Python can run signal handlers at a
// clean message boundary and retry; mid-message interrupts retry here — the
// peer has committed to the message and it completes in bounded time).
int read_exact(int fd, void* buf, size_t n, bool* started) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r == 0) {                                // peer closed
      g_last_errno = 0;
      return -1;
    }
    if (r < 0) {
      if (errno == EINTR) {
        if (!*started) return -2;
        continue;
      }
      g_last_errno = errno;
      return -1;
    }
    *started = true;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return 0;
}

}  // namespace

extern "C" {

// Send one framed message (header + payload) with writev; loops until done.
// Returns 0 on success, -1 on error, -2 when a signal arrived before any byte
// was written (caller retries from Python so signal handlers run).
int tr_send(int fd, const void* buf, uint64_t n) {
  uint64_t hdr = to_be64(n);
  struct iovec iov[2];
  iov[0].iov_base = &hdr;
  iov[0].iov_len = sizeof(hdr);
  iov[1].iov_base = const_cast<void*>(buf);
  iov[1].iov_len = static_cast<size_t>(n);
  int idx = 0;
  bool started = false;
  while (idx < 2) {
    ssize_t w = ::writev(fd, &iov[idx], 2 - idx);
    if (w < 0) {
      if (errno == EINTR) {
        if (!started) return -2;
        continue;
      }
      g_last_errno = errno;
      return -1;
    }
    if (w > 0) started = true;
    auto remaining = static_cast<size_t>(w);
    while (idx < 2 && remaining >= iov[idx].iov_len) {
      remaining -= iov[idx].iov_len;
      ++idx;
    }
    if (idx < 2 && remaining > 0) {
      iov[idx].iov_base = static_cast<uint8_t*>(iov[idx].iov_base) + remaining;
      iov[idx].iov_len -= remaining;
    }
  }
  return 0;
}

// Receive one framed message. On success returns the payload length and sets
// *out to a malloc'd buffer (caller frees via tr_free). Returns -1 on
// EOF/error, -2 when a signal arrived before any byte of the message was read
// (caller retries from Python). No buffer is allocated on either error.
int64_t tr_recv(int fd, void** out) {
  uint64_t hdr;
  bool started = false;
  int rc = read_exact(fd, &hdr, sizeof(hdr), &started);
  if (rc != 0) return rc;
  uint64_t n = to_be64(hdr);
  void* buf = std::malloc(n ? static_cast<size_t>(n) : 1);
  if (buf == nullptr) {
    g_last_errno = ENOMEM;
    return -1;
  }
  if (n && read_exact(fd, buf, static_cast<size_t>(n), &started) != 0) {
    std::free(buf);
    return -1;
  }
  *out = buf;
  return static_cast<int64_t>(n);
}

void tr_free(void* p) { std::free(p); }

// errno of this thread's most recent tr_send/tr_recv failure (0 = the peer
// closed the connection in an orderly way). Valid immediately after a -1.
int tr_last_errno() { return g_last_errno; }

}  // extern "C"
