"""Small intra-module AST call-graph utilities shared by the checks.

Scope here is one module: bare-name calls to module-level functions and
``self.x()`` calls to methods of the enclosing class — the building blocks.
Cross-module resolution (imports, ``module.f()`` chains, instance typing,
re-export chains) lives in :mod:`autodist_tpu.analysis.program`, which
composes these utilities into the whole-program :class:`ProgramIndex` the
interprocedural checks (GL001/GL002/GL009-GL011) run on.
"""

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple


def dotted_name(node) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain; None for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_attr(node) -> Optional[str]:
    """The final component of a Name/Attribute chain (``c`` of ``a.b.c``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def name_tokens(name: Optional[str]) -> Set[str]:
    """Lower-cased underscore tokens of an identifier (``_write_mutex`` ->
    {"write", "mutex"}). Token matching avoids substring traps ("block"
    contains "lock")."""
    if not name:
        return set()
    return {t for t in name.lower().split("_") if t}


class ModuleIndex:
    """Per-module map of callable definitions. Call RESOLUTION lives in
    :class:`~autodist_tpu.analysis.program.ProgramIndex`, which consumes
    these maps — this class only indexes what one module defines."""

    def __init__(self, tree: ast.Module):
        self.module_funcs: Dict[str, ast.FunctionDef] = {}
        self.methods: Dict[Tuple[str, str], ast.FunctionDef] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_funcs[node.name] = node
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.methods[(node.name, item.name)] = item


def calls_under(node) -> Iterator[ast.Call]:
    """Every Call node in ``node``'s subtree, in source order."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def innermost_function(tree: ast.Module, node) -> Optional[ast.FunctionDef]:
    """The innermost FunctionDef/AsyncFunctionDef whose span contains
    ``node``'s line, or None at module level — the shared scope lookup the
    program-level checks use for local-type inference. The per-module span
    index is built once and memoized ON the tree object (lifetime-correct:
    it dies with the tree), so each lookup is O(defs), not O(AST)."""
    spans = getattr(tree, "_graftlint_fn_spans", None)
    if spans is None:
        spans = [(fn.lineno, fn.end_lineno or fn.lineno, fn)
                 for fn in ast.walk(tree)
                 if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))]
        tree._graftlint_fn_spans = spans
    best = None
    line = node.lineno
    for start, end, fn in spans:
        if start <= line <= end and (best is None or start >= best.lineno):
            best = fn
    return best


def walk_executed(node) -> Iterator[ast.AST]:
    """``ast.walk`` that does NOT descend into function/lambda bodies:
    code inside a ``def``/``lambda`` under a ``with lock:`` is *deferred* —
    it runs when the callback is called, not while the lock is held — so
    lock-holding analyses must skip it (the nested def gets analyzed in its
    own right by module-wide walks). Decorators and argument defaults DO
    execute in place and are walked. Applies to the start node too: to walk
    a function's own body, iterate its ``.body`` statements."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(n.decorator_list)
            stack.extend(n.args.defaults)
            stack.extend(d for d in n.args.kw_defaults if d is not None)
            continue
        if isinstance(n, ast.Lambda):
            continue
        stack.extend(ast.iter_child_nodes(n))


def calls_executed(node) -> Iterator[ast.Call]:
    """Call nodes that actually execute as part of ``node``'s own flow
    (see :func:`walk_executed`)."""
    for sub in walk_executed(node):
        if isinstance(sub, ast.Call):
            yield sub


# The intra-module reaching-call search that used to live here was
# superseded by the cross-module version in
# :meth:`autodist_tpu.analysis.program.ProgramIndex.find_reaching_call` —
# one search, one set of semantics.
