"""Priced gradient & wire compression plane.

Pins the PR's contract end to end: the host-side push compressor
(``WirePushCompressor`` — int8/fp16/bf16 quantize with error feedback,
row-sparse frames for gather-only embeddings, size-floor bypass), the
transport's capability probe and ``apply_sparse`` opcode, the convergence
semantics (compressed-with-EF tracks exact; int8 WITHOUT EF on an
ill-conditioned problem is the documented divergent negative control), and
the autotuner's pricing (``wire_dtype`` adopted only when the wire is the
bound — the quantize seconds are a real cost, not a free win).

(Named ``test_wire_compress`` so it sorts at the tier-1 alphabetical tail —
the 870s budget truncates there, and the loopback convergence runs are the
expensive part of this file.)
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from autodist_tpu import const  # noqa: E402
from autodist_tpu.parallel import ps_transport as tp  # noqa: E402
from autodist_tpu.parallel import wire  # noqa: E402
from autodist_tpu.parallel.synchronization import (  # noqa: E402
    SparseRows, WirePushCompressor, densify_sparse_rows)
from autodist_tpu.testing import faults  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.clear()


def _roundtrip(tree):
    """What the server's decode hands its apply path for a pushed tree."""
    return wire.decode(wire.encode(tree))


# -------------------------------------------------------------------- flags

def test_new_flags_registered_and_typed(monkeypatch):
    for flag in ("AUTODIST_WIRE_DTYPE", "AUTODIST_COMPRESS_MIN_BYTES",
                 "AUTODIST_SPARSE_PUSH"):
        assert flag in const.KNOWN_FLAGS and const.KNOWN_FLAGS[flag]
        assert hasattr(const.ENV, flag)
    assert const.ENV.AUTODIST_WIRE_DTYPE.val == ""
    monkeypatch.setenv("AUTODIST_WIRE_DTYPE", "int8")
    assert const.ENV.AUTODIST_WIRE_DTYPE.val == "int8"
    monkeypatch.setenv("AUTODIST_COMPRESS_MIN_BYTES", "1024")
    assert const.ENV.AUTODIST_COMPRESS_MIN_BYTES.val == 1024
    monkeypatch.setenv("AUTODIST_SPARSE_PUSH", "0")
    assert const.ENV.AUTODIST_SPARSE_PUSH.val is False


# -------------------------------------------------------- compressor unit

def test_floor_and_kind_bypass():
    """Vectors, scalars, ints, and sub-floor matrices ship exact."""
    comp = WirePushCompressor("int8", min_bytes=1 << 16)
    grads = {"bias": np.ones(64, np.float32),           # 1-D: bypass
             "scalar": np.float32(0.5),
             "ids": np.arange(6, dtype=np.int64).reshape(2, 3),
             "small": np.ones((8, 8), np.float32),      # under the floor
             "big": np.ones((256, 256), np.float32)}    # compressed
    out, has_sparse = comp.compress(grads)
    assert not has_sparse
    for name in ("bias", "scalar", "ids", "small"):
        assert not isinstance(out[name], wire.QuantizedArray)
        np.testing.assert_array_equal(np.asarray(out[name]),
                                      np.asarray(grads[name]))
    assert isinstance(out["big"], wire.QuantizedArray)
    # Accounting: only the compressed leaf counts, saved = in - out.
    assert comp.bytes_in == grads["big"].nbytes
    assert comp.bytes_out == out["big"].wire_nbytes
    assert comp.bytes_saved == comp.bytes_in - comp.bytes_out > 0
    assert comp.quantize_s >= 0.0


def test_error_feedback_residual_carries_over():
    """The quantization residual joins the NEXT step's gradient: pushing the
    same gradient N times applies (after dequantize) a running sum whose
    error stays BOUNDED (one-step quantization error), instead of growing
    linearly as it does with EF off."""
    rng = np.random.RandomState(0)
    g = (rng.randn(4, 512) * 0.01).astype(np.float32)
    g[0] += 3.0   # per-row scales: row 0's outliers don't crush rows 1-3

    def total_error(error_feedback, steps=20):
        comp = WirePushCompressor("int8", min_bytes=0,
                                  error_feedback=error_feedback)
        applied = np.zeros_like(g)
        for _ in range(steps):
            out, _ = comp.compress({"w": g.copy()})
            applied += _roundtrip(out)["w"]
        return float(np.max(np.abs(applied - steps * g)))

    bounded = total_error(True)
    drifting = total_error(False)
    # One int8 step's error bound is scale/2 per element; with EF the total
    # must stay near that bound, while EF-off accumulates ~steps x.
    step_bound = float(np.max(np.abs(g)) / 127.0)
    assert bounded <= 2 * step_bound
    assert drifting > 4 * bounded


def test_sparse_frames_and_counters():
    """A plan-marked row-sparse param ships (indices, rows); the server-side
    densify reconstructs the exact dense gradient (gather-only provenance:
    zero off the touched rows)."""
    vocab, dim = 50, 8
    idx = np.array([[3, 7], [7, -1]], np.int64)   # dup + negative wrap
    dense = np.zeros((vocab, dim), np.float32)
    touched = {3, 7, vocab - 1}
    for i in touched:
        dense[i] = np.random.RandomState(i).randn(dim)
    comp = WirePushCompressor(sparse_params={"emb": "idx"})
    assert comp.active and not comp.wire_dtype
    out, has_sparse = comp.compress({"emb": dense.copy()},
                                    batch={"idx": idx})
    assert has_sparse and isinstance(out["emb"], SparseRows)
    assert set(np.asarray(out["emb"].indices)) == touched
    got = densify_sparse_rows(_roundtrip(out))["emb"]
    np.testing.assert_array_equal(got, dense)
    assert comp.bytes_saved == dense.nbytes - out["emb"].rows.nbytes \
        - out["emb"].indices.nbytes
    # Without the index leaf in the batch the leaf ships dense (exact).
    out2, has_sparse2 = comp.compress({"emb": dense.copy()}, batch={})
    assert not has_sparse2 and not isinstance(out2["emb"], SparseRows)


def test_int8_without_ef_diverges_negative_control():
    """The documented failure mode EF exists for: a [1, dim] gradient gets
    ONE int8 scale, so a heavy-tailed coordinate (alternating +-1000 noise,
    zero mean) pins the scale at ~7.9 and the persistent -1 signal in every
    other coordinate rounds to zero EVERY step — without EF that signal is
    lost forever; with EF the residual accumulates until it ships."""
    dim = 32

    def run(error_feedback, lr=0.01, steps=200):
        comp = WirePushCompressor("int8", min_bytes=0,
                                  error_feedback=error_feedback)
        w = np.zeros((1, dim), np.float32)
        for t in range(steps):
            g = np.full((1, dim), -1.0, np.float32)
            g[0, 0] = 1000.0 if t % 2 == 0 else -1000.0
            out, _ = comp.compress({"w": g})
            w = w - lr * _roundtrip(out)["w"]
        return w

    w_ef = run(True)
    w_no_ef = run(False)
    # The zero-mean outlier coordinate nets out either way...
    assert abs(w_ef[0, 0]) < 11.0
    assert abs(w_no_ef[0, 0]) < 11.0
    # ...but the persistent signal (sum of grads = -200 -> w = +2.0 at
    # lr=0.01) survives ONLY under error feedback.
    np.testing.assert_allclose(w_ef[0, 1:], 2.0, atol=0.25)
    assert np.max(np.abs(w_no_ef[0, 1:])) == 0.0


# ----------------------------------------------------- loopback transport

def _cnn_problem():
    from autodist_tpu.models import resnet
    cfg = resnet.ResNet50Config(num_classes=10, stage_sizes=(1, 1), width=8,
                                dtype=jnp.float32, norm_groups=4)
    model, params = resnet.init_params(cfg, image_size=32)
    loss_fn = resnet.make_loss_fn(model)
    batch = resnet.synthetic_batch(cfg, batch_size=8, image_size=32)
    return loss_fn, params, batch


def _loopback_losses(loss_fn, params, batch, compressor, steps, lr=0.05):
    from autodist_tpu import AutoDist
    from autodist_tpu.strategy import PS
    ad = AutoDist(strategy_builder=PS(sync=False))
    runner = ad.create_distributed_session(
        loss_fn, params, optax.sgd(lr), example_batch=batch, num_workers=1)
    runner.init(params)
    server = tp.PSServer(runner, host="127.0.0.1")
    host, port = server.address
    remote = tp.RemotePSWorker(f"{host}:{port}", runner, worker_id=0,
                               overlap=False, compressor=compressor)
    try:
        remote.warmup(batch)
        return [float(remote.step(batch, timeout=60)) for _ in range(steps)]
    finally:
        remote.close()
        server.close()


def test_cnn_convergence_parity_int8_ef_vs_exact():
    """The tentpole's convergence acceptance on a real model path: int8+EF
    through the full loopback PS stack (quantized frames on a real socket,
    dequantize-on-decode apply) tracks the exact run's loss trajectory."""
    loss_fn, params, batch = _cnn_problem()
    steps = 10
    exact = _loopback_losses(loss_fn, params, batch,
                             WirePushCompressor(""), steps)
    comp = WirePushCompressor("int8", min_bytes=1024)
    compressed = _loopback_losses(loss_fn, params, batch, comp, steps)
    assert exact[-1] < exact[0]              # both genuinely train
    assert compressed[-1] < compressed[0]
    assert comp.bytes_saved > 0              # and it really compressed
    # Loss trajectories agree within a small relative tolerance.
    np.testing.assert_allclose(compressed, exact, rtol=0.05)


def test_worker_adopts_tuned_plan_wire_dtype():
    """The knob rides the plan: a ``TunedPlan`` carrying ``wire_dtype``
    (autotuner winner or plan cache) configures the worker's compressor
    without any env flag."""
    from autodist_tpu.strategy.autotune import TunedPlan

    from autodist_tpu import AutoDist
    from autodist_tpu.strategy import PS

    # 256x128 float32 = 128 KiB: above the default compression size floor.
    params = {"w": np.zeros((256, 128), np.float32)}
    rng = np.random.RandomState(1)
    batch = {"x": rng.randn(16, 256).astype(np.float32),
             "y": rng.randn(16, 128).astype(np.float32)}

    def loss(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    ad = AutoDist(strategy_builder=PS(sync=False))
    runner = ad.create_distributed_session(
        loss, params, optax.sgd(0.1), example_batch=batch, num_workers=1)
    runner.init(params)
    runner.tuned_plan = TunedPlan(
        builder_spec={"name": "PS", "kwargs": {"sync": False}},
        wire_dtype="int8")
    server = tp.PSServer(runner, host="127.0.0.1")
    host, port = server.address
    remote = tp.RemotePSWorker(f"{host}:{port}", runner, worker_id=0,
                               overlap=False)
    try:
        assert remote._compressor is not None
        assert remote._compressor.wire_dtype == "int8"
        remote.warmup(batch)
        for _ in range(3):
            remote.step(batch, timeout=60)
        assert remote._compressor.bytes_saved > 0
    finally:
        remote.close()
        server.close()


def test_capability_degrade_to_exact_push(monkeypatch):
    """Against a server with no ``wire_caps`` op (an old chief) the worker
    degrades to exact pushes instead of shipping frames the server cannot
    decode — the eager flavor of the ``read_min`` capability pattern."""
    from autodist_tpu import AutoDist
    from autodist_tpu.strategy import PS

    orig = tp.PSServer._dispatch

    def old_server(self, msg):
        if isinstance(msg, tuple) and msg and msg[0] == "wire_caps":
            return ("error", "PSClientError", "unknown op 'wire_caps'")
        return orig(self, msg)

    monkeypatch.setattr(tp.PSServer, "_dispatch", old_server)

    params = {"w": np.zeros((64, 32), np.float32)}
    rng = np.random.RandomState(2)
    batch = {"x": rng.randn(8, 64).astype(np.float32),
             "y": rng.randn(8, 32).astype(np.float32)}

    def loss(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    ad = AutoDist(strategy_builder=PS(sync=False))
    runner = ad.create_distributed_session(
        loss, params, optax.sgd(0.1), example_batch=batch, num_workers=1)
    runner.init(params)
    server = tp.PSServer(runner, host="127.0.0.1")
    host, port = server.address
    remote = tp.RemotePSWorker(
        f"{host}:{port}", runner, worker_id=0, overlap=False,
        compressor=WirePushCompressor("int8", min_bytes=0))
    try:
        # The probe dropped every regime: exact pushes for the lifetime.
        assert remote._compressor is None
        remote.warmup(batch)
        losses = [float(remote.step(batch, timeout=60)) for _ in range(3)]
        assert losses[-1] < losses[0]
    finally:
        remote.close()
        server.close()


# -------------------------------------------------------- autotuner pricing

def _fake_model_spec(nbytes=40_000_000):
    class _S:
        byte_size = nbytes
        sparse = False

    class _MS:
        trainable = {"w": _S()}

    return _MS()


def _predict_async(wire_dtype, wire_rate, overlap=True):
    import importlib
    autotune = importlib.import_module("autodist_tpu.strategy.autotune")
    from autodist_tpu.telemetry import costmodel
    calib = costmodel.Calibration(
        flops_per_s=5e10, bytes_per_s=5e9, host_s_per_dispatch=2e-3,
        wire_bytes_per_s=wire_rate, quantize_bytes_per_s=2e9)
    cand = autotune.Candidate({"name": "PS"}, overlap=overlap,
                              wire_dtype=wire_dtype, asynchronous=True)
    comm, quant = autotune._wire_terms(_fake_model_spec(), cand)
    rec = {"flops": 1e9, "bytes_accessed": 1e8, "steps": 1, "dispatches": 1}
    return costmodel.predict(rec, calib, comm_bytes_per_step=comm,
                             quantize_bytes_per_step=quant)


def test_autotuner_adopts_compression_when_wire_bound():
    """Slow wire (50 MB/s): int8's 4x byte cut beats its quantize seconds,
    and the prediction knows the run is comm-bound."""
    exact = _predict_async("", 50e6)
    int8 = _predict_async("int8", 50e6)
    assert exact["bound"] == "comm"
    assert int8["step_s"] < 0.5 * exact["step_s"]


def test_autotuner_declines_compression_when_wire_not_bound():
    """Fast wire (10 GB/s): the quantize seconds are NOT paid back, so exact
    predicts faster — priced, not guessed (the negative the tentpole pins)."""
    exact = _predict_async("", 10e9)
    int8 = _predict_async("int8", 10e9)
    assert exact["bound"] != "comm"
    assert exact["step_s"] < int8["step_s"]


def test_wire_terms_direction_split():
    """Push compresses, pull does not: the non-overlap candidate pays the
    FULL pull on top of the compressed push (the `_wire_bytes_per_s`
    symmetric-rate note's required composition)."""
    import importlib
    autotune = importlib.import_module("autodist_tpu.strategy.autotune")
    ms = _fake_model_spec(nbytes=1000)
    mk = lambda **kw: autotune.Candidate({"name": "PS"}, asynchronous=True,
                                         **kw)
    assert autotune._wire_terms(ms, mk(overlap=True)) == (1000.0, 0.0)
    assert autotune._wire_terms(ms, mk(overlap=False)) == (2000.0, 0.0)
    comm, quant = autotune._wire_terms(ms, mk(overlap=False,
                                              wire_dtype="int8"))
    assert comm == 1000.0 + 1000.0 * autotune._WIRE_RATIO["int8"]
    assert quant == 1000.0
    # Sync candidates cross no host wire.
    assert autotune._wire_terms(ms, autotune.Candidate({"name": "AllReduce"})) \
        == (0.0, 0.0)


def test_enumerate_crosses_wire_dtypes_async_only():
    from autodist_tpu.model_spec import ModelSpec
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.strategy.autotune import enumerate_candidates

    params = {"w": np.zeros((8, 4), np.float32)}
    batch = {"x": np.zeros((4, 8), np.float32),
             "y": np.zeros((4, 4), np.float32)}

    def loss(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    spec = ModelSpec.from_loss_fn(loss, params, batch)
    cands = enumerate_candidates(spec, ResourceSpec(None), optax.sgd(0.1),
                                 unrolls=(1,), include_async=True,
                                 budget=64)
    async_c = [c for c in cands if c.asynchronous]
    assert {(c.overlap, c.wire_dtype) for c in async_c} == {
        (ov, wd) for ov in (True, False) for wd in ("", "fp16", "int8")}
    assert all(not c.wire_dtype for c in cands if not c.asynchronous)
    assert any("wire=int8" in c.name for c in async_c)


def test_tuned_plan_rides_wire_dtype():
    from autodist_tpu.strategy.autotune import TunedPlan
    plan = TunedPlan(builder_spec={"name": "PS", "kwargs": {"sync": False}},
                     wire_dtype="int8", cache_key="k")
    assert "wire=int8" in plan.name
    assert plan.to_dict()["knobs"]["wire_dtype"] == "int8"
    back = TunedPlan.from_dict(plan.to_dict())
    assert back.wire_dtype == "int8"
    # Old cache entries (no wire_dtype key) load as exact-wire plans.
    d = plan.to_dict()
    del d["knobs"]["wire_dtype"]
    assert TunedPlan.from_dict(d).wire_dtype == ""


# ------------------------------------------------------------ fault harness

def test_wire_slow_throttle_is_standing_not_consumed():
    faults.install("wire_slow@bytes_per_s=1e6")
    assert faults.throttle_s(500_000) == pytest.approx(0.5)
    # Non-consuming: a bandwidth is a condition, not an event.
    assert faults.throttle_s(500_000) == pytest.approx(0.5)
    faults.clear()
    assert faults.throttle_s(500_000) == 0.0
