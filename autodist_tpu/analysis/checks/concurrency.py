"""Concurrency checks: GL001 lock-across-dispatch, GL002 lock order, GL005
unbounded blocking.

These descend from real bugs in this repo's history: PR 2 shipped a
machine-dependent deadlock where concurrently dispatched multi-device XLA
programs interleaved their collective rendezvous (fixed by
``AsyncPSRunner._collective_lock``), and ``staleness.ParameterService``
documents a strict ``_write_mutex -> _lock`` order plus a "device execution
never runs under the snapshot lock" rule that nothing previously enforced.

GL001 and GL002 are WHOLE-PROGRAM checks: the lock-body reachability search
runs over :class:`~autodist_tpu.analysis.program.ProgramIndex`, so a
``with lock:`` body that reaches ``runner.run`` or a socket send *through
another module* (the historical blind spot — resolution used to stop at
5 same-module hops) fails lint too.
"""

import ast
from typing import Dict, List, Optional, Set, Tuple

from autodist_tpu.analysis import callgraph
from autodist_tpu.analysis.core import (Context, Finding, Module, register,
                                        register_program)

_LOCK_TOKENS = {"lock", "rlock", "mutex", "mtx", "cond", "condition",
                "sem", "semaphore"}
# The san_* names are testing/sanitizer.py's env-armed factories — disarmed
# they return the bare primitive, so a `self._lock = san_lock()` site is a
# lock definition exactly like `threading.Lock()` and must stay visible to
# _definite_locks (factory adoption must not blind GL001/GL002/GL012).
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
               "san_lock", "san_rlock", "san_condition"}
_DISPATCH_ATTRS = {"block_until_ready", "device_put", "device_get",
                   "sendall", "sendmsg", "sendto", "recv", "recv_into",
                   "recvfrom", "recvmsg", "connect", "accept"}
_DISPATCH_METHODS = {"run", "run_many"}


def _definite_locks(tree: ast.Module) -> Set[str]:
    """Dotted targets assigned a ``threading.Lock()``-family constructor."""
    locks: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) \
                or not isinstance(node.value, ast.Call):
            continue
        ctor = callgraph.last_attr(node.value.func)
        if ctor not in _LOCK_CTORS:
            continue
        for target in node.targets:
            name = callgraph.dotted_name(target)
            if name:
                locks.add(name)
    return locks


def _lock_name(expr, definite: Set[str]) -> Optional[str]:
    """The lock's short name when ``expr`` looks like a lock, else None.
    Either the expression was assigned a threading constructor in this module,
    or its final identifier carries a lock-ish token (``_collective_lock``,
    ``_write_mutex``, ``_cond`` — token match, so "block" never trips)."""
    dotted = callgraph.dotted_name(expr)
    last = callgraph.last_attr(expr)
    if dotted is not None and dotted in definite:
        return last or dotted
    if callgraph.name_tokens(last) & _LOCK_TOKENS:
        return last
    return None


def _jitted_names(tree: ast.Module) -> Set[str]:
    """Dotted targets assigned from a ``jax.jit(...)``/``jit(...)`` call."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) \
                or not isinstance(node.value, ast.Call):
            continue
        fn = callgraph.dotted_name(node.value.func) or ""
        if fn == "jit" or fn.endswith(".jit"):
            for target in node.targets:
                name = callgraph.dotted_name(target)
                if name:
                    names.add(name)
    return names


def _enclosing_class(module: Module, index: callgraph.ModuleIndex,
                     node) -> Optional[str]:
    """Class name owning ``node``'s enclosing method, for self-call resolution."""
    scope = module.scope_at(node)
    head = scope.split(".")[0] if scope else ""
    if any(cls == head for cls, _ in index.methods):
        return head
    return None


def _dispatch_predicate(jitted_by_module: Dict[str, Set[str]]):
    """The GL001 blocking-call predicate, program-aware: jitted-name sets
    are per MODULE (the module whose code the search is currently in)."""

    def predicate(call: ast.Call, info) -> Optional[str]:
        dotted = callgraph.dotted_name(call.func)
        last = callgraph.last_attr(call.func)
        if last in _DISPATCH_ATTRS:
            return dotted or last
        if last in _DISPATCH_METHODS and isinstance(call.func, ast.Attribute):
            return dotted or last
        jitted = jitted_by_module.get(info.relpath)
        if jitted is None:
            jitted = _jitted_names(info.module.tree)
            jitted_by_module[info.relpath] = jitted
        if dotted is not None and dotted in jitted:
            return f"{dotted} (jitted)"
        return None

    return predicate


def _scope_function(module: Module, node):
    """The enclosing FunctionDef of ``node`` (for local-type inference), or
    None at module level."""
    return callgraph.innermost_function(module.tree, node)


@register_program("GL001", "lock held across device dispatch / blocking I/O")
def check_lock_across_dispatch(program, ctx: Context) -> List[Finding]:
    """GL001 — lock-held-across-dispatch (interprocedural).

    Flags a ``with <lock>:`` body that reaches a blocking operation — a
    jit-compiled callable, ``runner.run``/``run_many``,
    ``jax.block_until_ready``, or socket send/recv — directly or through
    helper calls, ACROSS MODULE BOUNDARIES: resolution runs over the
    whole-program call graph (imports, ``module.f()`` chains, methods of
    locally-constructed instances; bounded at
    :data:`~autodist_tpu.analysis.program.MAX_DEPTH` hops). Holding a lock
    across multi-device XLA execution can wedge the collective rendezvous —
    the PR 2 deadlock, which hung the whole tier-1 suite 3/3 on a 2-core
    box — and holding a hot-path snapshot lock across device execution
    stalls every reader for a whole program (the
    ``staleness.ParameterService`` rule: the apply's device execution runs
    under the writer mutex only, never the snapshot Condition). The old
    same-module 5-hop limit was the documented blind spot this closes: a
    critical section that reached a socket send through an imported helper
    passed lint until now.

    Locks that exist precisely to serialize execution (e.g.
    ``AsyncPSRunner._collective_lock``) are legitimate; annotate those sites
    with ``# graftlint: disable=GL001(reason)`` so the intent is explicit and
    reviewed, instead of implicit and forgettable.
    """
    findings: List[Finding] = []
    jitted_by_module: Dict[str, Set[str]] = {}
    predicate = _dispatch_predicate(jitted_by_module)
    for info in program.modules():
        module = info.module
        definite = _definite_locks(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                lock = _lock_name(item.context_expr, definite)
                if lock is None:
                    continue
                cls = _enclosing_class(module, info.index, node)
                hit = program.find_reaching_call(
                    info, list(node.body), cls,
                    _scope_function(module, node), predicate)
                if hit is None:
                    continue
                _, label, path = hit
                via = " via " + " -> ".join(path[:-1]) if len(path) > 1 else ""
                findings.append(Finding(
                    "GL001", module.relpath, node.lineno, node.col_offset,
                    f"lock `{lock}` is held across blocking call "
                    f"`{label}`{via}; dispatching device programs or socket "
                    f"I/O inside a critical section risks deadlocking the "
                    f"collective rendezvous (PR 2) and stalls every other "
                    f"thread on the lock",
                    scope=module.scope_at(node)))
                break  # one finding per with-statement is enough signal
    return findings


def _lock_identity(program, info, expr_or_name, definite: Set[str]):
    """The IDENTITY of a lock — ``(defining module relpath, name)`` — when
    statically knowable, else None. A bare name is only comparable across
    modules through its definition site: `_lock` in two unrelated modules
    is two locks; `a_lock` imported by both from the same module is one."""
    if isinstance(expr_or_name, str):
        name = expr_or_name
        sym = info.import_sym.get(name)
        if sym is not None:
            target = program.by_dotted.get(sym[0])
            return ((target.relpath if target is not None else sym[0]),
                    sym[1])
        if name in definite:
            return (info.relpath, name)
        return None
    if isinstance(expr_or_name, ast.Name):
        return _lock_identity(program, info, expr_or_name.id, definite)
    dotted = callgraph.dotted_name(expr_or_name)
    if dotted is not None and dotted in definite:
        return (info.relpath, dotted)
    return None


def _nested_lock_edges(program, info, definite: Set[str],
                       definite_by_module: Dict[str, Set[str]]):
    """(outer, inner, node, report_module) lock-acquisition edges: direct
    ``with`` nesting plus one level of call resolution — now PROGRAM-wide,
    so ``with a_lock: other_module.helper()`` sees the ``with b_lock:``
    inside the helper. The finding stays anchored in the module holding the
    outer lock (where the fix belongs); the inner module's definite-lock
    and declared-order facts still apply."""
    module = info.module
    edges = []
    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        outers = [(
            _lock_name(i.context_expr, definite),
            _lock_identity(program, info, i.context_expr, definite))
            for i in node.items]
        outers = [(o, oid) for o, oid in outers if o]
        if not outers:
            continue
        cls = _enclosing_class(module, info.index, node)
        scope_fn = _scope_function(module, node)
        local_types = program.local_types(info, scope_fn) \
            if scope_fn is not None else {}
        # walk_executed: a `with B:` inside a def merely DEFINED under A is
        # deferred code — not an A->B acquisition.
        inner_withs = [(sub, info) for body in node.body
                       for sub in callgraph.walk_executed(body)
                       if isinstance(sub, (ast.With, ast.AsyncWith))]
        for call in (c for body in node.body
                     for c in callgraph.calls_executed(body)):
            resolved = program.resolve_call(info, call, cls, local_types)
            if resolved is not None:
                inner_withs.extend(
                    (sub, resolved.info) for stmt in resolved.fn.body
                    for sub in callgraph.walk_executed(stmt)
                    if isinstance(sub, (ast.With, ast.AsyncWith)))
        for sub, sub_info in inner_withs:
            if sub_info is info:
                sub_definite = definite
            else:
                sub_definite = definite_by_module.get(sub_info.relpath)
                if sub_definite is None:
                    sub_definite = _definite_locks(sub_info.module.tree)
                    definite_by_module[sub_info.relpath] = sub_definite
            for item in sub.items:
                inner = _lock_name(item.context_expr, sub_definite)
                if inner is None:
                    continue
                if sub_info is not info and not (
                        isinstance(item.context_expr, ast.Name)
                        and item.context_expr.id in sub_definite):
                    # A foreign CLASS's instance-internal leaf lock
                    # (metrics' per-instrument `self._lock`, the queue's
                    # `self._cond`) is that module's encapsulated
                    # discipline — its own intra-module pass orders it.
                    # Cross-module edges track the callee's MODULE-GLOBAL
                    # locks, where an inversion is two subsystems racing.
                    continue
                inner_id = _lock_identity(program, sub_info,
                                          item.context_expr, sub_definite)
                anchor = sub if sub_info is info else node
                for outer, outer_id in outers:
                    if outer != inner:
                        edges.append((outer, inner, anchor, sub_info,
                                      outer_id, inner_id))
    return edges


@register_program("GL002", "lock-order inversion / undeclared nesting")
def check_lock_order(program, ctx: Context) -> List[Finding]:
    """GL002 — lock-order inversion (interprocedural).

    Derives the acquisition order of named locks (direct ``with`` nesting
    plus one level of call resolution, including calls INTO OTHER MODULES
    via the program call graph) and flags (a) any pair acquired in both
    orders anywhere in the module — a classic ABBA deadlock — and (b) any
    nested acquisition not covered by a declared order directive. Declare
    the intended order once, next to the lock definitions:

        # graftlint: lock-order=_write_mutex->_lock

    A cross-module edge honors the declaration in EITHER module involved
    (the lock's home module is where its discipline is documented). The
    directive is the machine-readable version of the prose rule
    ``staleness.ParameterService`` always had ("Order: _write_mutex ->
    _lock, never the reverse"); with it declared, a future path acquiring
    ``_lock`` then ``_write_mutex`` fails lint instead of deadlocking a
    production chief under load.
    """
    findings: List[Finding] = []
    definite_by_module: Dict[str, Set[str]] = {
        info.relpath: _definite_locks(info.module.tree)
        for info in program.modules()}
    # Cross-module comparisons run on lock IDENTITY ((defining module,
    # name) — resolved through imports), never on bare names: `_lock` in
    # two unrelated modules is two locks, while `a_lock` two modules both
    # import from a shared module is one. Two modules declaring (a, b) and
    # (b, a) over the SAME identity pair — or acquiring one in opposite
    # orders through each other's helpers — are two subsystems one
    # scheduler decision away from deadlock. Same-module edges keep
    # module-local name matching as before.
    decls = []   # (relpath, a, b, id(a), id(b))
    for info in program.modules():
        definite = definite_by_module[info.relpath]
        for a, b in sorted(set(info.module.lock_orders)):
            decls.append((info.relpath, a, b,
                          _lock_identity(program, info, a, definite),
                          _lock_identity(program, info, b, definite)))
    for rel, a, b, ida, idb in decls:
        if ida is None or idb is None:
            continue
        for rel2, a2, b2, ida2, idb2 in decls:
            if rel2 > rel and (ida2, idb2) == (idb, ida):
                findings.append(Finding(
                    "GL002", rel2, 1, 0,
                    f"declares lock-order `{a2}` -> `{b2}`, contradicting "
                    f"{rel}'s declared `{a}` -> `{b}` over the same locks; "
                    f"the two modules promise opposite acquisition orders "
                    f"— one of the declarations (and its paths) must flip"))
    cross_seen: Dict[Tuple[Tuple[str, str], Tuple[str, str]], str] = {}
    for info in program.modules():
        module = info.module
        definite = definite_by_module[info.relpath]
        declared = set(module.lock_orders)
        seen: Dict[Tuple[str, str], ast.AST] = {}
        reported: Set[Tuple[str, str, str]] = set()

        for outer, inner, node, sub_info, outer_id, inner_id \
                in _nested_lock_edges(program, info, definite,
                                      definite_by_module):
            scope = module.scope_at(node)
            if (outer, inner, scope) in reported:
                continue
            reported.add((outer, inner, scope))
            cross = sub_info is not info
            edge_declared = declared if not cross \
                else declared | set(sub_info.module.lock_orders)
            if outer_id is not None and inner_id is not None:
                # Program-wide ABBA runs on every identity-resolved edge
                # (direct nestings of shared imported locks included, not
                # just call-resolved ones), and is NOT exempted by a
                # module's own-direction declaration: declaring your order
                # does not make the other module's opposite acquisition
                # safe — the conflict is the deadlock. Same-module
                # inversions stay with the name-based per-module pass.
                other = cross_seen.get((inner_id, outer_id))
                if other is not None and other != module.relpath:
                    findings.append(Finding(
                        "GL002", module.relpath, node.lineno,
                        node.col_offset,
                        f"acquires `{inner}` while holding `{outer}`, but "
                        f"{other} takes the same locks in the opposite "
                        f"order — a program-wide ABBA deadlock across "
                        f"modules",
                        scope=scope))
                cross_seen.setdefault((outer_id, inner_id), module.relpath)
            if (inner, outer) in seen or (inner, outer) in edge_declared:
                findings.append(Finding(
                    "GL002", module.relpath, node.lineno, node.col_offset,
                    f"acquires `{inner}` while holding `{outer}`, "
                    f"conflicting with the established order `{inner}` -> "
                    f"`{outer}`; two threads taking these locks in opposite "
                    f"orders deadlock each other",
                    scope=scope))
            elif (outer, inner) not in edge_declared:
                findings.append(Finding(
                    "GL002", module.relpath, node.lineno, node.col_offset,
                    f"nested lock acquisition `{outer}` -> `{inner}` has no "
                    f"declared order; add `# graftlint: "
                    f"lock-order={outer}->{inner}` at module level so future "
                    f"paths cannot silently invert it",
                    scope=scope))
            seen.setdefault((outer, inner), node)
    return findings


def static_lock_edges(program) -> Dict[Tuple[Tuple[str, str],
                                             Tuple[str, str]],
                                       Tuple[str, int]]:
    """Every identity-resolved static lock-order edge, for ``--crosscheck``:
    ``{((outer relpath, outer name), (inner relpath, inner name)):
    (reporting module, line)}``. Same harvest GL002 runs on, restricted to
    edges whose both endpoints resolve to a definition site — the only ones
    a runtime observation can be matched against."""
    definite_by_module = {info.relpath: _definite_locks(info.module.tree)
                          for info in program.modules()}
    edges: Dict[Tuple[Tuple[str, str], Tuple[str, str]],
                Tuple[str, int]] = {}
    for info in program.modules():
        definite = definite_by_module[info.relpath]
        for (_outer, _inner, node, _sub, outer_id, inner_id) \
                in _nested_lock_edges(program, info, definite,
                                      definite_by_module):
            if outer_id is None or inner_id is None:
                continue
            edges.setdefault((outer_id, inner_id),
                             (info.relpath, node.lineno))
    return edges


def _fmt_site(key) -> str:
    path, name, cls = key
    return f"{path}:{name}" + (f" ({cls})" if cls else "")


def crosscheck(program, observed: List[dict]) \
        -> Tuple[List[Finding], List[dict]]:
    """Merge sanitizer-observed lock-order edges into GL002's static graph.

    ``observed`` is the parsed edge records from
    ``.graftlint_cache/observed_locks.jsonl`` (``testing/sanitizer.py``
    export): ``{"outer": {"path", "name", "cls"}, "inner": {...},
    "count": n}``. Site keys align with GL002's lock identities by
    construction — the sanitizer keys a lock by its creation site's
    ``(repo-relative path, assignment lhs)``, the same ``(relpath,
    "self._lock")`` pair ``_lock_identity`` resolves.

    Returns ``(findings, unexercised)``:

    - a cycle in the MERGED observed digraph is a finding — each in-process
      run aborts on its own cycles, so one surviving the merge is
      dynamic-only evidence spanning runs/processes that no single
      execution (and no static identity edge) could show;
    - an observed edge whose reverse direction exists as a static identity
      edge is a finding — the runtime took the locks in the opposite order
      the code's static nesting establishes (ABBA with one half dynamic);
    - a static identity edge never observed is returned in ``unexercised``
      (informational): the lock model has coverage the test run didn't
      earn, the same way an untested branch reads.
    """
    static = static_lock_edges(program)
    static_pairs = {((o[0], o[1]), (i[0], i[1])): loc
                    for (o, i), loc in static.items()}

    def nkey(d: dict):
        return (d.get("path", "?"), d.get("name", "?"), d.get("cls"))

    adj: Dict[tuple, Set[tuple]] = {}
    obs_edges: Set[Tuple[tuple, tuple]] = set()
    for rec in observed:
        o, i = nkey(rec["outer"]), nkey(rec["inner"])
        adj.setdefault(o, set()).add(i)
        obs_edges.add((o, i))

    findings: List[Finding] = []

    # (a) cycles in the merged observed digraph.
    color: Dict[tuple, int] = {}
    stack: List[tuple] = []
    seen_cycles: Set[frozenset] = set()

    def dfs(u):
        color[u] = 1
        stack.append(u)
        for v in sorted(adj.get(u, ()), key=str):
            c = color.get(v, 0)
            if c == 0:
                dfs(v)
            elif c == 1:
                cyc = stack[stack.index(v):] + [v]
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    findings.append(Finding(
                        "GL002", cyc[0][0], 1, 0,
                        "crosscheck: observed lock-order cycle "
                        + " -> ".join(_fmt_site(n) for n in cyc)
                        + " in the merged runtime edges; no single "
                        "acquisition order exists — a dynamic-only "
                        "deadlock the static graph cannot see",
                        scope=None))
        stack.pop()
        color[u] = 2

    for u in sorted(adj, key=str):
        if color.get(u, 0) == 0:
            dfs(u)

    # (b) observed edges contradicting a static identity edge.
    for o, i in sorted(obs_edges, key=str):
        loc = static_pairs.get(((i[0], i[1]), (o[0], o[1])))
        if loc is not None:
            rel, line = loc
            findings.append(Finding(
                "GL002", rel, line, 0,
                f"crosscheck: runtime acquired {_fmt_site(i)} while "
                f"holding {_fmt_site(o)}, the opposite of the static "
                f"nesting established here — an ABBA deadlock with one "
                f"half only reachable dynamically",
                scope=None))

    # (c) static identity edges the run never exercised.
    observed_pairs = {((o[0], o[1]), (i[0], i[1])) for o, i in obs_edges}
    unexercised = [
        {"outer": {"path": okey[0], "name": okey[1]},
         "inner": {"path": ikey[0], "name": ikey[1]},
         "path": rel, "line": line}
        for (okey, ikey), (rel, line) in sorted(static.items())
        if (okey, ikey) not in observed_pairs]
    return findings, unexercised


@register("GL005", "unbounded blocking wait in runtime code")
def check_unbounded_wait(module: Module, ctx: Context) -> List[Finding]:
    """GL005 — blocking call without a timeout path.

    In ``autodist_tpu/`` runtime code (handlers the PS transport runs per
    connection, gate waits, prefetch joins), flags ``Condition.wait`` /
    ``wait_for`` / ``Event.wait`` calls with no timeout argument (or a
    literal ``None``): a dead peer or wedged producer then parks the thread
    forever with no diagnosable failure. The PS server bounds the
    wait-indefinitely gate default for the same reason
    (``ps_transport._dispatch``: client-requested finite timeouts are
    honored exactly; ``None`` gets a 24h ceiling so a vanished peer cannot
    park handler threads forever). Tests and tools are exempt (a test
    hanging is loud; a server thread leaking is silent).
    """
    if module.tree is None or not module.relpath.startswith("autodist_tpu/"):
        return []
    findings: List[Finding] = []
    for call in callgraph.calls_under(module.tree):
        last = callgraph.last_attr(call.func)
        if last not in ("wait", "wait_for"):
            continue
        if last == "wait":
            receiver = call.func.value if isinstance(call.func, ast.Attribute) \
                else None
            tokens = callgraph.name_tokens(callgraph.last_attr(receiver))
            if not tokens & (_LOCK_TOKENS | {"event", "ev", "done", "ready"}):
                continue  # p.wait() on a process etc. — not a lock primitive
            has_timeout = bool(call.args) or any(
                k.arg == "timeout" for k in call.keywords)
            timeout_arg = call.args[0] if call.args else next(
                (k.value for k in call.keywords if k.arg == "timeout"), None)
        else:
            has_timeout = len(call.args) >= 2 or any(
                k.arg == "timeout" for k in call.keywords)
            timeout_arg = call.args[1] if len(call.args) >= 2 else next(
                (k.value for k in call.keywords if k.arg == "timeout"), None)
        if has_timeout and not (isinstance(timeout_arg, ast.Constant)
                                and timeout_arg.value is None):
            continue
        dotted = callgraph.dotted_name(call.func) or last
        findings.append(Finding(
            "GL005", module.relpath, call.lineno, call.col_offset,
            f"unbounded `{dotted}` — no timeout, so a dead peer or wedged "
            f"producer parks this thread forever; pass a timeout and handle "
            f"expiry (see StalenessController.start_step)",
            scope=module.scope_at(call)))
    return findings
