"""Pipeline-parallel Transformer LM over the mesh ``pipe`` axis.

Beyond reference parity (the reference scoped pipeline parallelism out,
``docs/design/architecture.rst:49-51``). The model is a pure-JAX functional
transformer whose block weights are *stacked* along a leading layer dimension —
the natural layout for pipelining on TPU: the ``Pipeline`` strategy shards that
dimension ``P("pipe", ...)`` so each device stores (and runs) a contiguous group
of layers, and the forward pass streams microbatches through
``parallel/pipeline.pipeline_apply`` (GPipe schedule, ``lax.ppermute`` handoffs).
Embedding, final norm, and LM head stay replicated across pipe ranks (cheap
redundant compute in exchange for zero extra communication).
"""

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu import const
from autodist_tpu.parallel.pipeline import pipelined


@dataclasses.dataclass(frozen=True)
class PipelineLMConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 8
    d_ff: int = 2048
    max_len: int = 1024
    n_stages: int = 4
    num_microbatches: int = 4
    # Interleaved 1F1B: each device runs n_chunks virtual stages (layer groups
    # c mod n_stages == rank) instead of one contiguous group — thinner
    # pipeline ticks, ~half the fill/drain bubble (parallel/pipeline docs).
    # 1 = plain contiguous stages (GPipe / 1F1B).
    n_chunks: int = 1
    dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if self.d_model % self.n_heads:
            raise ValueError("d_model must be divisible by n_heads")
        if self.n_layers % (self.n_stages * self.n_chunks):
            raise ValueError(
                "n_layers must be divisible by n_stages * n_chunks")
        if self.n_chunks > 1 and self.num_microbatches % self.n_stages:
            raise ValueError(
                "interleaved schedule (n_chunks > 1) needs num_microbatches "
                "divisible by n_stages")


def _layer_norm(x, scale, bias):
    x32 = x.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = ((x32 - mean) ** 2).mean(-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + 1e-6)
    return (y * scale + bias).astype(x.dtype)


def _block_apply(p, x, config: PipelineLMConfig):
    """One pre-LN transformer block; ``p`` holds this layer's weights (no layer dim)."""
    cfg = config
    b, t, d = x.shape
    hd = d // cfg.n_heads

    h = _layer_norm(x, p["ln1_s"], p["ln1_b"])
    qkv = h @ p["wqkv"].astype(x.dtype)                      # [B, T, 3D]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, t, cfg.n_heads, hd)
    k = k.reshape(b, t, cfg.n_heads, hd)
    v = v.reshape(b, t, cfg.n_heads, hd)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd).astype(np.float32)
    causal = jnp.tril(jnp.ones((t, t), bool))
    scores = jnp.where(causal, scores.astype(jnp.float32), -1e9)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, t, d)
    x = x + ctx @ p["wo"].astype(x.dtype)

    h = _layer_norm(x, p["ln2_s"], p["ln2_b"])
    h = jax.nn.gelu(h @ p["w1"].astype(x.dtype))
    return x + h @ p["w2"].astype(x.dtype)


# Forward-path pieces shared by PipelineLM.apply (GPipe + autodiff) and
# make_onef_oneb_value_and_grad (1F1B): ONE definition each, so the two
# schedules can never silently compute different math.

def _embed_microbatches(cfg: PipelineLMConfig, params, tokens):
    """Embedding + positions, reshaped [B, T, D] -> [M, B/M, T, D] (microbatch
    index outermost-within-batch so data sharding stays on the per-microbatch
    batch dim)."""
    b, t = tokens.shape
    m = cfg.num_microbatches
    if b % m:
        raise ValueError(f"batch {b} not divisible by num_microbatches {m}")
    x = params["embed"][tokens].astype(cfg.dtype)
    x = x + params["pos"][None, :t, :].astype(cfg.dtype)
    return x.reshape(b // m, m, t, cfg.d_model).swapaxes(0, 1)


def _stage_groups(cfg: PipelineLMConfig, block_params, n_groups: int = None):
    """[L, ...] block stacks -> [G, L/G, ...] stage groups (contiguous layers;
    G defaults to n_stages — the interleaved path passes S*v)."""
    n_groups = cfg.n_stages if n_groups is None else n_groups
    lps = cfg.n_layers // n_groups
    return jax.tree_util.tree_map(
        lambda a: a.reshape(n_groups, lps, *a.shape[1:]), block_params)


def layer_execution_order(cfg: PipelineLMConfig):
    """Stored-row -> execution-position mapping for the block stack.

    ``n_chunks == 1``: identity — stored layer i executes i-th. ``n_chunks >
    1``: blocks are STORED in device-major chunk order (device r's rows are
    contiguous, so the plan's ``P("pipe")`` sharding gives each device
    exactly its chunks with ZERO per-step layout traffic — the permutation
    happens once, at init); stored group ``r*v + j`` holds execution group
    ``j*S + r`` (the one shared permutation, ``parallel.pipeline.chunk_perm``,
    expanded from groups to layers). Returns ``order`` with
    ``order[stored_row] = execution_position``.

    CHECKPOINT CAVEAT: this makes the stored block stack's meaning depend on
    ``(n_stages, n_chunks)``. A checkpoint written under one pipeline config
    restores bit-identically only into the SAME config; to change configs,
    round-trip through :func:`blocks_to_execution_order` /
    :func:`blocks_from_execution_order` (execution order is the
    config-independent canonical form)."""
    from autodist_tpu.parallel.pipeline import chunk_perm
    lps = cfg.n_layers // (cfg.n_stages * cfg.n_chunks)
    order = []
    for c in chunk_perm(cfg.n_stages, cfg.n_chunks):   # stored g reads virtual c
        order.extend(range(c * lps, (c + 1) * lps))
    return order


def _execution_to_stored(cfg: PipelineLMConfig):
    """index array: stored row i = execution-order row order[i]."""
    return np.asarray(layer_execution_order(cfg))


def blocks_to_execution_order(cfg: PipelineLMConfig, blocks):
    """Stored (device-major) block stack -> execution-order stack (the
    config-independent layout; use before moving a checkpoint between
    pipeline configs)."""
    inv = np.argsort(_execution_to_stored(cfg))
    return jax.tree_util.tree_map(lambda a: jnp.take(a, inv, axis=0), blocks)


def blocks_from_execution_order(cfg: PipelineLMConfig, blocks):
    """Execution-order block stack -> this config's stored (device-major)
    layout (inverse of :func:`blocks_to_execution_order`)."""
    idx = _execution_to_stored(cfg)
    return jax.tree_util.tree_map(lambda a: jnp.take(a, idx, axis=0), blocks)


def _make_stage_fn(cfg: PipelineLMConfig):
    def stage_fn(p, xb):
        p = jax.tree_util.tree_map(lambda a: a[0], p)  # drop stage shard dim
        def body(carry, layer_p):
            return _block_apply(layer_p, carry, cfg), None
        out, _ = jax.lax.scan(body, xb, p)
        return out
    return stage_fn


def _head_logits(tail_params, y):
    h = _layer_norm(y, tail_params["ln_f_s"], tail_params["ln_f_b"])
    return h.astype(jnp.float32) @ tail_params["head"]


def _nll(logits, targets):
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]


class PipelineLM:
    """Functional model object: ``apply(params, tokens) -> logits``."""

    def __init__(self, config: PipelineLMConfig):
        self.config = config

    def apply(self, params, tokens):
        cfg = self.config
        b, t = tokens.shape
        x_mb = _embed_microbatches(cfg, params, tokens)
        blocks = params["blocks"]
        if cfg.n_chunks > 1:
            # GPipe needs contiguous execution-order stage groups; with
            # device-major storage that costs one gather HERE (the GPipe
            # comparison path), keeping the 1F1B training step permute-free.
            blocks = blocks_to_execution_order(cfg, blocks)
        stage_params = _stage_groups(cfg, blocks)
        y_mb = pipelined(_make_stage_fn(cfg), cfg.n_stages,
                         axis=const.MESH_AXIS_PIPE)(stage_params, x_mb)
        h = y_mb.swapaxes(0, 1).reshape(b, t, cfg.d_model)
        return _head_logits(params, h)


def make_loss_fn(model: PipelineLM):
    def loss_fn(params, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        return _nll(model.apply(params, inputs), targets).mean()

    return loss_fn


def make_onef_oneb_value_and_grad(model: PipelineLM):
    """Full-model training step on the 1F1B schedule: ``f(params, batch) ->
    (loss, grads)`` with gradients for EVERY parameter.

    The model splits around the pipeline: embedding+positions run replicated
    before it (their gradient returns through the schedule's input-grad
    output), the stacked blocks run as pipeline stages, and the final
    norm+head+loss is the in-schedule tail at the last stage. Gradients match
    ``jax.grad(make_loss_fn(model))`` exactly; activation memory is
    O(n_stages) instead of growing with ``num_microbatches`` (see
    ``parallel/pipeline``). With ``cfg.n_chunks > 1`` the INTERLEAVED
    schedule runs — layer group ``c`` on device ``c mod n_stages``, ~half the
    fill/drain bubble — behind the same ``f(params, batch)`` surface: blocks
    are stored device-major (:func:`layer_execution_order`), so the step
    performs no layout permutes at all. Feed the result to any optax
    optimizer."""
    from autodist_tpu.parallel.pipeline import (interleaved_value_and_grad,
                                                pipelined_value_and_grad)

    cfg = model.config

    def f(params, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        b, t = inputs.shape
        m = cfg.num_microbatches

        def pre(pre_params, toks):
            return _embed_microbatches(cfg, pre_params, toks)

        pre_params = {"embed": params["embed"], "pos": params["pos"]}
        x_mb, vjp_pre = jax.vjp(pre, pre_params, inputs)
        targets_mb = targets.reshape(b // m, m, t).swapaxes(0, 1)
        tail_params = {"ln_f_s": params["ln_f_s"], "ln_f_b": params["ln_f_b"],
                       "head": params["head"]}

        def tail_fn(tp, y, tgt):
            return _nll(_head_logits(tp, y), tgt).mean()

        if cfg.n_chunks > 1:
            # Blocks are STORED device-major (layer_execution_order), so the
            # grouped view is already the schedule's layout: no per-step
            # permute, no cross-device layout traffic — grads come back in
            # the same stored order the optimizer state uses.
            n_groups = cfg.n_stages * cfg.n_chunks
            stage_params = _stage_groups(cfg, params["blocks"], n_groups)
            loss, gs, gt, gx = interleaved_value_and_grad(
                _make_stage_fn(cfg), tail_fn, cfg.n_stages, cfg.n_chunks,
                axis=const.MESH_AXIS_PIPE)(
                    stage_params, tail_params, x_mb, targets_mb)
        else:
            stage_params = _stage_groups(cfg, params["blocks"])
            loss, gs, gt, gx = pipelined_value_and_grad(
                _make_stage_fn(cfg), tail_fn, cfg.n_stages,
                axis=const.MESH_AXIS_PIPE)(
                    stage_params, tail_params, x_mb, targets_mb)
        d_pre, _ = vjp_pre(gx.astype(x_mb.dtype))
        grads = {
            "embed": d_pre["embed"], "pos": d_pre["pos"],
            "blocks": jax.tree_util.tree_map(
                lambda g: g.reshape(cfg.n_layers, *g.shape[2:]), gs),
            "ln_f_s": gt["ln_f_s"], "ln_f_b": gt["ln_f_b"],
            "head": gt["head"],
        }
        return loss, grads

    return f


def init_params(config: PipelineLMConfig, rng: Optional[jax.Array] = None):
    cfg = config
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    keys = jax.random.split(rng, 8)
    d, f, l, v = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.vocab_size

    def normal(key, shape, scale):
        return (scale * jax.random.normal(key, shape)).astype(jnp.float32)

    params = {
        "embed": normal(keys[0], (v, d), 0.02),
        "pos": normal(keys[1], (cfg.max_len, d), 0.02),
        "blocks": {
            "ln1_s": jnp.ones((l, d), jnp.float32),
            "ln1_b": jnp.zeros((l, d), jnp.float32),
            "wqkv": normal(keys[2], (l, d, 3 * d), d ** -0.5),
            "wo": normal(keys[3], (l, d, d), d ** -0.5),
            "ln2_s": jnp.ones((l, d), jnp.float32),
            "ln2_b": jnp.zeros((l, d), jnp.float32),
            "w1": normal(keys[4], (l, d, f), d ** -0.5),
            "w2": normal(keys[5], (l, f, d), f ** -0.5),
        },
        "ln_f_s": jnp.ones((d,), jnp.float32),
        "ln_f_b": jnp.zeros((d,), jnp.float32),
        "head": normal(keys[6], (d, v), d ** -0.5),
    }
    return PipelineLM(cfg), params


def sequential_apply(model: PipelineLM, params, tokens):
    """Reference forward without the pipeline (for parity tests): same math, plain
    layer loop in EXECUTION order (stored order differs when n_chunks > 1,
    see :func:`layer_execution_order`)."""
    cfg = model.config
    _, t = tokens.shape
    x = params["embed"][tokens].astype(cfg.dtype)
    x = x + params["pos"][None, :t, :].astype(cfg.dtype)
    blocks = blocks_to_execution_order(cfg, params["blocks"]) \
        if cfg.n_chunks > 1 else params["blocks"]
    for i in range(cfg.n_layers):
        layer_p = jax.tree_util.tree_map(lambda a, i=i: a[i], blocks)
        x = _block_apply(layer_p, x, cfg)
    x = _layer_norm(x, params["ln_f_s"], params["ln_f_b"])
    return x.astype(jnp.float32) @ params["head"]


def synthetic_batch(config: PipelineLMConfig, batch_size: int, seq_len: int,
                    seed: int = 0):
    rng = np.random.RandomState(seed)
    return {"tokens": rng.randint(0, config.vocab_size,
                                  size=(batch_size, seq_len + 1)).astype(np.int32)}
