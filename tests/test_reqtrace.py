"""Request-scoped distributed tracing (PR 19): reqtrace ring, wire-propagated
trace context, p99 exemplars, and adtrace.

NAMED to sort inside the tier-1 alphabetical window (next to the serve
tests). No subprocesses: fleets are in-process ``InferenceServer`` replicas
behind a real ``RouterServer`` over loopback (the test_serve_fleet
topology), so the process-global lifecycle ring sees every hop — router and
replica marks join on the router-scope rid exactly as they do across real
processes, minus the clock skew (pinned separately via ``ntp_offset``).

Coverage per the PR 19 contract:
- DISARMED is the production default and costs one attribute read: no ring
  growth, no clock read, no lock (the spans-contract twin, test-pinned);
- the ring is bounded and columnar; ``group_records`` orders per-rid marks;
- the trace-context token rides the existing generate framing: the replica
  decomposes WIRE time from queue time via the router-estimated clock
  offset (``cluster.ntp_offset`` rebasing pinned with a synthetic skew);
- a replayed request keeps its rid with a bumped hop — one trace, a
  visible failover (marks + Chrome-trace instant + both flow-id hops);
- ``serve.latency_s.total`` carries a slowest-in-window exemplar (rid +
  phase breakdown) that a firing ``serve_p99_burn`` books into the alert
  record, ``active()``, and the flight-recorder manifest — and the adtrace
  waterfall names decode on the guilty replica (the e2e acceptance pin);
- fleet merge is deterministic; the merged Chrome trace is schema-valid
  JSON with paired flow halves; reqtrace JSONL dumps round-trip;
- the ``serve.request`` span carries the rid (the span-args bugfix);
- adtop's ``req`` line and adfleet's ``attr`` column render the
  attribution gauges and the booked exemplar;
- the new env flags are registered (GL007's runtime face).
"""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from autodist_tpu import const, telemetry  # noqa: E402
from autodist_tpu.serving import (Batcher, InferenceServer,  # noqa: E402
                                  Router, RouterServer, ServeClient,
                                  ServeConfig, default_buckets)
from autodist_tpu.telemetry import alerts, cluster, history  # noqa: E402
from autodist_tpu.telemetry import metrics, recorder  # noqa: E402
from autodist_tpu.telemetry import reqtrace  # noqa: E402


# ------------------------------------------------------------------ fixtures

@pytest.fixture(autouse=True)
def _reqtrace_reset():
    """Leave the process-global planes as found: ring empty and DISARMED,
    no alert engine, no history, span ring empty (instruments stay — the
    registry is additive-only and shared across the suite)."""
    def reset():
        reqtrace.disable()
        reqtrace.clear()
        alerts.set_engine(None)
        history.set_history(None)
        telemetry.disable()
        telemetry.clear()
    reset()
    yield
    reset()


class FakeEngine:
    """Deterministic jax-free engine (the test_serve_fleet pattern): token =
    100*slot + step index; optional per-step delay so decode takes real
    wall time (the slow-replica and kill legs need requests in flight)."""

    def __init__(self, capacity=2, max_len=32, step_s=0.0):
        self.capacity = capacity
        self.max_len = max_len
        self.buckets = default_buckets(max_len)
        self.admits = []
        self._steps = np.zeros(capacity, np.int64)
        self.step_s = step_s

    def make_keys(self, seed, n):
        return None

    def admit(self, slot, prompt, key):
        self.admits.append((slot, int(prompt.size)))
        self._steps[slot] = 0
        return 100 * slot

    def step(self, keys):
        if self.step_s:
            time.sleep(self.step_s)
        self._steps += 1
        return (100 * np.arange(self.capacity) + self._steps).astype(np.int32)

    def free(self, slot):
        pass


def _replica_factory(capacity=2, max_queue=8, step_s=0.0, fleet=None,
                     step_s_list=None):
    """Factory for in-process replicas; ``step_s_list`` hands each created
    replica its own per-step delay (first replica gets the first entry),
    ``fleet`` collects (engine, server) pairs in creation order."""
    def factory():
        delay = step_s
        if step_s_list:
            delay = step_s_list.pop(0)
        engine = FakeEngine(capacity=capacity, step_s=delay)
        server = InferenceServer(
            Batcher(engine, ServeConfig(max_batch=capacity,
                                        max_queue=max_queue)), port=0)
        if fleet is not None:
            fleet.append((engine, server))
        return server
    return factory


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(os.path.dirname(__file__), os.pardir,
                           "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fleet_marks():
    """The in-process fleet's marks grouped per rid (one process-global
    ring — router and replica marks already share it)."""
    return reqtrace.group_records(reqtrace.snapshot_marks())


# ------------------------------------------------- ring + disarmed contract

def test_disarmed_mark_is_one_attribute_read(monkeypatch):
    """DISARMED (the production default) a mark must return after the one
    ``enabled`` attribute check: no clock read, no lock, no ring append.
    Pinned by making the clock and the lock explode — the disarmed path
    must never reach either."""
    assert not reqtrace.enabled()

    def boom(*a, **kw):
        raise AssertionError("disarmed mark touched the armed path")

    class BoomLock:
        __enter__ = __exit__ = boom

    monkeypatch.setattr(reqtrace.time, "perf_counter_ns", boom)
    monkeypatch.setattr(reqtrace._STATE, "lock", BoomLock())
    reqtrace.mark("rid-0", "queued", depth=3)      # must not raise
    monkeypatch.undo()
    assert reqtrace.snapshot_marks() == []         # and recorded nothing
    # Armed, the same call records (and DOES read the clock).
    reqtrace.enable()
    reqtrace.mark("rid-0", "queued", depth=3)
    assert reqtrace.snapshot_marks() == [
        ("rid-0", "queued", pytest.approx(time.perf_counter_ns(), abs=5e9),
         {"depth": 3})]


def test_ring_bound_and_group_records(monkeypatch):
    monkeypatch.setattr(reqtrace, "_STATE", reqtrace._State(4))
    reqtrace.enable()
    for i in range(10):
        reqtrace.mark(f"r{i % 2}", "queued", i=i)
    marks = reqtrace.snapshot_marks()
    assert len(marks) == 4                         # bounded, oldest evicted
    assert [m[3]["i"] for m in marks] == [6, 7, 8, 9]
    grouped = reqtrace.group_records(marks)
    assert set(grouped) == {"r0", "r1"}
    for recs in grouped.values():                  # per-rid, time-ordered
        assert [t for _, t, _ in recs] == sorted(t for _, t, _ in recs)


def test_reqtrace_flags_registered():
    """GL007's runtime face: the new knobs are typed ENV members AND
    registered in KNOWN_FLAGS (adenv/doctor see them)."""
    assert "AUTODIST_REQTRACE" in const.KNOWN_FLAGS
    assert "AUTODIST_REQTRACE_RING" in const.KNOWN_FLAGS
    assert isinstance(const.ENV.AUTODIST_REQTRACE.val, bool)
    assert int(const.ENV.AUTODIST_REQTRACE_RING.val) >= 1


# ------------------------------------- clock rebase / wire decomposition

def test_ntp_offset_synthetic_skew_and_median_rejection():
    """The router-side estimate the replica decomposes wire time with: a
    remote clock 5ms ahead over a symmetric 1ms-each-way path comes back as
    +5ms (+-rtt/2); one delayed outlier exchange is rejected by the
    median."""
    skew, leg = 5_000_000, 1_000_000
    samples = []
    for i in range(3):
        t0 = i * 10_000_000
        samples.append((t0, t0 + leg + skew, t0 + 2 * leg))
    off, err = cluster.ntp_offset(samples)
    assert off == skew
    assert err == leg
    # An asymmetric outlier (reply path stalled 50ms) would estimate the
    # offset 25ms off — the median across rounds ignores it.
    t0 = 90_000_000
    samples.append((t0, t0 + leg + skew, t0 + 2 * leg + 50_000_000))
    off, err = cluster.ntp_offset(samples)
    assert off == skew


def test_wire_time_decomposed_with_clock_offset(monkeypatch):
    """The replica rebases the token's origin send stamp through the
    router-estimated offset: with a forced -40ms offset (replica's clock
    behind) the decomposed wire time reads ~40ms above the true loopback
    wire; with the true (zero, shared-clock) offset it reads ~0."""
    from autodist_tpu.serving.router import Replica
    reqtrace.enable()
    router = Router(_replica_factory(), n_replicas=1, start=False)
    server = RouterServer(router)
    try:
        client = ServeClient(server.address)
        client.generate(np.arange(1, 4), 2, seed=0)
        monkeypatch.setattr(Replica, "clock_offset_ns",
                            lambda self: -40_000_000)
        client.generate(np.arange(1, 4), 2, seed=1)
    finally:
        server.close()
    wire_ns = [a["wire_ns"] for rid, recs in _fleet_marks().items()
               for p, _, a in recs if p == "received" and "wire_ns" in a]
    assert len(wire_ns) == 2
    assert 0 <= wire_ns[0] < 30_000_000            # shared clock: ~loopback
    assert wire_ns[1] >= 40_000_000                # rebased through -40ms
    assert wire_ns[1] < 90_000_000


# ------------------------------------------------------- fleet lifecycle

def test_fleet_lifecycle_marks_and_adtrace_report(tmp_path):
    """One armed request through a real RouterServer books the full
    lifecycle under ONE rid; adtrace renders the phase table and a
    waterfall naming the replica; the merged Chrome trace is schema-valid
    with PAIRED flow halves."""
    reqtrace.enable()
    router = Router(_replica_factory(), n_replicas=2, start=False)
    server = RouterServer(router)
    try:
        for i in range(3):
            ServeClient(server.address).generate(np.arange(1, 5), 3, seed=i)
    finally:
        server.close()
    grouped = _fleet_marks()
    rids = [r for r in grouped if str(r).startswith("router-")]
    assert len(rids) == 3
    phases = [p for p, _, _ in grouped[rids[0]]]
    # Router + replica marks joined on the rid, in causal order ("received"
    # appears twice: hop 0 at the router, then at the replica with wire_ns).
    for want in ("received", "sent", "queued", "admitted", "prefill_start",
                 "prefill_end", "first_token", "done", "finished"):
        assert want in phases, (want, phases)
    assert phases.index("sent") < phases.index("queued")
    assert phases.index("done") < phases.index("finished")
    sent = next(a for p, _, a in grouped[rids[0]] if p == "sent")
    assert sent["hop"] == 0
    assert sent["replica"] in {r.name for r in router.replicas()}

    adtrace = _load_tool("adtrace")
    states = [telemetry.local_reqtrace_state()]
    report = adtrace.render_report(states, top=2)
    for needle in ("queue", "decode", "total", str(rids[0]), "replica="):
        assert needle in report, (needle, report)

    out = str(tmp_path / "fleet.json")
    adtrace.write_chrome_trace(out, states)
    doc = json.load(open(out))
    assert doc["displayTimeUnit"] == "ms"
    events = doc["traceEvents"]
    assert {e["ph"] for e in events} >= {"M", "X", "s", "f"}
    for e in events:
        assert {"ph", "pid", "tid"} <= set(e)
        if e["ph"] in ("X", "s", "f", "i"):
            assert isinstance(e["ts"], float)
        if e["ph"] == "X":
            assert e["dur"] >= 0
    # Every flow start the router stamped has its replica-side finish.
    s_ids = sorted(e["id"] for e in events if e["ph"] == "s")
    f_ids = sorted(e["id"] for e in events if e["ph"] == "f")
    assert s_ids and s_ids == f_ids
    assert "decode" in {e["name"] for e in events if e["ph"] == "X"}


def test_replay_keeps_rid_with_bumped_hop():
    """Kill a replica with requests in flight: the replayed request's marks
    stay under ONE rid — a 'replayed' instant plus a second 'sent' with a
    bumped hop — so the trace shows the failover instead of losing the
    request at the dead replica."""
    reqtrace.enable()
    Router_backoff = Router.RESPAWN_BACKOFF_S
    Router.RESPAWN_BACKOFF_S = 0.02
    fleet = []
    router = Router(_replica_factory(step_s=0.01, fleet=fleet),
                    n_replicas=2, start=False)
    server = RouterServer(router)
    try:
        victim = router.replicas()[0]

        def killer():
            deadline = time.monotonic() + 5.0
            while victim.in_flight == 0 and time.monotonic() < deadline:
                time.sleep(0.001)
            victim.server.kill()

        errors = []

        def one(i):
            try:
                ServeClient(server.address).generate(np.arange(1, 4), 8,
                                                     seed=i)
            except Exception as e:   # noqa: BLE001 - the assert reports it
                errors.append(repr(e))

        kt = threading.Thread(target=killer)
        kt.start()
        threads = [threading.Thread(target=one, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        kt.join()
        assert errors == []
    finally:
        server.close()
        Router.RESPAWN_BACKOFF_S = Router_backoff
    replayed = {rid: recs for rid, recs in _fleet_marks().items()
                if any(p == "replayed" for p, _, _ in recs)}
    assert replayed, "the kill never landed mid-flight"
    rid, recs = next(iter(replayed.items()))
    hops = [a["hop"] for p, _, a in recs if p == "sent"]
    assert sorted(hops) == list(range(len(hops))) and len(hops) >= 2
    assert any(p == "finished" for p, _, _ in recs)   # same rid completed
    # The failover renders: one rid, a replay instant, both flow hops.
    events = cluster.reqtrace_trace_events(
        telemetry.local_reqtrace_state(), pid=0, origin_ns=0)
    mine = [e for e in events
            if e.get("args", {}).get("rid") == str(rid)
            or str(e.get("id", "")).startswith(f"{rid}/")]
    assert any(e["ph"] == "i" and e["name"] == "replayed" for e in mine)
    flow_hops = {e["id"] for e in mine if e["ph"] == "s"}
    assert {f"{rid}/0", f"{rid}/1"} <= flow_hops


# ----------------------------------------- exemplars + the e2e burn pin

def test_histogram_exemplar_slowest_in_window():
    reg = metrics.Registry()
    h = reg.histogram("rt.lat", buckets=(0.1, 1.0))
    assert h.exemplar() is None
    h.observe(0.5, exemplar={"rid": "a"})
    h.observe(0.2, exemplar={"rid": "b"})          # faster: not booked
    assert h.exemplar() == {"rid": "a", "value": 0.5}
    h.observe(0.9, exemplar={"rid": "c"})          # slower: replaces
    assert h.exemplar()["rid"] == "c"
    h.observe(2.0)                                 # no exemplar offered
    assert h.exemplar()["rid"] == "c"
    # The exemplar stays OUT of snapshots (deterministic exposition).
    assert "exemplar" not in json.dumps(reg.snapshot())
    # Window expiry: a stale exemplar stops answering and any fresh
    # observation may rebook, even a faster one.
    h._ex_t -= metrics.EXEMPLAR_WINDOW_S + 1
    assert h.exemplar() is None
    h.observe(0.1, exemplar={"rid": "d"})
    assert h.exemplar()["rid"] == "d"


def test_p99_burn_books_exemplar_and_adtrace_names_guilty_replica(tmp_path):
    """The PR's e2e acceptance pin: one SLOW replica in a 2-replica fleet
    drives serve.latency_s.total's p99 over a tight SLO; the firing
    serve_p99_burn books the slowest request's exemplar (rid + phase
    breakdown) into the alert record, ``active()``, and the flight-recorder
    manifest; adtrace's waterfall for that rid names decode on the guilty
    replica."""
    reqtrace.enable()
    rule = alerts.AlertRule(name="serve_p99_burn", kind="burn_rate",
                            metric="serve.latency_s.total", q=0.99,
                            objective_s=0.05, long_s=1.2, short_s=0.6)
    eng = alerts.AlertEngine(rules=[rule], action="warn")
    alerts.set_engine(eng)
    h = history.MetricsHistory(out_dir="", min_interval_s=0.0)
    h.sample()                                     # window-opening baseline

    fleet = []
    router = Router(_replica_factory(step_s_list=[0.08, 0.0], fleet=fleet),
                    n_replicas=2, start=False)
    server = RouterServer(router)
    try:
        slow_name = "%s:%d" % fleet[0][1].address
        assert fleet[0][0].step_s == 0.08

        def storm():
            # 4 concurrent requests over 2x capacity-2 replicas: the
            # least-loaded spread parks two on the slow one (0.48s decode)
            # and two on the fast one (~0) — the slowest IS the exemplar.
            threads = [threading.Thread(
                target=lambda i=i: ServeClient(server.address).generate(
                    np.arange(1, 4), 6, seed=i)) for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        storm()                                    # burns the long window...
        h.sample()
        eng.evaluate(h)                            # ...maybe short on span
        storm()
        h.sample()
        fired = [f for f in eng.evaluate(h) + eng.active()
                 if f["rule"] == "serve_p99_burn"]
    finally:
        server.close()

    assert fired, "serve_p99_burn never fired"
    ex = fired[0].get("exemplar")
    assert ex is not None, fired[0]
    assert str(ex["rid"]).startswith("router-")
    assert ex["total_s"] >= 0.4                    # the slow replica's work
    assert ex["decode_s"] >= 0.8 * ex["total_s"]   # phase breakdown rides
    # ...into the flight-recorder manifest (the non-creating accessor).
    manifest = recorder.build_manifest("test")
    booked = [a for a in manifest.get("alerts", ())
              if a.get("rule") == "serve_p99_burn"]
    assert booked and booked[0]["exemplar"]["rid"] == ex["rid"]

    # adtrace: the booked rid's trace pins decode as the dominant phase ON
    # the slow replica — the alert names a request, the trace names why.
    adtrace = _load_tool("adtrace")
    grouped = _fleet_marks()
    recs = grouped[ex["rid"]]
    assert next(a for p, _, a in recs
                if p == "sent")["replica"] == slow_name
    durations = adtrace.phase_durations(reqtrace.snapshot_marks())
    decode = dict((rid, s) for s, rid in durations["decode"])
    assert decode[ex["rid"]] >= 0.4
    report = adtrace.render_report([telemetry.local_reqtrace_state()],
                                   top=8)
    assert str(ex["rid"]) in report
    assert f"replica={slow_name}" in report


# ------------------------------------- merge determinism + offline dumps

def _synthetic_ring():
    reqtrace.enable()
    t = [0]

    def tick(rid, phase, **args):
        reqtrace.mark(rid, phase, **args)
    tick("r-1", "received", hop=0)
    tick("r-1", "sent", replica="a:1", hop=0, send_wall_ns=123)
    tick("r-1", "received", hop=0, wire_ns=250_000)
    tick("r-1", "queued", depth=1)
    tick("r-1", "admitted", slot=0)
    tick("r-1", "prefill_start", prompt_len=4)
    tick("r-1", "prefill_end")
    tick("r-1", "first_token")
    tick("r-2", "shed", reason="fleet_busy")
    tick("r-1", "done", tokens=3)
    tick("r-1", "finished", replica="a:1")
    del t


def test_merge_determinism_and_jsonl_roundtrip(tmp_path):
    """Same blobs in -> byte-identical Chrome trace out (twice); a reqtrace
    JSONL dump loads back into the same rebased marks, and tracedump merges
    it offline into the same flow-linked timeline."""
    _synthetic_ring()
    state = telemetry.local_reqtrace_state(worker_id=7)
    p1, p2 = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    cluster.merge_trace_states([], p1, reqtrace_states=[state])
    cluster.merge_trace_states([], p2, reqtrace_states=[state])
    b1, b2 = open(p1, "rb").read(), open(p2, "rb").read()
    assert b1 == b2 and b1                         # deterministic merge

    dump = str(tmp_path / "req.jsonl")
    telemetry.dump_reqtrace_jsonl(dump, worker_id=7)
    loaded = telemetry.load_reqtrace_jsonl(dump)
    # Lossless round-trip: identical records; the absolute wall stamps may
    # jitter by the dump's own back-to-back wall/perf pair (sub-us).
    got, want = cluster.reqtrace_marks(loaded), cluster.reqtrace_marks(state)
    assert ([(m["rid"], m["phase"], m["args"]) for m in got]
            == [(m["rid"], m["phase"], m["args"]) for m in want])
    assert all(abs(g["wall_ns"] - w["wall_ns"]) < 1_000_000
               for g, w in zip(got, want))
    with pytest.raises(ValueError, match="reqtrace"):
        bad = tmp_path / "spans.jsonl"
        bad.write_text('{"meta": {"kind": "spans"}}\n')
        telemetry.load_reqtrace_jsonl(str(bad))

    tracedump = _load_tool("tracedump")
    p3 = str(tmp_path / "c.json")
    tracedump.merge_dumps(p3, [], reqtrace_files=[dump])
    doc = json.load(open(p3))
    names = {e.get("name") for e in doc["traceEvents"] if e["ph"] == "X"}
    assert {"queue", "prefill", "decode", "route", "wire"} <= names
    assert any(e["ph"] == "i" and e["name"] == "shed"
               for e in doc["traceEvents"])


def test_reqtrace_pull_opcode_and_dedupe(tmp_path):
    """Both server kinds answer the ``reqtrace`` pull; adtrace collapses the
    in-process fleet's identical ring blobs to one per OS process before
    merging (no triple-counted marks)."""
    reqtrace.enable()
    router = Router(_replica_factory(), n_replicas=1, start=False)
    server = RouterServer(router)
    try:
        ServeClient(server.address).generate(np.arange(1, 4), 2, seed=0)
        adtrace = _load_tool("adtrace")
        addrs = ["%s:%d" % server.address,
                 router.replicas()[0].name]
        pulled = adtrace.collect(addrs)
        assert not pulled["errors"]
        states = pulled["states"]
        assert len(states) == 2                    # one blob per endpoint...
        assert len(adtrace.dedupe_states(states)) == 1   # ...one process
        n_marks = len(adtrace.merged_marks(states))
        assert n_marks == len(reqtrace.snapshot_marks())
    finally:
        server.close()


# --------------------------------------------------- spans + console lines

def test_serve_request_span_carries_rid():
    """The span-args bugfix: the replica's serve.request span names BOTH
    its local rid and the router-scope rid token, so a span ring pulled
    from one replica joins the fleet-wide trace."""
    telemetry.enable()
    router = Router(_replica_factory(), n_replicas=1, start=False)
    server = RouterServer(router)
    try:
        ServeClient(server.address).generate(np.arange(1, 4), 2, seed=0)
    finally:
        server.close()
    spans = [(name, args) for name, _, _, _, args in
             telemetry.snapshot_spans() if name == "serve.request"]
    tokens = [a.get("rid_token") for _, a in spans if a and "rid_token" in a]
    assert tokens and all(str(t).startswith("router-") for t in tokens)
    assert any(a and "rid" in a for _, a in spans)


def test_consoles_render_attr_shares_and_exemplar():
    adtop = _load_tool("adtop")
    reg = {"serve.attr.wire": 0.02, "serve.attr.queue": 0.1,
           "serve.attr.prefill": 0.18, "serve.attr.decode": 0.7}
    lines = adtop._req_lines(reg, {"active": [
        {"rule": "serve_p99_burn", "exemplar": {"rid": "router-3"}}]})
    assert len(lines) == 1
    assert "attr" in lines[0] and "decode .70" in lines[0]
    assert "exemplar router-3 (serve_p99_burn)" in lines[0]
    assert adtop._req_lines({}, {}) == []          # un-armed: line off

    adfleet = _load_tool("adfleet")
    row = adfleet._row("x:1", {"kind": "serve", "uptime_s": 5,
                               "capacity": 2, "queue_depth": 0,
                               "registry": reg})
    assert "attr w.02/q.10/p.18/d.70" in row
    bare = adfleet._row("x:1", {"kind": "serve", "uptime_s": 5,
                                "capacity": 2, "queue_depth": 0,
                                "registry": {}})
    assert "attr" not in bare


def test_attr_gauges_sum_to_one_per_round():
    """serve.attr.* (the serving twin of train.attr.*): after served
    traffic the per-round shares exist and sum to ~1.0."""
    reqtrace.enable()
    router = Router(_replica_factory(), n_replicas=1, start=False)
    server = RouterServer(router)
    try:
        for i in range(3):
            ServeClient(server.address).generate(np.arange(1, 5), 3, seed=i)
        deadline = time.monotonic() + 2.0
        shares = {}
        while time.monotonic() < deadline:
            snap = telemetry.snapshot()
            shares = {p: snap.get(f"serve.attr.{p}")
                      for p in ("wire", "queue", "prefill", "decode")}
            if all(isinstance(v, (int, float)) for v in shares.values()):
                break
            time.sleep(0.01)
    finally:
        server.close()
    assert all(isinstance(v, (int, float)) for v in shares.values()), shares
    assert sum(shares.values()) == pytest.approx(1.0, abs=0.01)
