"""Process-global metrics registry: Counter / Gauge / Histogram.

The runtime previously had four disconnected accounting islands
(``WireCounters``, ``ThroughputMeter``, ad-hoc log fields, benchmark logger
rows); this registry gives them one namespace with a deterministic
``snapshot()`` that is wire-encodable (plain str/int/float/dict values), so
the PS ``stats`` opcode can ship a remote worker's or the chief's metrics
across the transport verbatim.

All instruments are lock-guarded and ``__slots__``-small; creation is
get-or-create by name so instrumentation sites never race registration.
Metric names are dotted lowercase (``ps.wire.bytes_sent``,
``train.readback_wait_s``) — the convention the docs and the stats plane
assume.
"""

import bisect
import collections
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union
from autodist_tpu.testing.sanitizer import san_lock

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "registry",
           "counter", "gauge", "histogram", "snapshot", "event", "events",
           "family_buckets", "quantile", "merge_histograms"]

Number = Union[int, float]

# Default histogram bucket upper bounds for second-valued observations
# (latency-style: 1ms .. 10s, +inf implicit).
SECONDS_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0)
# For small-integer distributions (staleness lag, queue depths).
COUNT_BUCKETS = (0, 1, 2, 4, 8, 16, 32)
# Millisecond-scale edges for online-serving latencies (0.5ms .. 2.5s): the
# step-time default above puts everything under 1ms in one bucket, which is
# where a whole loopback serving distribution lives.
MS_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
              0.25, 0.5, 1.0, 2.5)
# Log-spaced edges for norm-valued observations (the training-health plane's
# gradient-norm distribution): healthy norms cluster around O(1); the decades
# on either side are where vanishing/exploding shows up.
NORM_BUCKETS = (0.001, 0.01, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 100.0,
                1000.0)

# Per-family default-bucket overrides, keyed by metric-name prefix (a family
# matches ``name == prefix`` or ``name.startswith(prefix + '.')``; the
# longest match wins). Histograms created WITHOUT explicit buckets resolve
# their family here, so e.g. every ``serve.latency_s.*`` instrument gets
# ms-scale edges without each call site repeating them. Names outside every
# family keep SECONDS_BUCKETS — the pre-existing default is unchanged.
BUCKET_FAMILIES: Dict[str, Tuple[Number, ...]] = {
    "serve.latency_s": MS_BUCKETS,
    "train.health.grad_norm": NORM_BUCKETS,
}


def family_buckets(name: str) -> Tuple[Number, ...]:
    """The default bucket edges for ``name``: its longest matching family in
    :data:`BUCKET_FAMILIES`, else :data:`SECONDS_BUCKETS`."""
    best: Optional[str] = None
    for prefix in BUCKET_FAMILIES:
        if (name == prefix or name.startswith(prefix + ".")) \
                and (best is None or len(prefix) > len(best)):
            best = prefix
    return BUCKET_FAMILIES[best] if best is not None else SECONDS_BUCKETS


class Counter:
    """Monotonically increasing sum (ints or floats)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value: Number = 0
        self._lock = san_lock()

    def inc(self, n: Number = 1):
        with self._lock:
            self._value += n

    @property
    def value(self) -> Number:
        return self._value

    def snapshot(self) -> Number:
        return self._value


class Gauge:
    """Last-set instantaneous value."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value: Number = 0
        self._lock = san_lock()

    def set(self, value: Number):
        with self._lock:
            self._value = value

    def inc(self, n: Number = 1):
        with self._lock:
            self._value += n

    @property
    def value(self) -> Number:
        return self._value

    def snapshot(self) -> Number:
        return self._value


EXEMPLAR_WINDOW_S = 300.0


class Histogram:
    """Fixed-bucket histogram: ``observe(v)`` lands in the first bucket whose
    upper bound satisfies ``v <= bound`` (Prometheus ``le`` semantics), with
    an implicit ``+inf`` overflow bucket. Bucket edges are fixed at
    construction — snapshots from different processes with the same edges
    merge by element-wise addition.

    Observation sites may attach an EXEMPLAR — a small wire-encodable dict
    identifying the concrete observation (a serving rid plus its phase
    breakdown). The histogram keeps only the SLOWEST exemplar of the last
    :data:`EXEMPLAR_WINDOW_S` seconds, so an alert firing on this histogram
    can name one traceable request instead of an anonymous quantile.
    Exemplars carry wall-clock time and live OUTSIDE :meth:`snapshot`
    (which stays deterministic); read them via :meth:`exemplar`."""

    __slots__ = ("name", "buckets", "_counts", "_sum", "_count", "_lock",
                 "_ex", "_ex_value", "_ex_t")

    def __init__(self, name: str, buckets: Sequence[Number] = SECONDS_BUCKETS):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(f"histogram buckets must be non-empty and "
                             f"ascending, got {buckets!r}")
        self.name = name
        self.buckets: Tuple[Number, ...] = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # +1: the +inf bucket
        self._sum: float = 0.0
        self._count = 0
        self._ex: Optional[Dict[str, object]] = None
        self._ex_value = 0.0
        self._ex_t = 0.0
        self._lock = san_lock()

    def observe(self, value: Number,
                exemplar: Optional[Dict[str, object]] = None):
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            if exemplar is not None:
                now = time.time()
                if (value >= self._ex_value or self._ex is None
                        or now - self._ex_t > EXEMPLAR_WINDOW_S):
                    self._ex = dict(exemplar, value=float(value))
                    self._ex_value = float(value)
                    self._ex_t = now

    def exemplar(self) -> Optional[Dict[str, object]]:
        """The slowest exemplar observed within the last
        :data:`EXEMPLAR_WINDOW_S` seconds (a copy), else None."""
        with self._lock:
            if self._ex is None or time.time() - self._ex_t > EXEMPLAR_WINDOW_S:
                return None
            return dict(self._ex)

    @property
    def count(self) -> int:
        return self._count

    def snapshot(self) -> Dict[str, Number]:
        """Wire-encodable dict: per-bucket counts keyed ``le:<bound>`` (plus
        ``le:+inf``), total ``count`` and ``sum``."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        out: Dict[str, Number] = {}
        for bound, n in zip(self.buckets, counts):
            out[f"le:{bound:g}"] = n
        out["le:+inf"] = counts[-1]
        out["count"] = total
        out["sum"] = s
        return out

    def format_compact(self) -> str:
        """``lag{0:5,1:3,+inf:1}``-style rendering of the NON-EMPTY buckets,
        for one-line log summaries (the per-worker ``PSServer closed:``
        breakdown)."""
        with self._lock:
            counts = list(self._counts)
        labels = [f"{b:g}" for b in self.buckets] + ["+inf"]
        body = ",".join(f"{l}:{n}" for l, n in zip(labels, counts) if n)
        return "{" + body + "}"


def quantile(hist: Dict[str, Number], q: float) -> Optional[float]:
    """Estimate the ``q``-quantile of a histogram SNAPSHOT dict (the
    ``le:<bound>`` + ``count`` wire form every histogram ships).

    This is the ONE bucket-interpolation everybody uses — the alert engine's
    burn-rate predicate, ``tools/adtop.py``'s serving SLO line, and
    ``tools/adfleet.py``'s fleet aggregation — so three consumers can never
    drift apart on what "p99" means. Linear interpolation inside the winning
    bucket (the first bucket's lower edge is 0, clamped to the edge when the
    edge is negative); a quantile landing in the ``+inf`` overflow bucket
    returns the largest finite edge — a LOWER bound, which is the honest
    answer a fixed-bucket histogram can give. Returns None for an empty
    histogram (or a non-histogram dict)."""
    try:
        total = hist["count"]
    except (TypeError, KeyError):
        return None
    if not total:
        return None
    edges = sorted((float(k[3:]), v) for k, v in hist.items()
                   if k.startswith("le:") and k != "le:+inf")
    target = max(0.0, min(1.0, q)) * total
    seen = 0.0
    lower = None
    for bound, n in edges:
        if n and seen + n >= target:
            lo = min(0.0, bound) if lower is None else lower
            return lo + (bound - lo) * (target - seen) / n
        seen += n
        lower = bound
    return edges[-1][0] if edges else None


def merge_histograms(snaps: Sequence[Dict[str, Number]]) -> Dict[str, Number]:
    """Element-wise sum of histogram snapshot dicts — the cross-process
    aggregation (identical edges merge exactly; a snapshot with different
    edges contributes its buckets verbatim, which keeps :func:`quantile`
    a defensible estimate rather than raising mid-console-render)."""
    out: Dict[str, Number] = {}
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        for k, v in snap.items():
            if isinstance(v, (int, float)):
                out[k] = out.get(k, 0) + v
    return out


# Structured events kept per registry (newest win; anomaly records from the
# PS watchdog, not a general log sink).
_EVENT_RING = 256


class Registry:
    """Named get-or-create instrument store with a deterministic snapshot.

    Besides instruments, a registry keeps a bounded ring of STRUCTURED
    EVENTS (:meth:`event`) — discrete anomaly records like the PS watchdog's
    straggler flags, where a counter says "how many" but not "which worker,
    when". Events carry wall-clock timestamps, so they live OUTSIDE
    :meth:`snapshot` (which stays deterministic for a given set of recorded
    values); ship them explicitly via :meth:`events`."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._events = collections.deque(maxlen=_EVENT_RING)
        self._lock = san_lock()

    def _get(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args)
                self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(f"metric {name!r} is already registered as "
                            f"{type(m).__name__}, not {cls.__name__}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str,
                  buckets: Optional[Sequence[Number]] = None) -> Histogram:
        return self._get(name, Histogram, buckets or family_buckets(name))

    def get(self, name: str) -> Optional[object]:
        """The live instrument registered under ``name``, or None — a
        NON-CREATING lookup for consumers (the alert engine's exemplar
        attach) that must observe, never register."""
        with self._lock:
            return self._metrics.get(name)

    def instruments(self) -> List[Tuple[str, object]]:
        """A point-in-time, name-sorted copy of the live instrument objects
        — the public walk :meth:`snapshot` and the OpenMetrics renderer
        share (renderers need the instrument TYPE, which the snapshot's
        plain values erase)."""
        with self._lock:
            return sorted(self._metrics.items())

    def snapshot(self) -> Dict[str, object]:
        """``{name: value-or-histogram-dict}``, keys sorted — deterministic
        for a given set of recorded values regardless of registration order,
        and wire-encodable as-is (the ``stats`` opcode ships it)."""
        return {name: m.snapshot() for name, m in self.instruments()}

    def event(self, name: str, **fields) -> Dict[str, object]:
        """Record a structured event (``{"name", "t_wall_s", **fields}``) into
        the bounded event ring; returns the record. Field values must be
        wire-encodable plain data (the stats plane ships events verbatim)."""
        rec: Dict[str, object] = {"name": name,
                                  "t_wall_s": round(time.time(), 3)}
        rec.update(fields)
        with self._lock:
            self._events.append(rec)
        return rec

    def events(self) -> List[Dict[str, object]]:
        """A point-in-time copy of the event ring, oldest first."""
        with self._lock:
            return list(self._events)

    def clear(self):
        """Drop every instrument and event (tests; production registries live
        for the process)."""
        with self._lock:
            self._metrics.clear()
            self._events.clear()

    def clear_events(self):
        """Drop the event ring only, keeping instruments — for consumers
        (tests, a snapshot-and-reset exporter) that need a clean anomaly
        window without discarding counters other subsystems still hold."""
        with self._lock:
            self._events.clear()


_REGISTRY = Registry()


def registry() -> Registry:
    """The process-global registry every instrumented subsystem shares."""
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str, buckets: Optional[Sequence[Number]] = None) -> Histogram:
    return _REGISTRY.histogram(name, buckets)


def snapshot() -> Dict[str, object]:
    return _REGISTRY.snapshot()


def event(name: str, **fields) -> Dict[str, object]:
    return _REGISTRY.event(name, **fields)


def events() -> List[Dict[str, object]]:
    return _REGISTRY.events()
