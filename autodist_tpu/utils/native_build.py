"""Shared lazy build-and-load for the native C++ extensions.

One definition of the compile recipe (content-hashed cache under the working
dir, pid-suffixed temp + atomic rename so concurrent builders race safely,
warning + ``None`` fallback when no compiler is available) used by the data
loader (``data/loader.py``) and the PS transport (``parallel/ps_transport.py``).
"""

import ctypes
import hashlib
import os
import subprocess
from typing import Optional, Sequence

from autodist_tpu import const
from autodist_tpu.utils import logging


def build_native_lib(src_path: str, name: str,
                     extra_flags: Sequence[str] = ()) -> Optional[ctypes.CDLL]:
    """Compile ``src_path`` into a cached shared library and load it.

    Returns ``None`` (after logging a warning) when the toolchain or filesystem
    is unavailable — callers fall back to their pure-Python paths. The cache key
    is the source content hash, so editing the .cc rebuilds automatically.
    """
    try:
        with open(src_path, "rb") as f:
            tag = hashlib.sha256(f.read()).hexdigest()[:16]
        out_dir = os.path.join(const.DEFAULT_WORKING_DIR, "native")
        os.makedirs(out_dir, exist_ok=True)
        lib_path = os.path.join(out_dir, f"{name}-{tag}.so")
        if not os.path.exists(lib_path):
            tmp = lib_path + f".tmp{os.getpid()}"
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp,
                 src_path, *extra_flags],
                check=True, capture_output=True)
            os.replace(tmp, lib_path)  # atomic: concurrent builders race safely
        return ctypes.CDLL(lib_path)
    except Exception as e:  # no g++, sandboxed tmp, ... -> pure-Python fallback
        logging.warning("Native %s unavailable (%s); using the Python fallback",
                        name, e)
        return None
