#!/usr/bin/env python
"""adtrace — request-scoped tracing console for a serving fleet.

The rendering half of the request-trace plane
(``autodist_tpu/telemetry/reqtrace.py``): every serving process records
request lifecycle marks (received / queued / admitted / prefill / decode /
shed / replayed / finished) keyed by the ROUTER-SCOPE rid when
``AUTODIST_REQTRACE=1`` is armed. adtrace pulls those rings fleet-wide via
the ``reqtrace`` wire opcode, rebases every process onto ONE clock
(``ping``-based ntp offsets, the cluster trace plane's estimator), joins
the marks by rid, and answers "why was this p99 request slow":

- a per-phase breakdown table — wire / queue / admit-wait / prefill /
  decode / total with n, p50, p99, max across every completed request;
- top-K slowest-request WATERFALLS — one request's marks as a relative
  timeline, naming the replica each hop landed on (a replayed request
  shows its failover inline, same rid, bumped hop);
- ``--out trace.json`` — the merged flow-linked Chrome trace (router lane
  -> replica lane arrows, one sub-lane per request) for ui.perfetto.dev,
  with each process's span ring pulled alongside via the ``trace`` opcode.

A router endpoint is expanded automatically: its ``status`` reply carries
the replica fleet table, so pointing adtrace at the front door traces the
whole fleet. Offline, ``--jsonl`` merges ``telemetry.dump_reqtrace_jsonl``
files instead (no transport up — post-mortem).

Usage:
    python tools/adtrace.py ROUTER_HOST:PORT             # tables + waterfalls
    python tools/adtrace.py A:1 B:2 --top 5 --out t.json
    python tools/adtrace.py --jsonl r0.jsonl r1.jsonl
"""

import argparse
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

# Interval phases priced in the breakdown table: (row, start mark, end mark).
# ``wire`` is special-cased (it is an ARG on the replica's received mark, not
# a mark pair — the trace token decomposed it from queue time on arrival).
PHASE_ROWS = (("queue", "queued", "admitted"),
              ("prefill", "prefill_start", "prefill_end"),
              ("decode", "first_token", "done"),
              ("total", "received", "finished"))


def _endpoint_offset_ns(client, rounds: int = 3) -> int:
    """Tool-clock-minus-endpoint clock offset via ntp over ``ping``
    round-trips — the sign :func:`cluster.merge_trace_states` rebasing
    expects (offset ADDED to the blob's wall clock lands on ours)."""
    from autodist_tpu.telemetry import cluster
    samples = []
    for _ in range(rounds):
        t0 = time.time_ns()
        _, s_ns = client.call("ping", t0)
        samples.append((t0, int(s_ns), time.time_ns()))
    off, _err = cluster.ntp_offset(samples)   # endpoint minus tool
    return -int(off)


def discover(addresses, timeout: float = 2.0) -> List[str]:
    """Expand the address list through router fleet tables: any endpoint
    whose ``status`` reply is ``kind="router"`` contributes its replicas'
    ``host:port`` names. Unreachable endpoints stay in the list — collect()
    reports them as errors rather than silently shrinking the fleet."""
    from autodist_tpu.parallel.ps_transport import _PSClient
    out, seen = [], set()
    for addr in addresses:
        if addr in seen:
            continue
        seen.add(addr)
        out.append(addr)
        client = _PSClient(_parse_addr(addr), connect_timeout=timeout,
                           read_timeout=timeout)
        try:
            st = client.call("status")[0]
        except Exception:
            continue
        finally:
            client.close()
        if isinstance(st, dict) and st.get("kind") == "router":
            for row in st.get("replicas") or []:
                name = row.get("replica")
                if name and name not in seen:
                    seen.add(name)
                    out.append(name)
    return out


def collect(addresses, timeout: float = 2.0,
            with_spans: bool = False) -> Dict[str, object]:
    """Pull every endpoint's reqtrace ring (and span ring when
    ``with_spans``) onto the tool's clock. Returns ``{"states": [...],
    "span_states": [...], "errors": {addr: msg}}``; each blob's
    ``worker_id`` is set to its endpoint string so merged lanes read as
    addresses, and its ``clock_offset_ns`` to the ping-estimated
    tool-minus-endpoint offset."""
    from autodist_tpu.parallel.ps_transport import _PSClient
    states, span_states, errors = [], [], {}
    for addr in addresses:
        client = _PSClient(_parse_addr(addr), connect_timeout=timeout,
                           read_timeout=timeout)
        try:
            off = _endpoint_offset_ns(client)
            st = client.call("reqtrace")[0]
            st["worker_id"] = addr
            st["clock_offset_ns"] = off
            states.append(st)
            if with_spans:
                sp = client.call("trace")[0]
                sp["worker_id"] = addr
                sp["clock_offset_ns"] = off
                span_states.append(sp)
        except Exception as e:
            errors[addr] = f"{type(e).__name__}: {e}"
        finally:
            client.close()
    return {"states": states, "span_states": span_states, "errors": errors}


def dedupe_states(states) -> List[dict]:
    """One blob per OS process. The rings are process-global, so an
    in-process fleet (the tests' loopback topology — router and replicas in
    one interpreter) returns the SAME ring from every endpoint; keeping one
    blob per ``(host, pid)`` (the fullest, pulls race the ring) stops the
    merged report triple-counting every mark. Distinct processes always
    differ in OS pid and are never collapsed."""
    best: Dict[Tuple[object, object], dict] = {}
    order: List[Tuple[object, object]] = []

    def _n(st):
        return len(st.get("rids", st.get("t0_ns", ())))

    for st in states:
        key = (st.get("host"), st.get("pid"))
        cur = best.get(key)
        if cur is None:
            best[key] = st
            order.append(key)
        elif _n(st) > _n(cur):
            best[key] = st
    return [best[k] for k in order]


def merged_marks(states) -> List[dict]:
    """Every blob's marks rebased onto one clock, tagged with their source
    endpoint (``src``), time-sorted — the row-wise form the tables and
    waterfalls consume."""
    from autodist_tpu.telemetry import cluster
    marks: List[dict] = []
    for st in dedupe_states(states):
        src = st.get("worker_id")
        src = str(src) if src is not None else f"pid {st.get('pid', '?')}"
        for m in cluster.reqtrace_marks(st):
            m["src"] = src
            marks.append(m)
    marks.sort(key=lambda m: (int(m["wall_ns"]), str(m["rid"])))
    return marks


def phase_durations(marks) -> Dict[str, List[Tuple[float, object]]]:
    """Per-phase ``(seconds, rid)`` samples across requests: the
    :data:`PHASE_ROWS` intervals (first start to last end per rid — a
    replayed request prices its WHOLE story, failover included), ``wire``
    from the received marks' decomposed ``wire_ns`` args, ``admit_wait``
    from the gap between an admit_wait mark and the admission."""
    from autodist_tpu.telemetry import reqtrace
    out: Dict[str, List[Tuple[float, object]]] = {}
    for rid, recs in reqtrace.group_records(marks).items():
        first, last = {}, {}
        for phase, t, args in recs:
            first.setdefault(phase, (t, args))
            last[phase] = (t, args)
        for row, p0, p1 in PHASE_ROWS:
            if p0 in first and p1 in last:
                dt = (last[p1][0] - first[p0][0]) / 1e9
                if dt >= 0:
                    out.setdefault(row, []).append((dt, rid))
        for phase, t, args in recs:
            if phase == "received" and args.get("wire_ns") is not None:
                out.setdefault("wire", []).append(
                    (int(args["wire_ns"]) / 1e9, rid))
        if "admit_wait" in first and "admitted" in last:
            dt = (last["admitted"][0] - first["admit_wait"][0]) / 1e9
            if dt >= 0:
                out.setdefault("admit_wait", []).append((dt, rid))
    return out


def _pct(samples: List[float], q: float) -> float:
    xs = sorted(samples)
    if not xs:
        return 0.0
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


def _ms(s: float) -> str:
    return f"{s * 1e3:9.2f}ms"


def render_table(durations) -> str:
    """The per-phase breakdown: n / p50 / p99 / max, phases in pipeline
    order. The number ROADMAP 1's disaggregation work reads: where the
    fleet's request time actually goes."""
    order = ("wire", "queue", "admit_wait", "prefill", "decode", "total")
    lines = [f"  {'phase':<11} {'n':>6} {'p50':>11} {'p99':>11} {'max':>11}"]
    for row in order:
        samp = durations.get(row)
        if not samp:
            continue
        xs = [s for s, _ in samp]
        lines.append(f"  {row:<11} {len(xs):>6} {_ms(_pct(xs, 0.5))} "
                     f"{_ms(_pct(xs, 0.99))} {_ms(max(xs))}")
    if len(lines) == 1:
        return "  (no completed requests recorded — is AUTODIST_REQTRACE=1 " \
               "armed on the fleet?)"
    return "\n".join(lines)


def render_waterfall(rid, recs) -> List[str]:
    """One request's marks as a relative timeline: +offset, phase, source
    endpoint, and the arg payload that names the story (replica routed to,
    hop, wire decomposition, shed reason)."""
    t0 = recs[0][1] if recs else 0
    lines = []
    for phase, t, args in recs:
        extra = ""
        if args:
            parts = []
            for k in ("replica", "hop", "slot", "reason", "depth", "tokens",
                      "prompt_len", "pages_needed", "pages_free"):
                if k in args:
                    parts.append(f"{k}={args[k]}")
            if "wire_ns" in args:
                parts.append(f"wire={int(args['wire_ns']) / 1e6:.2f}ms")
            extra = "  " + " ".join(parts) if parts else ""
        src = args.get("src", "") if args else ""
        lines.append(f"    +{(t - t0) / 1e6:9.2f}ms  {phase:<13}"
                     f"{(' @' + src) if src else '':<24}{extra}")
    return lines


def render_report(states, top: int = 3) -> str:
    """The whole plain-text report for a set of reqtrace blobs: breakdown
    table, then the top-K slowest completed requests as waterfalls. One
    rendering path for live pulls, offline JSONL merges, and tests."""
    from autodist_tpu.telemetry import reqtrace
    marks = merged_marks(states)
    for m in marks:   # thread the source into the args the waterfall prints
        m["args"] = dict(m.get("args") or {}, src=m["src"])
    durations = phase_durations(marks)
    lines = [f"adtrace — {len(states)} process(es), "
             f"{len(marks)} mark(s), "
             f"{len(durations.get('total', []))} completed request(s)"]
    lines.append(render_table(durations))
    slowest = sorted(durations.get("total", []), reverse=True,
                     key=lambda sr: sr[0])[:max(0, top)]
    if slowest:
        grouped = reqtrace.group_records(marks)
        lines.append(f"  slowest {len(slowest)} request(s):")
        for total_s, rid in slowest:
            lines.append(f"  rid {rid}  total {total_s * 1e3:.2f}ms")
            lines.extend(render_waterfall(rid, grouped.get(rid, [])))
    return "\n".join(lines)


def write_chrome_trace(out_path: str, states, span_states=()) -> str:
    """The merged flow-linked Chrome trace: span lanes (when pulled) plus
    per-request reqtrace lanes and router->replica flow arrows, one clock."""
    from autodist_tpu.telemetry import cluster
    return cluster.merge_trace_states(dedupe_states(span_states), out_path,
                                      reqtrace_states=dedupe_states(states))


def _parse_addr(addr: str) -> Tuple[str, int]:
    host, sep, port = addr.rpartition(":")
    if not sep:
        raise ValueError(f"endpoint {addr!r} is not HOST:PORT")
    return host, int(port)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="adtrace", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("addresses", nargs="*", default=[],
                    help="serving endpoints host:port (a router endpoint "
                         "expands to its replica fleet; default: "
                         "AUTODIST_ROUTER_ADDR / AUTODIST_SERVE_ADDR)")
    ap.add_argument("--jsonl", action="append", default=[], metavar="FILE",
                    help="offline reqtrace JSONL dump "
                         "(telemetry.dump_reqtrace_jsonl file; repeatable — "
                         "replaces the live pull)")
    ap.add_argument("--top", type=int, default=3,
                    help="slowest-request waterfalls to print (default 3)")
    ap.add_argument("--out", default="",
                    help="also write the merged flow-linked Chrome trace "
                         "JSON here (pulls span rings alongside)")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-endpoint connect/read deadline seconds")
    args = ap.parse_args(argv)
    if args.jsonl:
        from autodist_tpu.telemetry import cluster
        try:
            states = [cluster.load_reqtrace_jsonl(p) for p in args.jsonl]
        except (OSError, ValueError) as e:
            print(f"adtrace: {e}", file=sys.stderr)
            return 1
        errors = {}
        span_states = []
    else:
        addresses = list(args.addresses)
        if not addresses:
            from autodist_tpu import const
            addresses = [a for a in (str(const.ENV.AUTODIST_ROUTER_ADDR.val),
                                     str(const.ENV.AUTODIST_SERVE_ADDR.val))
                         if a]
        if not addresses:
            print("adtrace: no endpoints given and neither "
                  "AUTODIST_ROUTER_ADDR nor AUTODIST_SERVE_ADDR is set",
                  file=sys.stderr)
            return 2
        addresses = discover(addresses, timeout=args.timeout)
        got = collect(addresses, timeout=args.timeout,
                      with_spans=bool(args.out))
        states, span_states = got["states"], got["span_states"]
        errors = got["errors"]
    print(render_report(states, top=args.top))
    for addr, msg in sorted(errors.items()):
        print(f"adtrace: {addr} unreachable ({msg})", file=sys.stderr)
    if args.out:
        write_chrome_trace(args.out, states, span_states)
        print(f"adtrace: wrote {args.out} ({len(states)} reqtrace + "
              f"{len(span_states)} span lane(s))")
    if not states:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
