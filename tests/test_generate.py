"""Autoregressive generation: KV-cache decode parity and sampling.

The reference had no inference loop (serving = SavedModel export only); the
TPU-native ``transformer_lm.generate`` is beyond-reference. These tests pin
the property that makes a KV cache correct at all: decode-mode logits equal
the full non-decode forward at every position.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.models import transformer_lm
from autodist_tpu.models.transformer_lm import (TransformerLMConfig, generate,
                                                make_generate_fn,
                                                sample_logits)


def _small_cfg(**kw):
    kw.setdefault("vocab_size", 97)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("d_ff", 64)
    kw.setdefault("max_len", 32)
    kw.setdefault("dtype", jnp.float32)  # exact-comparison friendly
    return TransformerLMConfig(**kw)


def _tokens(cfg, batch, length, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randint(0, cfg.vocab_size, size=(batch, length)),
                       jnp.int32)


@pytest.mark.parametrize("tied", [True, False])
def test_decode_logits_match_full_forward(tied):
    """Prefill (chunked cache write) + per-token decode reproduce the full
    forward's logits at every position — the KV-cache invariant."""
    cfg = _small_cfg(tied_output=tied)
    model, params = transformer_lm.init_params(cfg)
    toks = _tokens(cfg, batch=3, length=10)

    full = model.apply({"params": params}, toks)                   # [B, L, V]

    prefill_len = 6
    dec_logits = []
    logits, variables = model.apply({"params": params}, toks[:, :prefill_len],
                                    decode=True, mutable=["cache"])
    dec_logits.append(logits)
    cache = variables["cache"]
    for i in range(prefill_len, toks.shape[1]):
        logits, variables = model.apply(
            {"params": params, "cache": cache}, toks[:, i:i + 1],
            pos_offset=i, decode=True, mutable=["cache"])
        cache = variables["cache"]
        dec_logits.append(logits)
    dec = jnp.concatenate(dec_logits, axis=1)

    np.testing.assert_allclose(np.asarray(dec), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("tied", [True, False])
def test_greedy_generate_matches_naive_rollout(tied):
    """generate(temperature=0) equals the no-cache rollout that reruns the
    full forward over the growing sequence and argmaxes the last position.

    Both head configs: the untied branch exercises generate()'s prefill
    projection through ``params["lm_head"]["kernel"]`` (common.lm_head_logits),
    which no other end-to-end test reaches."""
    cfg = _small_cfg(tied_output=tied)
    model, params = transformer_lm.init_params(cfg)
    prompt = _tokens(cfg, batch=2, length=5, seed=3)
    n_new = 7

    out = generate(model, params, prompt, n_new, temperature=0.0)
    assert out.shape == (2, n_new) and out.dtype == jnp.int32

    seq = prompt
    for _ in range(n_new):
        logits = model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), -1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(seq[:, prompt.shape[1]:]))


def test_generate_jitted_and_seeded_sampling():
    cfg = _small_cfg()
    model, params = transformer_lm.init_params(cfg)
    prompt = _tokens(cfg, batch=2, length=4, seed=1)
    gen = make_generate_fn(model, max_new_tokens=6, temperature=0.8, top_k=5)

    a = gen(params, prompt, jax.random.PRNGKey(7))
    b = gen(params, prompt, jax.random.PRNGKey(7))
    c = gen(params, prompt, jax.random.PRNGKey(8))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # seeded
    assert not np.array_equal(np.asarray(a), np.asarray(c))      # seed matters
    assert np.asarray(a).min() >= 0 and np.asarray(a).max() < cfg.vocab_size


def test_top_k_one_is_greedy_and_sampler_shapes():
    logits = jnp.asarray(np.random.RandomState(0).randn(4, 13), jnp.float32)
    key = jax.random.PRNGKey(0)
    greedy = sample_logits(logits, key, temperature=0.0)
    topk1 = sample_logits(logits, key, temperature=1.3, top_k=1)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(topk1))
    assert greedy.shape == (4,) and greedy.dtype == jnp.int32


def test_top_p_nucleus_sampling():
    """top_p keeps exactly the smallest head of the distribution reaching p
    (the token crossing the threshold included), never an empty nucleus."""
    # Row with known probabilities: softmax of these logits ~= [.6, .3, .1].
    # One jitted vmap over 200 keys per p (a 200-key python loop of eager
    # sample_logits dispatches costs ~25s of tier-1 budget for the same
    # distributional evidence).
    logits = jnp.log(jnp.asarray([[0.6, 0.3, 0.1]], jnp.float32))
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(200))

    def sweep(p, n=200):
        draws = jax.jit(jax.vmap(
            lambda k: sample_logits(logits, k, temperature=1.0, top_p=p)[0]
        ))(keys[:n])
        return set(np.asarray(draws).tolist())

    # p=0.5: nucleus = {0} (0.6 crosses the threshold) -> always token 0.
    assert sweep(0.5) == {0}
    # p=0.7: nucleus = {0, 1} (0.6 < p, +0.3 crosses) -> never token 2.
    assert sweep(0.7) == {0, 1}
    # A tiny p still keeps the argmax (nucleus never empty).
    assert sweep(1e-6, n=20) == {0}
    # Composes with top_k and threads through both generate APIs.
    cfg = _small_cfg()
    model, params = transformer_lm.init_params(cfg)
    toks = generate(model, params, _tokens(cfg, 2, 4), 5,
                    temperature=0.9, top_k=8, top_p=0.9,
                    rng=jax.random.PRNGKey(3))
    assert toks.shape == (2, 5) and int(toks.max()) < cfg.vocab_size
    from autodist_tpu.models import lstm_lm
    lcfg = lstm_lm.LSTMLMConfig(vocab_size=61, emb_dim=16, hidden_dim=24,
                                n_layers=1, dtype=jnp.float32)
    lmodel, lparams = lstm_lm.init_params(lcfg)
    lt = lstm_lm.generate(lmodel, lparams, _tokens(lcfg, 2, 3), 4,
                          temperature=0.9, top_p=0.8,
                          rng=jax.random.PRNGKey(4))
    assert lt.shape == (2, 4) and int(lt.max()) < lcfg.vocab_size


def test_generate_single_token_and_remat_decode():
    """max_new_tokens=1 short-circuits the scan; a remat training config still
    decodes (remat is skipped on the decode path, which keeps no residuals)."""
    cfg = _small_cfg(remat=True)
    model, params = transformer_lm.init_params(cfg)
    prompt = _tokens(cfg, batch=2, length=3)
    out = generate(model, params, prompt, 1)
    assert out.shape == (2, 1)


def test_generate_validates():
    cfg = _small_cfg(max_len=8)
    model, params = transformer_lm.init_params(cfg)
    with pytest.raises(ValueError, match="exceeds max_len"):
        generate(model, params, _tokens(cfg, 1, 6), 3)
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(model, params, _tokens(cfg, 1, 3), 0)


def test_lstm_decode_carry_matches_full_forward():
    """The carry cache invariant: hidden states from prefill + per-token
    decode equal the full recurrence over the same tokens."""
    from autodist_tpu.models import lstm_lm
    cfg = lstm_lm.LSTMLMConfig(vocab_size=61, emb_dim=16, hidden_dim=24,
                               n_layers=2, dtype=jnp.float32)
    model, params = lstm_lm.init_params(cfg)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 61, (3, 9)),
                       jnp.int32)
    full = model.apply({"params": params}, toks)
    h, variables = model.apply({"params": params}, toks[:, :5], decode=True,
                               mutable=["cache"])
    parts, cache = [h], variables["cache"]
    for i in range(5, toks.shape[1]):
        h, variables = model.apply({"params": params, "cache": cache},
                                   toks[:, i:i + 1], decode=True,
                                   mutable=["cache"])
        cache = variables["cache"]
        parts.append(h)
    dec = jnp.concatenate(parts, axis=1)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(full),
                               rtol=2e-5, atol=2e-5)


def test_lstm_greedy_generate_matches_naive_rollout():
    from autodist_tpu.models import lstm_lm
    cfg = lstm_lm.LSTMLMConfig(vocab_size=61, emb_dim=16, hidden_dim=24,
                               n_layers=2, dtype=jnp.float32)
    model, params = lstm_lm.init_params(cfg)
    prompt = jnp.asarray(np.random.RandomState(1).randint(0, 61, (2, 4)),
                         jnp.int32)
    n_new = 5
    out = lstm_lm.generate(model, params, prompt, n_new)
    assert out.shape == (2, n_new) and out.dtype == jnp.int32
    # The jitted form produces the same greedy tokens.
    jit_out = lstm_lm.make_generate_fn(model, n_new)(params, prompt)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(jit_out))

    w, b = params["softmax_w"], params["softmax_b"]
    seq = prompt
    for _ in range(n_new):
        h = model.apply({"params": params}, seq)
        logits = (h[:, -1] @ w.T.astype(h.dtype) + b).astype(jnp.float32)
        nxt = jnp.argmax(logits, -1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(seq[:, prompt.shape[1]:]))
