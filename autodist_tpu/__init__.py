"""autodist_tpu — a TPU-native distributed training framework.

A strategy-compiled engine in the spirit of AutoDist (reference:
``autodist/autodist.py``, ``docs/design/architecture.rst:27-39``): the user writes
single-device model code; a per-variable distribution **Strategy** (PS, load-balanced PS,
partitioned PS, AllReduce, partitioned/random-axis AllReduce, Parallax hybrid) is built
from the model plus a YAML **resource spec**, and materialized by a backend. Here the
backend is idiomatic JAX/XLA: ``pjit``/``shard_map`` shardings and
``psum``/``reduce_scatter``/``all_gather`` collectives over a TPU mesh (ICI/DCN), instead
of TensorFlow graph rewriting over grpc/NCCL.

Layer map (mirrors reference SURVEY.md §1, re-targeted):

- User API:        :mod:`autodist_tpu.autodist`  (``AutoDist(...).scope()`` / ``function()``)
- Strategy:        :mod:`autodist_tpu.strategy`  (8 builders -> Strategy proto -> compiler)
- IR:              :mod:`autodist_tpu.model_spec` (param-pytree metadata; replaces GraphItem)
- Kernel backend:  :mod:`autodist_tpu.parallel`  (sharding compiler, synchronizers, mesh)
- Runtime:         :mod:`autodist_tpu.runner`    (DistributedRunner; replaces WrappedSession)
- Cluster:         :mod:`autodist_tpu.cluster`, :mod:`autodist_tpu.coordinator`
- Checkpoint:      :mod:`autodist_tpu.checkpoint`
"""

from autodist_tpu.version import __version__

# Typo'd flags (a misspelled AUTODIST_PS_OVERLAP etc.) silently no-op; warn
# at import so they surface at startup instead of in a perf investigation.
from autodist_tpu.const import warn_unknown_autodist_flags as _warn_flags

_warn_flags()

__all__ = ["AutoDist", "get_default_autodist", "ResourceSpec", "train",
           "__version__"]


def __getattr__(name):  # PEP 562 lazy imports to keep `import autodist_tpu` light
    if name in ("AutoDist", "get_default_autodist"):
        from autodist_tpu import autodist
        return getattr(autodist, name)
    if name == "ResourceSpec":
        from autodist_tpu.resource_spec import ResourceSpec
        return ResourceSpec
    if name == "train":
        from autodist_tpu.training import train
        return train
    raise AttributeError(f"module 'autodist_tpu' has no attribute {name!r}")
