"""VGG-16 — the dense-parameter-heavy benchmark model.

The reference used VGG16 as the PS/partitioning stress case (its ~500MB of dense fc
weights are why ``PartitionedPS`` exists; chunk-size tuning at
``examples/benchmark/imagenet.py:150-160``). The huge fc layers are exactly what the
partitioned strategies shard across the mesh.
"""

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp


class VGG16(nn.Module):
    num_classes: int = 1000
    dtype: type = jnp.bfloat16

    @nn.compact
    def __call__(self, images):
        x = images.astype(self.dtype)
        for stage, (filters, convs) in enumerate(
                [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]):
            for c in range(convs):
                x = nn.relu(nn.Conv(filters, (3, 3), dtype=self.dtype,
                                    param_dtype=jnp.float32,
                                    name=f"conv{stage}_{c}")(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, param_dtype=jnp.float32,
                             name="fc1")(x))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, param_dtype=jnp.float32,
                             name="fc2")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def make_loss_fn(model: VGG16) -> Callable:
    from autodist_tpu.models.common import make_classification_loss_fn
    return make_classification_loss_fn(model)
