"""Host data pipeline: native threaded prefetch with a pure-Python fallback.

The reference delegated its input pipeline to TF's C++ runtime (queues,
iterators, staging — SURVEY.md §2.4 "host data plane") and its examples read
real corpora from disk (``examples/lm1b/lm1b_train.py:30-50``,
``examples/benchmark/imagenet.py``); this module owns the equivalent native
capability in-tree. ``DataLoader`` serves shuffled, fixed-size batches from
in-memory arrays OR from ``.npy`` shard files on disk:

- **Native path** (default): ``native/loader.cc`` is compiled once with g++ into
  the working dir and driven via ctypes. A C++ worker thread reshuffles indices
  per epoch and gathers rows into a prefetch ring off the GIL, so batch assembly
  overlaps the TPU step.
- **File-backed datasets** (``files=``): each key names one or more ``.npy``
  shards, opened with ``np.load(mmap_mode='r')`` — the gather thread reads rows
  straight out of the page cache (cold pages fault in on the worker thread,
  overlapped with the step), so datasets larger than RAM stream without ever
  materializing. Shards are row-aligned across keys and virtually concatenated;
  shuffling is global across all shards.
- **Fallback path**: the same semantics in numpy (used when no C++ toolchain is
  available, and as the reference implementation in tests).

``device_prefetch`` composes either path with the runner's feed remapping
through the unified async input pipeline (:mod:`autodist_tpu.data.prefetch`):
a bounded background producer keeps ``depth`` pre-sharded batches in flight
on-device (``shard_batch`` = device_put with the batch sharding) so host
loading AND host->HBM transfer overlap the step. ``save_shards`` writes a
dict of arrays as row-aligned ``.npy`` shard files (the writer side of the
``files=`` contract).
"""

import ctypes
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

from autodist_tpu import const
from autodist_tpu.utils import logging
from autodist_tpu.testing.sanitizer import san_lock, san_condition

_BUILD_LOCK = san_lock()
_LIB = None
_LIB_FAILED = False

FileSpec = Union[str, os.PathLike, Sequence[Union[str, os.PathLike]]]


def _source_path() -> str:
    return os.path.join(os.path.dirname(__file__), "native", "loader.cc")


def _build_native() -> Optional[ctypes.CDLL]:
    """Compile and load the native loader; None when unavailable."""
    global _LIB, _LIB_FAILED
    # graftlint: disable=GL001(this lock EXISTS to serialize the one-time native compile — concurrent cc1 invocations over the same .so path corrupt the artifact; no device program or socket runs under it)
    with _BUILD_LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        from autodist_tpu.utils.native_build import build_native_lib
        lib = build_native_lib(_source_path(), "loader",
                               extra_flags=("-O3", "-lpthread"))
        if lib is None:
            _LIB_FAILED = True
            return None
        lib.dl_create_sharded.restype = ctypes.c_void_p
        lib.dl_create_sharded.argtypes = [
            ctypes.c_uint64, ctypes.c_uint64, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int, ctypes.c_uint64]
        lib.dl_next.restype = ctypes.c_int
        lib.dl_next.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_void_p)]
        lib.dl_epochs_completed.restype = ctypes.c_uint64
        lib.dl_epochs_completed.argtypes = [ctypes.c_void_p]
        lib.dl_destroy.restype = None
        lib.dl_destroy.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


def save_shards(arrays: Dict[str, np.ndarray], directory: str,
                rows_per_shard: int) -> Dict[str, List[str]]:
    """Write ``arrays`` as row-aligned ``.npy`` shard files under
    ``directory`` (``<key>-00000.npy``, ...), returning the ``files=`` dict
    that loads them back. The writer side of the file-backed contract."""
    if rows_per_shard < 1:
        raise ValueError("rows_per_shard must be >= 1")
    lengths = {k: len(v) for k, v in arrays.items()}
    if len(set(lengths.values())) != 1:
        raise ValueError(f"All arrays must share a leading dim, got {lengths}")
    n = next(iter(lengths.values()))
    os.makedirs(directory, exist_ok=True)
    out: Dict[str, List[str]] = {}
    for key, arr in arrays.items():
        # Sweep the key's previous shards first: re-preparing a SMALLER corpus
        # must not leave stale high-numbered shards for glob-based consumers
        # to silently mix into the dataset.
        import glob as _glob
        for stale in _glob.glob(os.path.join(_glob.escape(directory),
                                             f"{_glob.escape(key)}-*.npy")):
            os.remove(stale)
        paths = []
        for i, start in enumerate(range(0, n, rows_per_shard)):
            path = os.path.join(directory, f"{key}-{i:05d}.npy")
            np.save(path, np.ascontiguousarray(arr[start:start + rows_per_shard]))
            paths.append(path)
        out[key] = paths
    return out


def shard_files_for_process(files: Dict[str, FileSpec], process_id: int,
                            num_processes: int) -> Dict[str, List[str]]:
    """Multi-host input sharding at FILE granularity: process ``i`` reads
    shards ``i::n`` of every key — the reference's ``dataset.shard(
    num_input_pipelines, input_pipeline_id)`` applied to its file list
    (``examples/benchmark/imagenet.py:219-229``,
    ``utils/input_pipeline.py``). Keys stay row-aligned because all keys drop
    the same shard indices. Each process then builds its own ``DataLoader``
    over its subset and feeds its local devices — no process ever reads
    another's bytes.

    Requires at least as many shards as processes (a process with zero shards
    is a bug in the prep step's ``rows_per_shard``, not a valid
    configuration)."""
    if not 0 <= process_id < num_processes:
        raise ValueError(f"process_id {process_id} out of [0, {num_processes})")
    out: Dict[str, List[str]] = {}
    for key, spec in files.items():
        paths = [spec] if isinstance(spec, (str, os.PathLike)) else list(spec)
        mine = [os.fspath(p) for p in paths[process_id::num_processes]]
        if not mine:
            raise ValueError(
                f"files[{key!r}]: {len(paths)} shard(s) cannot feed "
                f"{num_processes} processes; re-prep with smaller "
                f"rows_per_shard")
        out[key] = mine
    return out


def _open_segments(files: Dict[str, FileSpec]) -> Dict[str, List[np.ndarray]]:
    """mmap every shard; validate row alignment across keys and dtype/shape
    consistency across a key's shards."""
    segs: Dict[str, List[np.ndarray]] = {}
    for key, spec in files.items():
        paths = [spec] if isinstance(spec, (str, os.PathLike)) else list(spec)
        if not paths:
            raise ValueError(f"files[{key!r}] names no shards")
        arrs = [np.load(os.fspath(p), mmap_mode="r") for p in paths]
        head = arrs[0]
        for p, a in zip(paths, arrs):
            if a.dtype != head.dtype or a.shape[1:] != head.shape[1:]:
                raise ValueError(
                    f"files[{key!r}]: shard {p} is {a.dtype}{a.shape[1:]} but "
                    f"the first shard is {head.dtype}{head.shape[1:]}")
        segs[key] = arrs
    counts = {k: [len(a) for a in v] for k, v in segs.items()}
    first = next(iter(counts.values()))
    for k, c in counts.items():
        if c != first:
            raise ValueError(
                f"Shards must be row-aligned across keys: per-shard rows "
                f"{counts}")
    return segs


class DataLoader:
    """Shuffled fixed-size batches over a dict of same-length arrays, or over
    row-aligned ``.npy`` shard files (``files=``, memory-mapped).

    Continuous stream: iteration never ends (epochs reshuffle internally,
    drop-last semantics — static batch shapes only, the TPU constraint).
    ``native=None`` auto-selects; ``native=False`` forces the numpy fallback.
    """

    def __init__(self, arrays: Optional[Dict[str, np.ndarray]] = None,
                 batch_size: int = 1, shuffle: bool = True, seed: int = 0,
                 prefetch: int = 2, native: Optional[bool] = None,
                 files: Optional[Dict[str, FileSpec]] = None):
        if (arrays is None) == (files is None):
            raise ValueError("pass exactly one of arrays= or files=")
        if files is not None:
            self._segs = _open_segments(files)
        else:
            if not arrays:
                raise ValueError("DataLoader needs at least one array")
            lengths = {k: len(v) for k, v in arrays.items()}
            if len(set(lengths.values())) != 1:
                raise ValueError(
                    f"All arrays must share a leading dim, got {lengths}")
            self._segs = {k: [v] for k, v in arrays.items()}
        self._keys = list(self._segs)
        # C-contiguous row-major so a row is one contiguous memcpy. save_shards
        # writes C-order, so this only ever copies misbehaved in-memory inputs
        # (arrays= keeps accepting any layout — a row-sliced memmap view there
        # copies just the selected rows). A non-contiguous FILE shard (a
        # foreign Fortran-order .npy) is refused instead: ascontiguousarray
        # would silently materialize the whole file in RAM — the opposite of
        # the files= streaming contract.
        def _as_rows(key, v):
            if v.flags.c_contiguous:
                return v
            if files is not None:
                raise ValueError(
                    f"files[{key!r}]: shard is not C-contiguous "
                    f"(Fortran-order .npy?); rewrite it row-major — copying a "
                    f"memory-mapped shard would materialize the whole file")
            return np.ascontiguousarray(v)
        self._segs = {k: [_as_rows(k, v) for v in vs]
                      for k, vs in self._segs.items()}
        self._seg_rows = [len(v) for v in self._segs[self._keys[0]]]
        self.n_rows = sum(self._seg_rows)
        if batch_size < 1 or batch_size > self.n_rows:
            raise ValueError(f"batch_size {batch_size} out of range "
                             f"[1, {self.n_rows}]")
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.prefetch = max(1, prefetch)

        self._lib = _build_native() if native in (None, True) else None
        if native is True and self._lib is None:
            raise RuntimeError("native=True but the native loader failed to build")
        # Async prefetch (data/prefetch.py) pulls next() from a background
        # producer thread, so close() can race an in-flight native dl_next —
        # dl_destroy frees the C++ loader and a parked waiter would wake on
        # freed memory. The condition tracks in-flight native calls: close()
        # flips `_closing` (new next() calls fail fast) and waits (bounded)
        # for the in-flight count to drain before destroying.
        self._native_cv = san_condition()
        self._native_inflight = 0
        self._closing = False
        self._handle = None
        if self._lib is not None:
            self._handle = self._create_native()
            if not self._handle:
                raise RuntimeError("dl_create rejected the loader configuration")
        else:
            self._rng = np.random.RandomState(seed)
            self._perm = None
            self._cursor = 0
            self._epochs = 0
            self._seg_starts = np.cumsum([0] + self._seg_rows)

    # ------------------------------------------------------------------ native
    def _create_native(self):
        n, n_seg = len(self._keys), len(self._seg_rows)
        ptrs = (ctypes.c_void_p * (n * n_seg))(*[
            self._segs[k][s].ctypes.data
            for k in self._keys for s in range(n_seg)])
        row_bytes = (ctypes.c_uint64 * n)(
            *[self._row_bytes(k) for k in self._keys])
        seg_rows = (ctypes.c_uint64 * n_seg)(*self._seg_rows)
        return self._lib.dl_create_sharded(
            n, n_seg, ptrs, row_bytes, seg_rows, self.batch_size,
            self.prefetch, int(self.shuffle), self.seed)

    def _row_bytes(self, key: str) -> int:
        head = self._segs[key][0]
        return head.nbytes // len(head) if len(head) else 0

    def _row_shape(self, key: str):
        return self._segs[key][0].shape[1:]

    def _dtype(self, key: str):
        return self._segs[key][0].dtype

    @property
    def is_native(self) -> bool:
        return self._handle is not None

    @property
    def epochs_completed(self) -> int:
        """Epoch wraps so far. Native path: producer-side (the prefetch worker
        runs up to ``prefetch`` batches ahead of consumption, so this can read
        ahead of what ``next()`` has returned). Fallback: consumer-side."""
        if self._handle is not None:
            return int(self._lib.dl_epochs_completed(self._handle))
        return self._epochs

    def next(self) -> Dict[str, np.ndarray]:
        """The next batch (blocks on the prefetch ring in the native path).

        Thread-safe against :meth:`close`: a concurrent close waits for
        in-flight native calls to return before destroying the C++ loader,
        and calls arriving DURING OR AFTER the close raise cleanly (a
        closed native loader must not fall into the numpy-fallback branch,
        whose state was never initialized)."""
        if self._closing:
            raise RuntimeError("Native loader was shut down")
        out = {k: np.empty((self.batch_size,) + self._row_shape(k),
                           self._dtype(k)) for k in self._keys}
        # Branch on _lib (immutable), NOT _handle: a close() completing
        # between the check above and here nulls _handle, and a native-mode
        # call must then raise below — never fall into the numpy fallback,
        # whose state native mode leaves uninitialized.
        if self._lib is not None:
            with self._native_cv:
                if self._closing or self._handle is None:
                    raise RuntimeError("Native loader was shut down")
                handle = self._handle
                self._native_inflight += 1
            try:
                ptrs = (ctypes.c_void_p * len(self._keys))(
                    *[out[k].ctypes.data for k in self._keys])
                rc = self._lib.dl_next(handle, ptrs)
            finally:
                with self._native_cv:
                    self._native_inflight -= 1
                    self._native_cv.notify_all()
            if rc != 0:
                raise RuntimeError("Native loader was shut down")
            return out
        # numpy fallback: same drop-last/reshuffle-on-wrap semantics.
        if self._perm is None or self.n_rows - self._cursor < self.batch_size:
            if self._perm is not None:
                self._epochs += 1
            self._perm = (self._rng.permutation(self.n_rows) if self.shuffle
                          else np.arange(self.n_rows))
            self._cursor = 0
        idx = self._perm[self._cursor:self._cursor + self.batch_size]
        self._cursor += self.batch_size
        seg = np.searchsorted(self._seg_starts, idx, side="right") - 1
        local = idx - self._seg_starts[seg]
        # Per-segment groupings are key-independent: compute once per batch.
        groups = [(s, seg == s) for s in np.unique(seg)]
        for k in self._keys:
            for s, mask in groups:
                out[k][mask] = self._segs[k][s][local[mask]]
        return out

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            yield self.next()

    def close(self, timeout_s: float = 60.0):
        """Shut the native loader down. Safe against a concurrent
        :meth:`next` from a prefetch producer thread: new calls fail fast,
        in-flight ones are drained (bounded wait — one call returns within
        one batch-gather) before ``dl_destroy`` frees the C++ state. A
        drain that somehow exceeds ``timeout_s`` leaks the handle with a
        warning instead of freeing memory under a live waiter."""
        if self._handle is None:
            return
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._native_cv:
            self._closing = True
            while self._native_inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    logging.warning(
                        "DataLoader.close: %d native next() call(s) still "
                        "in flight after %.0fs; leaking the native handle "
                        "instead of freeing it under a live waiter",
                        self._native_inflight, timeout_s)
                    self._handle = None
                    return
                self._native_cv.wait(min(0.2, remaining))
            handle, self._handle = self._handle, None
        self._lib.dl_destroy(handle)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def device_prefetch(loader, runner, depth: int = 2, unroll: int = 1,
                    workers: Optional[int] = None):
    """Iterator of on-device sharded batches, ``depth`` transfers ahead —
    a thin wrapper over the unified async pipeline
    (:func:`autodist_tpu.data.prefetch.prefetch_to_device`).

    A background producer pulls from the host ``loader`` (any iterable of
    host batches) and applies ``runner.shard_batch`` (the feed remapping:
    split over data axes / replicate) ``depth`` ahead of consumption, so
    BOTH host batch assembly and host->HBM transfer overlap the running
    step — the TPU analogue of the reference's staged input queues. A
    finite loader ends iteration cleanly (no PEP 479 ``RuntimeError``);
    a loader exception re-raises at ``next()``; the returned producer's
    ``close()`` (also a context manager) shuts the thread down.

    With ``unroll=K`` (K > 1) each yielded item is instead a pre-sharded
    :class:`~autodist_tpu.runner.BatchBlock` stacking K consecutive loader
    batches (``runner.shard_block``) for the fused multi-step path
    (``runner.run_many``); ``depth`` then counts blocks, so the queue keeps
    ``depth * K`` steps of data in flight. A source that exhausts mid-block
    drops the partial remainder (logged) and ends cleanly.

    ``workers`` (default ``AUTODIST_PREFETCH_WORKERS``) parallelizes the
    shard/stack stage; loader pulls stay serialized and emission order is
    the loader order.
    """
    from autodist_tpu.data import prefetch as _prefetch
    return _prefetch.prefetch_to_device(loader, runner, depth=depth,
                                        unroll=unroll, workers=workers)
