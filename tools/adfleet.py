#!/usr/bin/env python
"""adfleet — a multi-endpoint fleet console for autodist servers.

Where ``adtop`` watches ONE process, adfleet polls the ``status`` wire opcode
across N addresses concurrently and renders a merged screen: one row per
process (role, uptime, step rate, MFU, staleness bound/worst lag, serving
queue/slots, SLO p50/p99, active alerts), then FLEET-AGGREGATED serving
quantiles (latency histograms merged element-wise before the quantile — the
mathematically right aggregation; averaging per-replica p99s is not), the
union of active alerts, and the newest events across the fleet. This is the
signal surface ROADMAP 2's replica router reads: which replica to drain, who
is burning SLO budget, whether an alert names a culprit.

Usage:
    python tools/adfleet.py HOST:PORT HOST:PORT ...   # live screen, 2s poll
    python tools/adfleet.py A:1 B:2 --once            # one plain-text pass
    python tools/adfleet.py A:1 B:2 --raw             # one JSON pass
    python tools/adfleet.py --endpoints A:1,B:2 --interval 5

With no addresses, ``AUTODIST_PS_ADDR`` and ``AUTODIST_SERVE_ADDR`` seed the
list. A dead endpoint renders as an error row — the fleet view must survive
any one replica being the incident.
"""

import argparse
import concurrent.futures
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)
_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

# The single-endpoint console's formatters, reused so the two consoles
# cannot drift on how an age or an alert line reads.
from adtop import _alert_line, _fmt_age  # noqa: E402


def fetch_fleet(addresses, timeout: float = 2.0) -> dict:
    """``{address: status-payload-or-{"error": ...}}`` polled CONCURRENTLY —
    a fleet poll must take one slowest-endpoint round-trip, not the sum.

    ``timeout`` is deliberately SHORT (the PS client retries a refused
    connect until this deadline — right for a worker waiting on its chief,
    wrong for a liveness poll): a crashed replica must read as DOWN in a
    couple of seconds, not stall every screen refresh for the worker-grade
    10s."""
    from autodist_tpu.parallel.ps_transport import _PSClient

    def one(address):
        # read_timeout too: a hung-but-accepting server must read as DOWN,
        # not park the poll thread on a reply that never comes.
        client = _PSClient(address, connect_timeout=timeout,
                           read_timeout=timeout)
        try:
            return client.call("status")[0]
        finally:
            client.close()

    out = {}
    with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(16, max(1, len(addresses)))) as pool:
        futs = {pool.submit(one, a): a for a in addresses}
        for fut in concurrent.futures.as_completed(futs):
            addr = futs[fut]
            try:
                out[addr] = fut.result()
            except Exception as e:
                out[addr] = {"error": f"{type(e).__name__}: {e}"}
    return out


def _fmt_q(value) -> str:
    return f"{value * 1e3:.0f}ms" if value is not None else "-"


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}GB"


def _row(address: str, status: dict) -> str:
    from autodist_tpu.telemetry import metrics as _metrics
    if status.get("error") and "kind" not in status:
        return f"  {address:<22} DOWN   {status['error']}"
    kind = status.get("kind", "?")
    reg = status.get("registry", {}) or {}
    cols = [f"  {address:<22} {kind:<6}",
            f"up {_fmt_age(status.get('uptime_s', 0)):>6}"]
    rate = reg.get("train.steps_per_s")
    cols.append(f"steps/s {rate:7.2f}" if isinstance(rate, (int, float))
                else "steps/s       -")
    mfu = reg.get("train.mfu")
    cols.append(f"mfu {100.0 * mfu:5.1f}%" if isinstance(mfu, (int, float))
                else "mfu      -")
    if kind == "ps":
        lags = [w.get("lag") for w in (status.get("per_worker") or {}).values()
                if isinstance(w.get("lag"), (int, float))]
        bound = status.get("staleness_bound")
        cols.append(f"lag {max(lags) if lags else 0}/"
                    f"{bound if bound is not None else 'inf'}")
    elif kind == "serve":
        cap = status.get("capacity", 0)
        busy = len(status.get("in_flight") or [])
        cols.append(f"q {status.get('queue_depth', 0)} "
                    f"slots {busy}/{cap}")
        total = reg.get("serve.latency_s.total")
        if isinstance(total, dict):
            cols.append(f"p50 {_fmt_q(_metrics.quantile(total, 0.5))} "
                        f"p99 {_fmt_q(_metrics.quantile(total, 0.99))}")
        shares = [(p, reg.get(f"serve.attr.{p}"))
                  for p in ("wire", "queue", "prefill", "decode")]
        shares = [(p, v) for p, v in shares if isinstance(v, (int, float))]
        if any(v for _, v in shares):
            # Compact phase-attribution fingerprint (serve.attr.* — the
            # reqtrace plane's per-round shares): where this replica's
            # request time goes, w/q/p/d. Un-armed replicas keep the
            # column off, like recov/wiresave.
            cols.append("attr " + "/".join(
                f"{p[0]}{v:.2f}".replace(f"{p[0]}0.", f"{p[0]}.")
                for p, v in shares))
        used = reg.get("serve.kv.pages_used")
        free = reg.get("serve.kv.pages_free")
        if isinstance(used, (int, float)) or isinstance(free, (int, float)):
            # Paged-KV occupancy fingerprint (dense-slab replicas keep the
            # column off, like recov/wiresave).
            cols.append(f"pages {int(used or 0)}/"
                        f"{int(used or 0) + int(free or 0)}")
    elif kind == "router":
        replicas = status.get("replicas") or []
        n_up = sum(1 for r in replicas
                   if not r.get("down") and not r.get("draining"))
        cols.append(f"replicas {n_up}/{len(replicas)} up")
        shed = reg.get("serve.router.shed")
        routed = reg.get("serve.router.routed")
        if isinstance(routed, (int, float)):
            cols.append(f"routed {int(routed)}")
        if isinstance(shed, (int, float)) and shed:
            # Admission sheds are the router's overload fingerprint: a
            # nonzero column is the signal to raise max_replicas or shrink
            # the offered load, BEFORE p99 melts.
            cols.append(f"shed {int(shed)}")
    mem = status.get("memory") or {}
    if mem.get("live_bytes") or mem.get("owned"):
        # Memory-plane fingerprint: worst-device HBM used vs the booked
        # budget (the mem.pressure ratio's own numbers). Processes whose
        # plane never armed keep the column off, like recov/wiresave.
        devs = mem.get("devices") or {}
        used = max((d.get("bytes_in_use", 0) for d in devs.values()),
                   default=mem.get("live_bytes", 0))
        limit = max((d.get("bytes_limit", 0) for d in devs.values()),
                    default=mem.get("budget_bytes", 0))
        col = f"hbm {_fmt_bytes(used)}"
        if limit:
            col += f"/{_fmt_bytes(limit)}"
        cols.append(col)
    active = (status.get("alerts") or {}).get("active") or []
    if active:
        cols.append("ALERT " + ",".join(sorted(a.get("rule", "?")
                                               for a in active)))
    saved = reg.get("ps.wire.bytes_saved")
    if saved:
        # Compact compression fingerprint: a replica pushing quantized or
        # sparse gradients shows its cumulative wire savings in the fleet
        # table (exact-wire replicas keep the column off, like recov).
        cols.append(f"wiresave {_fmt_bytes(saved)}")
    counts = (status.get("recovery") or {}).get("counts") or {}
    if any(counts.values()):
        # Compact recovery fingerprint: evictions/rejoins/rollbacks/respawns
        # this process has performed — a replica that has been self-healing
        # is visible in the fleet table, not just on its own adtop screen.
        cols.append("recov E%d/J%d/B%d/S%d" % (
            counts.get("evicted", 0), counts.get("rejoined", 0),
            counts.get("rollbacks", 0), counts.get("respawns", 0)))
    return "  ".join(cols)


def render(fleet: dict) -> str:
    """One plain-text screen for a fleet poll — the single rendering path
    behind ``--once`` and the live loop (the adtop contract: tests pin
    exactly what operators see)."""
    from autodist_tpu.telemetry import metrics as _metrics
    lines = [f"adfleet — {len(fleet)} endpoint(s)  "
             f"{time.strftime('%H:%M:%S')}"]
    lines.append("  endpoint               role   uptime    throughput ...")
    for addr in sorted(fleet):
        lines.append(_row(addr, fleet[addr]))

    # Fleet-aggregated serving quantiles: merge the latency histograms
    # element-wise across replicas, THEN take the quantile (the only
    # aggregation that answers "what latency does a fleet user see").
    hists = [(s.get("registry") or {}).get("serve.latency_s.total")
             for s in fleet.values() if isinstance(s, dict)]
    hists = [h for h in hists if isinstance(h, dict)]
    if hists:
        merged = _metrics.merge_histograms(hists)
        count = merged.get("count", 0)
        lines.append(
            f"fleet    serve n={len(hists)}  requests {count}  "
            f"p50 {_fmt_q(_metrics.quantile(merged, 0.5))}  "
            f"p99 {_fmt_q(_metrics.quantile(merged, 0.99))}")

    # The union of active alerts, who is firing them, and the newest events.
    firing = []
    for addr in sorted(fleet):
        for a in ((fleet[addr].get("alerts") or {}).get("active") or []):
            firing.append((addr, a))
    if firing:
        lines.append(f"alerts   {len(firing)} active")
        for addr, a in firing:
            # adtop's shared alert-line formatter with the endpoint spliced
            # in — two consoles, one rendering of an alert record.
            lines.append(_alert_line(a, where=f" @ {addr}"))
    events = []
    for addr, s in fleet.items():
        for rec in (s.get("events") or [])[-3:]:
            if isinstance(rec, dict):
                events.append((rec.get("t_wall_s") or 0, addr, rec))
    # Sort on (time, endpoint) ONLY: two same-millisecond events would
    # otherwise fall through to comparing the record dicts and raise.
    for t_wall, addr, rec in sorted(events, key=lambda e: e[:2])[-5:]:
        when = time.strftime("%H:%M:%S", time.localtime(t_wall)) \
            if t_wall else "--:--:--"
        lines.append(f"  {when}  {rec.get('name', 'event')} @ {addr}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="adfleet", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("addresses", nargs="*", default=[],
                    help="server host:port endpoints (default: "
                         "AUTODIST_PS_ADDR + AUTODIST_SERVE_ADDR)")
    ap.add_argument("--endpoints", default="",
                    help="comma-separated host:port list (merged with "
                         "positional addresses)")
    ap.add_argument("--once", action="store_true",
                    help="print one merged snapshot and exit")
    ap.add_argument("--raw", action="store_true",
                    help="print one raw JSON fleet payload and exit")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh seconds for the live screen (default 2)")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-endpoint connect/read deadline seconds "
                         "(default 2 — a dead replica reads DOWN fast)")
    args = ap.parse_args(argv)
    addresses = list(args.addresses)
    addresses += [a for a in args.endpoints.split(",") if a]
    if not addresses:
        from autodist_tpu import const
        addresses = [a for a in (str(const.ENV.AUTODIST_PS_ADDR.val),
                                 str(const.ENV.AUTODIST_SERVE_ADDR.val)) if a]
    if not addresses:
        print("adfleet: no endpoints given and neither AUTODIST_PS_ADDR nor "
              "AUTODIST_SERVE_ADDR is set", file=sys.stderr)
        return 2
    fleet = fetch_fleet(addresses, timeout=args.timeout)
    if args.raw:
        print(json.dumps(fleet, default=str, indent=1))
        return 0
    if args.once:
        print(render(fleet))
        # Every endpoint down is an exit-code failure (scripts gate on it);
        # a PARTIALLY-down fleet still renders and exits 0.
        all_down = all(isinstance(s, dict) and s.get("error")
                       and "kind" not in s for s in fleet.values())
        return 1 if all_down else 0
    try:
        while True:
            sys.stdout.write("\x1b[2J\x1b[H" + render(fleet) + "\n")
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
            fleet = fetch_fleet(addresses, timeout=args.timeout)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
