"""Partitioned PS strategy — shard large parameters, then place shards round-robin.

Port of reference ``autodist/strategy/partitioned_ps_strategy.py``: per-variable shard
count = smallest divisor >= 2 of dim0 (``:125-135``), shards placed greedily
round-robin by load (``:88-95``), emitted as ``partitioner`` + ``part_config``
children (``:106-122``). Parameters that cannot be partitioned (scalars, dim0 < 2)
fall back to plain load-balanced PS. On TPU the shards additionally map the parameter
itself onto the ``model`` mesh axis when it has size > 1 (tensor-sharded storage).
"""

from autodist_tpu import const
from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import Strategy
from autodist_tpu.strategy.partition_utils import (make_num_shards, partitionable_axis,
                                                   smallest_divisor_at_least_2)
from autodist_tpu.strategy.ps_lb_strategy import PSLoadBalancing


class PartitionedPS(PSLoadBalancing):
    """PS with per-parameter variable partitioning (reference PartitionedPS)."""

    # Shard-count policy; the uneven variant overrides this single hook.
    @staticmethod
    def _shard_count(dim0: int, cap: int):
        return smallest_divisor_at_least_2(dim0, cap)

    def build(self, model_spec: ModelSpec, resource_spec: ResourceSpec) -> Strategy:
        strategy = Strategy()
        n_dest = self._num_destinations(resource_spec)
        loads = [0] * n_dest
        for spec in model_spec.trainable.values():
            node = strategy.proto.node_config.add(var_name=spec.name)
            node.sparse = spec.sparse
            axis = partitionable_axis(spec)
            k = self._shard_count(spec.shape[axis], n_dest * 4) if axis is not None else None
            if k is None or k < 2:
                dest = min(range(n_dest), key=loads.__getitem__)
                loads[dest] += self._load_fn(spec)
                self._fill_ps(node, dest)
                continue
            node.partitioner.num_shards.extend(make_num_shards(len(spec.shape), axis, k))
            node.partitioner.mesh_axis = const.MESH_AXIS_MODEL
            shard_load = max(self._load_fn(spec) // k, 1)
            for i in range(k):
                # Round-robin greedy placement of shards (reference :88-95).
                dest = min(range(n_dest), key=loads.__getitem__)
                loads[dest] += shard_load
                part = node.part_config.add(var_name=f"{spec.name}/part_{i}")
                part.sparse = spec.sparse
                self._fill_ps(part, dest)
        self._fill_mesh_config(strategy, resource_spec,
                               self._resolved_axes(resource_spec, self._default_axes))
        return strategy

    def _fill_ps(self, node, dest: int):
        node.ps_synchronizer.reduction_destination = f"reduce:{dest}"
        node.ps_synchronizer.local_replication = self._local_proxy_variable
        node.ps_synchronizer.sync = self._sync
        node.ps_synchronizer.staleness = self._staleness
