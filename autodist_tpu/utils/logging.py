"""Framework logger.

Parity with reference ``autodist/utils/logging.py:33-106``: a dedicated logger with a
``[PID#...:time:file#Lline:LEVEL]`` format, dual handlers (file under the working dir's
``logs/`` plus stderr), and verbosity taken from ``AUTODIST_MIN_LOG_LEVEL``.
"""

import logging as _pylogging
import os
import sys
import time

from autodist_tpu import const

_LOGGER_NAME = "autodist_tpu"
_FORMAT = "[PID%(process)d %(asctime)s %(filename)s#L%(lineno)d:%(levelname)s] %(message)s"

_logger = None


def _get_logger() -> _pylogging.Logger:
    global _logger
    if _logger is not None:
        return _logger
    logger = _pylogging.getLogger(_LOGGER_NAME)
    logger.propagate = False
    level = const.ENV.AUTODIST_MIN_LOG_LEVEL.val.upper()
    logger.setLevel(level)
    fmt = _pylogging.Formatter(_FORMAT)

    stream = _pylogging.StreamHandler(sys.stderr)
    stream.setFormatter(fmt)
    logger.addHandler(stream)

    try:
        os.makedirs(const.DEFAULT_LOG_DIR, exist_ok=True)
        path = os.path.join(const.DEFAULT_LOG_DIR, f"{int(time.time())}.log")
        fileh = _pylogging.FileHandler(path)
        fileh.setFormatter(fmt)
        logger.addHandler(fileh)
    except OSError:  # read-only filesystem etc. — stderr still works
        pass

    _logger = logger
    return logger


def set_verbosity(level):
    _get_logger().setLevel(level)


def debug(msg, *args, **kwargs):
    _get_logger().debug(msg, *args, **kwargs)


def info(msg, *args, **kwargs):
    _get_logger().info(msg, *args, **kwargs)


def warning(msg, *args, **kwargs):
    _get_logger().warning(msg, *args, **kwargs)


def error(msg, *args, **kwargs):
    _get_logger().error(msg, *args, **kwargs)
