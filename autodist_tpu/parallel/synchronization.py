"""Gradient synchronization: the synchronizer kernels, TPU-native.

Reference counterparts:

- ``kernel/synchronization/all_reduce_synchronizer.py:102-130`` wrapped each gradient
  in ``collective_ops.all_reduce`` through a Compressor. Here the uncompressed path
  is simply the implicit psum XLA inserts for a sharded-batch ``value_and_grad``;
  the compressed path uses ``jax.shard_map`` so the cross-replica mean really rides
  the compressed (bfloat16 or low-rank) representation over ICI.
- ``kernel/synchronization/compressor.py``: ``NoneCompressor`` (:146-166),
  ``HorovodCompressor`` (:169-201, a dtype-cast codec) and ``HorovodCompressorEF``
  (:120-143, error feedback) map to NONE / BF16 / BF16_EF. ``PowerSGDCompressor``
  — which the reference drafted but left disabled (:208-284) — is implemented and
  working here as POWER_SGD: rank-r factorization M ~= P Q^T with one power
  iteration per step, QR orthogonalization, and error feedback; only the [n, r]
  and [m, r] factors cross the wire.
- PS synchronizers need no explicit code here: weight-update sharding is expressed
  entirely through the plan's opt-state shardings (XLA emits the reduce-scatter /
  all-gather), replacing accumulators and token queues (``ps_synchronizer.py``).
"""

from typing import Any, Callable, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.parallel import plan as plan_lib
from autodist_tpu.parallel.plan import (COMP_BF16, COMP_BF16_EF, COMP_NONE,
                                        COMP_POWER_SGD, ShardingPlan)

PyTree = Any


class PowerSGDState(NamedTuple):
    """Per-parameter PowerSGD carry: the EF residual and the reused Q factor
    (warm-starting Q across steps is what makes one power iteration enough)."""

    error: jax.Array   # same shape as the parameter
    q: jax.Array       # [prod(shape[1:]), rank]


def _powersgd_applies(shape) -> bool:
    # Like the reference draft, only matrix-shaped (rank >= 2) tensors are
    # factorized; vectors/scalars all-reduce exactly.
    return len(shape) >= 2


# --------------------------------------------------------------------- compressors

def compress(x: jax.Array, kind: int) -> jax.Array:
    if kind in (COMP_BF16, COMP_BF16_EF):
        return x.astype(jnp.bfloat16)
    return x


def decompress(x: jax.Array, dtype) -> jax.Array:
    return x.astype(dtype)


# ------------------------------------------------------------------ grad functions

def make_grad_fn(sharding_plan: ShardingPlan, model_spec: ModelSpec, mesh: Mesh,
                 loss_fn: Callable, has_aux: bool = False) -> Callable:
    """Build ``grad_fn(params, batch, ef_state) -> (grads, loss, aux, new_ef_state)``.

    Two lowerings:

    - **Implicit** (no compressor anywhere): plain ``value_and_grad`` of the global
      loss; the batch is sharded over the data axes, so XLA inserts the gradient
      all-reduce (and, with sharded opt state, the reduce-scatter) itself.
    - **Explicit** (some parameter has a compressor): ``jax.shard_map`` over the data
      axes — each shard computes a local gradient, compresses, ``lax.pmean``s the
      compressed payload so the wire format is bfloat16, then decompresses. Error
      feedback keeps a residual per parameter: x = g + ef; send compress(x);
      ef' = x - decompress(compress(x)).
    """
    if not sharding_plan.has_compression:
        def implicit(params, batch, ef_state):
            if has_aux:
                (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, batch)
            else:
                loss, grads = jax.value_and_grad(loss_fn)(params, batch)
                aux = ()
            return grads, loss, aux, ef_state
        return implicit

    if not sharding_plan.all_params_replicated:
        raise NotImplementedError(
            "Gradient compression currently requires replicated parameters "
            "(AllReduce-family strategies); partitioned parameters with a compressor "
            "are not supported in one strategy")

    from autodist_tpu.model_spec import _path_name as name_of
    comp_by_name: Dict[str, int] = {n: p.compressor
                                    for n, p in sharding_plan.params.items()}

    def local_fn(params, batch, ef_state):
        if has_aux:
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, batch)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            aux = ()

        def synced_leaf(path, g, ef):
            kind = comp_by_name.get(name_of(path), COMP_NONE)
            if kind == COMP_NONE:
                return jax.lax.pmean(g, plan_lib.DP_AXES)
            payload = compress(g + ef, kind) if kind == COMP_BF16_EF else compress(g, kind)
            return decompress(jax.lax.pmean(payload, plan_lib.DP_AXES), g.dtype)

        def ef_leaf(path, g, ef):
            kind = comp_by_name.get(name_of(path), COMP_NONE)
            if kind != COMP_BF16_EF:
                return ef
            # Error feedback: x = g + ef; send compress(x); keep the residual.
            x = g + ef
            return x - decompress(compress(x, kind), g.dtype)

        synced = jax.tree_util.tree_map_with_path(synced_leaf, grads, ef_state)
        new_ef = jax.tree_util.tree_map_with_path(ef_leaf, grads, ef_state)
        loss = jax.lax.pmean(loss, plan_lib.DP_AXES)
        aux = jax.tree_util.tree_map(lambda a: jax.lax.pmean(a, plan_lib.DP_AXES), aux)
        return synced, loss, aux, new_ef

    batch_spec_fn = _batch_spec_maker(sharding_plan)

    def explicit(params, batch, ef_state):
        batch_specs = jax.tree_util.tree_map(batch_spec_fn, batch)
        replicated = jax.tree_util.tree_map(lambda _: P(), params)
        ef_specs = jax.tree_util.tree_map(lambda _: P(), ef_state)
        out = jax.shard_map(
            local_fn, mesh=mesh,
            in_specs=(replicated, batch_specs, ef_specs),
            out_specs=(replicated, P(), P(), ef_specs),
            check_vma=False,
        )(params, batch, ef_state)
        return out

    return explicit


def _batch_spec_maker(sharding_plan: ShardingPlan):
    dp = sharding_plan.dp_size

    def spec_for(leaf):
        shape = getattr(leaf, "shape", ())
        if shape and shape[0] % dp == 0:
            return sharding_plan.batch_pspec(len(shape))
        return P()

    return spec_for


def init_ef_state(sharding_plan: ShardingPlan, params: PyTree) -> PyTree:
    """Zeros for every parameter using error feedback; 0-size scalars otherwise.

    Shaped like ``params`` so it can ride the same sharding derivation. (Reference
    kept the EF residual as Python-side state inside the compressor object,
    ``compressor.py:120-143``; functionally it belongs in the train state.)
    """
    names = {n for n, p in sharding_plan.params.items() if p.compressor == COMP_BF16_EF}
    from autodist_tpu.model_spec import _path_name

    def leaf(path, x):
        if _path_name(path) in names:
            return jnp.zeros_like(x)
        return jnp.zeros((), dtype=x.dtype)

    return jax.tree_util.tree_map_with_path(leaf, params)
