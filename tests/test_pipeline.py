"""Pipeline parallelism: GPipe loop correctness, gradients, strategy, e2e training."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu import AutoDist, ResourceSpec
from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.models import pipeline_lm
from autodist_tpu.parallel.pipeline import pipelined
from autodist_tpu.parallel.plan import ShardingPlan
from autodist_tpu.strategy import Pipeline, StrategyCompiler

TINY = pipeline_lm.PipelineLMConfig(
    vocab_size=64, d_model=16, n_heads=2, n_layers=4, d_ff=32, max_len=32,
    n_stages=4, num_microbatches=4, dtype=jnp.float32)


def _spec_for(n_devices=8, mesh=None):
    return ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "tpus": n_devices, "chief": True}],
        **({"mesh": mesh} if mesh else {}),
    })


def _pipe_mesh(n_stages=4):
    from autodist_tpu.parallel.mesh import build_mesh
    return build_mesh(axes={"pipe": n_stages, "data": -1})


def test_gpipe_loop_matches_sequential_forward_and_grad():
    rng = np.random.RandomState(0)
    d, s, m = 8, 4, 6
    w = (rng.randn(s, d, d) * 0.3).astype(np.float32)
    x_mb = rng.randn(m, 4, d).astype(np.float32)
    mesh = _pipe_mesh(s)

    def stage_fn(p, x):
        return jnp.tanh(x @ p[0])

    f = pipelined(stage_fn, s, mesh=mesh)

    def loss_pipe(w, x):
        return (f(w, x) ** 2).sum()

    def loss_seq(w, x):
        h = x
        for i in range(s):
            h = jnp.tanh(h @ w[i])
        return (h ** 2).sum()

    with mesh:
        lp, gp = jax.jit(jax.value_and_grad(loss_pipe))(w, x_mb)
        ls, gs = jax.jit(jax.value_and_grad(loss_seq))(w, x_mb)
    np.testing.assert_allclose(float(lp), float(ls), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gs), rtol=1e-4, atol=1e-5)


def test_pipeline_lm_matches_sequential_apply():
    model, params = pipeline_lm.init_params(TINY)
    batch = pipeline_lm.synthetic_batch(TINY, batch_size=8, seq_len=16)
    tokens = jnp.asarray(batch["tokens"][:, :-1])
    mesh = _pipe_mesh(TINY.n_stages)
    with mesh:
        piped = jax.jit(model.apply)(params, tokens)
    seq = pipeline_lm.sequential_apply(model, params, tokens)
    np.testing.assert_allclose(np.asarray(piped), np.asarray(seq),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_strategy_shards_block_stacks():
    model, params = pipeline_lm.init_params(TINY)
    model_spec = ModelSpec.from_params(params)
    rs = _spec_for(8)
    strategy = StrategyCompiler(model_spec, rs).compile(
        Pipeline(n_stages=4).build(model_spec, rs))
    assert strategy.mesh_axes()["pipe"] == 4
    assert strategy.mesh_axes()["data"] == 2

    plan = ShardingPlan.from_strategy(strategy, model_spec)
    block_plans = [p for n, p in plan.params.items() if "blocks" in n]
    assert len(block_plans) == 8
    for p in block_plans:
        assert p.partition_mesh_axis == "pipe"
        assert p.pspec[0] == "pipe"
    assert plan.params["embed"].pspec == jax.sharding.PartitionSpec()


def test_pipeline_lm_trains_end_to_end():
    model, params = pipeline_lm.init_params(TINY)
    loss_fn = pipeline_lm.make_loss_fn(model)
    batch = pipeline_lm.synthetic_batch(TINY, batch_size=8, seq_len=16)
    ad = AutoDist(_spec_for(8), strategy_builder=Pipeline(n_stages=4))
    step = ad.function(loss_fn, params, optax.adam(1e-2), example_batch=batch)
    losses = [float(step(batch)) for _ in range(4)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]

    # Block stacks live sharded over the pipe axis.
    state = step.get_state()
    spec = state.params["blocks"]["wqkv"].sharding.spec
    assert spec and spec[0] == "pipe"


def test_pipeline_e2e_loss_matches_unsharded():
    model, params = pipeline_lm.init_params(TINY)
    loss_fn = pipeline_lm.make_loss_fn(model)
    batch = pipeline_lm.synthetic_batch(TINY, batch_size=8, seq_len=16)

    def seq_loss(params, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits = pipeline_lm.sequential_apply(model, params, inputs)
        logprobs = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(
            logprobs, targets[..., None], axis=-1)[..., 0].mean()

    expected = float(seq_loss(params, {k: jnp.asarray(v) for k, v in batch.items()}))
    ad = AutoDist(_spec_for(8), strategy_builder=Pipeline(n_stages=4))
    step = ad.function(loss_fn, params, optax.sgd(0.0), example_batch=batch)
    np.testing.assert_allclose(float(step(batch)), expected, rtol=2e-5)


def test_pipelined_rejects_mesh_stage_mismatch():
    import pytest
    mesh = _pipe_mesh(2)
    f = pipelined(lambda p, x: x, n_stages=4, mesh=mesh)
    with mesh, pytest.raises(ValueError, match="pipe"):
        jax.jit(lambda w, x: f(w, x))(jnp.zeros((4, 2, 2)), jnp.zeros((2, 2, 2)))
