"""Partitioned AllReduce strategy.

Port of reference ``autodist/strategy/partitioned_all_reduce_strategy.py``: partition
each parameter's dim0 by its smallest divisor >= 2, then AllReduce each shard, with
fusion group ids assigned from a running shard counter (``:62-118``). On TPU the
shards map onto the ``model`` mesh axis (tensor-sharded storage) while gradients still
reduce over the data axes; a single fused reduction is strictly better than per-shard
collectives, so group ids remain combiner hints.
"""

from autodist_tpu import const
from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.all_reduce_strategy import parse_ar_options
from autodist_tpu.strategy.base import AR_DEFAULT_AXES, Strategy, StrategyBuilder
from autodist_tpu.strategy.partition_utils import (make_num_shards, partitionable_axis,
                                                   smallest_divisor_at_least_2)


class PartitionedAR(StrategyBuilder):
    def __init__(self, chunk_size: int = 128, all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor"):
        self._chunk_size, self._spec, self._compressor = parse_ar_options(
            chunk_size, all_reduce_spec, compressor)

    def _choose_axis_and_count(self, spec, seed_idx: int):
        axis = partitionable_axis(spec)
        if axis is None:
            return None, None
        k = smallest_divisor_at_least_2(spec.shape[axis])
        return axis, k

    def build(self, model_spec: ModelSpec, resource_spec: ResourceSpec) -> Strategy:
        strategy = Strategy()
        shard_counter = 0  # running shard counter -> group ids (reference :62-118)
        for idx, spec in enumerate(model_spec.trainable.values()):
            node = strategy.proto.node_config.add(var_name=spec.name)
            node.sparse = spec.sparse
            axis, k = self._choose_axis_and_count(spec, idx)
            if axis is None or k is None or k < 2:
                ar = node.all_reduce_synchronizer
                ar.spec = self._spec
                ar.compressor = self._compressor
                ar.group = shard_counter // self._chunk_size
                shard_counter += 1
                continue
            node.partitioner.num_shards.extend(make_num_shards(len(spec.shape), axis, k))
            node.partitioner.mesh_axis = const.MESH_AXIS_MODEL
            for i in range(k):
                part = node.part_config.add(var_name=f"{spec.name}/part_{i}")
                part.sparse = spec.sparse
                ar = part.all_reduce_synchronizer
                ar.spec = self._spec
                ar.compressor = self._compressor
                ar.group = shard_counter // self._chunk_size
                shard_counter += 1
        self._fill_mesh_config(strategy, resource_spec,
                               self._resolved_axes(resource_spec, AR_DEFAULT_AXES))
        return strategy
