"""Strategy builders — the "compiler frontend".

Eight builders with the same distribution policies as the reference
(``autodist/strategy/*``), operating on (ModelSpec, ResourceSpec) and emitting a
serializable Strategy proto. The policies are pure placement/synchronization
algorithms and port at the algorithm level; what changes is the target: node configs
compile into mesh shardings instead of TF device strings.
"""

from autodist_tpu.strategy.base import Strategy, StrategyBuilder, StrategyCompiler
from autodist_tpu.strategy.ps_strategy import PS
from autodist_tpu.strategy.ps_lb_strategy import PSLoadBalancing, byte_size_load_fn
from autodist_tpu.strategy.partitioned_ps_strategy import PartitionedPS
from autodist_tpu.strategy.uneven_partition_ps_strategy import UnevenPartitionedPS
from autodist_tpu.strategy.all_reduce_strategy import AllReduce
from autodist_tpu.strategy.partitioned_all_reduce_strategy import PartitionedAR
from autodist_tpu.strategy.random_axis_partition_all_reduce_strategy import RandomAxisPartitionAR
from autodist_tpu.strategy.parallax_strategy import Parallax
from autodist_tpu.strategy.expert_parallel_strategy import ExpertParallel
from autodist_tpu.strategy.pipeline_strategy import Pipeline
from autodist_tpu.strategy.sequence_parallel_strategy import SequenceParallel
from autodist_tpu.strategy.auto_strategy import AutoStrategy
from autodist_tpu.strategy.tuner import (CandidateResult, TuneResult,
                                         measure_candidate, tune_strategy)
from autodist_tpu.strategy.autotune import (Candidate, TunedPlan, autotune,
                                            enumerate_candidates,
                                            plan_cache_key)

__all__ = [
    "Strategy", "StrategyBuilder", "StrategyCompiler",
    "PS", "PSLoadBalancing", "byte_size_load_fn", "PartitionedPS",
    "UnevenPartitionedPS", "AllReduce", "PartitionedAR",
    "RandomAxisPartitionAR", "Parallax", "ExpertParallel", "Pipeline",
    "SequenceParallel", "AutoStrategy", "tune_strategy", "TuneResult",
    "measure_candidate", "CandidateResult",
    "autotune", "TunedPlan", "Candidate", "enumerate_candidates",
    "plan_cache_key",
]
