"""Production serving plane: continuous-batching inference on the zero-copy
wire (docs/usage/serving.md).

The repo trains 12 model families; this package serves them. Five layers,
one subsystem:

- :mod:`autodist_tpu.serving.batcher` — request queue + continuous/static
  batching loop (jax-free host core; ``ServeConfig`` knobs, bucketed prompt
  padding, decode-step-granularity admission, early-exit slot reuse, paged
  admission gating via the engine's ``can_admit`` hook).
- :mod:`autodist_tpu.serving.runtime` — model runtime adapters:
  ``LMEngine`` drives the Transformer LM's prefill+decode KV-cache path with
  a shared multi-slot dense cache; ``ApplyEngine`` jit-applies the stateless
  classifier/recommender families over padded batches.
- :mod:`autodist_tpu.serving.paged` — ``PagedLMEngine``: the dense
  ``[max_batch, max_len]`` slab re-cut into ``[num_pages, page_len]`` pages
  with lazy allocation, completion-time free, and a shared-prefix page cache
  (copy-on-write at the first divergent page) — same bit-exact outputs,
  admission gated on free pages instead of slots.
- :mod:`autodist_tpu.serving.transport` — ``InferenceServer`` /
  ``ServeClient`` speaking ``generate``/``infer``/``stats``/``status``/
  ``ping`` opcodes on the PR 2 scatter-gather wire (GL006-covered dispatch,
  request-id replay dedup for the fleet router).
- :mod:`autodist_tpu.serving.router` — ``Router`` / ``RouterServer``: one
  front door over N replicas (least-loaded spread, typed ``ServeBusy``
  shedding, idempotent replay around a dead replica, ``serve_p99_burn``
  alert-driven drain + scale-out on the coordinator's respawn budget).

SLO metrics (``serve.latency_s.*`` ms-bucket histograms, queue/batch gauges,
request counters, ``serve.router.*`` / ``serve.kv.*`` fleet families) ride
:mod:`autodist_tpu.telemetry`; spans appear in the PR 5 cluster trace as
``serve.*``.

Typical wiring (see ``examples/serve_lm.py``)::

    config = serving.ServeConfig.from_env(max_batch=8)
    engine = serving.LMEngine(model, params, config)
    server = serving.InferenceServer(serving.Batcher(engine, config))
    client = serving.ServeClient("%s:%d" % server.address)
    tokens, timing = client.generate(prompt, max_new_tokens=32)

Fleet wiring (paged replicas behind the router)::

    def replica():
        cfg = serving.ServeConfig.from_env(page_len=16)
        engine = serving.PagedLMEngine(model, params, cfg)
        return serving.InferenceServer(serving.Batcher(engine, cfg))
    front = serving.RouterServer(serving.Router(replica, n_replicas=2))
    client = serving.ServeClient(front.address)   # unchanged client
"""

from autodist_tpu.serving.batcher import (ApplyBatcher, Batcher, ServeBusy,
                                          ServeConfig, ServeError,
                                          ServeRequest, bucket_for,
                                          default_buckets, pad_prompt)
from autodist_tpu.serving.paged import (PagedLMEngine, PageAllocator,
                                        page_buckets)
from autodist_tpu.serving.router import Replica, Router, RouterServer
from autodist_tpu.serving.runtime import ApplyEngine, LMEngine
from autodist_tpu.serving.transport import InferenceServer, ServeClient

__all__ = [
    "ServeConfig", "ServeError", "ServeBusy", "ServeRequest",
    "Batcher", "ApplyBatcher", "LMEngine", "ApplyEngine", "PagedLMEngine",
    "PageAllocator", "InferenceServer", "ServeClient",
    "Replica", "Router", "RouterServer",
    "bucket_for", "default_buckets", "pad_prompt", "page_buckets",
]
