"""Performance-attribution plane: cost extraction, shares, profiles, adprof.

Covers the PR 9 contract end to end (docs/usage/observability.md
"Performance attribution" / "Profiles and adprof" / "Cost model
calibration"):

- the shared peak-spec helper (flags, env overrides, flops.py delegation);
- per-signature static-cost caching at the runner's compile-probe site
  (one record per compiled program, dispatch counts on reuse, a new shape
  signature opening a new record);
- attribution shares summing to ~1.0 at train() log boundaries, with the
  ``train.mfu`` / ``train.attr.*`` gauges landing in the metrics snapshot;
- the schema-versioned profile JSON (pinned keys/version) and
  ``AUTODIST_PROFILE_DIR`` auto-write;
- ``tools/adprof.py`` run in-process (tracedump-style): self-diff exits 0,
  a deliberately-injected data stall diffs as a named ``phase:data_wait``
  regression with exit 1, non-profile input exits 2;
- the calibrated cost model: unit arithmetic (roofline max, host
  amortization, comm term) and prediction-vs-measured agreement on the CPU
  micro-model within the pinned band.

Pure in-process host tests — no subprocess spawns (GL008-clean), named to
sort inside the tier-1 window (before test_image_data).
"""

import importlib.util
import json
import os
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from autodist_tpu import AutoDist, const, telemetry, train  # noqa: E402
from autodist_tpu.strategy import AllReduce  # noqa: E402
from autodist_tpu.telemetry import costmodel, profiling  # noqa: E402


@pytest.fixture(autouse=True)
def _profiling_reset():
    """Leave process-global telemetry/profiling as found: disabled, empty
    span ring, empty cost/period stores (instruments stay — the registry is
    additive-only and shared)."""
    telemetry.disable()
    telemetry.clear()
    profiling.disable()
    profiling.reset()
    yield
    telemetry.disable()
    telemetry.clear()
    profiling.disable()
    profiling.reset()


# ------------------------------------------------------------------ fixtures

def _loss(p, b):
    return jnp.mean((b["y"] - b["x"] @ p["w"]) ** 2)


def _params():
    return {"w": np.random.RandomState(0).randn(8, 4).astype(np.float32)}


def _batch(i, rows=16):
    rng = np.random.RandomState(100 + i)
    return {"x": rng.randn(rows, 8).astype(np.float32),
            "y": rng.randn(rows, 4).astype(np.float32)}


def _session():
    ad = AutoDist(strategy_builder=AllReduce())
    return ad.create_distributed_session(
        _loss, _params(), optax.adam(1e-2), example_batch=_batch(0))


def _profiled_run(steps=24, log_every=8, batch_fn=_batch):
    profiling.enable()
    profiling.reset()
    runner = _session()
    train(runner, _params(), batch_fn, steps=steps, log_every=log_every)
    return runner


def _adprof():
    spec = importlib.util.spec_from_file_location(
        "adprof_cli", os.path.join(os.path.dirname(__file__), os.pardir,
                                   "tools", "adprof.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------- flags + peak spec

def test_new_flags_registered_and_typed(monkeypatch):
    for flag in ("AUTODIST_PROFILE", "AUTODIST_PROFILE_DIR",
                 "AUTODIST_PEAK_MEMBW"):
        assert flag in const.KNOWN_FLAGS and const.KNOWN_FLAGS[flag]
        assert hasattr(const.ENV, flag)
    monkeypatch.setenv("AUTODIST_PROFILE", "1")
    assert const.ENV.AUTODIST_PROFILE.val is True
    monkeypatch.setenv("AUTODIST_PROFILE_DIR", "/tmp/x")
    assert const.ENV.AUTODIST_PROFILE_DIR.val == "/tmp/x"
    monkeypatch.setenv("AUTODIST_PEAK_MEMBW", "8.1e11")
    assert const.ENV.AUTODIST_PEAK_MEMBW.val == "8.1e11"


def test_peak_spec_env_overrides_and_flops_delegation(monkeypatch):
    from autodist_tpu.utils import flops as flops_util
    monkeypatch.delenv("AUTODIST_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("AUTODIST_PEAK_MEMBW", raising=False)
    spec = profiling.peak_spec()
    # Suite runs on the CPU sim: no spec-sheet peaks, nothing invented.
    assert spec.flops_per_s is None and spec.membw_bytes_per_s is None
    assert flops_util.device_peak_flops() is None
    monkeypatch.setenv("AUTODIST_PEAK_FLOPS", "123e12")
    monkeypatch.setenv("AUTODIST_PEAK_MEMBW", "8.1e11")
    spec = profiling.peak_spec()
    assert spec.flops_per_s == pytest.approx(123e12)
    assert spec.membw_bytes_per_s == pytest.approx(8.1e11)
    assert spec.source == "env"
    # flops.py's MFU math reads the SAME helper — the two can never drift.
    assert flops_util.device_peak_flops() == pytest.approx(123e12)


def test_profile_enable_implies_span_recording():
    assert not telemetry.enabled()
    profiling.enable()
    assert telemetry.enabled() and profiling.active()


def test_malformed_peak_override_degrades_instead_of_raising(monkeypatch):
    """observe_period calls peak_spec at every training log boundary — a
    typo'd override must warn and read as unknown, never crash the run."""
    monkeypatch.setenv("AUTODIST_PEAK_FLOPS", "197T")
    monkeypatch.setenv("AUTODIST_PEAK_MEMBW", "fast")
    spec = profiling.peak_spec()
    assert spec.flops_per_s is None and spec.membw_bytes_per_s is None


def test_mid_run_enable_baselines_dispatch_counters():
    """Telemetry-only runs count dispatches too; arming profiling mid-run
    must not charge the prior run's dispatches to its first period."""
    telemetry.enable()                     # spans on, profiling OFF
    for _ in range(50):
        profiling.note_dispatch("aa00aa00", "step", 1)
    profiling.enable()                     # window opens HERE
    profiling.note_dispatch("aa00aa00", "step", 1)
    rec = profiling.observe_period()
    assert rec is not None and rec["steps"] == 1


# ----------------------------------------------------- cost-cache behavior

def test_cost_cache_one_record_per_signature_reused_across_dispatches():
    profiling.enable()
    profiling.reset()
    runner = _session()
    state = runner.init(_params())
    for i in range(3):
        state, _ = runner.run(state, _batch(i))
    costs = profiling.program_costs()
    assert len(costs) == 1
    (rec,) = costs.values()
    assert rec.dispatches == 3          # reuse counts, no re-extraction
    assert rec.kind == "step" and rec.steps == 1
    assert rec.source == "xla" and rec.flops and rec.flops > 0
    assert rec.bytes_accessed and rec.bytes_accessed > 0
    assert rec.compile_s is not None and rec.compile_s > 0
    # A NEW shape signature compiles -> a second record with its own costs.
    state, _ = runner.run(state, _batch(9, rows=32))
    costs = profiling.program_costs()
    assert len(costs) == 2
    assert sorted(r.dispatches for r in costs.values()) == [1, 3]


def test_analytic_fallback_when_backend_reports_nothing():
    profiling.reset()
    profiling.set_analytic_flops(1e6)
    rec = profiling.record_program_cost("cafe0001", "many", 4, None)
    assert rec.source == "analytic"
    assert rec.flops == pytest.approx(4e6)   # per-dispatch = steps x analytic
    # Each accounting is a LOWER bound: a SHORT XLA count (partially-pallas
    # program — XLA is blind to the custom call's flops) loses to a larger
    # analytic estimate, a larger XLA count wins over a smaller estimate.
    rec = profiling.record_program_cost(
        "cafe0002", "step", 1, {"flops": 77.0, "bytes_accessed": 10.0})
    assert rec.source == "analytic" and rec.flops == pytest.approx(1e6)
    assert rec.bytes_accessed == 10.0        # bytes stay XLA's — no estimate
    rec = profiling.record_program_cost(
        "cafe0003", "step", 1, {"flops": 5e6, "bytes_accessed": 10.0})
    assert rec.source == "xla" and rec.flops == pytest.approx(5e6)
    profiling.set_analytic_flops(None)
    rec = profiling.record_program_cost("cafe0004", "step", 1, None)
    assert rec.source is None and rec.flops is None


# ------------------------------------------------- attribution + roofline

def test_attribution_shares_sum_to_one_and_mfu_gauge(monkeypatch):
    monkeypatch.setenv("AUTODIST_PEAK_FLOPS", "1e6")   # tiny peak: mfu > 0
    monkeypatch.setenv("AUTODIST_PEAK_MEMBW", "1e6")
    _profiled_run()
    snap = telemetry.snapshot()
    shares = {k: v for k, v in snap.items() if k.startswith("train.attr.")}
    assert set(shares) == {f"train.attr.{p}" for p in profiling.ATTR_PHASES}
    assert sum(shares.values()) == pytest.approx(1.0, abs=1e-3)
    assert snap["train.mfu"] > 0
    assert snap["train.membw_util"] > 0
    assert snap["train.flops_per_s"] > 0
    periods = profiling.attribution_periods()
    assert periods and all(
        sum(p["shares"].values()) == pytest.approx(1.0, abs=1e-3)
        for p in periods)
    # steps are accounted from dispatch deltas, so the series covers the run.
    assert sum(p["steps"] for p in periods) <= 24


def test_format_attr_line_compact():
    rec = {"shares": {"compute": 0.61, "comm": 0.05, "host": 0.22,
                      "data_wait": 0.07, "readback": 0.05}, "mfu": 0.283}
    line = profiling.format_attr_line(rec)
    assert "mfu 28.3%" in line and "comp .61" in line and "rb .05" in line
    assert profiling.format_attr_line(None) == ""


# ------------------------------------------------------------ profile store

def test_profile_schema_pinned(tmp_path):
    _profiled_run()
    path = str(tmp_path / "run.json")
    telemetry.write_profile(path)
    with open(path) as f:
        doc = json.load(f)
    assert doc["schema"] == "autodist-profile"
    assert doc["schema_version"] == 1
    for key in ("manifest", "peaks", "programs", "periods", "summary"):
        assert key in doc, key
    for key in ("host", "pid", "flags", "versions", "t_wall_s"):
        assert key in doc["manifest"], key
    assert set(doc["peaks"]) == {"flops_per_s", "membw_bytes_per_s",
                                 "source"}
    assert doc["programs"], "a compiled step must contribute a cost record"
    rec = next(iter(doc["programs"].values()))
    for key in ("kind", "steps", "flops", "bytes_accessed", "output_bytes",
                "compile_s", "dispatches", "source"):
        assert key in rec, key
    summary = doc["summary"]
    for key in ("wall_s", "steps", "dispatches", "steps_per_s", "step_s",
                "shares", "flops_per_step", "host_s_per_dispatch"):
        assert key in summary, key
    assert sum(summary["shares"].values()) == pytest.approx(1.0, abs=1e-3)


def test_short_run_tail_period_flushed_into_profile():
    """A run shorter than one log period (or with a partial tail) still
    profiles: _finish's end-of-run flush closes the final period before the
    profile is written — the PR 8 health-monitor contract, re-established
    for attribution."""
    _profiled_run(steps=6, log_every=50)   # no boundary ever fires in-loop
    doc = telemetry.profile_document()
    assert len(doc["periods"]) == 1
    assert doc["periods"][0]["steps"] == 6
    assert doc["summary"]["step_s"] and doc["summary"]["steps_per_s"]
    assert sum(doc["summary"]["shares"].values()) == pytest.approx(
        1.0, abs=1e-3)


def test_profile_dir_env_auto_write(tmp_path, monkeypatch):
    monkeypatch.setenv("AUTODIST_PROFILE_DIR", str(tmp_path))
    _profiled_run(steps=16)
    files = [f for f in os.listdir(tmp_path) if f.startswith("profile-")]
    assert len(files) == 1
    doc = _adprof().load_profile(str(tmp_path / files[0]))
    assert doc["summary"]["steps"] > 0


# ------------------------------------------------------------------ adprof

def test_adprof_self_diff_reports_zero_regressions(tmp_path, capsys):
    _profiled_run()
    path = str(tmp_path / "a.json")
    telemetry.write_profile(path)
    ad = _adprof()
    assert ad.main([path, path, "--threshold", "5"]) == 0
    out = capsys.readouterr().out
    assert "no regression" in out
    # Summary mode on one profile exits 0 too.
    assert ad.main([path]) == 0


def test_adprof_names_injected_data_stall(tmp_path, capsys):
    a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    _profiled_run()
    telemetry.write_profile(a)
    telemetry.clear()

    def stalled(i):
        time.sleep(0.004)           # the deliberate slowdown: data loading
        return _batch(i)

    profiling.reset()
    _profiled_run(batch_fn=stalled)
    telemetry.write_profile(b)
    ad = _adprof()
    rc = ad.main([a, b, "--threshold", "10"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSION" in out and "phase:data_wait" in out


def test_adprof_rejects_non_profile_input(tmp_path, capsys):
    bogus = tmp_path / "not_a_profile.json"
    bogus.write_text(json.dumps({"traceEvents": []}))
    ad = _adprof()
    assert ad.main([str(bogus)]) == 2
    assert "not an autodist profile" in capsys.readouterr().err
    wrong_version = tmp_path / "vnext.json"
    wrong_version.write_text(json.dumps({"schema": "autodist-profile",
                                         "schema_version": 999}))
    assert ad.main([str(wrong_version)]) == 2


# --------------------------------------------------------------- cost model

def test_costmodel_predict_arithmetic():
    calib = costmodel.Calibration(flops_per_s=1e9, bytes_per_s=1e8,
                                  host_s_per_dispatch=0.001,
                                  wire_bytes_per_s=1e6)
    # Compute-bound program: 1e9 flops at 1e9 flops/s = 1s; 1e7 bytes at
    # 1e8 B/s = 0.1s; roofline takes the max + host per dispatch.
    pred = costmodel.predict({"flops": 1e9, "bytes_accessed": 1e7,
                              "steps": 1}, calib)
    assert pred["step_s"] == pytest.approx(1.001)
    assert pred["bound"] == "compute"
    # Memory-bound flips the roofline.
    pred = costmodel.predict({"flops": 1e6, "bytes_accessed": 1e8,
                              "steps": 1}, calib)
    assert pred["step_s"] == pytest.approx(1.001)
    assert pred["bound"] == "memory"
    # A fused steps=4 block amortizes the dispatch across its steps.
    pred = costmodel.predict({"flops": 4e9, "bytes_accessed": 0,
                              "steps": 4}, calib)
    assert pred["step_s"] == pytest.approx(1.0 + 0.001 / 4)
    # The comm term rides the calibrated wire bandwidth.
    pred = costmodel.predict({"flops": 0, "bytes_accessed": 0, "steps": 1},
                             calib, comm_bytes_per_step=2e6)
    assert pred["step_s"] == pytest.approx(0.001 + 2.0)
    assert pred["bound"] == "comm"
    # Dispatch-weighted records charge host per dispatch.
    pred = costmodel.predict({"flops": 1e9, "steps": 1, "dispatches": 10},
                             calib)
    assert pred["step_s"] == pytest.approx(1.001)


def test_costmodel_calibration_roundtrip_from_dict():
    calib = costmodel.Calibration(flops_per_s=2.0, bytes_per_s=3.0,
                                  host_s_per_dispatch=0.5)
    again = costmodel.Calibration.from_dict(calib.to_dict())
    assert again == calib


def test_costmodel_prediction_within_band_on_micro_model():
    """The acceptance pin: calibrate from a real CPU micro-model run's
    profile and predict its own step time — agreement within a generous
    band (the run IS the calibration source, so gross disagreement means
    the model's accounting, not the machine, is wrong)."""
    _profiled_run(steps=32, log_every=8)
    doc = telemetry.profile_document()
    pred = costmodel.predict_from_profile(doc)
    assert pred["measured_step_s"] and pred["measured_step_s"] > 0
    assert pred["ratio"] is not None
    # Generous band: a loaded 2-core CI box jitters phase shares, but the
    # self-prediction must stay the right order of magnitude.
    assert 0.2 < pred["ratio"] < 5.0
    assert pred["bound"] in ("compute", "memory", "host", "comm")
    calib = costmodel.Calibration.from_dict(pred["calibration"])
    assert calib.host_s_per_dispatch >= 0
