"""The distributed CI stage's ssh leg, executed without docker.

The reference gated merges on a real 2-machine stage: a worker container ran
sshd, the chief ssh-launched the user script there and drove distributed
training (reference ``Jenkinsfile:91-131``). ``docker/compose.dist.yml``
reproduces that with containers; THIS test executes the same logical sequence
in-process with the ``docker/ssh_shim`` fake ssh/scp on PATH: the worker node
has a non-local address, so ``Cluster.remote_exec`` takes the REAL ssh branch
(command construction, shared_envs prefixing, strategy scp), the shim runs
the received remote command locally, and the two processes join one
``jax.distributed`` program — everything the compose stage runs except the
sshd network hop. ci.sh --dist runs the same leg.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np

import examples.multiprocess_linear_regression as mp_script

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SHIM_DIR = os.path.join(REPO, "docker", "ssh_shim")


def _spec_yaml(tmp_path) -> str:
    """The dist_stage_spec.yml shape with this repo's paths: chief local,
    worker behind the ssh config (address 'ci-worker' is NOT local, so the
    ssh branch must fire)."""
    key = tmp_path / "id_ci"
    key.write_text("fake key — the shim never reads it\n")
    spec = tmp_path / "stage_spec.yml"
    spec.write_text(f"""\
nodes:
  - address: 127.0.0.1
    tpus: 2
    chief: true
  - address: ci-worker
    tpus: 2
    ssh_config: ci
ssh:
  ci:
    username: root
    port: 12345
    key_file: {key}
    shared_envs:
      PYTHONPATH: {REPO}
      JAX_PLATFORMS: cpu
      XLA_FLAGS: --xla_force_host_platform_device_count=2
""")
    return str(spec)


def test_dist_stage_ssh_leg(tmp_path):
    out = tmp_path / "result.json"
    shim_log = tmp_path / "shim.log"
    env = dict(os.environ)
    for k in mp_script.ROLE_ENV_VARS:
        env.pop(k, None)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "AUTODIST_COORDINATOR_PORT": str(port),
        "AUTODIST_WORKING_DIR": str(tmp_path / "workdir"),
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "PATH": SHIM_DIR + os.pathsep + env.get("PATH", ""),
        "SYS_RESOURCE_PATH": _spec_yaml(tmp_path),
        "AUTODIST_SSH_SHIM_LOG": str(shim_log),
    })
    script = os.path.abspath(mp_script.__file__)
    proc = subprocess.run([sys.executable, script, str(out)], env=env,
                          cwd=REPO, capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == 0, (
        f"chief failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")

    # The ssh branch actually fired: strategy shipped by scp, worker launched
    # by ssh, both aimed at the non-local worker address.
    log = shim_log.read_text().splitlines()
    assert "scp root@ci-worker" in log, log
    assert "ssh root@ci-worker" in log, log

    # And the training it drove is value-exact vs hand-computed SGD (the same
    # c0 criterion the loopback 2-process test asserts).
    result = json.loads(out.read_text())
    assert result["process_count"] == 2
    assert result["device_count"] == 4
    w = b = 0.0
    losses = []
    for step in range(mp_script.STEPS):
        batch = mp_script.make_batch(step)
        x, y = batch["x"], batch["y"]
        resid = y - (w * x + b)
        losses.append(float(np.mean(resid ** 2)))
        w -= mp_script.LR * float(np.mean(-2.0 * x * resid))
        b -= mp_script.LR * float(np.mean(-2.0 * resid))
    np.testing.assert_allclose(result["w"], w, rtol=1e-5)
    np.testing.assert_allclose(result["b"], b, rtol=1e-5)
    np.testing.assert_allclose(result["losses"], losses, rtol=1e-5)
