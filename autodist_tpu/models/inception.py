"""Inception-V3 for ImageNet-class benchmarks.

Counterpart of the reference's Keras InceptionV3 benchmark entry
(``examples/benchmark/imagenet.py:150-170``, chunk_size=30). Same TPU-first
choices as ``models/resnet.py``: NHWC, bfloat16 activations over float32
parameters, GroupNorm instead of BatchNorm (pure train step, nothing to
synchronize). The branch structure is kept — XLA fuses each branch's
conv→norm→relu chain and the final channel concat feeds the next block's 1x1
convs on the MXU. The auxiliary classifier head is omitted (the reference
benchmark ran inference-topology Keras models without aux loss as well).
"""

import dataclasses
from typing import Any, Callable, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.models.common import num_groups as _num_groups


@dataclasses.dataclass(frozen=True)
class InceptionV3Config:
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    norm_groups: int = 32
    # Repeat counts for the (A, C, E) inception stages; (3, 4, 2) is the
    # paper's V3 topology (the default reproduces the original param names
    # mixed0..mixed10 exactly). The B and D grid reductions are structural
    # and always present, so ANY repeats config still exercises every block
    # type — reduced counts are for bring-up/test configs where the full
    # 11-block graph's compile time is the cost, not the math.
    repeats: Tuple[int, int, int] = (3, 4, 2)


class ConvNorm(nn.Module):
    """conv → GroupNorm → relu, the basic Inception cell."""

    config: InceptionV3Config
    features: int
    kernel: tuple
    strides: tuple = (1, 1)
    padding: str = "SAME"

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        x = nn.Conv(self.features, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=False, dtype=cfg.dtype,
                    param_dtype=jnp.float32, name="conv")(x)
        x = nn.GroupNorm(num_groups=_num_groups(self.features, cfg.norm_groups),
                         dtype=cfg.dtype, name="norm")(x)
        return nn.relu(x)


class InceptionA(nn.Module):
    config: InceptionV3Config
    pool_features: int

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b1 = ConvNorm(cfg, 64, (1, 1), name="b1_1x1")(x)
        b2 = ConvNorm(cfg, 48, (1, 1), name="b2_1x1")(x)
        b2 = ConvNorm(cfg, 64, (5, 5), name="b2_5x5")(b2)
        b3 = ConvNorm(cfg, 64, (1, 1), name="b3_1x1")(x)
        b3 = ConvNorm(cfg, 96, (3, 3), name="b3_3x3a")(b3)
        b3 = ConvNorm(cfg, 96, (3, 3), name="b3_3x3b")(b3)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = ConvNorm(cfg, self.pool_features, (1, 1), name="b4_pool")(b4)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionB(nn.Module):
    """Grid reduction 35x35 → 17x17."""

    config: InceptionV3Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b1 = ConvNorm(cfg, 384, (3, 3), strides=(2, 2), padding="VALID",
                      name="b1_3x3")(x)
        b2 = ConvNorm(cfg, 64, (1, 1), name="b2_1x1")(x)
        b2 = ConvNorm(cfg, 96, (3, 3), name="b2_3x3a")(b2)
        b2 = ConvNorm(cfg, 96, (3, 3), strides=(2, 2), padding="VALID",
                      name="b2_3x3b")(b2)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    """Factorized 7x7 branches at 17x17 resolution."""

    config: InceptionV3Config
    channels_7x7: int

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        c7 = self.channels_7x7
        b1 = ConvNorm(cfg, 192, (1, 1), name="b1_1x1")(x)
        b2 = ConvNorm(cfg, c7, (1, 1), name="b2_1x1")(x)
        b2 = ConvNorm(cfg, c7, (1, 7), name="b2_1x7")(b2)
        b2 = ConvNorm(cfg, 192, (7, 1), name="b2_7x1")(b2)
        b3 = ConvNorm(cfg, c7, (1, 1), name="b3_1x1")(x)
        b3 = ConvNorm(cfg, c7, (7, 1), name="b3_7x1a")(b3)
        b3 = ConvNorm(cfg, c7, (1, 7), name="b3_1x7a")(b3)
        b3 = ConvNorm(cfg, c7, (7, 1), name="b3_7x1b")(b3)
        b3 = ConvNorm(cfg, 192, (1, 7), name="b3_1x7b")(b3)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = ConvNorm(cfg, 192, (1, 1), name="b4_pool")(b4)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionD(nn.Module):
    """Grid reduction 17x17 → 8x8."""

    config: InceptionV3Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b1 = ConvNorm(cfg, 192, (1, 1), name="b1_1x1")(x)
        b1 = ConvNorm(cfg, 320, (3, 3), strides=(2, 2), padding="VALID",
                      name="b1_3x3")(b1)
        b2 = ConvNorm(cfg, 192, (1, 1), name="b2_1x1")(x)
        b2 = ConvNorm(cfg, 192, (1, 7), name="b2_1x7")(b2)
        b2 = ConvNorm(cfg, 192, (7, 1), name="b2_7x1")(b2)
        b2 = ConvNorm(cfg, 192, (3, 3), strides=(2, 2), padding="VALID",
                      name="b2_3x3")(b2)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionE(nn.Module):
    """Expanded-filterbank blocks at 8x8 resolution."""

    config: InceptionV3Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b1 = ConvNorm(cfg, 320, (1, 1), name="b1_1x1")(x)
        b2 = ConvNorm(cfg, 384, (1, 1), name="b2_1x1")(x)
        b2 = jnp.concatenate([ConvNorm(cfg, 384, (1, 3), name="b2_1x3")(b2),
                              ConvNorm(cfg, 384, (3, 1), name="b2_3x1")(b2)], axis=-1)
        b3 = ConvNorm(cfg, 448, (1, 1), name="b3_1x1")(x)
        b3 = ConvNorm(cfg, 384, (3, 3), name="b3_3x3")(b3)
        b3 = jnp.concatenate([ConvNorm(cfg, 384, (1, 3), name="b3_1x3")(b3),
                              ConvNorm(cfg, 384, (3, 1), name="b3_3x1")(b3)], axis=-1)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = ConvNorm(cfg, 192, (1, 1), name="b4_pool")(b4)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    config: InceptionV3Config

    @nn.compact
    def __call__(self, images):
        cfg = self.config
        x = images.astype(cfg.dtype)
        # Stem: 299x299x3 → 35x35x192.
        x = ConvNorm(cfg, 32, (3, 3), strides=(2, 2), padding="VALID",
                     name="stem_conv1")(x)
        x = ConvNorm(cfg, 32, (3, 3), padding="VALID", name="stem_conv2")(x)
        x = ConvNorm(cfg, 64, (3, 3), name="stem_conv3")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = ConvNorm(cfg, 80, (1, 1), name="stem_conv4")(x)
        x = ConvNorm(cfg, 192, (3, 3), padding="VALID", name="stem_conv5")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")

        n_a, n_c, n_e = cfg.repeats
        idx = 0
        a_widths = (32, 64, 64)
        for i in range(n_a):
            x = InceptionA(cfg, a_widths[min(i, 2)], name=f"mixed{idx}")(x)
            idx += 1
        x = InceptionB(cfg, name=f"mixed{idx}")(x)
        idx += 1
        c_widths = (128, 160, 160, 192)
        for i in range(n_c):
            x = InceptionC(cfg, c_widths[min(i, 3)], name=f"mixed{idx}")(x)
            idx += 1
        x = InceptionD(cfg, name=f"mixed{idx}")(x)
        idx += 1
        for _ in range(n_e):
            x = InceptionE(cfg, name=f"mixed{idx}")(x)
            idx += 1

        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(x)


def make_loss_fn(model: InceptionV3) -> Callable:
    from autodist_tpu.models.common import make_classification_loss_fn
    return make_classification_loss_fn(model)


def init_params(config: InceptionV3Config, rng=None, image_size: int = 299):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    model = InceptionV3(config)
    images = jnp.zeros((2, image_size, image_size, 3), jnp.float32)
    from autodist_tpu.models.common import jit_init
    return model, jit_init(model, images, rng=rng)


def synthetic_batch(config: InceptionV3Config, batch_size: int,
                    image_size: int = 299, seed: int = 0):
    rng = np.random.RandomState(seed)
    return {
        "images": rng.randn(batch_size, image_size, image_size, 3).astype(np.float32),
        "labels": rng.randint(0, config.num_classes, size=(batch_size,)).astype(np.int32),
    }
