"""Flash attention — pallas TPU kernel (forward) with blockwise-JAX backward.

Forward: grid (batch*heads, q-blocks, k-blocks); each K/V block streams through
VMEM via its own BlockSpec while VMEM scratch carries the online-softmax state
(running max, denominator, unnormalized accumulator) across the k dimension of the
grid — the [L, L] score matrix never exists, and resident VMEM is O(q_block +
k_block), independent of sequence length. Causal upper-triangular blocks are
skipped entirely (~2x fewer FLOPs).

Backward: ``jax.custom_vjp`` re-computes gradients with the differentiable
blockwise-JAX implementation (:mod:`blockwise_attention`) under the same O(L*block)
memory bound. (A dedicated pallas backward kernel is a further optimization, not a
semantic change.)

On non-TPU backends the kernel runs in pallas interpret mode, so tests exercise
the same code path on the CPU-sim mesh.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from autodist_tpu.ops.blockwise_attention import NEG_INF
from autodist_tpu.ops.blockwise_attention import blockwise_attention as _blockwise

DEFAULT_Q_BLOCK = 128
DEFAULT_K_BLOCK = 128
_LANES = 128  # scratch minor dim (TPU lane count)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  lk: int, q_block: int, k_block: int, causal: bool, scale: float):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * q_block
    k_start = ki * k_block
    # Causal: skip blocks strictly above the diagonal (no query can see them).
    needed = (k_start <= q_start + q_block - 1) if causal else True

    @pl.when(needed)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale          # [bq, d]
        k_blk = k_ref[0].astype(jnp.float32)              # [bk, d]
        v_blk = v_ref[0].astype(jnp.float32)
        bq, bk = q.shape[0], k_blk.shape[0]
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        invalid = k_pos >= lk                             # tail padding
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            invalid = invalid | (k_pos > q_pos)
        scores = jnp.where(invalid, NEG_INF, scores)

        m_prev = m_ref[:, :1]                             # [bq, 1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
        correction = jnp.exp(m_prev - m_new)
        p = jnp.where(scores <= NEG_INF * 0.5, 0.0, jnp.exp(scores - m_new))
        l_ref[:] = jnp.broadcast_to(l_prev * correction + p.sum(axis=-1, keepdims=True),
                                    l_ref.shape)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, q_block: int, k_block: int,
                   interpret: bool):
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = 1.0 / (d ** 0.5)

    # Collapse (batch, head) into the grid's first axis: [B*H, L, D].
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, lk, d)

    bq = min(q_block, lq)
    n_q = pl.cdiv(lq, bq)
    if n_q * bq - lq:
        qf = jnp.pad(qf, ((0, 0), (0, n_q * bq - lq), (0, 0)))
    bk = min(k_block, lk)
    n_k = pl.cdiv(lk, bk)
    if n_k * bk - lk:
        kf = jnp.pad(kf, ((0, 0), (0, n_k * bk - lk), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, n_k * bk - lk), (0, 0)))

    kernel = functools.partial(_flash_kernel, lk=lk, q_block=bq, k_block=bk,
                               causal=causal, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, n_q * bq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),       # acc
            pltpu.VMEM((bq, _LANES), jnp.float32),  # running max
            pltpu.VMEM((bq, _LANES), jnp.float32),  # running denominator
        ],
        interpret=interpret,
    )(qf, kf, vf)

    out = out[:, :lq, :].reshape(b, h, lq, d).transpose(0, 2, 1, 3)
    return out


def _use_interpret() -> bool:
    # The axon tunnel registers TPU devices under the 'axon' platform name; both it
    # and plain 'tpu' take the Mosaic path. Everything else interprets.
    return jax.default_backend() not in ("tpu", "axon")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, q_block, k_block):
    return _flash_forward(q, k, v, causal, q_block, k_block, _use_interpret())


def _flash_fwd(q, k, v, causal, q_block, k_block):
    return _flash(q, k, v, causal, q_block, k_block), (q, k, v)


def _flash_bwd(causal, q_block, k_block, residuals, g):
    q, k, v = residuals

    def ref(q, k, v):
        return _blockwise(q, k, v, causal=causal, block_size=k_block)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, q_block: int = DEFAULT_Q_BLOCK,
                    k_block: int = DEFAULT_K_BLOCK) -> jax.Array:
    """Flash attention over [B, L, H, D] tensors (pallas forward, blockwise bwd)."""
    return _flash(q, k, v, causal, q_block, k_block)
