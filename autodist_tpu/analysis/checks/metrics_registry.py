"""GL009 — metric/event-name registry: producers, consumers, and docs.

The fleet planes (PR 11) wired three kinds of metric CONSUMERS to the
telemetry registry by string name: alert-rule selectors
(``telemetry/alerts.py`` ``DEFAULT_RULES``), console field lookups
(``tools/adtop.py`` / ``tools/adfleet.py`` reading a status snapshot's
``registry`` dict), and the drift rules' ``ref_from="plan"`` phase mapping.
Every one of them fails SILENTLY on a typo: the selector never matches, the
console prints a dash, the drift trigger never fires — the PR 11 review
found an alert rule that was dead on arrival for exactly this reason, and
ROADMAP 4's Automap-style re-tune loop hangs off ``train.attr.*`` drift
rules, so a typo'd selector silently disables online retuning.

GL009 makes the name vocabulary itself a checked registry (the GL007 move,
applied to metrics): it harvests every ``counter("…")`` / ``gauge("…")`` /
``histogram("…")`` / ``span("…")`` call across the WHOLE program into a
producer registry — f-string names contribute prefix patterns
(``f"train.attr.{phase}"`` books ``train.attr.*``), string parameter
defaults are substituted (``metric_prefix="data"`` books
``data.producer_wait``), and one level of in-module wrapper functions is
followed (``recovery._counter("recover.evicted")``) — then flags:

- a consumer selector/lookup naming a metric NO producer books;
- a ``ref_from="plan"`` drift rule whose metric's phase suffix is not a
  plan-priced phase (the predicted-breakdown mapping's keys) — the
  reference would silently be 0 instead of the plan's bound;
- a producer name booked in ``autodist_tpu/`` package code but absent from
  ``docs/usage/observability.md``'s plane tables — the operator-facing
  contract the consoles and alert files are written against.

Consumer checks run only when the program books at least one producer (a
partial fixture tree is not a missing registry), and the docs check only
when observability.md exists under the repo root.
"""

import ast
import fnmatch
import re
from typing import Dict, List, Optional, Set, Tuple

from autodist_tpu.analysis import callgraph
from autodist_tpu.analysis.core import Context, Finding, register_program

_PRODUCER_FNS = {"counter", "gauge", "histogram", "span"}
_REG_TOKENS = {"reg", "registry", "metrics"}
_DOC_PATH = "docs/usage/observability.md"
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_*]+)+$")


def _name_pattern(arg, fn_defaults: Dict[str, str]) -> Optional[str]:
    """The (possibly wildcarded) metric name a call's first arg produces:
    a str constant verbatim; an f-string with constants kept, string
    parameter defaults substituted, and everything dynamic as ``*``."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for v in arg.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            elif isinstance(v, ast.FormattedValue) \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id in fn_defaults:
                parts.append(fn_defaults[v.value.id])
            else:
                parts.append("*")
        pat = "".join(parts)
        while "**" in pat:
            pat = pat.replace("**", "*")
        return pat if pat.strip("*") else None
    return None


def _str_defaults(fn) -> Dict[str, str]:
    """``param -> default`` for a function's string-defaulted parameters."""
    out: Dict[str, str] = {}
    args = fn.args
    pos = args.posonlyargs + args.args
    for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
        if isinstance(d, ast.Constant) and isinstance(d.value, str):
            out[a.arg] = d.value
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is not None and isinstance(d, ast.Constant) \
                and isinstance(d.value, str):
            out[a.arg] = d.value
    return out


def _param_forwarders(info, forwarded_arg) -> Dict[str, int]:
    """In-module functions that forward a parameter into a qualifying call
    -> the forwarded parameter's position. ``forwarded_arg(call)`` returns
    the candidate argument expression of a qualifying call (or None) —
    the ONE forwarding scanner both the producer-wrapper
    (``def _counter(name): return _metrics.counter(name)``) and the
    lookup-wrapper (``def _counter(reg, name): v = reg.get(name)``)
    harvests share, so the two kinds cannot drift."""
    out: Dict[str, int] = {}
    for name, fn in info.index.module_funcs.items():
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        for call in callgraph.calls_under(fn):
            arg = forwarded_arg(call)
            if arg is not None and isinstance(arg, ast.Name) \
                    and arg.id in params:
                out[name] = params.index(arg.id)
                break
    return out


def _producer_wrappers(info) -> Dict[str, int]:
    def forwarded(call):
        if callgraph.last_attr(call.func) in _PRODUCER_FNS and call.args:
            return call.args[0]
        return None

    return _param_forwarders(info, forwarded)


def _calls_with_defaults(node, defaults: Dict[str, str]):
    """Every Call node paired with its INNERMOST enclosing function's
    string-parameter defaults (so an f-string name substitutes the right
    scope's default exactly once)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield from _calls_with_defaults(child, _str_defaults(child))
            continue
        if isinstance(child, ast.Call):
            yield child, defaults
        yield from _calls_with_defaults(child, defaults)


def harvest_producers(program) -> Tuple[Dict[str, Tuple[str, int]],
                                        Dict[str, Tuple[str, int]]]:
    """``(exact, patterns)``: metric/span names the program books, each
    mapped to its first (path, line) booking site. Patterns contain ``*``."""
    exact: Dict[str, Tuple[str, int]] = {}
    patterns: Dict[str, Tuple[str, int]] = {}
    for info in program.modules():
        if info.relpath.startswith("tests/"):
            # Symmetric with the consumer-side exemption: a metric booked
            # only by a test fixture must not mask a production selector
            # gone dead (the very class GL009 exists to catch).
            continue
        wrappers = _producer_wrappers(info)
        for call, defaults in _calls_with_defaults(info.module.tree, {}):
            if not call.args:
                continue
            last = callgraph.last_attr(call.func)
            arg = None
            if last in _PRODUCER_FNS:
                arg = call.args[0]
            elif isinstance(call.func, ast.Name) and call.func.id in wrappers:
                pos = wrappers[call.func.id]
                if pos < len(call.args):
                    arg = call.args[pos]
            if arg is None:
                continue
            pat = _name_pattern(arg, defaults)
            if pat is None or not _NAME_RE.match(pat):
                continue
            site = (info.relpath, call.lineno)
            if "*" in pat:
                patterns.setdefault(pat, site)
            else:
                exact.setdefault(pat, site)
    return exact, patterns


def _booked(name: str, exact, patterns) -> bool:
    return name in exact or any(fnmatch.fnmatchcase(name, p)
                                for p in patterns)


def _prefix_bookable(prefix: str, exact, patterns) -> bool:
    """True when SOME booked name (or bookable pattern) can start with
    ``prefix`` — the ``selector.*`` fan-out case."""
    if any(n.startswith(prefix) for n in exact):
        return True
    for pat in patterns:
        head = pat.split("*", 1)[0]
        if head.startswith(prefix) or prefix.startswith(head):
            return True
    return False


def _alert_rule_dicts(tree):
    """Dict literals that look like alert rules: str-keyed with both a
    ``metric`` and a ``kind`` entry (the :class:`AlertRule` signature)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        fields: Dict[str, ast.AST] = {}
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                fields[k.value] = v
        if "metric" in fields and "kind" in fields:
            yield node, fields


def _plan_phases(program) -> Optional[Set[str]]:
    """The plan-priced phase vocabulary: keys of the dict literal mapping
    phases to ``breakdown.get("…")`` (``alerts.AlertRule._reference``).
    Harvested from NON-TEST modules only, like every other GL009 harvest —
    a test fixture must not become the phase vocabulary."""
    for info in program.modules():
        if info.relpath.startswith("tests/"):
            continue
        for node in ast.walk(info.module.tree):
            if not isinstance(node, ast.Dict) or not node.keys:
                continue
            keys: Set[str] = set()
            shape = True
            for k, v in zip(node.keys, node.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and isinstance(v, ast.Call)
                        and callgraph.last_attr(v.func) == "get"
                        and isinstance(v.func, ast.Attribute)
                        and callgraph.last_attr(v.func.value) == "breakdown"):
                    shape = False
                    break
                keys.add(k.value)
            if shape and keys:
                return keys
    return None


def _lookup_wrappers(info) -> Dict[str, int]:
    def forwarded(call):
        if callgraph.last_attr(call.func) == "get" \
                and isinstance(call.func, ast.Attribute) and call.args \
                and callgraph.name_tokens(
                    callgraph.last_attr(call.func.value)) & _REG_TOKENS:
            return call.args[0]
        return None

    return _param_forwarders(info, forwarded)


def _doc_wildcards(doc: str) -> List[str]:
    """Documented ``prefix.*`` wildcard families in the doc text."""
    return re.findall(r"[a-z][a-z0-9_.]*\.\*", doc)


def _documented(name: str, doc: str, wildcards: List[str]) -> bool:
    # Token-bounded, not substring: a booked `train.flops` must NOT count
    # as documented because `train.flops_per_s` appears in prose — that is
    # precisely the stragglers class the docs check exists to catch.
    if re.search(r"(?<![A-Za-z0-9_.*])" + re.escape(name)
                 + r"(?![A-Za-z0-9_*])", doc):
        return True
    head = name.split("*", 1)[0]
    for w in wildcards:
        wh = w[:-1]        # keep the trailing dot
        if head.startswith(wh) or (("*" in name) and wh.startswith(head)):
            return True
    return False


@register_program("GL009", "metric/event name not in the producer registry "
                           "or undocumented", full_program=True)
def check_metric_registry(program, ctx: Context) -> List[Finding]:
    """GL009 — metric/event-name registry (see the module docstring).

    The producer registry is generated from the program itself — every
    ``counter``/``gauge``/``histogram``/``span`` first-argument literal,
    with f-string sites contributing ``prefix.*`` patterns — so a metric is
    "registered" by being booked, never by being listed twice. Consumers
    (alert-rule ``metric`` selectors, registry ``.get("…")`` lookups in the
    consoles, ``ref_from="plan"`` phase suffixes) must resolve against it;
    producers in package code must appear in
    ``docs/usage/observability.md``. The PR 11 class this kills: an alert
    rule whose selector could never match a booked value was shipped dead —
    the incident it existed to page on would have passed silently.
    """
    findings: List[Finding] = []
    exact, patterns = harvest_producers(program)
    if not exact and not patterns:
        return []
    phases = _plan_phases(program)

    for info in program.modules():
        module = info.module
        if module.relpath.startswith("tests/"):
            # A test's rule dict or lookup is a fixture exercising the
            # machinery, not a shipped selector; the selectors operators
            # depend on live in package/tool code.
            continue
        tree = module.tree
        # --- consumers: alert-rule selectors --------------------------------
        for node, fields in _alert_rule_dicts(tree):
            metric = fields["metric"]
            if not (isinstance(metric, ast.Constant)
                    and isinstance(metric.value, str)):
                continue
            sel = metric.value
            if sel.endswith(".*"):
                ok = _prefix_bookable(sel[:-1], exact, patterns)
            else:
                ok = _booked(sel, exact, patterns)
            if not ok:
                findings.append(Finding(
                    "GL009", module.relpath, node.lineno, node.col_offset,
                    f"alert-rule selector {sel!r} matches no metric any "
                    f"producer books; the rule is dead on arrival — it can "
                    f"never fire (the PR 11 class)",
                    scope=module.scope_at(node)))
                continue
            ref_from = fields.get("ref_from")
            if phases is not None and isinstance(ref_from, ast.Constant) \
                    and ref_from.value == "plan" and not sel.endswith(".*"):
                phase = sel.rsplit(".", 1)[-1]
                if phase not in phases:
                    findings.append(Finding(
                        "GL009", module.relpath, node.lineno,
                        node.col_offset,
                        f"drift rule selects {sel!r} with ref_from='plan', "
                        f"but {phase!r} is not a plan-priced phase "
                        f"({', '.join(sorted(phases))}); the reference "
                        f"silently degrades to 0 instead of the plan's "
                        f"predicted bound",
                        scope=module.scope_at(node)))
        # --- consumers: registry field lookups ------------------------------
        wrappers = _lookup_wrappers(info)
        for call in callgraph.calls_under(tree):
            arg = None
            if callgraph.last_attr(call.func) == "get" \
                    and isinstance(call.func, ast.Attribute) and call.args:
                recv = callgraph.name_tokens(
                    callgraph.last_attr(call.func.value))
                if recv & _REG_TOKENS:
                    arg = call.args[0]
            elif isinstance(call.func, ast.Name) \
                    and call.func.id in wrappers:
                pos = wrappers[call.func.id]
                if pos < len(call.args):
                    arg = call.args[pos]
            if arg is None or not isinstance(arg, ast.Constant) \
                    or not isinstance(arg.value, str):
                continue
            name = arg.value
            if not _NAME_RE.match(name) or "*" in name:
                continue
            if not _booked(name, exact, patterns):
                findings.append(Finding(
                    "GL009", module.relpath, call.lineno, call.col_offset,
                    f"registry lookup reads {name!r} but no producer books "
                    f"it; the field can only ever be missing (a typo'd "
                    f"console/consumer selector fails silently)",
                    scope=module.scope_at(call)))

    # --- producers vs. the documented plane tables --------------------------
    doc = ctx.doc_text(_DOC_PATH)
    if doc is not None:
        wildcards = _doc_wildcards(doc)
        undocumented: List[Tuple[str, Tuple[str, int]]] = []
        for name, site in list(exact.items()) + list(patterns.items()):
            if site[0].startswith("autodist_tpu/") \
                    and not _documented(name, doc, wildcards):
                undocumented.append((name, site))
        for name, (path, line) in sorted(undocumented,
                                         key=lambda e: (e[1][0], e[1][1])):
            mod = program.info_for(path)
            findings.append(Finding(
                "GL009", path, line, 0,
                f"metric/span name {name!r} is booked here but absent from "
                f"{_DOC_PATH}'s plane tables; operators and alert files are "
                f"written against that catalog — document it (or the "
                f"family it belongs to)",
                scope=mod.module.scope_at(line) if mod else ""))
    return findings
