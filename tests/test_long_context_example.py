"""The long-context example (examples/long_context_lm.py) runs end-to-end on
tiny shapes: single-mesh flash/dot path and the sequence-parallel (ring) path.
The measured ceilings it reproduces on a chip are documented in the README."""

import examples.long_context_lm as lc
from shardmap_compat import requires_shard_map


def test_long_context_example_single_mesh():
    rate = lc.main(["--seq_len", "256", "--batch_size", "4", "--steps", "2",
                    "--d_model", "64", "--n_layers", "2", "--vocab", "256"])
    assert rate > 0


@requires_shard_map
def test_long_context_example_sequence_parallel():
    rate = lc.main(["--seq_len", "256", "--batch_size", "4", "--steps", "2",
                    "--d_model", "64", "--n_layers", "2", "--vocab", "256",
                    "--seq_axis", "2"])
    assert rate > 0
