"""Wire codec property tests + zero-copy framing plane.

The typed codec (``parallel/wire.py``) grew a scatter-gather face in the
zero-copy PR: ``encode_parts`` (borrowed ndarray buffers, byte-identical to
``encode``), ``decode(copy=False)`` (tensors alias the receive buffer), the
version-byte frame header, the refcount-gated recycled receive buffer, and
the overlapped push/pull client. These tests pin the codec property that
makes all of it safe to mix — SAME BYTES, either face — plus the
malformed-frame rejections and the overlap/serial client value parity.

(Named ``test_codec_wire`` so it sorts inside the tier-1 time window —
the suite's 870s budget truncates the alphabetical tail.)
"""

import socket
import struct
import sys
import threading

import numpy as np
import pytest

from autodist_tpu.parallel import ps_transport as tp
from autodist_tpu.parallel import wire


def _tree_equal(a, b):
    import dataclasses
    if isinstance(a, (np.ndarray, np.generic)) \
            or isinstance(b, (np.ndarray, np.generic)):
        # np scalars legally decode as 0-d arrays (same dtype/shape/bytes).
        a, b = np.asarray(a), np.asarray(b)
        return (a.dtype == b.dtype and a.shape == b.shape
                and np.array_equal(a, b))
    if isinstance(a, (tuple, list)):
        return (type(a) is type(b) and len(a) == len(b)
                and all(_tree_equal(x, y) for x, y in zip(a, b)))
    if isinstance(a, dict):
        return (isinstance(b, dict) and a.keys() == b.keys()
                and all(_tree_equal(v, b[k]) for k, v in a.items()))
    if dataclasses.is_dataclass(a):
        return type(a) is type(b) and all(
            _tree_equal(getattr(a, f.name), getattr(b, f.name))
            for f in dataclasses.fields(a))
    return type(a) is type(b) and a == b


def _vocabulary_cases():
    import jax.numpy as jnp

    from autodist_tpu.parallel.synchronization import EFState

    rng = np.random.RandomState(5)
    return [
        # bfloat16 rides as its true dtype name.
        {"bf16": np.asarray(jnp.arange(6, dtype=jnp.bfloat16).reshape(3, 2))},
        # Big-int escape (beyond i64) nested inside containers.
        ("ok", 1 << 90, [-(1 << 77), 42], {"v": (1 << 63)}),
        # Int (and mixed) dict keys — legal pytree keys.
        {0: "zero", -3: {"w": np.ones((2,), np.float32)}, "s": 1},
        # 0-d and empty arrays keep exact shape/dtype.
        {"scalar": np.float32(0.5), "zero_d": np.zeros((), np.int64),
         "empty": np.zeros((0, 3), np.float64),
         "fortran": np.asfortranarray(rng.randn(4, 5))},
        # Registered dataclass pytree nodes.
        ("ok", {"layer": EFState(error=rng.randn(2, 3, 4))}, None, 12),
    ]


@pytest.mark.parametrize("case", range(len(_vocabulary_cases())))
def test_roundtrip_both_faces_and_copy_modes(case):
    """encode/encode_parts x decode(copy=True/False) are all value-exact and
    BYTE-IDENTICAL on the wire."""
    obj = _vocabulary_cases()[case]
    flat = wire.encode(obj)
    parts = wire.encode_parts(obj)
    assert b"".join(bytes(p) for p in parts) == flat
    for buf in (flat, memoryview(bytearray(flat))):
        for copy in (True, False):
            got = wire.decode(buf, copy=copy)
            assert _tree_equal(got, obj), (copy, got)


def test_encode_parts_borrows_large_arrays():
    """A large C-contiguous tensor's payload part is the array's OWN memory
    (zero serialization copies), and small/non-contiguous ones are inlined."""
    big = np.random.randn(64, 1024).astype(np.float32)   # 256 KiB
    small = np.arange(4, dtype=np.int32)
    parts = wire.encode_parts({"big": big, "small": small})
    borrowed = [p for p in parts if isinstance(p, memoryview)]
    assert len(borrowed) == 1 and borrowed[0].nbytes == big.nbytes
    big[0, 0] = 1234.5   # mutating the source must show through the view
    assert np.frombuffer(borrowed[0], np.float32)[0] == np.float32(1234.5)
    # Fortran-order arrays cannot be borrowed (tobytes reorders): all inline.
    f = np.asfortranarray(np.random.randn(64, 1024))
    assert not any(isinstance(p, memoryview) for p in wire.encode_parts(f))


def test_decode_copy_false_aliases_and_is_readonly():
    a = np.arange(100000, dtype=np.float32)
    buf = bytearray(wire.encode({"a": a}))
    got = wire.decode(memoryview(buf), copy=False)["a"]
    assert not got.flags.writeable
    with pytest.raises(ValueError):
        got[0] = 1.0
    # Aliased, not copied: mutating the buffer shows through.
    struct.pack_into("!f", buf, len(buf) - 4, 7.5)
    assert got[-1] == np.frombuffer(struct.pack("!f", 7.5), np.float32)[0]


def test_malformed_frames_rejected():
    # Truncated payloads at every prefix length of a real message.
    msg = wire.encode(("ok", np.arange(5, dtype=np.int32), "tail"))
    for cut in (0, 1, 5, len(msg) // 2, len(msg) - 1):
        with pytest.raises(wire.WireError):
            wire.decode(msg[:cut])
    # Unknown tag byte.
    with pytest.raises(wire.WireError):
        wire.decode(b"Z")
    # Trailing garbage after a complete message.
    with pytest.raises(wire.WireError):
        wire.decode(msg + b"N")
    # Array payload length disagreeing with shape/dtype (the u64 nbytes field
    # sits right before the 16-byte payload).
    arr_msg = bytearray(wire.encode(np.zeros((4,), np.float32)))
    struct.pack_into("!Q", arr_msg, len(arr_msg) - 24, 999)
    with pytest.raises(wire.WireError):
        wire.decode(bytes(arr_msg))


def test_frame_header_version_byte():
    """The top header byte is the frame version: 0 == today's framing (so old
    peers' frames parse unchanged), anything else is rejected as malformed
    instead of being misparsed as an absurd length."""
    assert tp._frame_len(struct.pack("!Q", 12345)) == 12345
    bad = struct.pack("!Q", (3 << 56) | 10)
    with pytest.raises(wire.WireError):
        tp._frame_len(bad)
    # And the receive path surfaces it as WireError too (socket pair).
    a, b = socket.socketpair()
    try:
        a.sendall(bad + b"0123456789")
        with pytest.raises(wire.WireError):
            tp._recv_msg(b, pool=tp._RecvBuffer())
    finally:
        a.close()
        b.close()


def test_recv_buffer_refcount_gated_reuse():
    pool = tp._RecvBuffer()
    v1 = pool.take(100)
    base_id = id(v1.obj)   # identity only — a real reference would block reuse
    del v1
    # Nothing references the buffer: the next take reuses it.
    v2 = pool.take(200)
    assert id(v2.obj) == base_id
    # An alias (as wire.decode(copy=False) arrays hold) blocks reuse; the old
    # buffer stays alive under the keeper, so the id comparison is sound.
    keeper = np.frombuffer(v2, np.uint8)
    del v2
    v3 = pool.take(100)
    assert id(v3.obj) != base_id
    assert keeper.base is not None  # keeper still aliases the first buffer


def test_scatter_gather_send_interops_with_legacy_receiver():
    """Parts over sendmsg and legacy concat-sendall produce identical frames:
    each side decodes the other. The legacy endpoint is bench.py's shared
    reference implementation, so the interop this test pins is exactly what
    ``bench.py --wire`` measures against."""
    from bench import legacy_wire_recv as legacy_recv
    from bench import legacy_wire_send as legacy_send

    tree = ("apply", {"w": np.random.randn(1000, 64).astype(np.float32),
                      "meta": {"step": 3, "big": 1 << 70}})

    for send_fn, recv_fn in [
            (tp._send_msg, legacy_recv),
            (legacy_send, lambda s: tp._recv_msg(s, pool=tp._RecvBuffer())[0]),
            (tp._send_msg, lambda s: tp._recv_msg(s, pool=tp._RecvBuffer())[0]),
    ]:
        a, b = socket.socketpair()
        try:
            got = []
            t = threading.Thread(target=lambda: got.append(recv_fn(b)))
            t.start()
            send_fn(a, tree)
            t.join(timeout=30)
            assert not t.is_alive()
            assert _tree_equal(got[0], tree)
        finally:
            a.close()
            b.close()


def test_overlapped_client_matches_serial_client():
    """The pipelined push/pull client (second socket, read_min prefetch,
    post-gate revalidation) steps value-identically to the serial client,
    and its version reads are the service's live versions."""
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.parallel.ps_transport import PSServer, RemotePSWorker
    from autodist_tpu.strategy import PS

    params = {"w": np.zeros((8,), np.float32)}
    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(16, 8).astype(np.float32),
             "y": rng.randn(16).astype(np.float32)}

    def loss(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    losses = {}
    for overlap in (False, True):
        ad = AutoDist(strategy_builder=PS(sync=False))
        runner = ad.create_distributed_session(
            loss, params, optax.sgd(0.05), example_batch=batch, num_workers=1)
        runner.init(params)
        server = PSServer(runner, host="127.0.0.1")
        host, port = server.address
        remote = RemotePSWorker(f"{host}:{port}", runner, worker_id=0,
                                overlap=overlap)
        try:
            remote.warmup(batch)
            ls = [float(remote.step(batch, timeout=30)) for _ in range(4)]
            assert remote.last_version_read <= runner.service.version
            # The overlapped client's pull socket really exists/ran.
            if overlap:
                assert remote._pull_client is not None
            losses[overlap] = ls
        finally:
            remote.close()
            server.close()
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-6)


# ------------------------------------------------------------ quantized frames

def _q_header_len(msg: bytes) -> int:
    """Byte offset of the u32 nscales field in a top-level tag-``q`` frame:
    tag + two length-prefixed dtype strings + ndim byte + u64 dims."""
    off = 1
    for _ in range(2):
        (n,) = struct.unpack_from("!I", msg, off)
        off += 4 + n
    ndim = msg[off]
    return off + 1 + 8 * ndim


@pytest.mark.parametrize("wire_dtype", wire.WIRE_DTYPES)
def test_quantized_roundtrip_both_faces(wire_dtype):
    """A quantized frame is byte-identical across encode/encode_parts, and
    BOTH copy modes decode it to the same fresh dense array (dequantize-on-
    decode: the receiver sees exactly ``dequantize(qa)``, original dtype)."""
    rng = np.random.RandomState(7)
    x = (rng.randn(32, 48) * 3).astype(np.float32)
    qa = wire.quantize(x, wire_dtype)
    expect = wire.dequantize(qa)
    assert expect.dtype == x.dtype and expect.shape == x.shape
    flat = wire.encode(qa)
    parts = wire.encode_parts(qa)
    assert b"".join(bytes(p) for p in parts) == flat
    buf = bytearray(flat)
    for copy in (True, False):
        got = wire.decode(memoryview(buf), copy=copy)
        assert isinstance(got, np.ndarray) and got.dtype == x.dtype
        np.testing.assert_array_equal(got, expect)
        # Never aliases the receive buffer in either mode: writable + the
        # source bytes can be scribbled without the decoded value moving.
        assert got.flags.writeable
        got[0, 0] = -1.0


def test_quantized_int8_per_row_scales_bound_error():
    """int8 2-D grads carry one scale PER ROW, so an outlier row cannot
    crush another row's resolution: each row's error stays <= scale/2."""
    x = np.ones((3, 64), np.float32)
    x[0] *= 1e4        # outlier row
    x[1] *= 1e-3       # tiny row — would round to 0 under a tensor scale
    x[2] = 0.0         # all-zero row stores scale 0, payload 0
    qa = wire.quantize(x, "int8")
    assert qa.scale.size == 3
    deq = wire.dequantize(qa)
    for i in range(3):
        assert np.max(np.abs(deq[i] - x[i])) <= qa.scale[i] / 2 + 1e-12
    assert qa.scale[2] == 0.0 and np.all(deq[2] == 0.0)
    # A 1-D gradient gets one per-tensor scale.
    assert wire.quantize(np.ones(1000, np.float32), "int8").scale.size == 1


def test_quantized_payload_borrowed_by_encode_parts():
    """The low-precision payload rides as a zero-copy view of the qdata
    array's own memory under encode_parts (same borrow rule as tag ``a``)."""
    x = np.random.randn(64, 1024).astype(np.float32)
    qa = wire.quantize(x, "int8")       # 64 KiB payload, >= borrow floor
    parts = wire.encode_parts(qa)
    borrowed = [p for p in parts if isinstance(p, memoryview)]
    assert len(borrowed) == 1 and borrowed[0].nbytes == qa.qdata.nbytes
    # And the frame really is smaller than the dense encoding.
    assert len(wire.encode(qa)) < len(wire.encode(x)) / 3


def test_quantized_malformed_frames_rejected():
    x = np.random.randn(16, 16).astype(np.float32)
    msg = wire.encode(wire.quantize(x, "int8"))
    off = _q_header_len(msg)
    # Truncations through the scale section and the payload.
    (nscales,) = struct.unpack_from("!I", msg, off)
    for cut in (off + 2, off + 4 + 4 * nscales - 1, len(msg) - 1):
        with pytest.raises(wire.WireError):
            wire.decode(msg[:cut])
    # A scale count that is neither 1 nor rows.
    bad = bytearray(msg)
    struct.pack_into("!I", bad, off, 5)
    with pytest.raises(wire.WireError):
        wire.decode(bytes(bad))
    # Payload length disagreeing with shape/dtype.
    bad = bytearray(msg)
    struct.pack_into("!Q", bad, off + 4 + 4 * nscales, 999)
    with pytest.raises(wire.WireError):
        wire.decode(bytes(bad))
    # Building the frame with a bad scale vector fails at construction.
    with pytest.raises(wire.WireError):
        wire.QuantizedArray(np.zeros((4, 4), np.int8),
                            np.zeros(3, np.float32), np.float32)


def test_sparse_rows_roundtrip_and_densify():
    """The row-sparse push frame (indices + rows + dense shape) round-trips
    byte-identically through both faces, and server-side densify scatters
    EXACTLY — duplicate indices accumulate."""
    from autodist_tpu.parallel.synchronization import (SparseRows,
                                                       densify_sparse_rows)

    rng = np.random.RandomState(3)
    sp = SparseRows(indices=np.array([2, 7, 2], np.int64),
                    rows=rng.randn(3, 5).astype(np.float32),
                    shape=(10, 5))
    flat = wire.encode({"emb": sp})
    parts = wire.encode_parts({"emb": sp})
    assert b"".join(bytes(p) for p in parts) == flat
    got = wire.decode(flat)["emb"]
    assert isinstance(got, SparseRows)
    np.testing.assert_array_equal(got.indices, sp.indices)
    np.testing.assert_array_equal(got.rows, sp.rows)
    assert tuple(got.shape) == (10, 5)
    dense = densify_sparse_rows({"emb": got})["emb"]
    expect = np.zeros((10, 5), np.float32)
    np.add.at(expect, sp.indices, sp.rows)
    np.testing.assert_array_equal(dense, expect)
    assert np.count_nonzero(np.abs(dense).sum(axis=1)) == 2
    # Truncated index/row sections are rejected like any malformed frame.
    for cut in (len(flat) // 3, len(flat) - 2):
        with pytest.raises(wire.WireError):
            wire.decode(flat[:cut])
