"""Sentiment classifier — the sparse-gradient demo.

Port of reference ``examples/sentiment_classifier.py`` (embedding + sparse grads):
a bag-of-embeddings classifier whose embedding table receives row-sparse updates.
Under the default Parallax-style routing the table goes to PS placement while the
dense head uses gradient all-reduce.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu import AutoDist
from autodist_tpu.strategy import Parallax

VOCAB = 10_000
DIM = 64
SEQ = 32


def main(steps: int = 30, batch_size: int = 64):
    rng = np.random.RandomState(0)
    params = {
        "embedding": jnp.asarray(rng.randn(VOCAB, DIM) * 0.1, jnp.float32),
        "w": jnp.asarray(rng.randn(DIM, 1) * 0.1, jnp.float32),
        "b": jnp.zeros((1,)),
    }

    def loss_fn(p, batch):
        emb = jnp.take(p["embedding"], batch["tokens"], axis=0)   # [B, S, D]
        pooled = emb.mean(axis=1)
        logits = (pooled @ p["w"] + p["b"])[:, 0]
        labels = batch["labels"].astype(jnp.float32)
        return jnp.mean(jnp.maximum(logits, 0) - logits * labels
                        + jnp.log1p(jnp.exp(-jnp.abs(logits))))

    tokens = rng.randint(0, VOCAB, size=(512, SEQ)).astype(np.int32)
    labels = rng.randint(0, 2, size=(512,)).astype(np.int32)

    ad = AutoDist(strategy_builder=Parallax())
    step = ad.function(loss_fn, params, optax.adam(1e-2),
                       example_batch={"tokens": tokens[:8], "labels": labels[:8]})

    losses = []
    for i in range(steps):
        sl = slice((i * batch_size) % 512, (i * batch_size) % 512 + batch_size)
        losses.append(float(step({"tokens": tokens[sl], "labels": labels[sl]})))
        if i % 10 == 0:
            print(f"step {i}: loss={losses[-1]:.4f}")

    kinds = {n.var_name: n.WhichOneof("synchronizer") for n in ad._strategy.node_config}
    print("routing:", kinds)
    assert kinds["embedding"] == "ps_synchronizer", "sparse table should go to PS"
    assert losses[-1] < losses[0]
    return losses


if __name__ == "__main__":
    main()
