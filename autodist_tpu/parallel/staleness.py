"""Host-driven async / bounded-staleness execution.

The reference's PS synchronizer supported three update regimes
(``kernel/synchronization/ps_synchronizer.py``): synchronous (ConditionalAccumulator
taking ``num_workers`` gradients, chief-token FIFO queue of size 1, ``:335-385``),
bounded staleness (token queues of size ``staleness`` letting a fast worker run ahead,
``:387-458``), and fully async (``sync=False`` — each worker's gradient applied as it
arrives). SPMD collectives are inherently synchronous, so the two non-sync regimes
cannot live inside one XLA program; they are re-designed here as a **host-driven
dispatch loop** (SURVEY.md §7.3 hard part #1):

- :class:`ParameterService` owns the train state (on the mesh, sharded per the plan)
  and applies one worker's gradient at a time through a jitted update — the PS apply.
- :class:`StalenessController` reifies the reference's token queues as a condition
  variable over per-worker completed-step counts: a worker may *start* a step only
  while ``its_steps - min(all_steps) < staleness`` (so it can finish exactly
  ``staleness`` steps ahead before blocking — the behavior the reference asserts in
  ``tests/integration/cases/c9.py:92-126``). ``staleness == 0`` with ``sync=False``
  is fully async (unbounded).
- :class:`AsyncPSRunner` gives each logical worker (reference: one process per node,
  ``coordinator.py:66-90``) a handle whose ``step(batch)`` reads the *current* —
  possibly newer than its last read, never blocked on other workers' compute —
  parameters, computes gradients, and pushes them. jax.Array immutability gives
  stale-snapshot semantics for free: a worker holding an old reference keeps a
  consistent old version (state donation is disabled for exactly this reason).
"""

import contextlib
import math
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from autodist_tpu import telemetry
from autodist_tpu.parallel import recovery as _recovery
from autodist_tpu.runner import DistributedRunner, TrainState
from autodist_tpu.testing import faults as _faults
from autodist_tpu.telemetry.metrics import COUNT_BUCKETS, Histogram
from autodist_tpu.utils import logging
from autodist_tpu.testing.sanitizer import san_lock, san_condition

PyTree = Any

# Default server-side apply shard count when ZeRO is requested without an
# explicit count (AUTODIST_ZERO=1 / zero=True): enough fan-out to overlap
# several workers' applies without flooding a small chief with threads.
DEFAULT_PS_SHARDS = 4


def _named_leaves(tree: PyTree) -> Dict[str, Any]:
    """Flatten a pytree to ``{path-name: leaf}`` (the PS shard plane's
    addressing — the same '/'-joined names the Saver uses)."""
    from autodist_tpu.model_spec import _path_name
    return {_path_name(path): leaf
            for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]}


def _assign_shards(named: Dict[str, Any], shards: int) -> List[List[str]]:
    """Partition leaf names into ``<= shards`` balanced groups (greedy
    largest-first by byte size, deterministic: ties break by name)."""
    shards = max(1, min(int(shards), len(named)) if named else 1)
    sized = sorted(named.items(),
                   key=lambda kv: (-int(getattr(kv[1], "nbytes", 0) or 0),
                                   kv[0]))
    bins: List[List[str]] = [[] for _ in range(shards)]
    loads = [0] * shards
    for name, leaf in sized:
        s = loads.index(min(loads))
        bins[s].append(name)
        loads[s] += int(getattr(leaf, "nbytes", 0) or 0) or 1
    return [sorted(b) for b in bins if b]


class StalenessTimeout(TimeoutError):
    """A gated worker step did not become runnable within the timeout."""


class WorkerEvicted(RuntimeError):
    """The worker was retired from the staleness gate while (or before)
    waiting to step — the auto-eviction path's typed RPC failure. The
    transport ships it across the wire; :class:`RemotePSWorker` reacts by
    re-registering (seeded at the slowest live count) and catching up on
    the chief's live params, so an eviction costs the worker one rejoin,
    never the run."""


_STALENESS_TEL = None


def _staleness_registry_hist():
    """Cached process-global ``ps.staleness`` registry histogram, ``None``
    while telemetry is disabled — one enabled-check per gate entry instead
    of a registry get-or-create lookup."""
    if not telemetry.enabled():
        return None
    global _STALENESS_TEL
    if _STALENESS_TEL is None:
        _STALENESS_TEL = telemetry.histogram("ps.staleness", COUNT_BUCKETS)
    return _STALENESS_TEL


# Largest jump past the current gate size one register() may request; bounds
# the per-call slot allocation against malformed/hostile ids (the gate list
# grows one element at a time under its lock).
_MAX_SLOT_GROWTH = 4096


class StalenessController:
    """Bounded-staleness gate over per-worker completed-step counts.

    Token-queue parity (reference ``ps_synchronizer.py:387-458``): with bound ``s`` a
    worker can complete exactly ``s`` more steps than the slowest worker before its
    next ``start_step`` blocks. ``bound=None`` means unbounded (fully async).
    """

    def __init__(self, num_workers: int, staleness: int = 0):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        if staleness < 0:
            raise ValueError("staleness must be >= 0")
        self._bound = staleness if staleness > 0 else math.inf
        self._steps = [0] * num_workers
        self._retired = set()
        # Per-worker staleness-lag distribution, observed at every gate entry
        # (how many steps ahead of the slowest live worker each start_step
        # found this worker). Feeds the PS `stats` opcode and the per-worker
        # `PSServer closed:` breakdown; always recorded — a dict lookup and a
        # bisect per gate entry, far off any hot path.
        self._lag_hists: Dict[int, Histogram] = {}
        # Per-slot generation, bumped by register(): lets a disconnect handler
        # that observed an OLD occupant of a slot retire conditionally, so a
        # stale socket's death can never retire the live replacement.
        self._generation: dict = {}
        self._cond = san_condition()

    @property
    def steps(self):
        with self._cond:
            return list(self._steps)

    @property
    def bound(self):
        """The staleness bound (``math.inf`` when unbounded/fully async)."""
        return self._bound

    def live_lags(self) -> Dict[int, int]:
        """Instantaneous per-worker lag: completed steps ahead of the slowest
        LIVE worker, for every live worker, under one lock hold. A worker at
        the bound is parked; a worker at 0 while others sit at the bound is
        the straggler they are waiting for — the PS watchdog's signal."""
        with self._cond:
            live = {i: s for i, s in enumerate(self._steps)
                    if i not in self._retired}
        if not live:
            return {}
        slowest = min(live.values())
        return {i: s - slowest for i, s in live.items()}

    def _runnable(self, worker_id: int) -> bool:
        live = [s for i, s in enumerate(self._steps) if i not in self._retired]
        return not live or self._steps[worker_id] - min(live) < self._bound

    def generation(self, worker_id: int) -> int:
        """Current occupancy generation of a slot (bumped by register())."""
        with self._cond:
            return self._generation.get(worker_id, 0)

    def slot_state(self, worker_id: Optional[int]) -> str:
        """``"live"`` / ``"retired"`` / ``"new"`` (never-allocated or None)
        — lets :meth:`AsyncPSRunner.add_worker` tell a REJOIN (re-admitting
        a retired slot: the recovery plane's bookkeeping) from a first
        registration or an idempotent retry."""
        with self._cond:
            if worker_id is None or worker_id < 0 \
                    or worker_id >= len(self._steps):
                return "new"
            return "retired" if worker_id in self._retired else "live"

    def retire(self, worker_id: int, generation: Optional[int] = None) -> bool:
        """Remove a dead worker from the gate (its frozen step count would
        otherwise pin min(steps) and wedge every other worker at the bound).
        Used by the PS transport when a remote worker disconnects.

        With ``generation``, the retire applies only if the slot's occupancy
        generation still matches — a handler holding a long-dead socket for a
        slot that a replacement has since re-registered must not retire the
        live replacement.

        Returns True only when this call actually retired a LIVE worker —
        a stale-generation ignore or an already-retired slot returns False,
        so callers' bookkeeping (the recovery plane's eviction records)
        tracks gate ACTIONS, never no-ops."""
        with self._cond:
            if generation is not None \
                    and generation != self._generation.get(worker_id, 0):
                logging.info("Ignoring stale retire of worker %d (generation "
                             "%d != current %d)", worker_id, generation,
                             self._generation.get(worker_id, 0))
                return False
            if worker_id in self._retired:
                return False
            self._retired.add(worker_id)
            self._cond.notify_all()
            return True

    def register(self, worker_id: Optional[int] = None) -> int:
        """Admit a worker to the gate mid-run — a replacement for a retired
        worker (same or new id) or an elastic addition (``None`` allocates the
        next id). Returns the admitted id. Registering a slot that is already
        live is an idempotent no-op (a client retrying after a transport
        hiccup must not reset a live worker's count — that would let it run
        past the staleness bound).

        The admitted worker's completed-step count seeds at the slowest LIVE
        worker's count: seeding at 0 would pin ``min(steps)`` and wedge every
        other worker at the bound until the newcomer caught up; seeding at the
        max would let it surge ``bound`` steps ahead of the true slowest. (The
        reference had no elastic membership at all — fail-fast only,
        ``coordinator.py:98-110``.)"""
        with self._cond:
            if worker_id is not None and worker_id < 0:
                raise ValueError(f"worker_id must be >= 0, got {worker_id}")
            if worker_id is not None \
                    and worker_id > len(self._steps) + _MAX_SLOT_GROWTH:
                # The gate grows one slot at a time: an absurd id (e.g. a
                # malformed or hostile register over the transport) would
                # allocate that many slots under the lock and wedge/OOM the
                # chief. Legitimate elastic growth is incremental.
                raise ValueError(
                    f"worker_id {worker_id} is beyond the gate's current "
                    f"{len(self._steps)} slot(s) + growth margin "
                    f"{_MAX_SLOT_GROWTH}; register sequentially or pass None "
                    f"to allocate the next id")
            if worker_id is not None and worker_id < len(self._steps) \
                    and worker_id not in self._retired:
                # Already live: keep the count (a reseed would un-gate it) but
                # DO bump the generation — a reconnecting client's retry means
                # the old connection is dead, and its deferred retire must not
                # remove the live reconnection.
                self._generation[worker_id] = \
                    self._generation.get(worker_id, 0) + 1
                return worker_id
            if worker_id is None:
                worker_id = len(self._steps)
            while worker_id >= len(self._steps):
                # Intermediate brand-new slots stay retired until registered.
                self._steps.append(0)
                self._retired.add(len(self._steps) - 1)
            self._retired.discard(worker_id)
            self._generation[worker_id] = self._generation.get(worker_id, 0) + 1
            live = [s for i, s in enumerate(self._steps)
                    if i not in self._retired and i != worker_id]
            if live:
                self._steps[worker_id] = min(live)
            self._cond.notify_all()
            return worker_id

    def register_with_generation(self, worker_id: Optional[int] = None):
        """:meth:`register` plus the slot's resulting occupancy generation,
        read in the SAME critical section (``Condition()`` is RLock-backed, so
        the nested acquire is safe). The transport binds a connection's retire
        token to this pair; two separate calls would let a near-simultaneous
        second registration bump the generation in between, handing this
        caller the LIVE occupant's token — whose eventual stale retire would
        kill the live worker."""
        with self._cond:
            wid = self.register(worker_id)
            return wid, self._generation.get(wid, 0)

    def start_step(self, worker_id: int, timeout: Optional[float] = None) -> int:
        """Block until the worker is within the staleness bound.

        Returns the slot's occupancy generation, read under the SAME lock that
        admitted the step — the PS transport binds a connection's retire token
        to it, and a read outside this critical section could race a concurrent
        re-registration and hand back the replacement's token.

        Raises :class:`StalenessTimeout` if the bound does not open in ``timeout``
        seconds (the reference's queue dequeue blocked forever; a timeout keeps the
        failure mode debuggable).
        """
        with self._cond:
            live = [s for i, s in enumerate(self._steps)
                    if i not in self._retired]
            lag = self._steps[worker_id] - min(live) if live else 0
            hist = self._lag_hists.get(worker_id)
            if hist is None:
                hist = self._lag_hists[worker_id] = Histogram(
                    f"ps.staleness.worker{worker_id}", COUNT_BUCKETS)
            hist.observe(lag)
            tel = _staleness_registry_hist()
            if tel is not None:
                tel.observe(lag)
            with telemetry.span("ps.gate_wait", worker=worker_id):
                # A retire (auto-eviction, disconnect) WAKES a parked wait:
                # the evicted worker's pending gate RPC must fail typed so
                # its client can rejoin, instead of parking until timeout on
                # a slot that no longer gates anyone.
                if not self._cond.wait_for(
                        lambda: (worker_id in self._retired
                                 or self._runnable(worker_id)), timeout):
                    raise StalenessTimeout(
                        f"worker {worker_id} at step {self._steps[worker_id]} "
                        f"still >= {self._bound} ahead of the slowest worker "
                        f"after {timeout}s")
                if worker_id in self._retired:
                    raise WorkerEvicted(
                        f"worker {worker_id} was retired from the staleness "
                        f"gate (evicted or disconnected); re-register to "
                        f"rejoin")
            return self._generation.get(worker_id, 0)

    def finish_step(self, worker_id: int) -> int:
        """Advance the worker's completed-step count; returns the slot's
        occupancy generation (same atomicity rationale as :meth:`start_step`)."""
        with self._cond:
            self._steps[worker_id] += 1
            self._cond.notify_all()
            return self._generation.get(worker_id, 0)

    def staleness_histograms(self) -> Dict[int, Histogram]:
        """Per-worker gate-entry lag histograms (live objects; the PSServer
        close summary formats them)."""
        with self._cond:
            return dict(sorted(self._lag_hists.items()))

    def staleness_snapshot(self) -> Dict[int, Dict]:
        """Wire-encodable per-worker lag snapshots ``{worker_id: hist-dict}``
        — the staleness half of the ``stats`` opcode's per-worker payload."""
        with self._cond:
            hists = dict(self._lag_hists)
        return {wid: h.snapshot() for wid, h in sorted(hists.items())}


class ParameterService:
    """The PS: owns the train state, serializes gradient application.

    Counterpart of the reference's PS-device accumulators + update ops
    (``ps_synchronizer.py:556-633``), with the accumulator replaced by one-at-a-time
    application (async semantics: no cross-worker averaging).
    """

    def __init__(self, state: TrainState, apply_fn):
        self._state = state
        self._apply_fn = apply_fn
        # A Condition, not a bare Lock: read_min (the overlapped transport
        # client's prefetch) waits on version advancement; every state
        # replacement notifies. `with self._lock:` works unchanged.
        self._lock = san_condition()
        # Serializes WRITERS (apply/reset/adopt) separately from the snapshot
        # Condition above: the gradient application's device execution runs
        # under only this mutex, so readers (read/read_if_newer/read_min —
        # the transport's pull hot path) block for the brief state swap, not
        # for a whole apply program. Order: _write_mutex -> _lock, never the
        # reverse — declared for graftlint so an inverted path fails lint
        # (GL002) instead of deadlocking a chief under load.
        # graftlint: lock-order=_write_mutex->_lock
        self._write_mutex = san_lock()
        # Generation counter: bumps on EVERY state replacement (apply, reset,
        # adopt) and is never reused, so version equality implies state
        # identity — the contract read_if_newer's "not modified" answer (and
        # any transport-side cache built on it) depends on. The applied-update
        # count is tracked separately for the adopt() guard.
        self._version = 0
        self._updates = 0

    def reset(self, state: TrainState):
        """Replace the state (checkpoint restore). The update count restarts;
        the version keeps counting so stale cached pulls can never alias."""
        with self._write_mutex:
            with self._lock:
                self._state = state
                self._version += 1
                self._updates = 0
                self._lock.notify_all()

    @property
    def version(self) -> int:
        return self._version

    @property
    def state(self) -> TrainState:
        return self._state

    def read(self):
        """Consistent snapshot of (params, ef_state, version) under one lock hold.
        jax.Arrays are immutable, so the returned references stay consistent however
        far the service advances afterwards."""
        with self._lock:
            return self._state.params, self._state.ef_state, self._version

    def read_if_newer(self, version: int):
        """Conditional :meth:`read`: ``(params, ef_state, version)`` when the
        service has advanced past ``version``, else ``(None, None, version)``.
        The version check and the snapshot share one lock hold, so "not
        modified" is exact — the caller's copy at ``version`` IS the current
        state. This is the transport's bandwidth valve (the reference's proxy
        variables cached reads the same way, proxy_variable.py:74-114): a
        worker whose gate opened with no intervening applies skips re-pulling
        an identical parameter tree."""
        with self._lock:
            if self._version == version:
                return None, None, self._version
            return self._state.params, self._state.ef_state, self._version

    def read_min(self, min_version: int, have_version: int,
                 timeout: Optional[float] = None):
        """:meth:`read_if_newer` that first waits (up to ``timeout`` seconds)
        for the service to reach ``min_version``. The overlapped PS client
        prefetches with ``min_version = last_read + 1`` just before pushing
        its gradients: the reply is released the moment its own apply lands,
        so the parameter download overlaps the push and the gate round-trips
        instead of following them. On timeout the CURRENT state is returned
        (never an error) — the client revalidates against the live version
        anyway, so a missed floor only costs the overlap, not correctness."""
        with self._lock:
            self._lock.wait_for(lambda: self._version >= min_version, timeout)
            if self._version == have_version:
                return None, None, self._version
            return self._state.params, self._state.ef_state, self._version

    def apply(self, grads: PyTree) -> int:
        """Apply one worker's gradients; returns the new version.

        The device execution runs under the writer mutex only — we are the
        sole state replacer while holding it, so reading ``self._state``
        without the snapshot lock is safe, and concurrent readers keep
        snapshotting the pre-apply state (exactly what they would have seen
        mid-apply anyway) instead of stalling behind a whole apply program."""
        with self._write_mutex:
            with telemetry.span("ps.apply"):
                new_state = self._apply_fn(self._state, grads)
            with self._lock:
                self._state = new_state
                self._version += 1
                self._updates += 1
                self._lock.notify_all()
                return self._version

    @property
    def updates_applied(self) -> int:
        return self._updates

    def adopt(self, state: TrainState, place_fn) -> None:
        """Atomically adopt a foreign state iff no updates have been applied yet
        (the checkpoint-restore pattern). The identity check, version check, and
        replacement happen under the writer mutex so a concurrently stepping
        worker cannot slip an ``apply`` between check and reset."""
        with self._write_mutex:
            if state is self._state:
                return
            if self._updates != 0:
                raise RuntimeError(
                    "AsyncPSRunner.run was handed a state that is not the service's "
                    "current state after updates were already applied; use "
                    "restore(state) to adopt a checkpoint explicitly")
            placed = place_fn(state)
            with self._lock:
                self._state = placed
                self._version += 1  # new generation: cached pulls must refetch
                self._lock.notify_all()


class ShardedParameterService(ParameterService):
    """ZeRO-style sharded PS apply: the chief applies each worker's update over
    S concurrent parameter shards instead of one serial whole-tree program.

    The parameter tree is statically partitioned into S balanced groups of
    leaves; each shard owns its own mutex, its own optimizer-state slice
    (``optimizer.init`` over the shard's flat ``{name: leaf}`` sub-dict — the
    same per-leaf math as the whole-tree update for elementwise optimizer
    chains), and its own version counter. ``apply(grads)`` fans the gradient
    out to S tasks on a persistent pool: applies from DIFFERENT workers
    interleave at shard granularity (worker B's shard-0 apply only waits for
    worker A's shard-0, not A's whole tree) — the reference's multi-PS
    placement (one PS device per partition, ``ps_lb_strategy``) re-expressed
    as server-side concurrency.

    Consistency contract: reads are SHARD-GRAINED. A ``read()`` overlapping an
    apply may see some shards updated and others not — exactly the semantics
    of a real multi-endpoint PS, where workers pull each shard independently.
    The aggregate ``version`` bumps once per shard apply (S per full update),
    so version equality still implies byte identity and the conditional-pull
    /prefetch protocol (``read_if_newer``/``read_min``) is unchanged. The
    ``state`` property re-assembles a whole-tree optimizer state (cached per
    version) so checkpoints save UNSHARDED, restorable by any topology.

    Whole-tree writers (``reset``/``adopt``) keep the base class's atomicity:
    ``apply`` registers itself in an in-flight count under ``_write_mutex``,
    and the writers quiesce that count before re-splitting — a restore can
    never land between two shards of one worker's update.

    NOTE: per-shard ``optimizer.update`` is exact for elementwise
    transformations (sgd/momentum/adam-class — everything the async regime
    supports); a cross-leaf coupling like ``clip_by_global_norm`` would see
    per-shard norms. The async PS path already documents per-worker (unsynced)
    updates, so cross-leaf coupling is out of contract there.
    """

    def __init__(self, state: TrainState, optimizer, shards: int, exec_fn):
        """``exec_fn(fn, *args) -> out`` runs one jitted shard program to
        completion (the runner supplies mesh scoping + execution
        serialization); ``shards`` is clamped to the leaf count."""
        super().__init__(state, apply_fn=None)
        self._optimizer = optimizer
        self._exec = exec_fn
        self._params_flat = _named_leaves(state.params)
        leaves, treedef = jax.tree_util.tree_flatten(state.params)
        self._params_treedef = treedef
        self._params_order = list(self._params_flat)  # flatten order == names order
        self._assign = _assign_shards(self._params_flat, shards)
        self.shards = len(self._assign)
        self._shard_mutex = [san_lock() for _ in self._assign]
        self._shard_version = [0] * self.shards
        self._opt_template = state.opt_state
        self._shard_opt = [
            optimizer.init({n: self._params_flat[n] for n in names})
            for names in self._assign]
        self._step0 = int(np.asarray(jax.device_get(state.step)))
        self._assembled: Optional[TrainState] = None
        self._assembled_version = -1
        # Version at which self._state's nested params tree was last rebuilt
        # from the flat map (readers refresh lazily, cached per version).
        self._state_version = self._version
        # Whole-tree applies currently in flight (see class docstring).
        self._inflight = 0
        import optax as _optax
        self._optax = _optax

        def _apply_shard_fn(params_s, opt_s, grads_s):
            updates, new_opt = optimizer.update(grads_s, opt_s, params_s)
            return self._optax.apply_updates(params_s, updates), new_opt

        self._shard_apply = jax.jit(_apply_shard_fn)
        self._pool = ThreadPoolExecutor(max_workers=self.shards,
                                        thread_name_prefix="ps-shard-apply")
        logging.info("ShardedParameterService: %d apply shard(s) over %d "
                     "leaves", self.shards, len(self._params_flat))

    # ------------------------------------------------------------ shard plane
    @property
    def shard_versions(self) -> List[int]:
        """Per-shard apply counters (the staleness/stats plane's breakdown of
        the aggregate ``version``)."""
        with self._lock:
            return list(self._shard_version)

    def _rebuild_params(self):
        """Nested params tree from the flat map (callers hold ``_lock``)."""
        return jax.tree_util.tree_unflatten(
            self._params_treedef,
            [self._params_flat[n] for n in self._params_order])

    def _refresh_state_locked(self) -> TrainState:
        """``self._state`` with its params tree current at ``self._version``,
        rebuilding from the flat map at most once per version (callers hold
        ``_lock``). Shard applies only touch the flat map, so the O(leaves)
        unflatten is paid by the first reader after a change, not once per
        shard inside the apply path."""
        if self._state_version != self._version:
            base = self._state
            self._state = TrainState(
                step=base.step, params=self._rebuild_params(),
                opt_state=base.opt_state, ef_state=base.ef_state,
                plan=base.plan)
            self._state_version = self._version
        return self._state

    def _apply_one_shard(self, s: int, flat_grads: Dict[str, Any]):
        names = self._assign[s]
        grads_s = {n: flat_grads[n] for n in names}
        with self._shard_mutex[s]:
            with self._lock:
                params_s = {n: self._params_flat[n] for n in names}
                opt_s = self._shard_opt[s]
            with telemetry.span("ps.apply", shard=s, shards=self.shards):
                new_params_s, new_opt_s = self._exec(
                    self._shard_apply, params_s, opt_s, grads_s)
            with self._lock:
                self._params_flat.update(new_params_s)
                self._shard_opt[s] = new_opt_s
                self._shard_version[s] += 1
                self._version += 1
                self._lock.notify_all()
                if telemetry.enabled():
                    telemetry.gauge(f"ps.shard_version.s{s}").set(
                        self._shard_version[s])

    def apply(self, grads: PyTree) -> int:
        """Apply one worker's gradients across S concurrent shard programs;
        returns the aggregate version after ALL shards landed (so the push
        ack still means "my whole update is in", and finish_step ordering is
        unchanged).

        Registration under ``_write_mutex`` keeps whole-tree writers atomic:
        a concurrent ``reset``/``adopt`` either runs before this update's
        first shard or after its last, never in between — and different
        workers' applies still interleave at shard granularity (the mutex is
        held only for the counter bump, never across device work)."""
        flat_grads = _named_leaves(grads)
        with self._write_mutex:
            with self._lock:
                self._inflight += 1
        try:
            futures = [self._pool.submit(self._apply_one_shard, s, flat_grads)
                       for s in range(self.shards)]
            for f in futures:
                f.result()  # re-raise a shard failure to the pushing worker
        finally:
            with self._lock:
                self._inflight -= 1
                self._lock.notify_all()
        with self._lock:
            self._updates += 1
            return self._version

    # ----------------------------------------------------------- readers
    # The base class's readers return self._state directly; here the nested
    # params tree is rebuilt lazily from the flat map (cached per version),
    # so each override refreshes before snapshotting. Same lock discipline,
    # same return contracts.
    def read(self):
        with self._lock:
            st = self._refresh_state_locked()
            return st.params, st.ef_state, self._version

    def read_if_newer(self, version: int):
        with self._lock:
            if self._version == version:
                return None, None, self._version
            st = self._refresh_state_locked()
            return st.params, st.ef_state, self._version

    def read_min(self, min_version: int, have_version: int,
                 timeout: Optional[float] = None):
        with self._lock:
            self._lock.wait_for(lambda: self._version >= min_version, timeout)
            if self._version == have_version:
                return None, None, self._version
            st = self._refresh_state_locked()
            return st.params, st.ef_state, self._version

    # -------------------------------------------------- whole-tree interface
    @property
    def state(self) -> TrainState:
        """The assembled whole-tree state: params from the flat map, optimizer
        state RE-ASSEMBLED into the original (unsharded) structure by leaf
        name — checkpoints save exactly what an unsharded service would
        (gather-on-save), so they restore into any topology. Cached per
        version (the drop-in ``run()`` loop reads this every step)."""
        with self._lock:
            if self._assembled is not None \
                    and self._assembled_version == self._version:
                return self._assembled
            base = self._refresh_state_locked()
            shard_opt = list(self._shard_opt)
            version = self._version
            step = np.asarray(self._step0 + self._updates, np.int32)
        from autodist_tpu.model_spec import _path_name
        by_name: Dict[str, Any] = {}
        for opt_s in shard_opt:
            by_name.update(_named_leaves(opt_s))
        merged_opt = jax.tree_util.tree_map_with_path(
            lambda path, leaf: by_name.get(_path_name(path), leaf),
            self._opt_template)
        assembled = TrainState(step=step, params=base.params,
                               opt_state=merged_opt, ef_state=base.ef_state,
                               plan=base.plan)
        with self._lock:
            if self._version == version:
                self._assembled, self._assembled_version = assembled, version
        return assembled

    def _resplit_locked(self, state: TrainState, step0: int):
        """Adopt a whole-tree state: re-seed the flat param map and split its
        (unsharded) optimizer state back into per-shard slices by leaf name.
        Callers hold ``_write_mutex`` + every shard mutex and pass the
        already-read step counter (``step0``) so no device readback happens
        inside the critical section (GL001)."""
        from autodist_tpu.model_spec import _path_name
        incoming_opt = _named_leaves(state.opt_state)
        new_shard_opt = [
            jax.tree_util.tree_map_with_path(
                lambda path, leaf: incoming_opt.get(_path_name(path), leaf),
                opt_s)
            for opt_s in self._shard_opt]
        with self._lock:
            self._params_flat = _named_leaves(state.params)
            self._shard_opt = new_shard_opt
            self._opt_template = state.opt_state
            self._state = state
            self._step0 = step0
            self._version += 1
            self._state_version = self._version  # adopted tree IS current
            self._updates = 0
            self._assembled = None
            self._lock.notify_all()

    @contextlib.contextmanager
    def _all_shard_mutexes(self):
        # Ascending order everywhere; shard tasks only ever hold ONE, so the
        # whole-tree writers (reset/adopt) cannot deadlock against them.
        with contextlib.ExitStack() as stack:
            for m in self._shard_mutex:
                stack.enter_context(m)
            yield

    def _quiesce_locked(self):
        """Wait (bounded) for in-flight whole-tree applies to finish. Callers
        hold ``_write_mutex`` — new applies cannot register — so the count
        only falls. A shard program that wedges for 10 minutes is already a
        dead chief; raising names the writer instead of deadlocking it."""
        with self._lock:
            if not self._lock.wait_for(lambda: self._inflight == 0,
                                       timeout=600.0):
                raise RuntimeError(
                    "sharded PS apply did not quiesce within 600s; cannot "
                    "safely reset/adopt a whole-tree state")

    def reset(self, state: TrainState):
        step0 = int(np.asarray(jax.device_get(state.step)))  # before any lock
        with self._write_mutex:
            self._quiesce_locked()
            with self._all_shard_mutexes():
                self._resplit_locked(state, step0)

    def adopt(self, state: TrainState, place_fn) -> None:
        step0 = int(np.asarray(jax.device_get(state.step)))  # before any lock
        with self._write_mutex:
            self._quiesce_locked()
            if state is self._state or state is self._assembled:
                return
            if self._updates != 0:
                raise RuntimeError(
                    "AsyncPSRunner.run was handed a state that is not the "
                    "service's current state after updates were already "
                    "applied; use restore(state) to adopt a checkpoint "
                    "explicitly")
            placed = place_fn(state)
            with self._all_shard_mutexes():
                self._resplit_locked(placed, step0)

    def close(self):
        """Release the shard-apply pool (idle threads otherwise linger for
        the process's life)."""
        self._pool.shutdown(wait=False)


class AsyncWorker:
    """One logical worker's handle (reference: one re-executed user script per node)."""

    def __init__(self, runner: "AsyncPSRunner", worker_id: int):
        self._runner = runner
        self.worker_id = worker_id
        self.steps_completed = 0
        self.last_version_read = -1

    def step(self, batch: PyTree, timeout: Optional[float] = None):
        """One gated async step: wait for the staleness bound, pull current params,
        compute local gradients, push to the PS. Returns the local loss (or
        ``(loss, aux)`` when the runner was built with ``has_aux``)."""
        r = self._runner
        if _faults.armed():
            # Chaos harness (testing/faults.py): deterministic hang/crash
            # points so the self-heal tests drive the REAL gate/eviction
            # machinery. Un-armed cost: one module-global read.
            _faults.maybe_hang(step=self.steps_completed,
                               worker=self.worker_id)
            if _faults.should_fire("worker_crash", step=self.steps_completed,
                                   worker=self.worker_id):
                r.controller.retire(self.worker_id)
                raise _faults.WorkerCrashed(
                    f"worker {self.worker_id} crashed by fault injection at "
                    f"step {self.steps_completed}")
        r.controller.start_step(self.worker_id, timeout)
        params, ef_state, version = r.service.read()
        self.last_version_read = version
        sharded = r.shard_batch(batch)
        r._maybe_dump_async_graphs(params, sharded, ef_state)
        with r.mesh:
            # Gradient programs carry cross-replica collectives: run one at a
            # time to completion (see _collective_lock) so two workers' steps
            # can never interleave a rendezvous.
            # graftlint: disable=GL001(this lock EXISTS to serialize execution — the PR 2 deadlock fix; holding it across the dispatch is the point)
            with r._collective_lock:
                grads, loss, aux, _ef = r.grad_fn(params, sharded, ef_state)
                jax.block_until_ready((grads, loss, aux, _ef))
            r.service.apply(grads)
        r.controller.finish_step(self.worker_id)
        self.steps_completed += 1
        if r.has_aux:
            return loss, aux
        return loss


class AsyncPSRunner(DistributedRunner):
    """Async / bounded-staleness variant of the runner.

    Selected when the compiled strategy requests a non-synchronous PS regime
    (``sync=False`` or ``staleness>0`` on any PSSynchronizer node). The ``run``
    interface stays drop-in with :class:`DistributedRunner` — the state argument is
    accepted but the service's internal state is authoritative — so
    ``AutoDist.function`` works unchanged; multi-worker tests drive
    :meth:`worker` handles directly.
    """

    # Default gate timeout for the drop-in run() path: converts a mis-sized worker
    # pool (workers that never step) from a silent hang into a diagnosable error.
    DEFAULT_STEP_TIMEOUT = 600.0

    # No fused multi-step scan here: every step round-trips through the
    # parameter service (pull -> grad -> apply under the staleness gate), so
    # there is no K-step on-device program to build. run_many raises (see
    # DistributedRunner.run_many) and train(unroll=K) falls back to per-step.
    supports_run_many = False

    def __init__(self, compiled_strategy, model_spec, loss_fn, optimizer,
                 mesh=None, has_aux: bool = False, num_workers: int = 1,
                 donate_state: bool = False, plan=None,
                 ps_address: Optional[str] = None,
                 zero: Optional[Any] = None):
        # Never donate: stale workers hold references to old param buffers.
        super().__init__(compiled_strategy, model_spec, loss_fn, optimizer,
                         mesh=mesh, has_aux=has_aux, donate_state=False,
                         plan=plan, zero=zero)
        # The async regime's ZeRO knob is the SERVER-SIDE apply shard count
        # (the opt state lives on the chief, not spread over an SPMD mesh):
        # zero=N>1 picks N shards, zero=1/True the default fan-out, 0 off.
        self.ps_shards = self.zero if self.zero > 1 \
            else (DEFAULT_PS_SHARDS if self.zero else 1)
        if self.plan.has_compression:
            raise NotImplementedError(
                "Gradient compression is not supported in the async PS mode")
        self.num_workers = max(1, num_workers)
        self.staleness = self.plan.max_staleness
        self.controller = StalenessController(self.num_workers, self.staleness)
        self.service: Optional[ParameterService] = None
        # Cross-process wiring (multi-node async): the chief serves the service at
        # ps_address after init(); worker-role processes route run() through a
        # RemotePSWorker instead of the local service.
        self._ps_address = ps_address
        self._ps_listen_sock = None   # pre-bound socket from AutoDist._setup
        self._ps_server = None
        self._remote_worker = None
        self._last_returned = None
        # The un-jitted closure re-dispatches op-by-op; async steps call it outside
        # the (jitted) sync step_fn, so compile it here.
        self._jit_grad_fn = jax.jit(self._grad_fn)
        self._workers = {i: AsyncWorker(self, i) for i in range(self.num_workers)}
        self._membership_lock = san_lock()  # add_worker bookkeeping
        # Serializes multi-device program EXECUTION (dispatch + completion)
        # across this process's threads: two concurrently executing programs
        # that both carry cross-replica collectives can interleave their
        # rendezvous on the shared device pool and deadlock (observed on the
        # CPU backend when host threads < participants: each program's
        # all-reduce waits forever for participants the other program's
        # execution is holding). In-process async workers time-share one mesh
        # anyway — real concurrency lives across processes, whose devices are
        # disjoint — so the serialization costs ordering, not parallelism.
        self._collective_lock = san_lock()
        self._dump_lock = san_lock()
        self._dumped = False
        self._placer = None
        logging.info("AsyncPSRunner: %d worker(s), staleness=%s%s",
                     self.num_workers, self.staleness or "unbounded",
                     f", transport={ps_address}" if ps_address else "")

    @property
    def _is_remote_worker(self) -> bool:
        from autodist_tpu import const
        return bool(self._ps_address) and const.is_worker()

    @property
    def grad_fn(self):
        return self._jit_grad_fn

    @property
    def has_aux(self) -> bool:
        return self._has_aux

    # ------------------------------------------------------------------- state
    def init(self, params: PyTree, rng=None) -> TrainState:
        state = super().init(params, rng)
        if self._is_remote_worker:
            # The chief owns the authoritative state; this process only computes
            # gradients (its local state is a template for shapes/compile).
            return state
        if self.ps_shards > 1:
            # ZeRO PS path: S concurrent shard applies (shard-local opt
            # state, per-shard version counters) instead of one serial
            # whole-tree program.
            self.service = ShardedParameterService(
                state, self._optimizer, self.ps_shards, self._shard_exec)
        else:
            apply_fn = jax.jit(
                self._apply, in_shardings=(self._state_shardings, None),
                out_shardings=self._state_shardings)
            self.service = ParameterService(state, self._locked_apply(apply_fn))
        if self._ps_address:
            from autodist_tpu.parallel.ps_transport import PSServer
            host, _, port = self._ps_address.rpartition(":")
            self._ps_server = PSServer(self, host=host, port=int(port),
                                       listen_sock=self._ps_listen_sock)
        return state

    def evaluate(self, state: TrainState, batch: PyTree, fn=None):
        """Forward-only evaluation against the AUTHORITATIVE parameters.

        The chief evaluates the parameter service's current state (the passed
        ``state`` is a drop-in-API artifact — in the async regime the service
        owns the params). Remote worker processes hold only a shape template
        locally, so evaluating there would silently score untrained params:
        they raise instead."""
        if self._is_remote_worker:
            raise RuntimeError(
                "evaluate() is not available on async worker processes: the "
                "local state is a compile-shapes template; the chief's "
                "parameter service owns the authoritative parameters. "
                "Evaluate on the chief process")
        if self.service is not None:
            state = self.service.state
        return super().evaluate(state, batch, fn)

    def _apply(self, state: TrainState, grads: PyTree) -> TrainState:
        import optax
        updates, opt_state = self._optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(step=state.step + 1, params=params,
                          opt_state=opt_state, ef_state=state.ef_state,
                          plan=state.plan)

    def _locked_apply(self, apply_fn):
        # Execution serialized like the workers' gradient programs: the PS
        # apply is itself a multi-device program, and its (asynchronously
        # executing) collectives must not interleave with a concurrently
        # dispatched gradient program's (see _collective_lock).
        def run(state, grads):
            with self.mesh:
                # graftlint: disable=GL001(execution-serialization lock by design — the PS apply must not interleave its collectives with a worker grad program)
                with self._collective_lock:
                    new_state = apply_fn(state, grads)
                    jax.block_until_ready(new_state)
                    return new_state
        return run

    def _shard_exec(self, fn, *args):
        """Run one sharded-PS apply program to completion (the
        :class:`ShardedParameterService`'s ``exec_fn``): mesh-scoped, and
        execution-serialized for the same reason as :meth:`_locked_apply` —
        shard programs time-share this process's device pool with worker
        gradient programs, and concurrent multi-device executions must not
        interleave (the fan-out still overlaps host-side split/merge work and
        keeps per-shard mutexes independent across workers)."""
        with self.mesh:
            # graftlint: disable=GL001(execution-serialization lock by design — same contract as _locked_apply, per apply shard)
            with self._collective_lock:
                out = fn(*args)
                jax.block_until_ready(out)
                return out

    def wire_stats(self):
        """Transport wire counters for the async-PS log line — the worker's
        client-side accounting, or the chief's server-side aggregate; ``None``
        when this runner is not on the transport at all."""
        if self._remote_worker is not None:
            return self._remote_worker.wire_counters
        if self._ps_server is not None:
            return self._ps_server.wire
        return None

    def collect_cluster_trace(self, path: str, since_ns=None) -> str:
        """Emit the cluster timeline: this process's span ring merged with
        every span ring remote workers have pushed over the transport
        (``RemotePSWorker.push_trace`` / ``AUTODIST_TRACE_PULL=1``), one
        clock-rebased ``pid`` lane per worker, as Chrome trace JSON at
        ``path`` (:func:`autodist_tpu.telemetry.collect_cluster_trace`).

        On a worker-role process the merge instead carries this worker's own
        ring plus the chief's, pulled over the ``trace`` opcode — the local
        lane is labeled with this worker's id and rebased by its estimated
        chief-clock offset, so the two lanes align exactly like the
        chief-side merge (the chief's blob is the reference clock)."""
        rw = self._remote_worker
        if rw is not None:
            if rw.clock_offset_ns is None:
                rw.estimate_clock_offset()
            local = telemetry.local_trace_state(
                since_ns, worker_id=rw.worker_id,
                clock_offset_ns=rw.clock_offset_ns)
            return telemetry.merge_trace_states(
                [local, rw.trace(since_ns)], path)
        return telemetry.collect_cluster_trace(
            path, server=self._ps_server, since_ns=since_ns)

    def close(self):
        """Release transport endpoints (chief's server / worker's client). Called
        by AutoDist teardown; safe to call repeatedly or on single-node runners."""
        if self._ps_server is not None:
            self._ps_server.close()
            self._ps_server = None
        if self._remote_worker is not None:
            self._remote_worker.close()
            self._remote_worker = None
        if isinstance(self.service, ShardedParameterService):
            self.service.close()

    # ------------------------------------------------------------------ workers
    def worker(self, worker_id: int) -> AsyncWorker:
        if self.service is None:
            raise RuntimeError("Call init(params) before creating workers")
        if worker_id not in self._workers:
            # Membership check, not a range check: sparse elastic ids can
            # leave never-registered gap slots with no handle.
            raise ValueError(
                f"worker_id {worker_id} has no handle (known: "
                f"{sorted(self._workers)}); use add_worker({worker_id}) to "
                f"admit it")
        return self._workers[worker_id]

    def add_worker(self, worker_id: Optional[int] = None,
                   with_generation: bool = False):
        """Elastically (re-)admit a worker slot mid-run: a replacement for a
        retired (crashed) worker, or a brand-new slot (``worker_id=None``).
        Returns its handle; the gate seeds its step count at the slowest live
        worker's (see :meth:`StalenessController.register`). The reference
        could only fail-fast on worker loss (``coordinator.py:98-110``); the
        retire + register pair makes membership elastic.

        ``with_generation=True`` returns ``(handle, generation)`` where the
        generation was captured atomically with the registration — the retire
        token the PS transport binds to the admitting connection.

        Thread-safe: the PS transport calls this from per-connection handler
        threads (two remote workers may register simultaneously)."""
        if self.service is None:
            raise RuntimeError("Call init(params) before creating workers")
        # Rejoin detection BEFORE the register: re-admitting a retired slot
        # is the recovery plane's membership event (a replacement process, or
        # a wrongly-evicted worker healing itself); a fresh slot or an
        # idempotent retry on a live one is not. The check/register race is
        # benign — it only decides bookkeeping, never admission.
        was_retired = self.controller.slot_state(worker_id) == "retired"
        wid, gen = self.controller.register_with_generation(worker_id)
        with self._membership_lock:
            self.num_workers = max(self.num_workers, wid + 1)
            if wid not in self._workers:
                self._workers[wid] = AsyncWorker(self, wid)
        if was_retired:
            _recovery.log_rejoin(wid, gen,
                                 seeded_step=self.controller.steps[wid])
        logging.info("AsyncPSRunner: admitted worker %d (gate now %d slots)",
                     wid, len(self.controller.steps))
        if with_generation:
            return self._workers[wid], gen
        return self._workers[wid]

    def _place(self, state: TrainState) -> TrainState:
        """Place a state onto the mesh with the service's shardings (jit cached
        across calls so repeated adoption does not recompile)."""
        if self._placer is None:
            self._placer = jax.jit(lambda s: s, out_shardings=self._state_shardings)
        with self.mesh:
            return self._placer(state)

    def restore(self, state: TrainState):
        """Adopt a (checkpoint-restored) state as the service's."""
        if self.service is None:
            raise RuntimeError("Call init(params) before restore()")
        self.service.reset(self._place(state))

    def _maybe_dump_async_graphs(self, params, sharded_batch, ef_state):
        """AUTODIST_DUMP_GRAPHS stage snapshots for the async regime (the sync
        runner dumps in _build_step; async steps bypass it). Dumped once, from
        whichever worker steps first: 0-original = the user's loss fn,
        1-distributed = the gated grad fn the workers actually run (the PS-side
        apply is serialized on the service and has no per-step graph)."""
        from autodist_tpu import const
        if not const.ENV.AUTODIST_DUMP_GRAPHS.val:
            return
        with self._dump_lock:
            if self._dumped:
                return
            self._dumped = True
        from autodist_tpu.utils import tracing
        with self.mesh:
            tracing.dump_stage("async_step", "0-original", self._step_loss_fn,
                               params, sharded_batch)
            tracing.dump_stage("async_step", "1-distributed", self._grad_fn,
                               params, sharded_batch, ef_state)

    # --------------------------------------------------------------------- run
    def run(self, state, batch: PyTree = None, worker_id: int = 0):
        """Drop-in step: one async step on ``worker_id``; returns
        ``(current_state, fetches)`` like the synchronous runner.

        The PS owns the state in the async regimes, so the passed state is normally
        the service's own (as returned by the previous ``run``) and is ignored. A
        *foreign* state before the first applied update is a checkpoint restore
        (the ``init → run(restored_state, ...)`` pattern) and re-seeds the service;
        a foreign state later is ambiguous — other workers may have advanced the
        service past the caller's snapshot — and raises."""
        if batch is None:
            state, batch = None, state
        if self._is_remote_worker:
            # Worker process in a multi-node async run: gradients go to the
            # chief's service over the transport; the chief's state is
            # authoritative, so the local state passes through untouched.
            if self._remote_worker is None:
                from autodist_tpu import const
                from autodist_tpu.parallel.ps_transport import RemotePSWorker
                self._remote_worker = RemotePSWorker(
                    self._ps_address, self,
                    worker_id=const.ENV.AUTODIST_PROCESS_ID.val)
            fetched = self._remote_worker.step(batch,
                                               timeout=self.DEFAULT_STEP_TIMEOUT)
            return state, fetched
        # Only a genuinely foreign state (checkpoint restore) is adopted. A state
        # this runner previously returned is just the drop-in loop handing back
        # its last snapshot — other workers may have advanced the service since
        # (their applies land between our return and the next call), and adopting
        # would falsely report a conflict.
        if (state is not None and self.service is not None
                and state is not self._last_returned):
            self.service.adopt(state, self._place)
        fetched = self.worker(worker_id).step(batch, timeout=self.DEFAULT_STEP_TIMEOUT)
        current = self.service.state
        self._last_returned = current
        return current, fetched

    __call__ = run
