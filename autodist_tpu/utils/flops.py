"""FLOPs and MFU accounting for benchmark reporting.

The reference's benchmark suite reported raw rates only (examples/sec,
``examples/benchmark/utils/logs/metric.py``); rates alone cannot show whether a
regression is the framework or the model shape. Every README table row here
additionally carries MFU (model FLOPs utilization = achieved FLOP/s over the
chip's peak), from one of two estimators:

- :func:`train_step_flops` — XLA's own cost analysis of the compiled train
  step. Exact for what the chip executes, but blind to pallas custom calls
  (Mosaic kernels report no flops) and inflated by rematerialization.
- :func:`transformer_flops_per_token` — the standard analytic decoder count
  (attention projections + score/value matmuls + MLP + vocab head, backward =
  2x forward). Used for the LM benches whose hot path is pallas.

Peak FLOP/s comes from the shared peak-spec helper
(:func:`autodist_tpu.telemetry.profiling.peak_spec` — device-kind tables
plus the ``AUTODIST_PEAK_FLOPS``/``AUTODIST_PEAK_MEMBW`` overrides), the
same source the roofline gauges divide by.
"""

from typing import Optional


def device_peak_flops(device=None) -> Optional[float]:
    """Per-device bf16 peak FLOP/s, or None when unknown (e.g. CPU).

    Thin wrapper over the shared peak-spec helper so MFU reported here and
    the profiling plane's ``train.mfu`` gauge can never disagree on the
    denominator."""
    from autodist_tpu.telemetry import profiling
    return profiling.peak_spec(device).flops_per_s


def _flops_from_cost(cost) -> Optional[float]:
    if cost is None:
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    try:
        flops = float(cost.get("flops", 0.0))
    except Exception:  # noqa: BLE001
        return None
    return flops if flops > 0 else None


def train_step_flops(runner, state, sharded_batch) -> Optional[float]:
    """PER-DEVICE FLOPs of one compiled training step, from XLA's cost
    analysis (the SPMD module computes one device's batch shard — exactly the
    numerator MFU against a per-device peak wants).

    ``runner`` is a DistributedRunner whose plain step (no fetches) has already
    compiled — lowering again hits the jit cache. Returns None when the backend
    reports no analysis (or the step is pallas-dominated and reports ~0)."""
    fn = runner._step_fns.get(None)
    if fn is None:
        return None
    try:
        with runner.mesh:
            cost = fn.lower(state, sharded_batch).compile().cost_analysis()
    except Exception:  # noqa: BLE001 — accounting must never break a bench
        return None
    return _flops_from_cost(cost)


def jit_flops(jitted, *args) -> Optional[float]:
    """Cost-analysis FLOPs for an arbitrary jitted callable at ``args``."""
    try:
        cost = jitted.lower(*args).compile().cost_analysis()
    except Exception:  # noqa: BLE001
        return None
    return _flops_from_cost(cost)


def transformer_flops_per_token(d_model: int, n_layers: int, d_ff: int,
                                vocab_size: int, seq_len: int,
                                n_experts_active: int = 1) -> float:
    """Analytic training FLOPs per token for a decoder LM.

    Forward per token: ``8*d^2`` attention projections + ``4*s*d`` score/value
    matmuls per layer, ``4*d*d_ff`` MLP per layer (times the active expert
    count for MoE), ``2*d*V`` vocab head; training = 3x forward (backward is
    2x). Matches the usual 6ND + attention accounting; the full score matrix
    is counted because that is what the kernels execute (the causal mask
    discards, not skips, the upper triangle)."""
    per_layer = (8 * d_model * d_model + 4 * seq_len * d_model
                 + 4 * d_model * d_ff * n_experts_active)
    fwd = n_layers * per_layer + 2 * d_model * vocab_size
    return 3.0 * fwd


def mfu(flops_per_sec: Optional[float],
        peak: Optional[float] = None) -> Optional[float]:
    """Model FLOPs utilization in [0, 1], or None when either side is unknown."""
    peak = peak if peak is not None else device_peak_flops()
    if not flops_per_sec or not peak:
        return None
    return flops_per_sec / peak


def format_mfu(value: Optional[float]) -> str:
    return f"{100.0 * value:.1f}%" if value is not None else "n/a"


def report_mfu(flops_per_step: Optional[float], steps_per_sec: Optional[float],
               label: str = "mfu") -> Optional[float]:
    """Print the benchmark scripts' shared MFU line; returns the MFU fraction.

    Line format is part of the tooling contract: ``run_all.py`` scrapes
    ``<label> <pct>%``."""
    if not flops_per_step or not steps_per_sec:
        return None
    value = mfu(flops_per_step * steps_per_sec)
    if value is None:
        return None
    print(f"{label} {100.0 * value:.2f}% "
          f"({flops_per_step * steps_per_sec / 1e12:.1f} TFLOP/s, "
          f"{flops_per_step / 1e9:.2f} GFLOP/step)")
    return value
