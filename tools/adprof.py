#!/usr/bin/env python
"""adprof — summarize and diff autodist performance profiles.

Reads the schema-versioned per-run profile JSONs the attribution plane
writes (``telemetry.write_profile`` / ``AUTODIST_PROFILE_DIR``): per-program
static costs, phase-attribution series, MFU/roofline readings, and the env
manifest.

Usage:
    python tools/adprof.py RUN.json                    # one-run summary
    python tools/adprof.py BASE.json NEW.json          # regression diff
    python tools/adprof.py BASE.json NEW.json --threshold 5
    python tools/adprof.py RUN.json --predict          # cost-model check
    python tools/adprof.py ... --json                  # machine-readable

Diff mode compares NEW against BASE and NAMES what moved: overall step time,
MFU, each attribution phase's per-step seconds (share x step time — so a
phase "regressed 40%" means the step spends 40% more wall time there), and
per-signature program costs/compile counts. Exit codes are the CI contract:

    0  no regression beyond --threshold (default 10%%)
    1  step time OR any phase regressed beyond the threshold
    2  usage / unreadable / non-profile input

A profile diffed against itself therefore always exits 0 (the ci.sh smoke).
``--predict`` runs the calibrated cost model's self-consistency probe
(telemetry/costmodel.py): calibrate from the profile, predict its own
program mix, report predicted-vs-measured step time.
"""

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

PHASES = ("compute", "comm", "host", "data_wait", "readback")


def load_profile(path: str) -> dict:
    """Read and validate one profile JSON; raises ValueError on schema
    mismatch (a trace.json or metrics.json fed by mistake must fail loudly,
    not diff as zeros)."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("schema") != "autodist-profile":
        raise ValueError(f"{path}: not an autodist profile "
                         f"(schema={doc.get('schema') if isinstance(doc, dict) else None!r})")
    version = doc.get("schema_version")
    if version != 1:
        raise ValueError(f"{path}: unsupported profile schema_version "
                         f"{version!r} (this adprof reads version 1)")
    return doc


def _fmt_pct(x) -> str:
    return f"{100.0 * x:.1f}%" if x is not None else "n/a"


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return f"{int(n)}B" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}GiB"


def _phase_seconds(summary: dict) -> dict:
    """Per-step seconds each phase costs: share x step_s (0.0 when the
    profile recorded no periods)."""
    step_s = summary.get("step_s") or 0.0
    shares = summary.get("shares") or {}
    return {p: (shares.get(p) or 0.0) * step_s for p in PHASES}


def summarize(doc: dict) -> list:
    """Human lines for one profile."""
    s = doc.get("summary") or {}
    peaks = doc.get("peaks") or {}
    man = doc.get("manifest") or {}
    lines = [f"profile  host {man.get('host', '?')}  pid {man.get('pid', '?')}"
             f"  programs {len(doc.get('programs') or {})}"
             f"  periods {len(doc.get('periods') or [])}"]
    if s.get("steps_per_s") is not None:
        lines.append(f"rate     {s['steps_per_s']:.2f} steps/s  "
                     f"({1e3 * (s.get('step_s') or 0):.2f} ms/step, "
                     f"{s.get('steps', 0)} steps over "
                     f"{s.get('wall_s', 0):.1f}s)")
    if s.get("mfu") is not None or s.get("membw_util") is not None:
        lines.append(f"roofline mfu {_fmt_pct(s.get('mfu'))}  "
                     f"membw {_fmt_pct(s.get('membw_util'))}  "
                     f"(peaks: {peaks.get('source', '?')})")
    shares = s.get("shares")
    if shares:
        lines.append("attr     " + "  ".join(
            f"{p} {shares.get(p, 0.0):.3f}" for p in PHASES))
    for sig, rec in sorted((doc.get("programs") or {}).items()):
        fl = rec.get("flops")
        lines.append(
            f"  prog {sig} [{rec.get('kind', '?')}/x{rec.get('steps', 1)}] "
            f"{(fl / 1e9):.3f} GFLOP/dispatch " if fl else
            f"  prog {sig} [{rec.get('kind', '?')}/x{rec.get('steps', 1)}] "
            f"flops n/a ")
        lines[-1] += (f"dispatches {rec.get('dispatches', 0)}  "
                      f"source {rec.get('source') or '?'}")
    # Per-program memory ledger (the runner's memory_analysis() record):
    # args/out are the program's bound buffers, temp is the transient HBM
    # the cost model's peak_hbm_bytes adds to resident state. Absent on
    # backends that report no analysis — the table stays off.
    mem = [(sig, rec) for sig, rec in
           sorted((doc.get("programs") or {}).items())
           if any(rec.get(k) is not None
                  for k in ("argument_bytes", "output_bytes",
                            "temp_bytes", "generated_code_bytes"))]
    if mem:
        lines.append("memory   sig       args        out       temp"
                     "    codegen")
        for sig, rec in mem:
            lines.append(
                f"  {sig:<8}"
                f"{_fmt_bytes(rec.get('argument_bytes')):>9} "
                f"{_fmt_bytes(rec.get('output_bytes')):>10} "
                f"{_fmt_bytes(rec.get('temp_bytes')):>10} "
                f"{_fmt_bytes(rec.get('generated_code_bytes')):>10}")
        temps = [rec.get("temp_bytes") for _, rec in mem
                 if rec.get("temp_bytes") is not None]
        if temps:
            lines.append(f"  peak temp {_fmt_bytes(max(temps))} "
                         f"(the transient term of predicted peak HBM)")
    return lines


def diff(base: dict, new: dict, threshold_pct: float) -> dict:
    """Compare two profiles; returns {"regressions": [...], "improvements":
    [...], "lines": [...], "regressed": bool}. A regression is step time (or
    one phase's per-step seconds, or per-program compile count growth)
    increasing more than ``threshold_pct`` — phases below 2%% of the step
    are ignored as noise."""
    b, n = base.get("summary") or {}, new.get("summary") or {}
    lines, regressions, improvements = [], [], []

    def compare(label, bv, nv, unit="s", invert=False):
        """invert=True: bigger is better (MFU)."""
        if not bv or nv is None:
            return
        change = (nv - bv) / bv * 100.0
        worse = change < -threshold_pct if invert else change > threshold_pct
        better = change > threshold_pct if invert else change < -threshold_pct
        arrow = f"{bv:.6g} -> {nv:.6g} {unit} ({change:+.1f}%)"
        lines.append(f"  {label:<12} {arrow}")
        if worse:
            regressions.append({"what": label, "base": bv, "new": nv,
                                "change_pct": round(change, 2)})
        elif better:
            improvements.append({"what": label, "base": bv, "new": nv,
                                 "change_pct": round(change, 2)})

    compare("step_time", b.get("step_s"), n.get("step_s"))
    compare("mfu", b.get("mfu"), n.get("mfu"), unit="", invert=True)
    bp, np_ = _phase_seconds(b), _phase_seconds(n)
    step_b = b.get("step_s") or 0.0
    for p in PHASES:
        # A phase that is noise-level in BOTH runs cannot "regress 300%"
        # off a microsecond base; require it to matter in at least one run.
        if max(bp[p], np_[p]) < 0.02 * max(step_b, n.get("step_s") or 0.0):
            continue
        compare(f"phase:{p}", bp[p], np_[p])
    progs_b = base.get("programs") or {}
    progs_n = new.get("programs") or {}
    for sig in sorted(set(progs_b) & set(progs_n)):
        compare(f"prog:{sig}:flops", progs_b[sig].get("flops"),
                progs_n[sig].get("flops"), unit="flops")
    only_new = sorted(set(progs_n) - set(progs_b))
    if only_new:
        lines.append(f"  new program signature(s) in NEW: {only_new} "
                     f"(recompiles the base run never paid)")
    return {"regressions": regressions, "improvements": improvements,
            "lines": lines, "regressed": bool(regressions)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="adprof", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("base", help="profile JSON (the baseline in diff mode)")
    ap.add_argument("new", nargs="?", default=None,
                    help="second profile: diff NEW against BASE")
    ap.add_argument("--threshold", type=float, default=10.0,
                    help="regression threshold in percent (default 10)")
    ap.add_argument("--predict", action="store_true",
                    help="run the calibrated cost model's self-consistency "
                         "probe on the (first) profile")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)
    try:
        base = load_profile(args.base)
        new = load_profile(args.new) if args.new else None
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"adprof: {e}", file=sys.stderr)
        return 2

    if new is None:
        out = {"summary": base.get("summary"),
               "programs": base.get("programs")}
        if args.predict:
            from autodist_tpu.telemetry import costmodel
            pred = costmodel.predict_from_profile(base)
            out["predict"] = pred
        if args.json:
            print(json.dumps(out, indent=1, default=str))
        else:
            print("\n".join(summarize(base)))
            if args.predict:
                pred = out["predict"]
                ratio = pred.get("ratio")
                print(f"predict  {1e3 * pred['step_s']:.3f} ms/step "
                      f"(measured "
                      f"{1e3 * (pred.get('measured_step_s') or 0):.3f}, "
                      f"ratio {ratio:.2f}x)  bound: {pred['bound']}"
                      if ratio is not None else
                      f"predict  {pred['step_s']:.6f} s/step  "
                      f"bound: {pred['bound']}")
        return 0

    result = diff(base, new, args.threshold)
    if args.json:
        print(json.dumps(result, indent=1, default=str))
    else:
        print(f"adprof diff: {args.base} -> {args.new} "
              f"(threshold {args.threshold:g}%)")
        print("\n".join(result["lines"]))
        for r in result["regressions"]:
            print(f"REGRESSION: {r['what']} {r['change_pct']:+.1f}% "
                  f"({r['base']:.6g} -> {r['new']:.6g})")
        if not result["regressed"]:
            print(f"no regression beyond {args.threshold:g}% "
                  f"({len(result['improvements'])} improvement(s))")
    return 1 if result["regressed"] else 0


if __name__ == "__main__":
    sys.exit(main())
