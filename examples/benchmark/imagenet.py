"""ImageNet-class CNN benchmark with examples/sec instrumentation.

Port of reference ``examples/benchmark/imagenet.py``: model selected by flag
(ResNet-50 / VGG16 here vs the reference's Keras zoo, ``:150-170``), strategy
selected by flag (``:161-170``), per-model AllReduce chunk sizes preserved as
fusion-group hints (``:150-160``: vgg16=25, resnet=200, else 512), and
TimeHistory-style examples/sec logging (``:84-133``).

Input: synthetic by default (the reference also supported synthetic ImageNet
input), or REAL images — ``--prep_images`` decodes a ``<class>/<file>`` tree
into uint8 record shards (the reference read tfrecords through
``input_fn(data_dir=...)``, ``:219-229`` + ``utils/imagenet_preprocessing``);
``--data_dir`` then streams them through the native loader with random
crop/flip/mean-subtraction ON DEVICE inside the jitted step
(``autodist_tpu/data/imagenet.py``). Disk-fed rates therefore INCLUDE input
cost.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu import AutoDist
from autodist_tpu.models import densenet, inception, resnet, vgg
from autodist_tpu.strategy import (AllReduce, Parallax, PartitionedPS, PS,
                                   PSLoadBalancing)
from autodist_tpu.utils.metrics import ThroughputMeter

# Reference chunk-size tuning constants (imagenet.py:150-160: vgg16=25,
# resnet101=200, inceptionv3=30, others=512). resnet50 isn't in the reference's
# zoo; it inherits resnet101's tuning rather than the generic default.
CHUNK_SIZES = {"vgg16": 25, "resnet50": 200, "resnet101": 200, "inceptionv3": 30,
               "default": 512}


def build_strategy(name: str, model_name: str):
    chunk = CHUNK_SIZES.get(model_name, CHUNK_SIZES["default"])
    return {
        "PS": lambda: PS(),
        "PSLoadBalancing": lambda: PSLoadBalancing(),
        "PartitionedPS": lambda: PartitionedPS(),
        "AllReduce": lambda: AllReduce(chunk_size=chunk),
        "Parallax": lambda: Parallax(chunk_size=chunk),
    }[name]()


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="resnet50",
                        choices=["resnet50", "resnet101", "vgg16", "densenet121",
                                 "inceptionv3"])
    parser.add_argument("--strategy", default="AllReduce",
                        choices=["PS", "PSLoadBalancing", "PartitionedPS",
                                 "AllReduce", "Parallax"])
    parser.add_argument("--steps", type=int, default=110)
    parser.add_argument("--batch_size", type=int, default=0,
                        help="global batch; 0 = 32 per device")
    parser.add_argument("--image_size", type=int, default=224)
    parser.add_argument("--accum", type=int, default=1,
                        help="gradient-accumulation micro-batches per step "
                             "(global batch = --batch_size; must divide it)")
    parser.add_argument("--log_every", type=int, default=100)
    parser.add_argument("--resource_spec", type=str, default=None)
    parser.add_argument("--data_dir", type=str, default=None,
                        help="train from image record shards (prepared by "
                             "--prep_images); default = synthetic input")
    parser.add_argument("--prep_images", type=str, default=None,
                        help="<class>/<file> image tree: decode into uint8 "
                             "record shards under --data_dir and exit")
    parser.add_argument("--record_size", type=int, default=256,
                        help="stored record side for --prep_images (crop "
                             "source; must exceed --image_size)")
    parser.add_argument("--pool_rows", type=int, default=0,
                        help="cache mode: HBM record-pool rows (0 = auto, "
                             "capped by DeviceDatasetCache's HBM budget)")
    parser.add_argument("--eval", action="store_true",
                        help="one pass over --data_dir with the eval "
                             "preprocessing (center crop, no flip): top-1/"
                             "top-5 accuracy (reference is_training=False)")
    parser.add_argument("--restore", type=str, default=None,
                        help="checkpoint prefix to evaluate (Saver format); "
                             "default = fresh init (chance accuracy)")
    parser.add_argument("--norm", choices=["group", "batch"], default="group",
                        help="resnet normalization: group (pure function) or "
                             "batch (cross-replica sync-BN). Caveat: sync-BN "
                             "tracks no running statistics, so --eval on a "
                             "--norm batch checkpoint normalizes with the "
                             "EVAL batch's own mean/var — accuracy depends "
                             "on eval batch size/composition (see "
                             "docs/usage/performance.md) — unless --bn_ema "
                             "calibrates stored statistics first")
    parser.add_argument("--bn_ema", type=int, default=0, metavar="N",
                        help="--eval --norm batch only (default off): run N "
                             "train-preprocessed calibration batches first, "
                             "EMA each SyncBatchNorm site's (mean, var) into "
                             "a bn_ema collection carried outside params, "
                             "and evaluate with THOSE statistics — reference "
                             "BatchNorm inference behavior, independent of "
                             "eval batch size/composition")
    parser.add_argument("--stages", type=str, default="",
                        help="resnet-only: comma-separated residual block "
                             "counts per stage overriding the model's "
                             "default (resnet50=3,4,6,3) — a bring-up/smoke "
                             "knob (e.g. --stages 1,1 compiles a 2-block "
                             "model in seconds); benchmark rates are only "
                             "comparable at the default depth")
    parser.add_argument("--input_mode", choices=["cache", "stream"],
                        default="cache",
                        help="--data_dir feed: 'cache' = HBM-resident record "
                             "pool with background refresh (the reference's "
                             "training_dataset_cache, right for weak "
                             "host->device links); 'stream' = full batches "
                             "over the link per step (right on real TPU-VM "
                             "PCIe)")
    args = parser.parse_args(argv)

    if args.prep_images:
        if not args.data_dir:
            parser.error("--prep_images needs --data_dir")
        from autodist_tpu.data import imagenet as imagenet_data
        paths = imagenet_data.prepare_image_shards(
            args.prep_images, args.data_dir, record_size=args.record_size)
        print(f"prepared {len(paths['images'])} image shard(s) in "
              f"{args.data_dir}; train with --data_dir {args.data_dir}")
        return 0

    n_dev = len(jax.devices())
    batch_size = args.batch_size or 32 * n_dev
    on_accel = jax.default_backend() != "cpu"
    dtype = jnp.bfloat16 if on_accel else jnp.float32
    if args.model == "inceptionv3":
        args.image_size = max(args.image_size, 299)  # V3 stem needs >=299

    num_classes = 1000
    batcher = cache = loader = None
    if args.eval and not args.data_dir:
        parser.error("--eval needs --data_dir")
    if args.data_dir:
        from autodist_tpu.data import imagenet as imagenet_data
        # Eval = one DETERMINISTIC pass: sequential read, center crop, no flip
        # (the reference's is_training=False input).
        loader, meta = imagenet_data.open_image_loader(
            args.data_dir, batch_size=batch_size, shuffle=not args.eval,
            prefetch=4)
        if meta["record_size"] < args.image_size:
            parser.error(f"records are {meta['record_size']}px, smaller than "
                         f"--image_size {args.image_size}")
        num_classes = len(meta["classes"])
        if args.eval or args.input_mode == "stream":
            batcher = imagenet_data.AugmentingBatcher(
                loader, image_size=args.image_size,
                record_size=meta["record_size"], train=not args.eval)
        else:
            cache = imagenet_data.DeviceDatasetCache(
                loader, record_size=meta["record_size"],
                image_size=args.image_size, dtype=dtype,
                pool_rows=args.pool_rows or None)

    # --eval --restore overwrites params wholesale: skip the (expensive on
    # large models) fresh initialization in that case.
    need_init = not (args.eval and args.restore)
    if args.model in ("resnet50", "resnet101"):
        stages = (3, 4, 23, 3) if args.model == "resnet101" else (3, 4, 6, 3)
        if args.stages:
            try:
                stages = tuple(int(s) for s in args.stages.split(","))
                if not stages or any(s < 1 for s in stages):
                    raise ValueError
            except ValueError:
                parser.error(f"--stages needs comma-separated positive "
                             f"integers, got {args.stages!r}")
        cfg = resnet.ResNet50Config(dtype=dtype, stage_sizes=stages,
                                    num_classes=num_classes, norm=args.norm)
        model = resnet.ResNet(cfg)
        params = resnet.init_params(cfg, image_size=args.image_size)[1] \
            if need_init else None
        loss_fn = resnet.make_loss_fn(model)
        batch = None if args.data_dir else resnet.synthetic_batch(cfg, batch_size, args.image_size)
    elif args.model == "densenet121":
        cfg = densenet.DenseNet121Config(dtype=dtype, num_classes=num_classes)
        model = densenet.DenseNet(cfg)
        params = densenet.init_params(cfg, image_size=args.image_size)[1] \
            if need_init else None
        loss_fn = densenet.make_loss_fn(model)
        batch = None if args.data_dir else densenet.synthetic_batch(cfg, batch_size, args.image_size)
    elif args.model == "inceptionv3":
        cfg = inception.InceptionV3Config(dtype=dtype, num_classes=num_classes)
        model = inception.InceptionV3(cfg)
        params = inception.init_params(cfg, image_size=args.image_size)[1] \
            if need_init else None
        loss_fn = inception.make_loss_fn(model)
        batch = None if args.data_dir else inception.synthetic_batch(cfg, batch_size, args.image_size)
    else:
        model = vgg.VGG16(dtype=dtype, num_classes=num_classes)
        params = vgg.init_params(model, image_size=args.image_size) \
            if need_init else None
        loss_fn = vgg.make_loss_fn(model)
        batch = None if args.data_dir else vgg.synthetic_batch(model.num_classes, batch_size, args.image_size)

    if batcher is not None:
        # Stream mode: raw uint8 records + on-device crop/flip/normalize fused
        # into the step (rates now include real input cost).
        from autodist_tpu.data import imagenet as imagenet_data
        loss_fn = imagenet_data.make_augmented_loss_fn(model, args.image_size,
                                                       dtype)
        batch = batcher.next()
    elif cache is not None:
        # Cache mode: the batch arrives pre-assembled on device (pool gather +
        # augment in their own jit); the step keeps the plain loss.
        batch = cache.next_batch(batch_size)

    ad = AutoDist(args.resource_spec, build_strategy(args.strategy, args.model))

    if args.bn_ema and not (args.eval and args.norm == "batch"
                            and args.model in ("resnet50", "resnet101")):
        parser.error("--bn_ema needs --eval and a resnet with --norm batch")

    if args.eval:
        if args.restore:
            from autodist_tpu.checkpoint import Saver
            params = Saver().restore_params(args.restore)
        from autodist_tpu.data import imagenet as imagenet_data

        bn_stats = eval_model = None
        if args.bn_ema:
            # Calibration pass: N shuffled, train-preprocessed batches feed
            # the EMA of per-site (mean, var); evaluation below then runs an
            # EMA-reading model — stats carried outside params, params
            # themselves untouched.
            import dataclasses as _dc
            cal_loader, _ = imagenet_data.open_image_loader(
                args.data_dir, batch_size=batch_size, shuffle=True, prefetch=2)
            cal_batcher = imagenet_data.AugmentingBatcher(
                cal_loader, image_size=args.image_size,
                record_size=meta["record_size"], train=True)

            def _cal_images():
                for _ in range(args.bn_ema):
                    b = cal_batcher.next()
                    yield imagenet_data.augment_images(
                        b["images"], b["crop_yx"], b["flip"], args.image_size,
                        dtype)

            bn_stats = resnet.calibrate_bn_ema(model, params, _cal_images())
            cal_loader.close()
            eval_model = resnet.ResNet(_dc.replace(cfg, bn_ema=True))
            print(f"calibrated SyncBatchNorm EMA over {args.bn_ema} "
                  f"batch(es); evaluating with stored statistics")

        def metric_fn(p, b):
            x = imagenet_data.augment_images(b["images"], b["crop_yx"],
                                             b["flip"], args.image_size, dtype)
            if bn_stats is not None:
                logits = eval_model.apply({"params": p, "bn_ema": bn_stats}, x)
            else:
                logits = model.apply({"params": p}, x)
            logits = logits.astype(jnp.float32)
            top5 = jax.lax.top_k(logits, min(5, logits.shape[-1]))[1]
            c1 = (jnp.argmax(logits, -1) == b["labels"]).sum()
            c5 = (top5 == b["labels"][:, None]).any(-1).sum()
            return jnp.stack([c1, c5])

        step = ad.function(loss_fn, params, optax.sgd(0.0),
                           example_batch=batch)
        state = step.get_state()
        n_batches = loader.n_rows // batch_size
        counts = np.zeros(2)
        for i in range(n_batches):
            # The example batch already consumed the loader's first rows
            # (sequential in eval) — score it rather than skipping them.
            b = batch if i == 0 else batcher.next()
            counts += np.asarray(step.runner.evaluate(state, b, fn=metric_fn))
        loader.close()
        seen = n_batches * batch_size
        skipped = loader.n_rows - seen
        if skipped:
            print(f"WARNING: {skipped} tail example(s) skipped (static batch "
                  f"shapes drop the remainder); pick a --batch_size dividing "
                  f"{loader.n_rows} for exact coverage")
        top1, top5 = counts / max(seen, 1)
        print(f"{args.model} eval ({seen}/{loader.n_rows} examples, center "
              f"crop {args.image_size}): top-1 {top1:.4f}  top-5 {top5:.4f}")
        return float(top1)

    # lr 0.1+momentum diverges within ~50 steps on synthetic random labels (any
    # dtype); the benchmark wants steady-state throughput with finite loss.
    step = ad.function(loss_fn, params, optax.sgd(0.01, momentum=0.9),
                       example_batch=batch, accumulation_steps=args.accum)
    feed = None
    if cache is not None:
        next_batch = lambda: cache.next_batch(batch_size)  # noqa: E731
    elif batcher is not None:
        from autodist_tpu.data import device_prefetch
        feed = device_prefetch(batcher, step.runner, depth=2)
        next_batch = lambda: next(feed)  # noqa: E731
    else:
        # Synthetic data lives on device for the whole run (the reference's
        # synthetic ImageNet input was likewise graph-resident): re-shipping a
        # multi-MB image batch from host every step would benchmark the host
        # link, not the chip.
        batch = step.runner.shard_batch(batch)
        next_batch = lambda: batch  # noqa: E731

    from autodist_tpu.utils.benchmark_logger import (gather_run_info,
                                                     get_benchmark_logger)
    bench_logger = get_benchmark_logger()
    bench_logger.log_run_info(gather_run_info(
        args.model, strategy_name=args.strategy, batch_size=batch_size))
    meter = ThroughputMeter(batch_size=batch_size, log_every=args.log_every)
    loss = None
    # try/finally: a failed step must still record run_status and close the
    # metric file handle instead of leaking it.
    try:
        for i in range(args.steps):
            loss = step(next_batch())
            rate = meter.step(sync=loss)
            if rate is not None:
                bench_logger.log_metric("examples_per_second", rate,
                                        unit="examples/s", global_step=i + 1)
        jax.device_get(loss)  # fence: trailing async steps must not inflate avg
        avg = meter.average or 0.0
        bench_logger.log_metric("average_examples_per_second", avg,
                                unit="examples/s", global_step=args.steps)
    except BaseException:
        bench_logger.on_finish(status="failure")
        raise
    finally:
        if feed is not None:
            feed.close()   # stop the producer before its loader goes away
        if loader is not None:
            loader.close()
    bench_logger.on_finish()
    src = "disk" if args.data_dir else "synthetic"
    print(f"{args.model}/{args.strategy} ({src}): final loss {float(loss):.4f}, "
          f"{avg:.1f} examples/sec ({avg / max(n_dev, 1):.1f}/device)")
    from autodist_tpu.utils import flops as flops_util
    # shard_batch so the cost-analysis lowering hits the training step's jit
    # cache (a host-layout batch would trigger a second compile).
    per_step = flops_util.train_step_flops(step.runner, step.get_state(),
                                           step.runner.shard_batch(batch))
    if per_step and args.accum > 1:
        # XLA's cost analysis counts a lax.scan body once, not per trip.
        per_step *= args.accum
    flops_util.report_mfu(per_step, avg / batch_size)
    return avg


if __name__ == "__main__":
    main()
