"""OpenMetrics/Prometheus exposition of the metrics registry.

The registry's ``snapshot()`` already crosses the PS wire, but only to
clients speaking this repo's codec. This module renders the SAME instruments
in the Prometheus text exposition format (version 0.0.4 — the format every
standard scraper, agent and gateway ingests) and serves it from a tiny
stdlib HTTP endpoint, so the whole stack becomes scrapeable with a
five-line scrape config and NO custom client:

- :func:`render` — zero-dependency text rendering straight off the live
  :class:`~autodist_tpu.telemetry.metrics.Registry`: counters as
  ``<name>_total``, gauges verbatim, histograms as CUMULATIVE
  ``_bucket{le="..."}`` series plus ``_sum``/``_count`` (the registry's
  ``le``-bucket semantics are already Prometheus's — only the running total
  differs from the per-bucket snapshot form). Metric names sanitize
  ``a.b.c`` -> ``a_b_c``; HELP/label text is escaped per the spec.
- :class:`MetricsExporter` — a daemon-threaded ``ThreadingHTTPServer``
  answering ``GET /metrics`` (the exposition) and ``GET /healthz`` (a JSON
  liveness probe carrying uptime and the active-alert count). Attach points:
  the trainer chief (``train()``), ``PSServer`` and ``InferenceServer`` all
  call :func:`maybe_serve` — a process-global get-or-create keyed off
  ``AUTODIST_METRICS_PORT``, so a process with both a PS server and a train
  loop still binds ONE port.

Trust model: same as every transport here — the endpoint is read-only and
unauthenticated; it binds all interfaces (scrapers live off-host by
definition), so exposing it past the cluster's trust domain is the
operator's explicit choice of port.
"""

import http.server
import json
import threading
import time
from typing import Dict, Optional, Tuple

from autodist_tpu import const
from autodist_tpu.telemetry import metrics as _metrics
from autodist_tpu.utils import logging
from autodist_tpu.testing.sanitizer import san_lock

__all__ = ["render", "metric_name", "MetricsExporter", "maybe_serve",
           "get_exporter", "set_exporter", "CONTENT_TYPE"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def metric_name(name: str) -> str:
    """``ps.wire.bytes_sent`` -> ``ps_wire_bytes_sent``: the registry's
    dotted-lowercase convention mapped onto the exposition charset
    (``[a-zA-Z_:][a-zA-Z0-9_:]*``); anything else becomes ``_``."""
    out = "".join(c if c.isalnum() or c in "_:" else "_" for c in name)
    if out and out[0].isdigit():
        out = "_" + out
    return out or "_"


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(value) -> str:
    if value != value:                    # NaN
        return "NaN"
    if value in (float("inf"), float("-inf")):
        return "+Inf" if value > 0 else "-Inf"
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return repr(value) if isinstance(value, float) else str(value)


def render(registry: Optional[_metrics.Registry] = None) -> str:
    """The full exposition for ``registry`` (default: the process-global
    one), deterministic for a given set of recorded values (names sorted —
    the same contract ``snapshot()`` keeps)."""
    reg = registry if registry is not None else _metrics.registry()
    lines = []
    for name, inst in reg.instruments():
        pname = metric_name(name)
        if isinstance(inst, _metrics.Counter):
            lines.append(f"# HELP {pname}_total {_escape_help(name)}")
            lines.append(f"# TYPE {pname}_total counter")
            lines.append(f"{pname}_total {_fmt(inst.snapshot())}")
        elif isinstance(inst, _metrics.Gauge):
            lines.append(f"# HELP {pname} {_escape_help(name)}")
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {_fmt(inst.snapshot())}")
        elif isinstance(inst, _metrics.Histogram):
            snap = inst.snapshot()
            lines.append(f"# HELP {pname} {_escape_help(name)}")
            lines.append(f"# TYPE {pname} histogram")
            cum = 0
            for bound in inst.buckets:
                cum += snap[f"le:{bound:g}"]
                le = _escape_label(_fmt(float(bound)))
                lines.append(f'{pname}_bucket{{le="{le}"}} {cum}')
            lines.append(f'{pname}_bucket{{le="+Inf"}} {snap["count"]}')
            lines.append(f"{pname}_sum {_fmt(snap['sum'])}")
            lines.append(f"{pname}_count {snap['count']}")
    return "\n".join(lines) + "\n" if lines else "\n"


class MetricsExporter:
    """The scrape endpoint: ``/metrics`` + ``/healthz`` on
    ``AUTODIST_METRICS_PORT`` (or an explicit ``port``; 0 binds ephemeral —
    the loopback tests' mode). One daemon accept thread, one handler thread
    per scrape (scrapes are rare and tiny; the render is a lock-guarded
    walk of the registry, never device work)."""

    def __init__(self, port: Optional[int] = None, host: str = "",
                 registry: Optional[_metrics.Registry] = None):
        if port is None:
            raw = str(const.ENV.AUTODIST_METRICS_PORT.val)
            port = int(raw) if raw else 0
        self._registry = registry
        self._t_started = time.monotonic()
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = render(outer._registry).encode()
                    self._reply(200, CONTENT_TYPE, body)
                elif path == "/healthz":
                    body = json.dumps(outer.health()).encode()
                    self._reply(200, "application/json", body)
                else:
                    self._reply(404, "text/plain", b"not found\n")

            def _reply(self, code: int, ctype: str, body: bytes):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass   # scrapes at scrape-interval rate must not spam logs

        class Server(http.server.ThreadingHTTPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="autodist-metrics-http")
        self._thread.start()
        logging.info("metrics exporter: /metrics + /healthz listening on "
                     ":%d", self.address[1])

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address[:2]

    def health(self) -> Dict[str, object]:
        """The ``/healthz`` body: liveness plus the one number a probe can
        act on without parsing the exposition."""
        from autodist_tpu.telemetry import alerts as _alerts
        return {"ok": True,
                "uptime_s": round(time.monotonic() - self._t_started, 3),
                "pid": const.ENV.AUTODIST_PROCESS_ID.val,
                "alerts_active": len(_alerts.active_alerts())}

    def close(self):
        self._server.shutdown()
        self._server.server_close()


_EXPORTER: Optional[MetricsExporter] = None
_EXPORTER_LOCK = san_lock()


def set_exporter(exporter: Optional[MetricsExporter]):
    """Install (or clear-and-close, with None) the process exporter."""
    global _EXPORTER
    with _EXPORTER_LOCK:
        if _EXPORTER is not None and _EXPORTER is not exporter:
            _EXPORTER.close()
        _EXPORTER = exporter


def get_exporter() -> Optional[MetricsExporter]:
    return _EXPORTER


def maybe_serve() -> Optional[MetricsExporter]:
    """The attach hook every server/loop entry point calls: start the
    process exporter when ``AUTODIST_METRICS_PORT`` is set and none is
    running yet; no-op (None) otherwise. A failed bind (port taken — e.g.
    two processes on one host sharing an inherited env) warns and returns
    None: observability must never take down the thing it observes.
    ``AUTODIST_METRICS_PORT=0`` stays disabled (the flag convention for
    off); an EXPLICIT ``MetricsExporter(port=0)`` binds ephemeral — the
    loopback tests' mode."""
    global _EXPORTER
    raw = str(const.ENV.AUTODIST_METRICS_PORT.val)
    if not raw or raw == "0":
        return _EXPORTER
    with _EXPORTER_LOCK:
        if _EXPORTER is None:
            try:
                _EXPORTER = MetricsExporter(port=int(raw))
            except (OSError, ValueError) as e:
                logging.warning("metrics exporter: cannot serve on "
                                "AUTODIST_METRICS_PORT=%s: %s", raw, e)
                return None
        return _EXPORTER
