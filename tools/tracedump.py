#!/usr/bin/env python
"""tracedump — merge per-worker host-span JSONL dumps into one Chrome trace.

The offline half of the cluster trace plane
(``autodist_tpu/telemetry/cluster.py``): when no PS transport was up to
``push_trace`` through — single-process debugging, a run that crashed before
collection, or logs copied off a pod — each worker's
``telemetry.dump_spans_jsonl(path, worker_id=..)`` file can still be merged
after the fact into the same clock-aligned, pid-lane-per-worker timeline
``telemetry.collect_cluster_trace`` produces online.

Usage:
    python tools/tracedump.py out.json w0.jsonl w1.jsonl [w2.jsonl ...]
    python tools/tracedump.py out.json *.jsonl --offset 1:250000 --offset 2:-80000
    python tools/tracedump.py out.json *.jsonl --events flightrec/snap-0000-*/events.jsonl

``--offset WID:NS`` overrides a dump's recorded chief-clock offset
(nanoseconds to ADD to that worker's wall clock) — for dumps written before
any offset was estimated. ``--events FILE`` (repeatable) merges structured
registry-event dumps (``telemetry.dump_events_jsonl`` files — the flight
recorder writes one per snapshot) into the timeline as INSTANT markers on
their own lane, so anomalies line up against the spans that surround them.
``--reqtrace FILE`` (repeatable) merges request-lifecycle dumps
(``telemetry.dump_reqtrace_jsonl`` files — the request-trace plane's
offline exit) as per-request lanes with router->replica flow arrows, so a
post-mortem gets the same flow-linked timeline ``tools/adtrace.py --out``
pulls live. Load the output in ui.perfetto.dev or chrome://tracing.
"""

import argparse
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)


def _parse_offset(spec: str):
    try:
        wid, ns = spec.split(":", 1)
        return int(wid), int(ns)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--offset wants WID:NANOSECONDS, got {spec!r}")


def merge_dumps(out_path: str, inputs, offsets=None, event_files=(),
                reqtrace_files=()) -> str:
    """Merge span JSONL dumps at ``inputs`` into one Chrome trace at
    ``out_path``; ``offsets`` maps worker id -> clock_offset_ns override,
    ``event_files`` are registry-event JSONL dumps overlaid as instant
    markers, and ``reqtrace_files`` are request-lifecycle JSONL dumps merged
    as per-request flow-linked lanes. Returns ``out_path`` (the test-facing
    entry point — main() is argv plumbing around it)."""
    from autodist_tpu.telemetry import cluster
    offsets = offsets or {}
    states = []
    for path in inputs:
        state = cluster.load_trace_jsonl(path)
        wid = state.get("worker_id")
        if wid in offsets:
            state["clock_offset_ns"] = offsets[wid]
        states.append(state)
    events = []
    for path in event_files:
        events.extend(cluster.load_events_jsonl(path))
    req_states = []
    for path in reqtrace_files:
        state = cluster.load_reqtrace_jsonl(path)
        wid = state.get("worker_id")
        if wid in offsets:
            state["clock_offset_ns"] = offsets[wid]
        req_states.append(state)
    return cluster.merge_trace_states(states, out_path,
                                      instant_events=events,
                                      reqtrace_states=req_states)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tracedump", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("out", help="output Chrome trace JSON path")
    ap.add_argument("inputs", nargs="+",
                    help="per-worker span JSONL dumps "
                         "(telemetry.dump_spans_jsonl files)")
    ap.add_argument("--offset", action="append", type=_parse_offset,
                    default=[], metavar="WID:NS",
                    help="override worker WID's chief-clock offset "
                         "(ns to add; repeatable)")
    ap.add_argument("--events", action="append", default=[], metavar="FILE",
                    help="registry-event JSONL dump "
                         "(telemetry.dump_events_jsonl file) to overlay as "
                         "instant markers (repeatable)")
    ap.add_argument("--reqtrace", action="append", default=[],
                    metavar="FILE",
                    help="request-lifecycle JSONL dump "
                         "(telemetry.dump_reqtrace_jsonl file) to merge as "
                         "flow-linked per-request lanes (repeatable)")
    args = ap.parse_args(argv)
    try:
        merge_dumps(args.out, args.inputs, offsets=dict(args.offset),
                    event_files=args.events, reqtrace_files=args.reqtrace)
    except (OSError, ValueError) as e:
        print(f"tracedump: {e}", file=sys.stderr)
        return 1
    print(f"tracedump: wrote {args.out} ({len(args.inputs)} lane(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
