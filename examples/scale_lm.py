"""Reproduce the README's decoder scaling table on one chip.

    PYTHONPATH=. python examples/scale_lm.py --d_model 768 --n_layers 12 --batch_size 192
    PYTHONPATH=. python examples/scale_lm.py --d_model 1024 --n_layers 12 --batch_size 128
    PYTHONPATH=. python examples/scale_lm.py --d_model 1024 --n_layers 24 --batch_size 96

Same framework and step as the flagship bench (AllReduce, bf16, fused pallas
head, XLA attention at seq 256), just a bigger decoder: MFU rises with model
size as the matmuls grow (48% at 52M -> ~59-60% at 217M on a v5e). The fused-head
kernels fit their tile sizes to VMEM automatically, which is what makes
d_model >= 768 with f32 tables work at all (ops/fused_xent.py).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import optax

from autodist_tpu import AutoDist
from autodist_tpu.models import transformer_lm
from autodist_tpu.ops import mosaic_compiles
from autodist_tpu.strategy import AllReduce
from autodist_tpu.utils import flops as flops_util


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--d_model", type=int, default=768)
    parser.add_argument("--n_layers", type=int, default=12)
    parser.add_argument("--batch_size", type=int, default=192)
    parser.add_argument("--seq_len", type=int, default=256)
    parser.add_argument("--vocab", type=int, default=32_000)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--remat", action="store_true")
    args = parser.parse_args(argv)

    on_accel = jax.default_backend() != "cpu"
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=args.vocab, d_model=args.d_model,
        n_heads=max(1, args.d_model // 64), n_layers=args.n_layers,
        d_ff=4 * args.d_model, max_len=args.seq_len,
        dtype=jnp.bfloat16 if on_accel else jnp.float32, tied_output=False,
        remat=args.remat, fused_head=mosaic_compiles())

    model, params = transformer_lm.init_params(cfg)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    loss_fn = transformer_lm.make_loss_fn(model)
    batch = transformer_lm.synthetic_batch(cfg, batch_size=args.batch_size,
                                           seq_len=args.seq_len)

    ad = AutoDist(strategy_builder=AllReduce())
    step = ad.function(loss_fn, params, optax.adam(1e-3), example_batch=batch)
    batch = step.runner.shard_batch(batch)

    for _ in range(2):
        loss = step(batch)
    _ = float(loss)  # compile + pipeline fence
    t0 = time.perf_counter()
    for _ in range(args.steps):
        loss = step(batch)
    _ = float(loss)
    dt = time.perf_counter() - t0

    tokens_per_step = args.batch_size * args.seq_len
    rate = tokens_per_step * args.steps / dt
    fpt = flops_util.transformer_flops_per_token(
        cfg.d_model, cfg.n_layers, cfg.d_ff, cfg.vocab_size, args.seq_len)
    print(f"d{cfg.d_model}x{cfg.n_layers} bs{args.batch_size} "
          f"seq{args.seq_len} ({n_params / 1e6:.0f}M params): "
          f"final loss {float(loss):.4f}, {rate:,.0f} tokens/sec")
    flops_util.report_mfu(fpt * tokens_per_step / len(jax.devices()),
                          rate / tokens_per_step)
    return rate


if __name__ == "__main__":
    main()
