"""Fleet serving (PR 17): paged KV cache + multi-replica router.

NAMED to sort inside the tier-1 alphabetical window (with the other serve
tests). No subprocesses: replicas are in-process ``InferenceServer``s over
loopback, killed via ``InferenceServer.kill()`` (severed sockets — exactly
what a dead replica process looks like to the router).

Coverage per the PR 17 contract:
- page allocator / page-bucket units (jax-free);
- paged engine output is BIT-IDENTICAL to the dense ``LMEngine`` through
  the real batcher, greedy and sampled, including shared-prefix admits;
- a prefix-cache hit produces identical tokens while booking
  ``serve.kv.prefix_hits`` and skipping the shared pages' prefill work;
- page-pool exhaustion HOLDS BACK admission (FIFO preserved) and sheds
  with a typed ``ServeBusy`` at the queue bound — never mid-decode
  corruption; an impossible request is rejected up front;
- page REUSE staleness: freed pages are poisoned with garbage and the next
  owner's tokens don't change (the dense slot-reuse invariant, re-pinned
  for pages — garbage must stay finite/bounded so the additive -1e9 mask
  zeroes it exactly; that bound is the documented invariant);
- router least-loaded spread, typed shed cascade, replay-with-same-rid
  around a killed replica (ZERO client-visible failures, >= 1 booked
  respawn), replica-side rid dedup (GL011: replay is idempotent), and
  alert-driven drain + scale-out via ``poll_once``;
- the new env flags are registered (GL007's runtime face).
"""

import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from autodist_tpu import telemetry  # noqa: E402
from autodist_tpu.coordinator import RespawnPolicy  # noqa: E402
from autodist_tpu.models import transformer_lm  # noqa: E402
from autodist_tpu.models.transformer_lm import TransformerLMConfig  # noqa: E402
from autodist_tpu.parallel import recovery as _recovery  # noqa: E402
from autodist_tpu.serving import (Batcher, LMEngine, PageAllocator,  # noqa: E402
                                  PagedLMEngine, Router, RouterServer,
                                  ServeBusy, ServeConfig, ServeError,
                                  InferenceServer, ServeClient,
                                  default_buckets, page_buckets)
from autodist_tpu.testing import faults  # noqa: E402


# ------------------------------------------------------------------ fixtures

def _small_cfg(**kw):
    kw.setdefault("vocab_size", 97)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("d_ff", 64)
    kw.setdefault("max_len", 32)
    kw.setdefault("dtype", jnp.float32)   # exact-comparison friendly
    return TransformerLMConfig(**kw)


@pytest.fixture(scope="module")
def lm():
    cfg = _small_cfg()
    model, params = transformer_lm.init_params(cfg)
    return model, params


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(1, 97, size=n).astype(np.int32)


def _drive(batcher, reqs, rounds=200):
    for _ in range(rounds):
        if all(r.done.is_set() for r in reqs):
            break
        batcher.run_once()
    assert all(r.done.is_set() for r in reqs), "batcher did not converge"


def _tokens_via_batcher(engine, config, requests):
    """Drive ``requests`` = [(prompt, max_new, seed), ...] through a real
    (unstarted) Batcher; returns each request's token tuple in input order."""
    b = Batcher(engine, config, start=False)
    reqs = [b.submit(p, n, seed=s) for p, n, s in requests]
    _drive(b, reqs)
    for r in reqs:
        assert r.error is None, r.error
    return [tuple(r.tokens) for r in reqs]


def _counter(name):
    v = telemetry.snapshot().get(name)
    return int(v) if isinstance(v, (int, float)) else 0


# --------------------------------------------------- page allocator units

def test_page_buckets_pow2_with_exact_max():
    assert page_buckets(8) == (1, 2, 4, 8)
    assert page_buckets(5) == (1, 2, 4, 5)    # non-pow2 max included
    assert page_buckets(1) == (1,)


def test_page_allocator_reserve_alloc_refcount():
    al = PageAllocator(5)          # 4 usable, page 0 is scratch
    assert al.usable == 4 and al.free_count() == 4
    al.reserve(3)
    assert al.available() == 1
    with pytest.raises(ServeError):
        al.reserve(2)              # over-reserve is a typed refusal
    pages = [al.alloc() for _ in range(3)]
    assert 0 not in pages and len(set(pages)) == 3
    assert al.free_count() == 1 and al.available() == 1
    # refcount: a shared page survives one release, dies at zero.
    al.retain(pages[0])
    al.release(pages[0])
    assert al.free_count() == 1
    al.release(pages[0])
    assert al.free_count() == 2
    for p in pages[1:]:
        al.release(p)
    assert al.free_count() == 4


def test_paged_engine_rejects_impossible_and_reserves():
    al = PageAllocator(3)
    al.reserve(2)
    with pytest.raises(AssertionError):
        # alloc beyond the reservation count is a programming error
        al.alloc(), al.alloc(), al.alloc()


# --------------------------------------------- paged vs dense bit-identity

# Mixed lengths, some sharing an 8-token (one-page at page_len=8) prefix —
# the shared-prefix admits exercise the split-prefill path against the
# dense engine's one-shot prefill.
_SHARED = _prompt(8, seed=7)
_REQUESTS = [
    (_prompt(5, seed=1), 4, 0),
    (np.concatenate([_SHARED, _prompt(6, seed=2)]), 5, 1),
    (_prompt(12, seed=3), 3, 2),
    (np.concatenate([_SHARED, _prompt(3, seed=4)]), 6, 3),
    (_prompt(1, seed=5), 4, 4),
    (np.concatenate([_SHARED, _prompt(9, seed=6)]), 2, 5),
]


@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_paged_matches_dense_bit_identical(lm, temperature):
    """The acceptance property: the paged engine's token streams equal the
    dense engine's bit for bit, through the real batcher, greedy and
    sampled, with prefix sharing in play."""
    model, params = lm
    dense_cfg = ServeConfig(max_batch=2, temperature=temperature)
    paged_cfg = ServeConfig(max_batch=2, temperature=temperature, page_len=8)
    dense = _tokens_via_batcher(LMEngine(model, params, dense_cfg),
                                dense_cfg, _REQUESTS)
    engine = PagedLMEngine(model, params, paged_cfg)
    paged = _tokens_via_batcher(engine, paged_cfg, _REQUESTS)
    assert paged == dense
    # Concurrency headroom: page capacity exceeds the dense slot count at
    # the same HBM budget (the whole point of paging).
    assert engine.capacity > dense_cfg.max_batch
    # Jit-cache boundedness: programs are keyed by (page-bucket, prompt
    # bucket), never by request — the compile count is bucket-bounded.
    n_prefill, n_total = engine.compiled_programs()
    max_prefill = len(engine.buckets) * len(page_buckets(engine.max_pages))
    assert n_prefill <= max_prefill
    assert n_total - n_prefill <= len(page_buckets(engine.max_pages))


def test_prefix_cache_hit_identical_tokens(lm):
    """A content-matched page-aligned prefix is REUSED (prefix_hits books)
    and the hit's tokens equal a no-cache engine's — shared pages are read
    immutably, divergence lands in the request's own pages."""
    model, params = lm
    cfg = ServeConfig(max_batch=2, page_len=8)
    requests = [(np.concatenate([_SHARED, _prompt(4, seed=11)]), 4, 0),
                (np.concatenate([_SHARED, _prompt(7, seed=12)]), 4, 1)]
    nocache_cfg = ServeConfig(max_batch=2, page_len=8, prefix_cache=False)
    want = _tokens_via_batcher(PagedLMEngine(model, params, nocache_cfg),
                               nocache_cfg, requests)
    hits0 = _counter("serve.kv.prefix_hits")
    engine = PagedLMEngine(model, params, cfg)
    got = _tokens_via_batcher(engine, cfg, requests)
    assert got == want
    assert _counter("serve.kv.prefix_hits") > hits0
    assert engine.pool_snapshot()["prefix_entries"] >= 1


def test_page_reuse_staleness_poisoned_pages_are_invisible(lm):
    """Satellite 6: freed pages return to the pool with stale K/V intact.
    Poison EVERY free page with bounded garbage, then serve a request —
    its tokens must equal a fresh engine's. (The invariant's boundary,
    documented in serving/paged.py: the -1e9 additive mask zeroes any
    FINITE bounded score exactly in f32 softmax; garbage of the same order
    as the mask would not be recoverable, which is why pages are only ever
    written by their owner.)"""
    model, params = lm
    cfg = ServeConfig(max_batch=2, page_len=8, prefix_cache=False)
    probe = [(_prompt(10, seed=21), 5, 3)]
    want = _tokens_via_batcher(PagedLMEngine(model, params, cfg), cfg, probe)

    engine = PagedLMEngine(model, params, cfg)
    # Occupy-and-free a first wave so real decode traffic has touched pages.
    warm = [(_prompt(14, seed=22), 6, 1), (_prompt(3, seed=23), 8, 2)]
    _tokens_via_batcher(engine, cfg, warm)
    assert engine.num_active == 0
    free_pages = np.asarray(engine._alloc._free, np.int32)
    assert free_pages.size > 0
    engine._pool = jax.tree_util.tree_map(
        lambda leaf: leaf if leaf.ndim == 0
        else leaf.at[free_pages].set(jnp.asarray(53.0, leaf.dtype)),
        engine._pool)
    got = _tokens_via_batcher(engine, cfg, probe)
    assert got == want


def test_page_exhaustion_holds_back_then_sheds(lm):
    """A pool too small for everyone HOLDS the overflow request back (FIFO:
    it completes later, correctly) and the queue bound sheds with a typed
    ServeBusy; a request that can NEVER fit is rejected up front."""
    model, params = lm
    # 3 usable pages; a 10-prompt/8-new request reserves all 3, so slots
    # (max_batch=3) are plentiful but pages admit ONE request at a time.
    cfg = ServeConfig(max_batch=3, page_len=8, kv_pages=4, max_queue=2,
                      prefix_cache=False)
    engine = PagedLMEngine(model, params, cfg)
    b = Batcher(engine, cfg, start=False)
    reqs = [b.submit(_prompt(10, seed=31), 8, seed=0)]
    b.run_once()
    assert engine.num_active == 1
    # Two more park behind the page budget (slots are free; pages are not)
    # and fill the queue; the next submit sheds with a typed ServeBusy.
    reqs += [b.submit(_prompt(10, seed=32 + i), 8, seed=1 + i)
             for i in range(2)]
    b.run_once()
    assert engine.num_active == 1
    # run_once parked the head-of-line request in the batcher's held slot,
    # freeing one queue position — one more filler refills the bound.
    reqs.append(b.submit(_prompt(4, seed=40), 4, seed=9))
    with pytest.raises(ServeBusy):
        b.submit(_prompt(4, seed=41), 4, seed=10)
    _drive(b, reqs)
    assert all(r.error is None for r in reqs)
    # Impossible request: needs more pages than the pool owns -> typed
    # rejection at admission, not head-of-line blocking.
    doomed = b.submit(_prompt(20, seed=42), 12, seed=11)
    b.run_once()
    assert doomed.done.is_set() and "KV pages" in (doomed.error or "")
    assert engine._alloc.available() == engine._alloc.usable  # ledger clean


# ------------------------------------------------------------- router units

class FakeEngine:
    """Deterministic jax-free engine (the test_batched_serving pattern):
    token = 100*slot + step index; optional per-step delay so requests stay
    in flight long enough to be killed mid-generation."""

    def __init__(self, capacity=2, max_len=32, step_s=0.0):
        self.capacity = capacity
        self.max_len = max_len
        self.buckets = default_buckets(max_len)
        self.admits = []
        self._steps = np.zeros(capacity, np.int64)
        self._step_s = step_s

    def make_keys(self, seed, n):
        return None

    def admit(self, slot, prompt, key):
        self.admits.append((slot, int(prompt.size)))
        self._steps[slot] = 0
        return 100 * slot

    def step(self, keys):
        if self._step_s:
            time.sleep(self._step_s)
        self._steps += 1
        return (100 * np.arange(self.capacity) + self._steps).astype(np.int32)

    def free(self, slot):
        pass


def _replica_factory(capacity=2, max_queue=8, step_s=0.0, engines=None):
    def factory():
        engine = FakeEngine(capacity=capacity, step_s=step_s)
        if engines is not None:
            engines.append(engine)
        b = Batcher(engine, ServeConfig(max_batch=capacity,
                                        max_queue=max_queue))
        return InferenceServer(b, port=0)
    return factory


@pytest.fixture
def clean_fleet_state():
    _recovery.reset()
    faults.clear()
    yield
    faults.clear()


def test_router_routes_and_spreads(clean_fleet_state):
    """Basic routing through RouterServer with an UNCHANGED ServeClient,
    least-loaded spread across both replicas under concurrency."""
    engines = []
    router = Router(_replica_factory(step_s=0.002, engines=engines),
                    n_replicas=2, start=False)
    server = RouterServer(router)
    routed0 = _counter("serve.router.routed")
    try:
        tokens, timing = ServeClient(server.address).generate(
            np.arange(1, 5), 3, seed=0)
        assert tokens.tolist() == [0, 1, 2]      # slot 0, steps 1..2
        assert "total_s" in timing
        results, errors = [], []

        def one(i):
            try:
                results.append(ServeClient(server.address).generate(
                    np.arange(1, 4), 4, seed=i)[0].tolist())
            except Exception as e:   # noqa: BLE001 - the assert reports it
                errors.append(repr(e))

        threads = [threading.Thread(target=one, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors and len(results) == 8
        assert _counter("serve.router.routed") - routed0 == 9
        # Least-loaded spread: with 8 concurrent requests over 2x capacity-2
        # replicas, both must have admitted work.
        assert all(engine.admits for engine in engines)
    finally:
        server.close()


def test_router_sheds_typed_busy_when_all_replicas_full(clean_fleet_state):
    """Both replicas' queues full -> the cascade tries everyone, then the
    router replies a typed ServeBusy instantly (serve.router.shed books).
    Deterministic: the replica batchers are never started, so their queues
    fill and stay full."""
    servers = []

    def factory():
        b = Batcher(FakeEngine(capacity=1), ServeConfig(max_batch=1,
                                                        max_queue=1),
                    start=False)
        server = InferenceServer(b, port=0)
        servers.append(server)
        return server

    router = Router(factory, n_replicas=2, start=False)
    server = RouterServer(router)
    shed0 = _counter("serve.router.shed")
    try:
        # Fill each replica's (unserviced) queue directly.
        for rep in servers:
            rep._batcher.submit(np.arange(1, 3), 2, seed=0)
        client = ServeClient(server.address)
        with pytest.raises(ServeBusy):
            client.generate(np.arange(1, 3), 2, seed=1)
        assert _counter("serve.router.shed") - shed0 == 1
    finally:
        server.close()


def test_kill_a_replica_completes_all_requests_zero_failures(
        clean_fleet_state, monkeypatch):
    """The PR's recovery acceptance: kill a replica with requests in flight;
    every request completes (replayed on a survivor with the SAME rid),
    zero client-visible failures, and the recovery plane books >= 1
    eviction + respawn + rejoin; the respawned replica carries a bumped
    generation and serves traffic."""
    monkeypatch.setattr(Router, "RESPAWN_BACKOFF_S", 0.02)
    router = Router(_replica_factory(step_s=0.01), n_replicas=2, start=False)
    server = RouterServer(router)
    replayed0 = _counter("serve.router.replayed")
    try:
        victim = router.replicas()[0]

        def killer():
            deadline = time.monotonic() + 5.0
            while victim.in_flight == 0 and time.monotonic() < deadline:
                time.sleep(0.001)
            victim.server.kill()

        results, errors = [], []

        def one(i):
            try:
                results.append(ServeClient(server.address).generate(
                    np.arange(1, 4), 8, seed=i)[0].tolist())
            except Exception as e:   # noqa: BLE001 - the assert reports it
                errors.append(repr(e))

        kt = threading.Thread(target=killer)
        kt.start()
        threads = [threading.Thread(target=one, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        kt.join()
        assert errors == []                       # ZERO client-visible failures
        assert len(results) == 6
        assert _counter("serve.router.replayed") > replayed0
        counts = _recovery.recovery_snapshot()["counts"]
        assert counts["evicted"] >= 1
        assert counts["respawns"] >= 1
        assert counts["rejoined"] >= 1
        live = [r for r in router.replicas() if not r.down]
        assert len(live) == 2                     # the fleet healed
        assert max(r.generation for r in live) == 1
        # The healed fleet serves.
        tokens, _ = ServeClient(server.address).generate(
            np.arange(1, 3), 2, seed=99)
        assert len(tokens) == 2
    finally:
        server.close()


def test_rid_dedup_replay_is_idempotent(clean_fleet_state):
    """GL011 at the replica: re-sending a completed request-id returns the
    CACHED reply without re-generating (one admit), so the router's replay
    after a replica death can never double-generate."""
    engine = FakeEngine(capacity=1)
    server = InferenceServer(Batcher(engine, ServeConfig(max_batch=1)),
                             port=0)
    try:
        from autodist_tpu.parallel.ps_transport import _PSClient
        client = _PSClient(server.address, connect_timeout=10.0)
        try:
            prompt = np.arange(1, 4).astype(np.int32)
            first = client.call("generate", prompt, 3, 0, None, "rid-x")
            again = client.call("generate", prompt, 3, 0, None, "rid-x")
            assert np.array_equal(first[0], again[0])
            assert first[1] == again[1]           # cached timing, same reply
            assert len(engine.admits) == 1        # generated ONCE
        finally:
            client.close()
    finally:
        server.close()


def test_router_drains_and_scales_out_on_alert(clean_fleet_state,
                                               monkeypatch):
    """serve_p99_burn active on a replica -> poll_once drains it (no new
    routes) and scales out on the respawn budget; the alert clearing
    rejoins it."""
    monkeypatch.setattr(Router, "RESPAWN_BACKOFF_S", 0.01)
    router = Router(_replica_factory(), n_replicas=2, start=False)
    try:
        burning = router.replicas()[0]
        real_call = burning.call
        burn_status = {"alerts": {"active": [{"rule": "serve_p99_burn"}]}}
        burning.call = lambda op, *a: (burn_status,) if op == "status" \
            else real_call(op, *a)
        router.poll_once()
        assert burning.draining
        assert len(router.replicas()) == 3        # scaled out
        assert router._pick([]) is not burning    # no new routes while draining
        counts = _recovery.recovery_snapshot()["counts"]
        assert counts["rejoined"] >= 1            # the scale-out replica
        # Alert clears -> the drained replica rejoins the rotation.
        burning.call = real_call
        router.poll_once()
        assert not burning.draining
        # Scale-out is bounded: every further poll with the alert active
        # must not exceed max_replicas.
        burning.call = lambda op, *a: (burn_status,) if op == "status" \
            else real_call(op, *a)
        for _ in range(router.max_replicas + 2):
            router.poll_once()
        assert len(router.replicas()) <= router.max_replicas
    finally:
        router.close()


def test_fault_hook_kills_replica_deterministically(clean_fleet_state,
                                                    monkeypatch):
    """testing/faults.py drives the SAME kill path deterministically: a
    worker_crash point matched on the router's request sequence kills the
    chosen replica before forwarding; the request still completes via
    replay. This is the bench's kill-a-replica mechanism."""
    monkeypatch.setattr(Router, "RESPAWN_BACKOFF_S", 0.02)
    router = Router(_replica_factory(), n_replicas=2, start=False)
    server = RouterServer(router)
    try:
        faults.install("worker_crash@step=1")
        client = ServeClient(server.address)
        t0 = client.generate(np.arange(1, 3), 2, seed=0)[0]   # seq 0: clean
        t1 = client.generate(np.arange(1, 3), 2, seed=1)[0]   # seq 1: killed
        assert len(t0) == 2 and len(t1) == 2
        counts = _recovery.recovery_snapshot()["counts"]
        assert counts["evicted"] == 1 and counts["respawns"] == 1
    finally:
        server.close()


def test_respawn_policy_budget_and_booking(clean_fleet_state):
    """RespawnPolicy (the coordinator's discipline, shared with the router):
    AUTODIST_RECOVER_MAX grants per key, each booked as recover.respawn,
    then None (the caller escalates)."""
    policy = RespawnPolicy(base_s=0.0, cap_s=0.0)
    budget = policy.budget()
    delays = [policy.grant("10.0.0.9:7000") for _ in range(budget)]
    assert all(d is not None for d in delays)
    assert policy.grant("10.0.0.9:7000") is None      # budget spent
    assert policy.grant("10.0.0.8:7000") is not None  # per-key ledger
    assert _recovery.recovery_snapshot()["counts"]["respawns"] == budget + 1


def test_fleet_flags_registered():
    """GL007's runtime face: the new flags resolve through const.ENV with
    their documented defaults."""
    from autodist_tpu import const
    for name in ("AUTODIST_SERVE_REPLICAS", "AUTODIST_KV_PAGE_LEN",
                 "AUTODIST_PREFIX_CACHE", "AUTODIST_ROUTER_ADDR"):
        assert name in const.KNOWN_FLAGS
    assert int(const.ENV.AUTODIST_SERVE_REPLICAS.val) == 2
    assert int(const.ENV.AUTODIST_KV_PAGE_LEN.val) == 0
    assert bool(const.ENV.AUTODIST_PREFIX_CACHE.val) is True


def test_router_status_renders_in_consoles(clean_fleet_state):
    """The kind="router" status payload renders a replica table in adtop
    and a replicas/shed row in adfleet (the PR's console satellite)."""
    router = Router(_replica_factory(), n_replicas=2, start=False)
    server = RouterServer(router)
    try:
        status = ServeClient(server.address).status()
        assert status["kind"] == "router"
        assert len(status["replicas"]) == 2
        import importlib.util
        import os
        for tool in ("adtop", "adfleet"):
            spec = importlib.util.spec_from_file_location(
                tool, os.path.join(os.path.dirname(__file__), os.pardir,
                                   "tools", f"{tool}.py"))
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            if tool == "adtop":
                screen = mod.render(status, "x:1")
                assert "router   routed" in screen
                assert "replica" in screen
            else:
                screen = mod.render({"x:1": status})
                assert "replicas 2/2 up" in screen
    finally:
        server.close()
