"""The 8 strategy builders — policy parity with reference autodist/strategy/*."""

import jax.numpy as jnp
import pytest

from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.proto import strategy_pb2
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import (AllReduce, Parallax, PartitionedAR, PartitionedPS,
                                   PS, PSLoadBalancing, RandomAxisPartitionAR,
                                   UnevenPartitionedPS, byte_size_load_fn)
from autodist_tpu.strategy.partition_utils import (smallest_divisor_at_least_2,
                                                   smallest_non_divisor_at_least_2)

RES = ResourceSpec("nodes: [{address: localhost, tpus: 8}]")
RES_REDUCE4 = ResourceSpec("{nodes: [{address: localhost, tpus: 8}], mesh: {reduce: 4, data: 2}}")


def _model(sparse=False):
    params = {
        "emb": jnp.zeros((12, 4)),     # 48 floats
        "w1": jnp.zeros((7, 3)),       # 21 floats, dim0 prime
        "w2": jnp.zeros((4, 4)),       # 16 floats
        "b": jnp.zeros((3,)),          # 3 floats
        "s": jnp.zeros(()),            # scalar
    }
    return ModelSpec(params, sparse_names=["emb"] if sparse else [])


def test_ps_all_vars_single_destination():
    s = PS().build(_model(), RES)
    assert len(s.node_config) == 5
    for n in s.node_config:
        assert n.WhichOneof("synchronizer") == "ps_synchronizer"
        assert n.ps_synchronizer.reduction_destination == "reduce:0"
        assert n.ps_synchronizer.sync
    # PS defaults to full weight-update sharding
    assert s.mesh_axes()["reduce"] == 8


def test_ps_lb_greedy_balance():
    s = PSLoadBalancing().build(_model(), RES_REDUCE4)
    dests = {n.var_name: n.ps_synchronizer.reduction_destination for n in s.node_config}
    # largest param (emb) goes to the first empty shard; the rest balance greedily
    assert len(set(dests.values())) == 4
    loads = {}
    model = _model()
    for name, d in dests.items():
        loads[d] = loads.get(d, 0) + byte_size_load_fn(model[name])
    # max load <= emb alone + smallest (greedy bound for this tiny instance)
    assert max(loads.values()) == byte_size_load_fn(model["emb"])


def test_partitioned_ps_shard_counts():
    s = PartitionedPS().build(_model(), RES_REDUCE4)
    nodes = {n.var_name: n for n in s.node_config}
    # emb dim0=12 -> smallest divisor 2
    assert list(nodes["emb"].partitioner.num_shards) == [2, 1]
    assert len(nodes["emb"].part_config) == 2
    assert nodes["emb"].part_config[0].var_name == "emb/part_0"
    # w1 dim0=7 prime -> divisor 7 = dim0 itself
    assert list(nodes["w1"].partitioner.num_shards) == [7, 1]
    # scalar s and b(dim0=3... prime=3 <= cap) get partitioned or fall back
    assert not nodes["s"].HasField("partitioner")


def test_uneven_partitioned_ps_non_divisor():
    s = UnevenPartitionedPS().build(_model(), RES_REDUCE4)
    nodes = {n.var_name: n for n in s.node_config}
    # emb dim0=12: smallest non-divisor >= 2 is 5
    assert list(nodes["emb"].partitioner.num_shards) == [5, 1]
    # w1 dim0=7: smallest non-divisor is 2
    assert list(nodes["w1"].partitioner.num_shards) == [2, 1]


def test_all_reduce_groups_and_compressor():
    s = AllReduce(chunk_size=2, compressor="HorovodCompressor").build(_model(), RES)
    groups = [n.all_reduce_synchronizer.group for n in s.node_config]
    assert groups == [0, 0, 1, 1, 2]
    for n in s.node_config:
        assert n.all_reduce_synchronizer.compressor == strategy_pb2.AllReduceSynchronizer.BF16
    assert s.mesh_axes()["data"] == 8


def test_all_reduce_rejects_bad_args():
    with pytest.raises(ValueError):
        AllReduce(chunk_size=0)
    with pytest.raises(ValueError):
        AllReduce(compressor="zip")
    with pytest.raises(ValueError):
        AllReduce(all_reduce_spec="banana")


def test_partitioned_ar_running_group_counter():
    s = PartitionedAR(chunk_size=3).build(_model(), RES)
    shards = []
    for n in s.node_config:
        if n.HasField("partitioner"):
            shards.extend(p.all_reduce_synchronizer.group for p in n.part_config)
        else:
            shards.append(n.all_reduce_synchronizer.group)
    # groups increase every chunk_size shards
    assert shards == sorted(shards)
    assert shards[0] == 0 and shards[-1] == (len(shards) - 1) // 3


def test_random_axis_deterministic_and_sparse_axis0():
    s1 = RandomAxisPartitionAR(seed=7).build(_model(sparse=True), RES)
    s2 = RandomAxisPartitionAR(seed=7).build(_model(sparse=True), RES)
    assert s1.proto.node_config == s2.proto.node_config
    nodes = {n.var_name: n for n in s1.node_config}
    if nodes["emb"].HasField("partitioner"):
        ns = list(nodes["emb"].partitioner.num_shards)
        assert ns[0] > 1 and all(x == 1 for x in ns[1:])  # sparse forced to axis 0


def test_parallax_routes_sparse_to_ps():
    s = Parallax().build(_model(sparse=True), RES)
    nodes = {n.var_name: n for n in s.node_config}
    assert nodes["emb"].WhichOneof("synchronizer") == "ps_synchronizer"
    assert nodes["w1"].WhichOneof("synchronizer") == "all_reduce_synchronizer"
    assert nodes["emb"].sparse


def test_divisor_helpers():
    assert smallest_divisor_at_least_2(12) == 2
    assert smallest_divisor_at_least_2(7) == 7
    assert smallest_divisor_at_least_2(9) == 3
    assert smallest_divisor_at_least_2(1) is None
    assert smallest_divisor_at_least_2(7, cap=5) is None
    assert smallest_non_divisor_at_least_2(12) == 5
    assert smallest_non_divisor_at_least_2(7) == 2
    assert smallest_non_divisor_at_least_2(1) is None
