"""Host data pipeline (native prefetch loader + async device prefetch +
per-host sharded loading + datasets)."""

from autodist_tpu.data import imagenet, mlm, movielens, prefetch, text_corpus
from autodist_tpu.data.loader import (DataLoader, device_prefetch,
                                      save_shards, shard_files_for_process)
from autodist_tpu.data.prefetch import (BoundedQueue, PrefetchProducer,
                                        assemble_global_batch, host_shard,
                                        host_shard_rows, prefetch_to_device)

__all__ = ["DataLoader", "device_prefetch", "save_shards",
           "shard_files_for_process", "imagenet", "mlm", "movielens",
           "text_corpus", "prefetch", "BoundedQueue", "PrefetchProducer",
           "prefetch_to_device", "host_shard", "host_shard_rows",
           "assemble_global_batch"]
