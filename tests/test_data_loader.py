"""Native + fallback data loader: batch semantics, shuffle, prefetch, device
feed, and file-backed (memory-mapped .npy shard) datasets."""

import numpy as np
import pytest

from autodist_tpu.data import DataLoader, device_prefetch, save_shards


def _dataset(n=64, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "x": rng.randn(n, 5).astype(np.float32),
        "y": rng.randint(0, 10, size=(n,)).astype(np.int32),
    }


def test_native_loader_builds_and_serves_correct_rows():
    data = _dataset()
    dl = DataLoader(data, batch_size=16, shuffle=True, seed=3, native=True)
    assert dl.is_native
    row_lookup = {tuple(np.round(r, 5)): i for i, r in enumerate(data["x"])}
    seen = set()
    for _ in range(4):  # one epoch: 64/16 batches
        batch = dl.next()
        assert batch["x"].shape == (16, 5) and batch["y"].shape == (16,)
        for bx, by in zip(batch["x"], batch["y"]):
            i = row_lookup[tuple(np.round(bx, 5))]     # row exists in the dataset
            assert data["y"][i] == by                  # arrays stay row-aligned
            seen.add(i)
    assert len(seen) == 64  # a full epoch covers every row exactly once
    dl.close()


def test_native_matches_fallback_semantics_unshuffled():
    data = _dataset(n=20)
    native = DataLoader(data, batch_size=8, shuffle=False, native=True)
    fallback = DataLoader(data, batch_size=8, shuffle=False, native=False)
    assert native.is_native and not fallback.is_native
    for _ in range(5):  # crosses the drop-last boundary (20 = 2*8 + 4 dropped)
        nb, fb = native.next(), fallback.next()
        np.testing.assert_array_equal(nb["x"], fb["x"])
        np.testing.assert_array_equal(nb["y"], fb["y"])
    # Epoch counting: fallback counts consumed wraps exactly; the native counter
    # is producer-side and may run up to `prefetch` batches ahead.
    assert fallback.epochs_completed == 2
    assert native.epochs_completed >= 2
    native.close()


def test_shuffle_is_seed_deterministic():
    data = _dataset()
    a = DataLoader(data, batch_size=16, shuffle=True, seed=7, native=True)
    b = DataLoader(data, batch_size=16, shuffle=True, seed=7, native=True)
    for _ in range(6):
        np.testing.assert_array_equal(a.next()["x"], b.next()["x"])
    a.close(), b.close()


def test_loader_validates_inputs():
    data = _dataset(n=8)
    with pytest.raises(ValueError, match="batch_size"):
        DataLoader(data, batch_size=9)
    with pytest.raises(ValueError, match="leading dim"):
        DataLoader({"x": np.zeros((4, 2)), "y": np.zeros((5,))}, batch_size=2)
    with pytest.raises(ValueError, match="at least one"):
        DataLoader({}, batch_size=1)


# ------------------------------------------------------------ file-backed

def test_file_backed_loader_streams_shards(tmp_path):
    """files=: multiple row-aligned .npy shards per key, mmap'd, virtually
    concatenated; the native gather serves the exact same rows as the
    in-memory loader over the concatenated data."""
    data = _dataset(n=100, seed=5)
    files = save_shards(data, str(tmp_path), rows_per_shard=33)  # 33/33/33/1
    assert len(files["x"]) == 4
    dl = DataLoader(files=files, batch_size=10, shuffle=True, seed=2,
                    native=True)
    assert dl.is_native and dl.n_rows == 100
    row_lookup = {tuple(np.round(r, 5)): i for i, r in enumerate(data["x"])}
    seen = set()
    for _ in range(10):  # one epoch
        batch = dl.next()
        for bx, by in zip(batch["x"], batch["y"]):
            i = row_lookup[tuple(np.round(bx, 5))]
            assert data["y"][i] == by      # keys stay row-aligned ACROSS shards
            seen.add(i)
    assert len(seen) == 100
    dl.close()


@pytest.mark.parametrize("native", [True, False])
def test_file_backed_matches_in_memory(tmp_path, native):
    """Same seed => a file-backed loader is row-for-row identical to the
    in-memory loader over the same data, native and fallback alike."""
    data = _dataset(n=48, seed=9)
    files = save_shards(data, str(tmp_path), rows_per_shard=20)
    mem = DataLoader(data, batch_size=8, shuffle=True, seed=4, native=native)
    fil = DataLoader(files=files, batch_size=8, shuffle=True, seed=4,
                     native=native)
    for _ in range(12):
        a, b = mem.next(), fil.next()
        np.testing.assert_array_equal(a["x"], b["x"])
        np.testing.assert_array_equal(a["y"], b["y"])
    mem.close(), fil.close()


def test_file_backed_dataset_larger_than_prefetch_ring(tmp_path):
    """A dataset far larger than the prefetch ring (ring = 2 batches of 4 rows;
    dataset = 10k rows across 7 shards) streams through mmap without
    materializing: full-epoch coverage with every row served exactly once."""
    n = 10_000
    rng = np.random.RandomState(1)
    ids = np.arange(n, dtype=np.int64)
    payload = rng.randint(0, 1 << 30, size=(n, 8)).astype(np.int64)
    files = save_shards({"id": ids, "payload": payload}, str(tmp_path),
                        rows_per_shard=1500)  # 6x1500 + 1000
    dl = DataLoader(files=files, batch_size=4, shuffle=True, seed=0,
                    prefetch=2, native=True)
    seen = np.zeros(n, np.int32)
    for _ in range(n // 4):
        b = dl.next()
        seen[b["id"]] += 1
        # row alignment holds for a spot row
        np.testing.assert_array_equal(b["payload"][0], payload[b["id"][0]])
    assert (seen == 1).all()   # exactly one epoch, every row once
    dl.close()


def test_file_backed_validates_alignment(tmp_path):
    np.save(str(tmp_path / "x-0.npy"), np.zeros((10, 2), np.float32))
    np.save(str(tmp_path / "x-1.npy"), np.zeros((5, 2), np.float32))
    np.save(str(tmp_path / "y-0.npy"), np.zeros((10,), np.int32))
    np.save(str(tmp_path / "y-1.npy"), np.zeros((6,), np.int32))
    with pytest.raises(ValueError, match="row-aligned"):
        DataLoader(files={"x": [str(tmp_path / "x-0.npy"),
                                str(tmp_path / "x-1.npy")],
                          "y": [str(tmp_path / "y-0.npy"),
                                str(tmp_path / "y-1.npy")]}, batch_size=2)
    np.save(str(tmp_path / "bad.npy"), np.zeros((5, 3), np.float32))
    with pytest.raises(ValueError, match="first shard"):
        DataLoader(files={"x": [str(tmp_path / "x-0.npy"),
                                str(tmp_path / "bad.npy")]}, batch_size=2)
    with pytest.raises(ValueError, match="exactly one"):
        DataLoader({"x": np.zeros((4, 2))}, batch_size=2,
                   files={"x": str(tmp_path / "x-0.npy")})


def test_file_backed_refuses_fortran_order_shard(tmp_path):
    """A Fortran-order .npy shard must be refused, not silently materialized:
    ascontiguousarray on the mmap would copy the whole file into RAM."""
    np.save(str(tmp_path / "f.npy"), np.asfortranarray(np.arange(24, dtype=np.float32)
                                                       .reshape(6, 4)))
    with pytest.raises(ValueError, match="C-contiguous"):
        DataLoader(files={"f": str(tmp_path / "f.npy")}, batch_size=2)
    # In-memory Fortran inputs still take the (cheap, explicit) copy path.
    dl = DataLoader({"f": np.asfortranarray(np.zeros((6, 4), np.float32))},
                    batch_size=2)
    assert dl.next()["f"].shape == (2, 4)
    dl.close()
    # arrays= keeps accepting memmap VIEWS too (copies the selected rows only
    # — the refusal is scoped to the files= streaming contract).
    np.save(str(tmp_path / "c.npy"), np.arange(40, dtype=np.float32).reshape(10, 4))
    mm = np.load(str(tmp_path / "c.npy"), mmap_mode="r")
    dl = DataLoader({"c": mm[::2]}, batch_size=2, shuffle=False)
    assert np.array_equal(dl.next()["c"],
                          np.arange(40, dtype=np.float32).reshape(10, 4)[::2][:2])
    dl.close()


def test_device_prefetch_feeds_training():
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.strategy import AllReduce

    rng = np.random.RandomState(0)
    w_true = rng.randn(5, 1).astype(np.float32)
    x = rng.randn(64, 5).astype(np.float32)
    data = {"x": x, "y": (x @ w_true + 0.01 * rng.randn(64, 1)).astype(np.float32)}

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    params = {"w": np.zeros((5, 1), np.float32)}
    dl = DataLoader(data, batch_size=16, shuffle=True, seed=0)
    ad = AutoDist(strategy_builder=AllReduce())
    step = ad.function(loss_fn, params, optax.sgd(0.1),
                       example_batch=dl.next())
    feed = device_prefetch(dl, step.runner, depth=2)
    losses = [float(step(next(feed))) for _ in range(20)]
    assert losses[-1] < 0.1 * losses[0]
    feed.close()   # stop the producer before its loader goes away
    dl.close()


def test_shard_files_for_process(tmp_path):
    """File-granularity multi-host input sharding (the reference's
    dataset.shard over its file list): processes get disjoint shard subsets
    that stay row-aligned across keys and cover every row exactly once."""
    import pytest

    from autodist_tpu.data import save_shards, shard_files_for_process

    rng = np.random.RandomState(0)
    arrays = {"a": rng.randn(50, 3).astype(np.float32),
              "b": np.arange(50, dtype=np.int32)}
    files = save_shards(arrays, str(tmp_path), rows_per_shard=8)  # 7 shards

    seen = []
    for pid in range(3):
        mine = shard_files_for_process(files, pid, 3)
        # Same shard indices for every key: row alignment survives.
        assert [p.split("-")[-1] for p in mine["a"]] == \
               [p.split("-")[-1] for p in mine["b"]]
        dl = DataLoader(files=mine, batch_size=1, shuffle=False, native=False)
        for _ in range(dl.n_rows):
            seen.append(int(dl.next()["b"][0]))
        dl.close()
    assert sorted(seen) == list(range(50))  # disjoint and complete

    with pytest.raises(ValueError, match="cannot feed"):
        shard_files_for_process(files, 7, 8)
    with pytest.raises(ValueError, match="out of"):
        shard_files_for_process(files, 3, 3)
