"""Bounded-staleness async parameter-server training across two processes.

Run it directly (CPU backend, loopback "2-node" cluster):

    PYTHONPATH=. python examples/async_ps_train.py

What happens, all through the public API (no manual transport plumbing):

1. The chief builds ``PS(sync=True, staleness=2)`` for a 2-node resource spec.
   ``create_distributed_session`` detects the non-synchronous regime: the
   processes stay independent JAX programs joined by the chief's parameter
   service instead of one SPMD collective program (the reference's async PS
   regime, ``ps_synchronizer.py:387-458``, rode its grpc plane the same way).
2. The Coordinator re-executes THIS script on the second "node" with the
   worker role env and the PS transport address.
3. Both processes call ``step(batch)``. The chief steps its local worker slot;
   the worker process pulls parameters over the TCP transport, computes
   gradients on its own devices, and pushes them back. The chief's
   staleness gate keeps any worker at most ``STALENESS`` steps ahead of the
   slowest one.
4. Parameter pulls are version-conditional (``read_if_newer``): a worker whose
   gate opened with no intervening updates re-uses its cached tree instead of
   re-downloading identical parameters — the summary prints the wire bytes the
   cache saved.

The chief prints a summary: applied update count (= both processes' steps),
each side's losses, and the worker's transport wire accounting.
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # the axon plugin overrides the env var

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from autodist_tpu import AutoDist, const  # noqa: E402
from autodist_tpu.strategy import PS  # noqa: E402

# Two "nodes" on loopback; on a real cluster these are distinct hosts (plus
# ssh_config entries) and the same script runs unchanged on each.
SPEC = ("nodes: [{address: localhost, tpus: 2, chief: true}, "
        "{address: 127.0.0.1, tpus: 2}]")
STALENESS = 2
STEPS = 8
LR = 0.05
DIM = 64


def make_batch(step: int):
    rng = np.random.RandomState(100 + step)
    x = rng.randn(32, DIM).astype(np.float32)
    w_true = np.linspace(-1.0, 1.0, DIM, dtype=np.float32)[:, None]
    y = x @ w_true + 0.5 + 0.05 * rng.randn(32, 1).astype(np.float32)
    return {"x": x, "y": y}


def loss_fn(p, b):
    pred = b["x"] @ p["w"] + p["b"]
    return jnp.mean((b["y"] - pred) ** 2)


def main(steps: int, staleness: int, out_path: str = None):
    if not const.is_worker():
        # A stale report from a previous run must not mask a worker crash.
        try:
            os.remove(_worker_report_path())
        except FileNotFoundError:
            pass
    ad = AutoDist(SPEC, PS(sync=True, staleness=staleness))
    params = {"w": np.zeros((DIM, 1), np.float32),
              "b": np.zeros((1,), np.float32)}
    step = ad.function(loss_fn, params, optax.adam(LR),
                       example_batch=make_batch(0))

    role = "worker" if const.is_worker() else "chief"
    losses = []
    for i in range(steps):
        loss = float(step(make_batch(i)))
        losses.append(loss)
        print(f"[{role}] step {i}: loss={loss:.4f}")

    if const.is_worker():
        # The worker's step closure drives a RemotePSWorker over the transport;
        # report its wire accounting back to the chief via a scratch file.
        remote = getattr(step.runner, "_remote_worker", None)
        wire = getattr(remote, "wire_bytes", (0, 0)) if remote else (0, 0)
        report = {"worker_losses": losses, "wire_sent": wire[0],
                  "wire_received": wire[1]}
        with open(_worker_report_path(), "w") as f:
            json.dump(report, f)
        return

    # Chief: wait for the worker process, then summarize the shared service.
    if not ad._coordinator.join(timeout=300.0):
        raise RuntimeError("worker process did not finish")
    runner = step.runner
    deadline = time.time() + 30
    while runner.service.updates_applied < 2 * steps and time.time() < deadline:
        time.sleep(0.05)
    try:
        with open(_worker_report_path()) as f:
            worker = json.load(f)
    except FileNotFoundError:
        worker = {}
    summary = {
        "applied_updates": runner.service.updates_applied,
        "chief_steps": steps,
        "worker_steps": len(worker.get("worker_losses", [])),
        "chief_final_loss": losses[-1],
        "worker_final_loss": (worker.get("worker_losses") or [None])[-1],
        "worker_wire_sent_bytes": worker.get("wire_sent"),
        "worker_wire_received_bytes": worker.get("wire_received"),
    }
    print("async PS summary:", json.dumps(summary, indent=2))
    if out_path:
        with open(out_path, "w") as f:
            json.dump(summary, f)
    assert summary["applied_updates"] == 2 * steps, summary


def _worker_report_path() -> str:
    return os.path.join(const.DEFAULT_WORKING_DIR, "async_ps_worker_report.json")


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--steps", type=int, default=STEPS)
    parser.add_argument("--staleness", type=int, default=STALENESS)
    parser.add_argument("--out", type=str, default=None)
    args, _ = parser.parse_known_args()
    main(args.steps, args.staleness, args.out)
