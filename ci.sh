#!/usr/bin/env bash
# One-command CI for autodist_tpu (the reference gated merges on an equivalent
# harness: lint -> unit -> integration -> real distributed stage,
# reference Jenkinsfile:24-131).
#
# Usage:
#   ./ci.sh            # lint + full suite + multi-chip dryrun + bench smoke
#   ./ci.sh --fast     # lint + suite only (skip dryrun + bench)
#   ./ci.sh --dist     # ONLY the distributed ssh-stage rehearsal (the
#                      # docker/compose.dist.yml sequence as local processes:
#                      # Cluster's real ssh branch through docker/ssh_shim,
#                      # strategy scp + worker relaunch + jax.distributed join)
#
# Environment notes (baked in below so a fresh clone needs nothing):
# - The test suite and dryrun run on an 8-device virtual CPU mesh
#   (XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu).
# - PYTHONPATH must APPEND to any existing value: on TPU images the accelerator
#   PJRT plugin registers via a sitecustomize dir already on PYTHONPATH;
#   replacing the variable wholesale breaks accelerator access.
# - bench.py runs on whatever platform is active (real TPU if present, CPU
#   otherwise — it scales its shapes down on CPU and prints one JSON line).

set -euo pipefail
cd "$(dirname "$0")"

REPO_ROOT="$(pwd)"
export PYTHONPATH="${REPO_ROOT}${PYTHONPATH:+:$PYTHONPATH}"
FAST=0
[[ "${1:-}" == "--fast" ]] && FAST=1

if [[ "${1:-}" == "--dist" ]]; then
    echo "=== distributed stage rehearsal (compose.dist.yml sequence, ssh shim) ==="
    JAX_PLATFORMS=cpu python -m pytest tests/test_ssh_stage.py -q
    echo "=== dist stage OK ==="
    exit 0
fi

echo "=== [1/5] lint ==="
# Prefer a real linter when the environment has one; otherwise fall back to a
# full-tree syntax check (this image ships no ruff/flake8).
if python -m ruff --version >/dev/null 2>&1; then
    python -m ruff check autodist_tpu tests examples
elif python -m flake8 --version >/dev/null 2>&1; then
    python -m flake8 autodist_tpu tests examples
else
    echo "(no ruff/flake8 in this environment; running compileall syntax check)"
    python -m compileall -q autodist_tpu tests examples bench.py __graft_entry__.py
fi
python - <<'EOF'
import autodist_tpu  # the package must import cleanly, no side effects required
print("import autodist_tpu OK:", autodist_tpu.__name__)
EOF
# graftlint: the project-specific analyzer (lock-across-dispatch and lock
# order — now WHOLE-PROGRAM across module boundaries — donation, tracer
# leaks, wire opcodes, env-flag registry, test-window rules, metric-name
# registry, resource-close discipline, wire-retry idempotency —
# docs/usage/static_analysis.md). Hard gate: NEW findings fail; the
# committed baseline (tools/graftlint_baseline.json) grandfathers old ones.
if ! python tools/graftlint.py --format json autodist_tpu tests examples bench.py > /tmp/graftlint.json; then
    echo "graftlint: NEW findings — fix, or suppress with '# graftlint: disable=GLnnn(reason)':"
    python tools/graftlint.py autodist_tpu tests examples bench.py || true
    exit 1
fi
# Warm-path assertion: the run above populated .graftlint_cache; an
# immediate identical run must hit the whole-program cache layer (this is
# what keeps stage 1 from growing linearly with the interprocedural pass).
# `|| true`: if the cached result ever DIVERGES to failing, the python
# assert below must get to print the diagnosis, not set -e at this line.
python tools/graftlint.py --format json autodist_tpu tests examples bench.py > /tmp/graftlint2.json || true
python - <<'EOF'
import json, os
d = json.load(open("/tmp/graftlint.json"))
d2 = json.load(open("/tmp/graftlint2.json"))
assert d2["ok"] == d["ok"] and len(d2["findings"]) == len(d["findings"]), \
    "graftlint cached result diverged from the live run"
if os.path.exists(".graftlint_cache/cache.json"):
    assert d2["cache"]["program_hit"], \
        f"graftlint cache warm path broken: {d2['cache']}"
    warm = f"(warm re-run: {d2['wall_time_s']}s, whole-program cache hit)"
else:
    # Unwritable cache dir (read-only checkout, full disk): a cache that
    # cannot persist is a slow cache, not a lint failure.
    warm = "(cache did not persist; warm-path assertion skipped)"
print(f"graftlint OK: {d['files_checked']} files in {d['wall_time_s']}s, "
      f"{len(d['suppressed'])} suppressed, {len(d['baselined'])} baselined "
      f"{warm}")
EOF

echo "=== [2/5] runtime sanitizer (graftsan) + crosscheck ==="
# Three cheap suites run with the concurrency sanitizer fully armed: the data
# plane's prefetch/loader threading, the fleet router units, and the request-
# trace plane (all FakeEngine — no LM build). A dynamic ABBA, an untimed wait,
# or a leaked non-daemon thread raises in-test; the artifact's meta line
# double-checks zero recorded violations. ~30s total
# (docs/usage/static_analysis.md#runtime-sanitizer-graftsan).
rm -f .graftlint_cache/observed_locks.jsonl
AUTODIST_SANITIZE=locks,waits,threads JAX_PLATFORMS=cpu python -m pytest -q \
    tests/test_data_plane.py \
    tests/test_reqtrace.py \
    tests/test_serve_fleet.py::test_router_routes_and_spreads \
    tests/test_serve_fleet.py::test_router_sheds_typed_busy_when_all_replicas_full \
    tests/test_serve_fleet.py::test_kill_a_replica_completes_all_requests_zero_failures \
    tests/test_serve_fleet.py::test_rid_dedup_replay_is_idempotent \
    tests/test_serve_fleet.py::test_router_drains_and_scales_out_on_alert \
    tests/test_serve_fleet.py::test_fault_hook_kills_replica_deterministically \
    tests/test_serve_fleet.py::test_respawn_policy_budget_and_booking \
    tests/test_serve_fleet.py::test_fleet_flags_registered \
    tests/test_serve_fleet.py::test_router_status_renders_in_consoles
python - <<'EOF'
import json
path = ".graftlint_cache/observed_locks.jsonl"
lines = [json.loads(l) for l in open(path, encoding="utf-8")]
assert lines, f"{path}: sanitizer exported nothing"
metas = [l["meta"] for l in lines if "meta" in l]
assert metas, f"{path}: no meta header"
bad = sum(m["violations"] for m in metas)
assert bad == 0, f"sanitizer recorded {bad} violation(s) — see the armed run"
print(f"graftsan OK: {sum(m['edges'] for m in metas)} observed lock-order "
      f"edge(s), {metas[-1]['locks_tracked']} lock site(s), 0 violations")
EOF
# The observed edges feed straight back into the static analyzer: a cycle in
# the merged runtime digraph or an edge opposite a static nesting fails here;
# never-observed static edges print as informational "unexercised" coverage.
python tools/graftlint.py --crosscheck

echo "=== [3/5] test suite (8-device CPU-sim mesh) ==="
# Sharded across 4 pytest processes (tools/parallel_tests.py): the slow tail
# is multi-process-cluster latency, not CPU, so sharding overlaps those waits
# with the compile-heavy files (41:31 -> 35:00 on this image's single core;
# bigger wins on multi-core hosts). AUTODIST_CI_SERIAL=1 forces the classic
# single-process run.
if [[ "${AUTODIST_CI_SERIAL:-0}" == "1" ]]; then
    python -m pytest tests/ -q
else
    # --no-lint: stage [1/4] above already gated on graftlint.
    python tools/parallel_tests.py -n 4 --no-lint
fi

if [[ "$FAST" == "1" ]]; then
    echo "=== --fast: skipping dryrun + bench ==="
    exit 0
fi

echo "=== [4/5] multi-chip dryrun (virtual 8-device mesh + real 2- and 4-process legs) ==="
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "=== [5/5] bench smoke ==="
# ZeRO weight-update sharding gate FIRST: it must run in a fresh process so
# it can simulate a dp=2 CPU mesh before the backend initializes; gates the
# per-device opt-state byte ratio against the zero_update row.
python bench.py --zero
# Wire micro-bench: CPU-safe, sub-minute, and it gates the zero-copy
# PS codec path against the recorded ps_wire row on every CI pass.
python bench.py --wire
# Telemetry cost gate: disabled-mode span overhead must stay within
# max_disabled_overhead_pct (PERF_BASELINE.json telemetry_overhead row).
python bench.py --telemetry-overhead
# Training-health monitor gate: the fused on-device numerics bundle must
# stay within max_overhead_pct of a host-bound step (health_overhead row).
python bench.py --health-overhead
# Performance-attribution plane gate: per-dispatch cost counting plus the
# log-boundary span join must stay within max_overhead_pct of a host-bound
# step (attr_overhead row); the enabled run's profile JSON lands in the
# smoke dir for the adprof self-diff below.
ADPROF_SMOKE_DIR=$(mktemp -d)
AUTODIST_PROFILE_DIR="$ADPROF_SMOKE_DIR" python bench.py --attr-overhead
# adprof self-diff smoke: a profile diffed against itself must report zero
# regressions (exit 0) — the CI-gating contract adprof's exit code carries.
ADPROF_SMOKE=$(ls "$ADPROF_SMOKE_DIR"/profile-*.json | head -1)
python tools/adprof.py "$ADPROF_SMOKE" "$ADPROF_SMOKE" --threshold 5
rm -rf "$ADPROF_SMOKE_DIR"
# Fleet metrics plane gate: a history sample (registry snapshot + JSONL
# shard line + the shipped alert-rule tick) plus one OpenMetrics render,
# amortized over a log period, must stay within max_overhead_pct of a
# host-bound step (metrics_overhead row).
python bench.py --metrics-overhead
# Memory plane gate: the census re-tag (params + opt_state weakref claims)
# plus one attributed sample_device_memory pass, amortized over a log
# period, must stay within max_overhead_pct of a host-bound step
# (mem_overhead row).
python bench.py --mem-overhead
# Cluster trace plane gate: a full-ring `trace` pull's chief-side
# snapshot+encode must stay under max_stall_ms (trace_pull row).
python bench.py --trace-pull-overhead
# Request-trace plane gate: armed lifecycle marks (AUTODIST_REQTRACE=1)
# must stay within max_overhead_pct of the mean served-request latency
# through a real router fleet (reqtrace_overhead row).
python bench.py --reqtrace-overhead
# Input-data plane gate: under an injected slow host loader the async
# prefetch producer must beat the synchronous feed by min_ratio steps/s,
# keep the data_wait share below the data_wait_drift band, keep naming
# the slow loader via data.producer_wait, and stay bit-identical
# (data_plane row).
python bench.py --data-plane
# Self-healing runtime gate: a worker killed mid-run by the fault harness
# must be evicted, respawned, and caught up over read_min, with the run
# completing on finite params at >= min_ratio of the fault-free steps/s
# after the eviction point (selfheal row).
python bench.py --selfheal
# Priced wire-compression gate: under an injected slow wire, int8+EF
# compressed pushes must beat exact pushes by min_ratio steps/s with
# consistent dense-minus-wire bytes_saved accounting and finite params
# (wire_compress row).
python bench.py --wire-compress
# Plan-autotuner gate: the predict-prune-probe search must measure at most
# top-k of the enumerated candidates and its winner must not lose to the
# default plan (autotune row: tuned/default >= min_ratio).
python bench.py --autotune
# Serving plane gate: continuous batching must beat static wave batching
# on loopback requests/s at equal-or-better p99 (serving row).
python bench.py --serve
# Fleet-serving gate: paged KV must pack >= min_concurrency_ratio x the
# dense slab's concurrent requests at the SAME HBM with bit-identical
# outputs, and the kill-a-replica leg must complete every request with
# zero client-visible failures and a booked respawn (serve_fleet row).
python bench.py --serve-fleet
python bench.py

echo "=== CI OK ==="
