"""Performance attribution: static program costs, phase shares, roofline gauges.

The telemetry stack records WHAT happened (spans, metrics, anomalies); this
module answers WHY A STEP IS SLOW, in three layers over the same substrate:

- **Static cost extraction** — at the runner's compile-probe site (the shape-
  signature dispatch in ``runner.py``), every first-of-its-signature program
  contributes ``lowered.compile().cost_analysis()`` (flops, bytes accessed,
  output bytes) to a per-signature :class:`ProgramCost` cache; later
  dispatches of the same signature only bump its dispatch count. Where the
  backend reports nothing (pallas-dominated programs), an analytic estimate
  installed via :func:`set_analytic_flops` (``utils/flops.py``'s counts)
  stands in, marked ``source="analytic"``.
- **Phase attribution + roofline gauges** — :func:`observe_period` decomposes
  each train() log period's wall time into ``train.attr.{data_wait,host,comm,
  compute,readback}`` share gauges by joining the period's span durations
  (``spans._export_columns``) against the host timeline, and books
  ``train.mfu`` / ``train.membw_util`` — achieved flops/s and bytes/s over
  the :func:`peak_spec` hardware peaks — from the period's dispatched program
  costs. ``compute`` is the residual: wall time the host spent neither
  producing data, dispatching, on the wire, nor syncing — i.e. parked behind
  the device. Shares always sum to 1.0 (test-pinned).
- **Profile store** — :func:`write_profile` emits one schema-versioned JSON
  per run (program costs, per-period attribution + MFU series, weighted
  summary, env manifest via the flight recorder's manifest helper);
  ``tools/adprof.py`` summarizes and DIFFS two profiles, naming the regressed
  phase, and :mod:`autodist_tpu.telemetry.costmodel` calibrates a step-time
  predictor from one — the interface ROADMAP item 3's strategy search calls.

Cost contract: everything here keys off :func:`active` — profiling rides the
span plane, so :func:`enable` also enables spans. With profiling off and
telemetry on, dispatch counting is one dict increment per dispatch; with
both off, the hot paths pay nothing new (``bench.py --attr-overhead`` gates
the enabled side at <=2% of a host-bound step).
"""

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from autodist_tpu import const
from autodist_tpu.telemetry import metrics as _metrics
from autodist_tpu.telemetry import spans as _spans
from autodist_tpu.utils import logging
from autodist_tpu.testing.sanitizer import san_lock

__all__ = ["PeakSpec", "peak_spec", "ProgramCost", "enable", "disable",
           "active", "reset", "note_dispatch", "record_program_cost",
           "program_costs", "set_analytic_flops", "set_applied_plan",
           "applied_plan", "observe_period",
           "format_attr_line", "format_shares", "attribution_periods",
           "profile_document",
           "write_profile", "maybe_write_profile", "PROFILE_SCHEMA",
           "PROFILE_SCHEMA_VERSION", "ATTR_PHASES"]

# Profile JSON identity, pinned by tests and read back by tools/adprof.py and
# telemetry/costmodel.py. Bump the version on any breaking key change.
PROFILE_SCHEMA = "autodist-profile"
PROFILE_SCHEMA_VERSION = 1

# The attribution phases, in the order log lines and adprof render them.
ATTR_PHASES = ("compute", "comm", "host", "data_wait", "readback")

# bf16 peak FLOP/s per chip by device_kind prefix (public spec sheets) —
# migrated here from utils/flops.py so FLOPs and bandwidth peaks live in ONE
# peak-spec table (flops.device_peak_flops delegates back to peak_spec()).
PEAK_BF16_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 197e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # v6e (Trillium)
    "TPU v6e": 918e12,
}

# HBM bandwidth per chip, bytes/s (public spec sheets), same prefix keying.
PEAK_HBM_BYTES = {
    "TPU v5 lite": 819e9,    # v5e: 819 GB/s
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v5": 819e9,
    "TPU v4": 1228e9,
    "TPU v6 lite": 1640e9,   # v6e
    "TPU v6e": 1640e9,
}


@dataclass(frozen=True)
class PeakSpec:
    """Per-device hardware peaks the roofline gauges divide by. ``None``
    means unknown (e.g. CPU without an override) — dependent gauges are
    simply not booked then, never guessed."""

    flops_per_s: Optional[float]
    membw_bytes_per_s: Optional[float]
    source: str   # "env" | "device:<kind>" | "unknown"

    def to_dict(self) -> Dict[str, Any]:
        return {"flops_per_s": self.flops_per_s,
                "membw_bytes_per_s": self.membw_bytes_per_s,
                "source": self.source}


_WARNED_PEAKS = set()


def _parse_peak(raw: str, flag: str) -> Optional[float]:
    """A peak override as float, or None when unset OR malformed — peaks
    must never break a run (observe_period calls this at every training log
    boundary), so a typo'd ``AUTODIST_PEAK_FLOPS=197T`` warns once and
    degrades to unknown instead of raising."""
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        if flag not in _WARNED_PEAKS:
            _WARNED_PEAKS.add(flag)
            logging.warning("%s=%r is not a number; ignoring the override "
                            "(use plain floats like 197e12)", flag, raw)
        return None


def peak_spec(device=None) -> PeakSpec:
    """The shared peak-spec helper: per-device peak FLOP/s and HBM bytes/s.

    ``AUTODIST_PEAK_FLOPS`` / ``AUTODIST_PEAK_MEMBW`` override either side
    (new hardware, calibrated peaks); otherwise both come from the device
    kind's spec-sheet tables. CPU (and unknown kinds) yield ``None`` sides —
    MFU against a meaningless peak would be noise."""
    flops_env = str(const.ENV.AUTODIST_PEAK_FLOPS.val)
    membw_env = str(const.ENV.AUTODIST_PEAK_MEMBW.val)
    flops = _parse_peak(flops_env, "AUTODIST_PEAK_FLOPS")
    membw = _parse_peak(membw_env, "AUTODIST_PEAK_MEMBW")
    if flops is None:
        flops_env = ""   # a rejected override falls through to the tables
    if membw is None:
        membw_env = ""
    if flops is not None and membw is not None:
        return PeakSpec(flops, membw, "env")
    kind = ""
    if flops is None or membw is None:
        try:
            import jax
            device = device or jax.devices()[0]
            if device.platform != "cpu":
                kind = getattr(device, "device_kind", "") or ""
        except Exception:  # noqa: BLE001 — peaks must never break a run
            kind = ""
    for prefix, peak in PEAK_BF16_FLOPS.items():
        if kind.startswith(prefix):
            flops = peak if flops is None else flops
            break
    for prefix, peak in PEAK_HBM_BYTES.items():
        if kind.startswith(prefix):
            membw = peak if membw is None else membw
            break
    if flops_env or membw_env:
        source = "env"
    elif kind:
        source = f"device:{kind}"
    else:
        source = "unknown"
    return PeakSpec(flops, membw, source)


@dataclass
class ProgramCost:
    """One compiled program's static cost record, keyed by the runner's
    shape-signature digest (the crc32 the ``jit.compile`` span carries).
    ``flops``/``bytes_accessed`` are PER DISPATCH of the program — a fused
    ``steps=K`` block program already contains its K scanned steps, so
    per-step numbers divide by ``steps``."""

    sig: str
    kind: str                       # "step" | "many" | caller-defined
    steps: int = 1                  # train steps one dispatch advances
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    output_bytes: Optional[float] = None
    compile_s: Optional[float] = None
    dispatches: int = 0
    source: Optional[str] = None    # "xla" | "analytic" | None (unknown)
    # The memory ledger: XLA's full memory_analysis() per program — the
    # bytes a dispatch pins while it runs (arguments + outputs + temps +
    # code), UNscaled by steps (unlike flops, a K-step block's working set
    # does not multiply). ``temp_bytes`` is the term the cost model adds to
    # resident state for its peak-HBM estimate.
    argument_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    generated_code_bytes: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "steps": self.steps, "flops": self.flops,
                "bytes_accessed": self.bytes_accessed,
                "output_bytes": self.output_bytes,
                "compile_s": self.compile_s, "dispatches": self.dispatches,
                "source": self.source,
                "argument_bytes": self.argument_bytes,
                "temp_bytes": self.temp_bytes,
                "generated_code_bytes": self.generated_code_bytes}


class _State:
    """Process-global profiling state; one lock covers the cost cache and the
    period bookkeeping (boundary-rate access only — never per dispatch
    beyond one dict increment)."""

    def __init__(self):
        self.enabled = False
        self.lock = san_lock()
        self.costs: Dict[str, ProgramCost] = {}
        self.analytic_flops_per_step: Optional[float] = None
        self.periods: List[Dict[str, Any]] = []
        self.period_start_ns: Optional[int] = None
        self.last_dispatches: Dict[str, int] = {}
        # The execution plan this process applied (the autotuner's record:
        # cache key + knobs + predicted vs measured) — attached to profile
        # JSONs and flight-recorder manifests so a snapshot or adprof diff
        # names which plan a run was executing. Survives reset(): it
        # describes the session, not an attribution period.
        self.applied_plan: Optional[Dict[str, Any]] = None


_STATE = _State()
_MAX_PERIODS = 4096   # ~4k log boundaries per run retained in a profile


def enable():
    """Turn the attribution plane on. Profiling joins span durations, so this
    also enables span recording (the reverse is not true: telemetry alone
    never pays for cost extraction)."""
    _STATE.enabled = True
    _spans.enable()
    with _STATE.lock:
        if _STATE.period_start_ns is None:
            _STATE.period_start_ns = time.perf_counter_ns()
            # Baseline the dispatch counters at the window open: telemetry-
            # only runs count dispatches too (note_dispatch), and a mid-run
            # enable() must not charge the whole prior run's dispatches to
            # its first period.
            _STATE.last_dispatches = {sig: rec.dispatches
                                      for sig, rec in _STATE.costs.items()}


def disable():
    _STATE.enabled = False
    with _STATE.lock:
        # Close the attribution clock: without this, the first
        # observe_period after a re-enable would charge the whole disabled
        # stretch (no spans recorded there, so it lands in "compute") to
        # its period — exactly what the interleaved attr-overhead bench
        # rounds would hit.
        _STATE.period_start_ns = None


def active() -> bool:
    return _STATE.enabled


def reset():
    """Drop every cost record and attribution period (tests; production
    profiling state lives for the process)."""
    with _STATE.lock:
        _STATE.costs.clear()
        _STATE.periods.clear()
        _STATE.last_dispatches.clear()
        _STATE.analytic_flops_per_step = None
        _STATE.period_start_ns = (time.perf_counter_ns()
                                  if _STATE.enabled else None)


def set_applied_plan(plan: Optional[Dict[str, Any]]):
    """Record the execution plan this process is running (the autotuner's
    ``TunedPlan.to_dict()`` + name). Rides every subsequently-written
    profile document (``"plan"`` key) and flight-recorder manifest, so
    diagnostics name the plan a run was executing. ``None`` clears."""
    with _STATE.lock:
        _STATE.applied_plan = dict(plan) if plan else None


def applied_plan() -> Optional[Dict[str, Any]]:
    with _STATE.lock:
        return dict(_STATE.applied_plan) if _STATE.applied_plan else None


def set_analytic_flops(flops_per_step: Optional[float]):
    """Install the analytic per-step FLOPs fallback (``utils/flops.py``'s
    counts) used when a compiled program reports no cost analysis — the
    pallas-kernel case, where XLA sees an opaque custom call."""
    with _STATE.lock:
        _STATE.analytic_flops_per_step = flops_per_step


def note_dispatch(sig: str, kind: str, steps: int = 1):
    """Count one dispatch of signature ``sig`` (get-or-create its record).
    Called by the runner for EVERY compiled-program dispatch while telemetry
    is enabled — one dict increment, so it is cheap enough to ride the
    existing signature computation."""
    with _STATE.lock:
        rec = _STATE.costs.get(sig)
        if rec is None:
            rec = _STATE.costs[sig] = ProgramCost(sig=sig, kind=kind,
                                                  steps=int(steps))
        rec.dispatches += 1


def record_program_cost(sig: str, kind: str, steps: int,
                        cost: Optional[Dict[str, float]],
                        compile_s: Optional[float] = None) -> ProgramCost:
    """Attach a compiled program's static costs to its signature record
    (creating it if the dispatch count never touched it). ``cost`` is the
    runner-extracted ``{"flops", "bytes_accessed", "output_bytes"}`` dict
    (plus the ``argument_bytes``/``temp_bytes``/``generated_code_bytes``
    memory ledger), or None when the backend reported nothing — the analytic
    fallback (scaled by ``steps``) stands in then."""
    with _STATE.lock:
        rec = _STATE.costs.get(sig)
        if rec is None:
            rec = _STATE.costs[sig] = ProgramCost(sig=sig, kind=kind,
                                                  steps=int(steps))
        rec.kind = kind
        rec.steps = int(steps)
        if compile_s is not None:
            rec.compile_s = float(compile_s)
        if cost:
            # The memory ledger rides independently of the flops report: a
            # pallas-opaque program can still name its working set.
            for field in ("argument_bytes", "temp_bytes",
                          "generated_code_bytes"):
                if cost.get(field) is not None:
                    setattr(rec, field, int(cost[field]))
        analytic = None
        if _STATE.analytic_flops_per_step is not None:
            analytic = float(_STATE.analytic_flops_per_step) * int(steps)
        if cost and cost.get("flops"):
            rec.flops = float(cost["flops"])
            rec.bytes_accessed = cost.get("bytes_accessed")
            rec.output_bytes = cost.get("output_bytes")
            rec.source = "xla"
            # Partially-pallas programs report nonzero-but-short flops (XLA
            # counts its own ops, not the custom call's — the flagship's
            # fused vocab head is the dominant term it misses). Each
            # accounting is a LOWER bound on what executes, so take
            # whichever sees more.
            if analytic is not None and analytic > rec.flops:
                rec.flops = analytic
                rec.source = "analytic"
        elif analytic is not None:
            rec.flops = analytic
            rec.source = "analytic"
        return rec


def program_costs() -> Dict[str, ProgramCost]:
    """A point-in-time copy of the per-signature cost cache."""
    with _STATE.lock:
        return dict(_STATE.costs)


# ------------------------------------------------------------- attribution

# Span-name -> phase classification. ``train.dispatch`` is the gross host
# cost of one step's feed/dispatch work (it wraps shard_batch + the enqueue
# + any synchronous PS exchange); ``ps.*`` spans nested inside it are pulled
# out as ``comm``, and the unrolled loop's ``runner.shard_block`` spans —
# recorded in gather(), OUTSIDE train.dispatch — are added back in (block
# stacking + h->d transfer is host work even when it overlaps the device;
# the attribution is a host-timeline decomposition). Outside train() (a
# bare runner loop) the dispatch spans themselves stand in for the host
# phase.
_HOST_SPANS = ("train.dispatch",)
_HOST_SIBLING_SPANS = ("runner.shard_block",)
_HOST_FALLBACK_SPANS = ("runner.run.dispatch", "runner.run_many.dispatch",
                        "runner.shard_batch", "runner.shard_block",
                        "jit.compile")


def _period_span_seconds(since_ns: int) -> Dict[str, float]:
    """Sum span durations since ``since_ns`` into phase buckets (seconds)."""
    (_, _, names, _, name_idx, _, t0s, durs, _,
     _, _, _) = _spans._export_columns(since_ns)
    by_name: Dict[str, float] = {}
    for n, dur in zip(name_idx, durs):
        name = names[n]
        by_name[name] = by_name.get(name, 0.0) + dur
    data_wait = by_name.get("train.data_wait", 0.0)
    readback = by_name.get("train.readback_wait", 0.0)
    comm = sum(v for k, v in by_name.items() if k.startswith("ps."))
    host = sum(by_name.get(k, 0.0) for k in _HOST_SPANS)
    if host:
        # ps.* exchanges run nested inside train.dispatch — pull them out so
        # comm is not double-counted as host; gather()'s shard_block spans
        # are train.dispatch SIBLINGS, so they add.
        host = max(0.0, host - comm) \
            + sum(by_name.get(k, 0.0) for k in _HOST_SIBLING_SPANS)
    else:
        host = sum(by_name.get(k, 0.0) for k in _HOST_FALLBACK_SPANS)
    return {"data_wait": data_wait / 1e9, "host": host / 1e9,
            "comm": comm / 1e9, "readback": readback / 1e9}


def observe_period(step: Optional[int] = None,
                   require_steps: bool = False) -> Optional[Dict[str, Any]]:
    """Close one attribution period at a train-loop log boundary.

    Joins the period's span durations against its dispatched program costs
    and books the gauges: ``train.attr.<phase>`` (fractions of period wall
    time, summing to 1.0 — ``compute`` is the unexplained residual, i.e. the
    host parked behind the device), ``train.mfu`` / ``train.membw_util``
    (achieved over :func:`peak_spec` peaks, only when both sides are known)
    and ``train.flops_per_s``. Returns the period record (appended to the
    profile's series), or None when profiling is off or the period is
    degenerate (zero wall time).

    ``require_steps=True`` (the end-of-run flush) drops a period that saw
    NO dispatches — a run whose last boundary just closed would otherwise
    append a step-less tail (checkpoint/teardown wall time) that distorts
    the period-weighted summary."""
    if not _STATE.enabled:
        return None
    now_ns = time.perf_counter_ns()
    with _STATE.lock:
        start_ns = _STATE.period_start_ns
        _STATE.period_start_ns = now_ns
        if start_ns is None or now_ns <= start_ns:
            return None
        # Dispatch deltas since the last boundary, joined against costs.
        flops = bytes_acc = 0.0
        steps = dispatches = 0
        flops_known = True
        for sig, rec in _STATE.costs.items():
            delta = rec.dispatches - _STATE.last_dispatches.get(sig, 0)
            if delta <= 0:
                continue
            _STATE.last_dispatches[sig] = rec.dispatches
            dispatches += delta
            steps += delta * rec.steps
            if rec.flops is not None:
                flops += delta * rec.flops
                if rec.bytes_accessed is not None:
                    bytes_acc += delta * rec.bytes_accessed
            else:
                flops_known = False
    if require_steps and steps == 0:
        return None
    period_s = (now_ns - start_ns) / 1e9
    measured = _period_span_seconds(start_ns)
    # Residual = wall time not explained by any instrumented host phase: the
    # loop parked behind the device (or uninstrumented host work). Clamped
    # at 0 when overlapped background threads (the PS prefetch socket) make
    # measured phase time exceed wall time; normalizing by the parts' sum
    # keeps the shares a distribution either way.
    residual = max(0.0, period_s - sum(measured.values()))
    parts = dict(measured, compute=residual)
    total = sum(parts.values())
    if total <= 0:
        return None
    shares = {k: parts[k] / total for k in ATTR_PHASES}
    peaks = peak_spec()
    flops_per_s = (flops / period_s) if flops else None
    bytes_per_s = (bytes_acc / period_s) if bytes_acc else None
    mfu = (flops_per_s / peaks.flops_per_s
           if flops_per_s and peaks.flops_per_s else None)
    membw = (bytes_per_s / peaks.membw_bytes_per_s
             if bytes_per_s and peaks.membw_bytes_per_s else None)
    record: Dict[str, Any] = {
        "step": step,
        "period_s": round(period_s, 6),
        "steps": steps,
        "dispatches": dispatches,
        "steps_per_s": round(steps / period_s, 4) if steps else None,
        "shares": {k: round(v, 4) for k, v in shares.items()},
        "flops_per_s": flops_per_s,
        "bytes_per_s": bytes_per_s,
        "flops_known": flops_known,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "membw_util": round(membw, 4) if membw is not None else None,
    }
    for phase in ATTR_PHASES:
        _metrics.gauge(f"train.attr.{phase}").set(record["shares"][phase])
    if flops_per_s is not None:
        _metrics.gauge("train.flops_per_s").set(flops_per_s)
    if mfu is not None:
        _metrics.gauge("train.mfu").set(record["mfu"])
    if membw is not None:
        _metrics.gauge("train.membw_util").set(record["membw_util"])
    with _STATE.lock:
        _STATE.periods.append(record)
        if len(_STATE.periods) > _MAX_PERIODS:
            del _STATE.periods[0]
    return record


def attribution_periods() -> List[Dict[str, Any]]:
    """A copy of the recorded per-period attribution series."""
    with _STATE.lock:
        return list(_STATE.periods)


_SHARE_ABBREV = {"compute": "comp", "comm": "comm", "host": "host",
                 "data_wait": "data", "readback": "rb"}


def format_shares(shares: Dict[str, float]) -> str:
    """``comp .61 comm .05 host .22 data .07 rb .05`` — the ONE compact
    share rendering, shared by the ``train:`` log-line suffix and adtop's
    ``perf`` line so the two can never drift. Phases absent from ``shares``
    are skipped (adtop renders whatever gauges the run booked)."""
    return " ".join(
        f"{_SHARE_ABBREV[k]} {shares[k]:.2f}".replace(" 0.", " .")
        for k in ATTR_PHASES if k in shares)


def format_attr_line(record: Optional[Dict[str, Any]]) -> str:
    """The compact ``train:`` log-line suffix for one period record:
    ``mfu 28.3% | comp .61 comm .05 host .22 data .07 rb .05`` (phases
    abbreviated, mfu omitted when unknown)."""
    if not record:
        return ""
    mfu = record.get("mfu")
    head = f"mfu {100.0 * mfu:.1f}% | " if mfu is not None else ""
    return f" | {head}{format_shares(record['shares'])}"


# ------------------------------------------------------------ profile store

def _summary(periods: List[Dict[str, Any]],
             costs: Dict[str, ProgramCost]) -> Dict[str, Any]:
    """Period_s-weighted aggregate of the attribution series plus per-step
    cost averages — the numbers adprof diffs and costmodel calibrates on."""
    total_s = sum(p["period_s"] for p in periods)
    total_steps = sum(p["steps"] for p in periods)
    total_disp = sum(p["dispatches"] for p in periods)
    out: Dict[str, Any] = {
        "wall_s": round(total_s, 6),
        "steps": total_steps,
        "dispatches": total_disp,
        "steps_per_s": round(total_steps / total_s, 4)
        if total_s and total_steps else None,
        "step_s": round(total_s / total_steps, 6)
        if total_steps else None,
    }
    if total_s:
        shares = {k: sum(p["shares"][k] * p["period_s"] for p in periods)
                  / total_s for k in ATTR_PHASES}
        out["shares"] = {k: round(v, 4) for k, v in shares.items()}
        mfus = [(p["mfu"], p["period_s"]) for p in periods
                if p.get("mfu") is not None]
        if mfus:
            out["mfu"] = round(sum(m * w for m, w in mfus)
                               / sum(w for _, w in mfus), 4)
        bw = [(p["membw_util"], p["period_s"]) for p in periods
              if p.get("membw_util") is not None]
        if bw:
            out["membw_util"] = round(sum(m * w for m, w in bw)
                                      / sum(w for _, w in bw), 4)
    flops = sum((r.flops or 0.0) * r.dispatches for r in costs.values())
    bytes_acc = sum((r.bytes_accessed or 0.0) * r.dispatches
                    for r in costs.values())
    run_steps = sum(r.steps * r.dispatches for r in costs.values())
    if run_steps:
        out["flops_per_step"] = flops / run_steps if flops else None
        out["bytes_per_step"] = bytes_acc / run_steps if bytes_acc else None
    if total_disp and total_steps and out.get("step_s") and out.get("shares"):
        # Host seconds per dispatch: what the cost model charges each
        # program launch (dispatch amortization is why unroll=K wins).
        out["host_s_per_dispatch"] = round(
            out["shares"]["host"] * out["step_s"] * total_steps / total_disp,
            9)
    return out


def profile_document(extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The in-memory profile: schema header, env manifest (the flight
    recorder's helper), hardware peaks, per-signature program costs, the
    attribution series, and the weighted summary."""
    from autodist_tpu.telemetry import recorder as _recorder
    periods = attribution_periods()
    costs = program_costs()
    doc: Dict[str, Any] = {
        "schema": PROFILE_SCHEMA,
        "schema_version": PROFILE_SCHEMA_VERSION,
        "manifest": _recorder.build_manifest("profile"),
        "peaks": peak_spec().to_dict(),
        "programs": {sig: rec.to_dict() for sig, rec in sorted(costs.items())},
        "periods": periods,
        "summary": _summary(periods, costs),
    }
    plan = applied_plan()
    if plan:
        # Which execution plan produced these numbers (autotuner record:
        # cache key + knobs + predicted vs measured) — so adprof diffs can
        # say "the regression is plan A vs plan B", not just "it got slower".
        doc["plan"] = plan
    # PS-wire traffic, when the run mirrored any (the registry's ps.wire.*
    # counters): costmodel.calibrate derives the measured wire bandwidth
    # from these + the comm share — the interconnect term of predict().
    snap = _metrics.snapshot()
    wire = {key: snap[f"ps.wire.{key}"] for key in
            ("bytes_sent", "bytes_received", "bytes_saved",
             "bytes_quantized")
            if isinstance(snap.get(f"ps.wire.{key}"), (int, float))
            and snap[f"ps.wire.{key}"] > 0}
    # The compressor's host seconds live under its own wire.* prefix (it is
    # not transport traffic); calibrate's quantize_bytes_per_s fit reads
    # bytes_quantized / quantize_s out of this same block.
    qs = snap.get("wire.quantize_s")
    if isinstance(qs, (int, float)) and qs > 0:
        wire["quantize_s"] = qs
    if wire:
        doc["wire"] = wire
    if extra:
        doc.update(extra)
    return doc


def write_profile(path: str,
                  extra: Optional[Dict[str, Any]] = None) -> str:
    """Write the per-run profile JSON to ``path``; returns ``path``. The
    document is self-contained — ``tools/adprof.py`` and
    :mod:`telemetry.costmodel` read it with no live process."""
    doc = profile_document(extra)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, default=str)
    logging.info("profiling: wrote profile (%d program(s), %d period(s)) "
                 "to %s", len(doc["programs"]), len(doc["periods"]), path)
    return path


_WRITE_SEQ = 0


def maybe_write_profile() -> Optional[str]:
    """End-of-run hook (``train()`` calls it): write a profile into
    ``AUTODIST_PROFILE_DIR`` when profiling is active and the flag names a
    directory; no-op (None) otherwise. A failed write logs and returns None —
    diagnostics must never take down the run they describe."""
    global _WRITE_SEQ
    if not _STATE.enabled:
        return None
    out_dir = str(const.ENV.AUTODIST_PROFILE_DIR.val)
    if not out_dir:
        return None
    proc = int(const.ENV.AUTODIST_PROCESS_ID.val)
    try:
        os.makedirs(out_dir, exist_ok=True)
        # pid + per-process seq: concurrent runs sharing a dir never clobber
        # (the recorder's snap-dir collision class).
        path = os.path.join(
            out_dir, f"profile-w{proc}-p{os.getpid()}-{_WRITE_SEQ:03d}.json")
        _WRITE_SEQ += 1
        return write_profile(path)
    except (OSError, ValueError, TypeError) as e:
        logging.warning("profiling: profile write failed: %s", e)
        return None


# AUTODIST_PROFILE=1 arms the attribution plane at import (and with it span
# recording), mirroring AUTODIST_TELEMETRY's contract — worker processes
# launched with an inherited env profile without code changes.
if const.ENV.AUTODIST_PROFILE.val:
    enable()
