"""Uneven-partition PS strategy.

Port of reference ``autodist/strategy/uneven_partition_ps_strategy.py``: identical to
PartitionedPS except the shard count is the smallest **non**-divisor >= 2 of dim0
(``:125-135``), deliberately producing uneven shards to exercise remainder logic. On
TPU uneven shards compile to pad-and-mask sharding (SURVEY.md §7.3 hard part #2).
"""

from autodist_tpu.strategy.partition_utils import smallest_non_divisor_at_least_2
from autodist_tpu.strategy.partitioned_ps_strategy import PartitionedPS


class UnevenPartitionedPS(PartitionedPS):
    @staticmethod
    def _shard_count(dim0: int, cap: int):
        return smallest_non_divisor_at_least_2(dim0, cap)
