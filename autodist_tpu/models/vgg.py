"""VGG-16 — the dense-parameter-heavy benchmark model.

The reference used VGG16 as the PS/partitioning stress case (its ~500MB of dense fc
weights are why ``PartitionedPS`` exists; chunk-size tuning at
``examples/benchmark/imagenet.py:150-160``). The huge fc layers are exactly what the
partitioned strategies shard across the mesh.
"""

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp


class VGG16(nn.Module):
    num_classes: int = 1000
    dtype: type = jnp.bfloat16

    @nn.compact
    def __call__(self, images):
        x = images.astype(self.dtype)
        for stage, (filters, convs) in enumerate(
                [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]):
            for c in range(convs):
                x = nn.relu(nn.Conv(filters, (3, 3), dtype=self.dtype,
                                    param_dtype=jnp.float32,
                                    name=f"conv{stage}_{c}")(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, param_dtype=jnp.float32,
                             name="fc1")(x))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, param_dtype=jnp.float32,
                             name="fc2")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def make_loss_fn(model: VGG16) -> Callable:
    from autodist_tpu.models.common import make_classification_loss_fn
    return make_classification_loss_fn(model)


def init_params(model: VGG16, rng=None, image_size: int = 224):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    images = jnp.zeros((2, image_size, image_size, 3), jnp.float32)
    from autodist_tpu.models.common import jit_init
    return jit_init(model, images, rng=rng)


def synthetic_batch(num_classes: int, batch_size: int, image_size: int = 224,
                    seed: int = 0):
    import numpy as np
    rng = np.random.RandomState(seed)
    return {
        "images": rng.randn(batch_size, image_size, image_size, 3).astype(np.float32),
        "labels": rng.randint(0, num_classes, size=(batch_size,)).astype(np.int32),
    }
