"""lm1b language-model training with words/sec instrumentation.

Port of reference ``examples/lm1b/lm1b_train.py`` (LSTM + sampled softmax +
``autodist.function`` stepping, wps printed per 100 steps at ``:64-74``), rebuilt
on the TPU-first Transformer LM with the Parallax hybrid strategy (dense layers
all-reduce, untied embedding to PS — the same routing the reference applied to
lm1b's sparse embedding).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax.numpy as jnp

from autodist_tpu import AutoDist
from autodist_tpu.models import lstm_lm, transformer_lm
from autodist_tpu.strategy import Parallax
from autodist_tpu.strategy.auto_strategy import choose_optimizer
from autodist_tpu.utils.metrics import ThroughputMeter


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", choices=["transformer", "lstm"],
                        default="transformer",
                        help="'lstm' = the reference's exact model family "
                             "(LSTM + sampled softmax)")
    parser.add_argument("--steps", type=int, default=200)
    # 0 = auto: 128 (v5e sweep: ~214k wps at 128 vs ~88k at 32), except 96
    # for the giant-vocab full-softmax config, whose parameters + Adafactor
    # state leave less HBM headroom (128 OOMs there).
    parser.add_argument("--batch_size", type=int, default=0)
    parser.add_argument("--seq_len", type=int, default=256)
    parser.add_argument("--log_every", type=int, default=100)
    parser.add_argument("--d_model", type=int, default=512)
    parser.add_argument("--n_layers", type=int, default=6)
    parser.add_argument("--vocab", type=int, default=32000)
    parser.add_argument("--full_softmax", action="store_true",
                        help="LSTM only: train with the EXACT full-vocab softmax "
                             "(pallas fused kernels; logits never materialized) "
                             "instead of the reference's sampled approximation — "
                             "works even at --vocab 793471 (lm1b's real size)")
    parser.add_argument("--resource_spec", type=str, default=None)
    parser.add_argument("--data_dir", type=str, default=None,
                        help="Stream training tokens from tokens-*.npy shards "
                             "in this directory (memory-mapped; written by "
                             "--write_synthetic_corpus or any tokenizer that "
                             "saves [rows, seq_len+1] int32 .npy shards) "
                             "instead of a device-resident synthetic batch")
    parser.add_argument("--write_synthetic_corpus", type=int, default=0,
                        metavar="ROWS",
                        help="Write ROWS synthetic token rows as .npy shards "
                             "into --data_dir and exit (corpus prep)")
    parser.add_argument("--tokenize_corpus", type=str, default=None,
                        metavar="GLOB",
                        help="Tokenize whitespace-split text files (the 1B-word"
                             "-benchmark layout the reference read, its "
                             "lm1b_train.py:26-50) into token shards under "
                             "--data_dir and exit. Word->id comes from "
                             "--vocab_file (the published 1b_word_vocab.txt "
                             "format) or, absent one, a frequency vocab of "
                             "size --vocab built from the corpus itself")
    parser.add_argument("--vocab_file", type=str, default=None,
                        help="Vocab file for --tokenize_corpus (word in the "
                             "first whitespace column per line, frequency-"
                             "sorted; OOV words hash into one extra bucket)")
    args = parser.parse_args(argv)
    if args.full_softmax and args.model != "lstm":
        parser.error("--full_softmax applies to --model lstm")
    if args.write_synthetic_corpus:
        if not args.data_dir:
            parser.error("--write_synthetic_corpus needs --data_dir")
        import numpy as np

        from autodist_tpu.data import save_shards, text_corpus
        rows = args.write_synthetic_corpus
        rng = np.random.RandomState(0)
        tokens = rng.randint(0, args.vocab, size=(rows, args.seq_len + 1),
                             ).astype(np.int32)
        files = save_shards({"tokens": tokens}, args.data_dir,
                            rows_per_shard=max(1, rows // 8))
        # Same sidecar a tokenized corpus carries, so the train run's
        # vocab/seq_len validation works for either prep.
        text_corpus.write_meta(args.data_dir, vocab_size=args.vocab,
                               seq_len=args.seq_len, rows=rows,
                               stride=args.seq_len + 1, oov_buckets=0)
        print(f"wrote {rows} rows across {len(files['tokens'])} shards "
              f"in {args.data_dir}")
        return None
    if args.tokenize_corpus:
        if not args.data_dir:
            parser.error("--tokenize_corpus needs --data_dir")
        from autodist_tpu.data import text_corpus
        # Known words cap at --vocab - 1 so the total INCLUDING the OOV
        # bucket never exceeds --vocab (the embedding size the train run
        # defaults to).
        if args.vocab_file:
            vocab = text_corpus.load_vocab(args.vocab_file,
                                           max_size=max(1, args.vocab - 1))
        else:
            vocab = text_corpus.build_vocab(args.tokenize_corpus,
                                            max_size=max(1, args.vocab - 1))
        shards = text_corpus.tokenize_to_shards(
            args.tokenize_corpus, vocab, args.data_dir, seq_len=args.seq_len)
        print(f"tokenized corpus -> {len(shards)} shards in {args.data_dir}; "
              f"train with --data_dir {args.data_dir} "
              f"--vocab {vocab.vocab_size} --seq_len {args.seq_len}")
        return None

    import jax
    on_accel = jax.default_backend() != "cpu"
    dtype = jnp.bfloat16 if on_accel else jnp.float32

    if args.model == "lstm":
        cfg = lstm_lm.LSTMLMConfig(
            vocab_size=args.vocab, emb_dim=args.d_model,
            hidden_dim=2 * args.d_model, n_layers=args.n_layers, dtype=dtype)
        model, params = lstm_lm.init_params(cfg)
        loss_fn = (lstm_lm.make_fused_full_softmax_loss_fn(model)
                   if args.full_softmax else lstm_lm.make_loss_fn(model))
    else:
        cfg = transformer_lm.TransformerLMConfig(
            vocab_size=args.vocab, d_model=args.d_model, n_heads=8,
            n_layers=args.n_layers, d_ff=4 * args.d_model, max_len=args.seq_len + 1,
            dtype=dtype, tied_output=False)
        model, params = transformer_lm.init_params(cfg)
        loss_fn = transformer_lm.make_loss_fn(model)

    # Optimizer choice is the strategy layer's: choose_optimizer shape-
    # evaluates Adam's exact state bytes against the device budget and falls
    # back to Adafactor's factored second moment when the moments don't fit
    # — the giant-vocab (793k) full-softmax config lands there (its two
    # ~4.9 GB tables put Adam's f32 moments past one v5e's HBM). The smaller
    # default batch rides the same decision: memory-tight configs get the
    # headroom-safe 96 (128 OOMs there; v5e sweep otherwise favors 128).
    choice = choose_optimizer(params, learning_rate=1e-3)
    optimizer = choice.optimizer
    print(f"optimizer: {choice.reason}")
    if not args.batch_size:
        args.batch_size = 96 if choice.factored else 128
    if args.model == "lstm":
        batch = lstm_lm.synthetic_batch(cfg, args.batch_size, args.seq_len,
                                        sampled=not args.full_softmax)
    else:
        batch = transformer_lm.synthetic_batch(cfg, args.batch_size, args.seq_len)

    ad = AutoDist(args.resource_spec, strategy_builder=Parallax())
    step = ad.function(loss_fn, params, optimizer, example_batch=batch)

    feed = None
    if args.data_dir:
        # Real input pipeline: tokens stream from memory-mapped .npy shards
        # through the native prefetch ring (gather off the GIL) and
        # device_prefetch (host->HBM ahead of the step) — the reference read
        # its lm1b corpus from files the same way (lm1b_train.py:30-50).
        if "neg_ids" in batch:
            parser.error("--data_dir feeds token shards; the sampled-softmax "
                         "LSTM draws negatives host-side per batch — use "
                         "--full_softmax (or the transformer) with --data_dir")
        import glob as globlib
        from autodist_tpu.data import DataLoader, device_prefetch
        shards = sorted(globlib.glob(os.path.join(args.data_dir, "tokens-*.npy")))
        if not shards:
            parser.error(f"no tokens-*.npy shards under {args.data_dir} "
                         f"(--write_synthetic_corpus prepares one)")
        import numpy as np
        head = np.load(shards[0], mmap_mode="r")
        if head.ndim != 2 or head.dtype != np.int32:
            parser.error(f"corpus shards must be [rows, seq_len+1] int32; "
                         f"{shards[0]} is {head.dtype} with {head.ndim} dims")
        if head.shape[1] != args.seq_len + 1:
            parser.error(f"corpus rows are {head.shape[1]} tokens wide; the "
                         f"model needs seq_len+1 = {args.seq_len + 1}")
        from autodist_tpu.data import text_corpus
        meta = text_corpus.read_meta(args.data_dir)
        if meta and meta["vocab_size"] > args.vocab:
            parser.error(
                f"corpus in {args.data_dir} was tokenized with vocab_size "
                f"{meta['vocab_size']} (see tokens-meta.json) but the model "
                f"has --vocab {args.vocab}; ids would gather out of range")
        loader = DataLoader(files={"tokens": shards},
                            batch_size=args.batch_size, shuffle=True)
        feed = device_prefetch(loader, step.runner, depth=2)
    else:
        # Keep the synthetic batch device-resident: re-shipping it from host
        # every step benchmarks the host link, not the chip.
        batch = step.runner.shard_batch(batch)

    # wps counted over target tokens, logged per --log_every steps (reference
    # lm1b_train.py:64-74 cadence).
    meter = ThroughputMeter(batch_size=args.batch_size * args.seq_len,
                            log_every=args.log_every, unit="words")
    loss = None
    try:
        for i in range(args.steps):
            loss = step(next(feed) if feed is not None else batch)
            meter.step(sync=loss)
    finally:
        if feed is not None:
            feed.close()   # stop the producer before its loader goes away
    print(f"final loss {float(loss):.4f}; average {meter.average or 0:.1f} words/sec")
    if not getattr(args, "full_softmax", False):
        # XLA cost analysis of the compiled step (skipped for --full_softmax,
        # whose fused pallas loss is invisible to the analysis).
        from autodist_tpu.utils import flops as flops_util
        tokens_per_step = args.batch_size * args.seq_len
        flops_util.report_mfu(
            flops_util.train_step_flops(step.runner, step.get_state(), batch),
            (meter.average or 0) / tokens_per_step)
    return meter.average


if __name__ == "__main__":
    main()
