"""Mesh construction over the 8-device virtual CPU backend."""

import jax
import pytest

from autodist_tpu import const
from autodist_tpu.parallel.mesh import (STANDARD_AXES, build_mesh, single_device_mesh,
                                        standard_mesh_shape)
from autodist_tpu.resource_spec import ResourceSpec
from shardmap_compat import requires_shard_map


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


def test_default_mesh_is_pure_data_parallel():
    mesh = build_mesh()
    assert mesh.axis_names == STANDARD_AXES
    assert mesh.shape[const.MESH_AXIS_DATA] == 8
    assert all(mesh.shape[a] == 1 for a in STANDARD_AXES if a != const.MESH_AXIS_DATA)


def test_mesh_from_resource_spec_axes():
    spec = ResourceSpec("{nodes: [{address: localhost, tpus: 8}], mesh: {model: 2}}")
    mesh = build_mesh(spec)
    assert mesh.shape[const.MESH_AXIS_MODEL] == 2
    assert mesh.shape[const.MESH_AXIS_DATA] == 4


def test_explicit_fill_axis():
    shape = standard_mesh_shape(8, {"data": 2, "reduce": -1})
    assert shape["reduce"] == 4


def test_bad_axis_name_rejected():
    with pytest.raises(ValueError, match="Unknown mesh axes"):
        standard_mesh_shape(8, {"banana": 2})


def test_non_divisible_rejected():
    with pytest.raises(ValueError):
        standard_mesh_shape(8, {"data": 3})


def test_overcommit_rejected():
    with pytest.raises(ValueError):
        standard_mesh_shape(8, {"data": 4, "model": 4})


def test_single_device_mesh():
    mesh = single_device_mesh()
    assert mesh.size == 1


@requires_shard_map
def test_psum_on_mesh_works():
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = build_mesh()
    x = np.arange(8.0)

    @jax.jit
    def total(v):
        return jax.lax.psum(v, const.MESH_AXIS_DATA)

    from jax import shard_map
    f = shard_map(total, mesh=mesh,
                  in_specs=P(const.MESH_AXIS_DATA),
                  out_specs=P())
    out = f(x)
    assert float(out[0]) == 28.0
