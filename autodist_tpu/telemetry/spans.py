"""Host-side span tracing: a thread-aware timeline for the dispatch loop.

``jax.profiler`` answers "what did the DEVICE do"; nothing answered "what did
the HOST do between dispatches" — data wait, feed sharding, gate round-trips,
readback sync. This module records named wall-clock spans into a bounded
in-memory ring buffer, exportable as Chrome trace-event JSON
(:func:`autodist_tpu.telemetry.export_chrome_trace`) that loads in Perfetto
next to the device trace (``docs/usage/observability.md`` shows the overlay
workflow).

Cost contract: when telemetry is DISABLED (the default), :func:`span` performs
exactly one attribute read and returns a shared no-op context manager — the
instrumented hot paths (``runner.run``, the train loop, the PS client) pay
nanoseconds per step, gated in ``bench.py --telemetry-overhead``. When
enabled, a span costs two ``perf_counter_ns`` reads and one deque append
(appends on a ``maxlen`` deque are atomic, so recording takes no lock).

Spans nest by containment: Chrome's trace viewer stacks same-thread ``"X"``
(complete) events whose time ranges nest, so no explicit parent ids are kept.
"""

import collections
import functools
import os
import threading
import time
from typing import Any, Dict, Optional

from autodist_tpu import const

__all__ = ["span", "traced", "enable", "disable", "enabled", "clear",
           "snapshot_spans"]


class _State:
    """Process-global telemetry state. ``enabled`` is THE hot-path gate: the
    disabled fast path reads this one attribute and nothing else."""

    __slots__ = ("enabled", "ring", "thread_names", "lock", "epoch_ns")

    def __init__(self, capacity: int):
        self.enabled = False
        self.ring = collections.deque(maxlen=capacity)
        self.thread_names: Dict[int, str] = {}
        self.lock = threading.Lock()
        # Export offsets span timestamps against this epoch so traces start
        # near t=0 instead of at an arbitrary monotonic-clock origin.
        self.epoch_ns = time.perf_counter_ns()


def _ring_capacity() -> int:
    cap = const.ENV.AUTODIST_TELEMETRY_RING.val
    return max(1, int(cap))


_STATE = _State(_ring_capacity())


class _NullSpan:
    """The shared disabled-mode context manager / decorator: every method is
    a no-op and ``span()`` returns this one instance, so the disabled cost is
    a single attribute check plus an identity return."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span: records ``(name, tid, t0_ns, dur_ns, args)`` on exit."""

    __slots__ = ("name", "args", "_t0")

    def __init__(self, name: str, args: Optional[Dict[str, Any]]):
        self.name = name
        self.args = args
        self._t0 = 0

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        st = _STATE
        tid = threading.get_ident()
        # Recording takes the state lock: a bare deque.append is atomic, but
        # readers (snapshot/export, possibly mid-`finally` while a prefetch
        # thread's span exits) iterate the ring, and CPython raises
        # "deque mutated during iteration" for a concurrent append. One
        # uncontended lock per span exit is ~100ns — inside the enabled-mode
        # budget bench.py --telemetry-overhead tracks.
        with st.lock:
            if tid not in st.thread_names:
                st.thread_names[tid] = threading.current_thread().name
            st.ring.append((self.name, tid, self._t0, t1 - self._t0,
                            self.args))
        return False


def span(name: str, **args):
    """Record the enclosed block as a named host-timeline span.

    ``with telemetry.span("dispatch"): ...`` — extra keyword arguments ride
    into the Chrome trace event's ``args`` (keep them small and
    JSON-serializable). Disabled mode returns a shared no-op context manager
    after a single attribute check."""
    if not _STATE.enabled:
        return _NULL_SPAN
    return _Span(name, args or None)


def traced(name: Optional[str] = None, **args):
    """Decorator face of :func:`span`: ``@telemetry.traced("load_batch")``
    (or bare ``@telemetry.traced()`` to use the function's qualname). The
    enabled check happens per CALL, so functions decorated at import time
    start recording when telemetry is enabled later."""
    def deco(fn):
        label = name or fn.__qualname__
        span_args = args or None

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            if not _STATE.enabled:
                return fn(*a, **kw)
            with _Span(label, span_args):
                return fn(*a, **kw)
        return wrapper
    return deco


def enable():
    """Turn span recording (and registry mirroring) on for this process."""
    _STATE.enabled = True


def disable():
    _STATE.enabled = False


def enabled() -> bool:
    return _STATE.enabled


def clear():
    """Drop all recorded spans and thread names (the registry is separate —
    see :func:`autodist_tpu.telemetry.registry`)."""
    with _STATE.lock:
        _STATE.ring.clear()
        _STATE.thread_names.clear()
        _STATE.epoch_ns = time.perf_counter_ns()


def snapshot_spans():
    """A point-in-time copy of the ring: a list of
    ``(name, tid, t0_ns, dur_ns, args)`` tuples, oldest first."""
    with _STATE.lock:
        return list(_STATE.ring)


def _export_state(since_ns: Optional[int] = None):
    """(pid, epoch_ns, spans, thread_names) for the exporter; ``since_ns``
    keeps only spans that STARTED at/after that perf_counter_ns stamp (the
    windowed-export filter ``tracing.trace(with_host_spans=True)`` uses)."""
    with _STATE.lock:
        spans = list(_STATE.ring)
        names = dict(_STATE.thread_names)
        epoch = _STATE.epoch_ns
    if since_ns is not None:
        spans = [s for s in spans if s[2] >= since_ns]
    return os.getpid(), epoch, spans, names


# AUTODIST_TELEMETRY=1 enables at import so every entry point (examples,
# bench, worker processes the coordinator launches with an inherited env)
# records without code changes.
if const.ENV.AUTODIST_TELEMETRY.val:
    enable()
