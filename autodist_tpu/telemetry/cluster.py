"""Cluster trace plane: clock-aligned multi-worker host timelines.

The span ring (:mod:`autodist_tpu.telemetry.spans`) answers "what did THIS
process do"; at pod scale the question is "what did the CLUSTER do during one
step" — which worker's gate wait, input stall, or compile is the bottleneck.
This module makes span rings portable and mergeable:

- :func:`local_trace_state` snapshots the ring as a COLUMNAR, wire-encodable
  blob (name/tid tables + int32 index columns + int64 ``t0_ns``/``dur_ns``
  arrays): 65536 spans ship as a handful of large ndarrays the zero-copy PS
  wire frames without per-span Python encoding — that is what keeps a
  full-ring pull off the chief's critical path (``bench.py
  --trace-pull-overhead`` gates it).
- The PS transport's ``trace`` opcode serves this blob on demand and
  ``push_trace`` lets a worker deposit its own ring on the chief
  (:mod:`autodist_tpu.parallel.ps_transport`); ``ping`` round-trips feed
  :func:`ntp_offset`, the NTP-style chief-clock offset estimate each worker
  stores per connection.
- :func:`collect_cluster_trace` (chief) / :func:`merge_trace_states` merge
  any set of blobs into ONE Chrome trace-event file with a ``pid`` lane per
  worker, every lane rebased onto the chief's wall clock via each blob's
  ``clock_offset_ns`` — loadable in Perfetto beside a ``jax.profiler``
  device trace.
- :func:`dump_spans_jsonl` / :func:`load_trace_jsonl` are the offline path:
  per-worker JSONL ring dumps that ``tools/tracedump.py`` merges after the
  run, when no transport was up to push through.

Clock model: spans are stamped with ``time.perf_counter_ns`` (monotonic,
process-local origin). Each blob carries one ``(wall_ns, perf_ns)`` pair
sampled back-to-back under the ring lock, so a span's wall-clock start is
``wall_ns + (t0_ns - perf_ns)``; adding the blob's ``clock_offset_ns``
(estimated as chief-clock minus local-clock, see :func:`ntp_offset`) lands it
on the chief's timeline. Offset uncertainty is bounded by half the best
observed ping RTT — microseconds on loopback, sub-millisecond on a pod's
DCN, far below the millisecond-scale spans the plane exists to compare.
"""

import json
import socket
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from autodist_tpu.telemetry import reqtrace as _reqtrace
from autodist_tpu.telemetry import spans as _spans
from autodist_tpu.utils import logging

__all__ = ["local_trace_state", "ntp_offset", "trace_state_events",
           "merge_trace_states", "collect_cluster_trace", "dump_spans_jsonl",
           "load_trace_jsonl", "dump_events_jsonl", "load_events_jsonl",
           "local_reqtrace_state", "reqtrace_marks", "reqtrace_trace_events",
           "dump_reqtrace_jsonl", "load_reqtrace_jsonl"]

# Trace-blob schema version (bumped on layout changes so an old tracedump
# rejects a new dump instead of misreading it).
TRACE_STATE_VERSION = 1

# Request-trace blob schema version (the `reqtrace` opcode's payload and the
# offline reqtrace JSONL dumps both carry it).
REQTRACE_STATE_VERSION = 1

_PLAIN = frozenset((str, int, float, bool, type(None)))


def _sanitize_args(args: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Span args restricted to wire/JSON-safe scalars (anything else rides as
    ``str(value)`` — a span arg must never make a ring unshippable)."""
    if not args:
        return None
    return {str(k): (v if type(v) in _PLAIN else str(v))
            for k, v in args.items()}


def _args_json(args_map: Dict[int, Dict[str, Any]]) -> str:
    """The sparse ``{span_index: args}`` map as ONE JSON string: C-speed
    serialization instead of thousands of nested wire dicts (a full-ring
    blob with per-step annotations would otherwise dominate the pull's
    chief-side stall — the ``bench.py --trace-pull-overhead`` gate)."""
    try:
        return json.dumps(args_map, default=str)
    except (TypeError, ValueError):
        # Pathological args (non-str/int dict keys etc.): sanitize per entry.
        return json.dumps({i: _sanitize_args(a)
                           for i, a in args_map.items()}, default=str)


def _parse_args_json(state: Dict[str, Any]) -> Dict[int, Dict[str, Any]]:
    """The blob's sparse args map with int span indices restored (JSON and
    the typed wire both stringify/accept the keys differently)."""
    raw = state.get("args_json")
    parsed = json.loads(raw) if raw else {}
    return {int(k): v for k, v in parsed.items()}


def local_trace_state(since_ns: Optional[int] = None,
                      worker_id: Optional[int] = None,
                      clock_offset_ns: int = 0) -> Dict[str, Any]:
    """Snapshot this process's span ring as a wire-encodable columnar blob.

    ``since_ns`` (a ``perf_counter_ns`` stamp) keeps only spans started
    at/after it; ``worker_id`` labels the blob's lane in a merged trace;
    ``clock_offset_ns`` is the chief-minus-local clock offset the holder
    estimated (0 for the chief itself). Columns: ``names``/``tids`` are
    de-duplicated tables, ``name_idx``/``tid_idx`` int32 index columns,
    ``t0_ns``/``dur_ns`` int64, sparse span args as one JSON string. The
    span ring is stored columnar with interned ids
    (:mod:`autodist_tpu.telemetry.spans`), so a full 65536-span ring
    snapshots + encodes in tens of milliseconds — no per-span Python tuples
    anywhere between the ring and the wire — which is what keeps a live
    trace pull off the chief's critical path (``bench.py
    --trace-pull-overhead`` gates it)."""
    (pid, epoch_ns, names, tids, name_idx, tid_idx, t0s, durs, args,
     thread_names, wall_ns, perf_ns) = _spans._export_columns(since_ns)
    return {
        "v": TRACE_STATE_VERSION,
        "pid": pid,
        "host": socket.gethostname(),
        "worker_id": worker_id,
        "wall_ns": wall_ns,
        "perf_ns": perf_ns,
        "epoch_ns": epoch_ns,
        "clock_offset_ns": int(clock_offset_ns),
        "names": names,
        "name_idx": np.array(name_idx, np.int32),
        "tids": tids,
        "tid_idx": np.array(tid_idx, np.int32),
        "t0_ns": np.array(t0s, np.int64),
        "dur_ns": np.array(durs, np.int64),
        "args_json": _args_json({i: a for i, a in enumerate(args) if a}),
        "thread_names": {int(t): nm for t, nm in thread_names.items()},
    }


def ntp_offset(samples: Sequence[Tuple[int, int, int]]) -> Tuple[int, int]:
    """NTP-style clock offset from ping round-trips.

    ``samples`` holds ``(t0_ns, server_ns, t1_ns)`` per round trip: the
    caller's wall clock at send and receive bracketing the server's wall
    stamp. Assuming symmetric delay, the server's clock leads the caller's by
    ``server_ns - (t0 + t1) / 2``; the MEDIAN across rounds rejects the
    odd delayed exchange. Returns ``(offset_ns, uncertainty_ns)`` where the
    uncertainty is half the best observed RTT — the worst-case error a fully
    asymmetric path could hide inside the tightest round trip."""
    if not samples:
        raise ValueError("ntp_offset needs at least one (t0, server, t1) sample")
    offsets = sorted(s_ns - (t0 + t1) // 2 for t0, s_ns, t1 in samples)
    rtt_min = min(t1 - t0 for t0, _, t1 in samples)
    return offsets[len(offsets) // 2], max(0, rtt_min // 2)


def _wall_starts(state: Dict[str, Any]) -> np.ndarray:
    """Per-span chief-timeline wall-clock starts (ns) for one blob."""
    base = (int(state["wall_ns"]) - int(state["perf_ns"])
            + int(state.get("clock_offset_ns", 0)))
    return np.asarray(state["t0_ns"], np.int64) + base


def _lane_label(state: Dict[str, Any]) -> str:
    wid = state.get("worker_id")
    who = "chief" if wid is None else f"worker {wid}"
    return f"{who} ({state.get('host', '?')} pid {state.get('pid', '?')})"


def trace_state_events(state: Dict[str, Any], pid: int,
                       origin_ns: int) -> List[Dict[str, Any]]:
    """One blob as Chrome trace events on lane ``pid``: an ``M``
    process_name event, ``M`` thread_name events, then one ``X`` (complete)
    event per span with ``ts``/``dur`` in microseconds relative to
    ``origin_ns`` (a chief-timeline wall stamp)."""
    events: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": _lane_label(state)}}]
    tids = [int(t) for t in state["tids"]]
    thread_names = {int(t): nm
                    for t, nm in dict(state.get("thread_names", {})).items()}
    for tid in sorted(set(tids)):
        nm = thread_names.get(tid)
        if nm:
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": nm}})
    names = list(state["names"])
    name_idx = np.asarray(state["name_idx"], np.int64)
    tid_idx = np.asarray(state["tid_idx"], np.int64)
    dur_ns = np.asarray(state["dur_ns"], np.int64)
    starts = _wall_starts(state)
    args_map = _parse_args_json(state)
    for i in range(len(name_idx)):
        events.append({
            "name": names[name_idx[i]],
            "ph": "X",
            "cat": "host",
            "ts": float(int(starts[i]) - origin_ns) / 1e3,
            "dur": float(dur_ns[i]) / 1e3,
            "pid": pid,
            "tid": tids[tid_idx[i]],
            "args": args_map.get(i) or {},
        })
    return events


def _assign_pid(state: Dict[str, Any], used: set) -> int:
    """Deterministic lane id: chief -> 0, worker w -> w + 1, collisions walk
    to the next free id (two blobs from the same worker id stay distinct).
    Non-numeric worker labels (adtrace tags blobs with their ``host:port``
    endpoint) start from the next free slot after the numeric lanes."""
    wid = state.get("worker_id")
    if wid is None:
        pid = 0
    else:
        try:
            pid = int(wid) + 1
        except (TypeError, ValueError):
            pid = len(used) + 1
    while pid in used:
        pid += 1
    used.add(pid)
    return pid


def instant_trace_events(records: Iterable[Dict[str, Any]], pid: int,
                         origin_ns: int) -> List[Dict[str, Any]]:
    """Registry event records (``telemetry.events()`` /
    :func:`load_events_jsonl`) as Chrome INSTANT events on lane ``pid``:
    a process_name metadata event plus one ``"i"`` (global-scope) marker per
    record, placed by its ``t_wall_s`` wall stamp relative to ``origin_ns``
    — so anomalies appear as vertical markers over the span timeline."""
    out: List[Dict[str, Any]] = []
    markers = []
    for rec in records:
        rec = dict(rec)
        name = str(rec.pop("name", "event"))
        t_wall_s = rec.pop("t_wall_s", None)
        if t_wall_s is None:
            continue
        markers.append({
            "name": name, "ph": "i", "s": "g", "cat": "anomaly",
            "ts": (float(t_wall_s) * 1e9 - origin_ns) / 1e3,
            "pid": pid, "tid": 0,
            "args": _sanitize_args(rec) or {},
        })
    if markers:
        out.append({"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                    "args": {"name": "events (anomalies)"}})
        out.extend(markers)
    return out


def merge_trace_states(states: Iterable[Dict[str, Any]], path: str,
                       instant_events: Iterable[Dict[str, Any]] = (),
                       reqtrace_states: Iterable[Dict[str, Any]] = ()) -> str:
    """Merge trace blobs into ONE Chrome trace file at ``path``.

    Every blob's spans are rebased onto the chief wall clock
    (``wall + clock_offset_ns``); the merged origin is the earliest rebased
    span start across all lanes, so the file opens at t=0 in Perfetto.
    ``instant_events`` (registry event records — anomalies) overlay the
    timeline as instant markers on their own lane. ``reqtrace_states``
    (request-lifecycle blobs, :func:`local_reqtrace_state`) add per-request
    lanes and flow arrows (router ``sent`` -> replica ``received``) on the
    SAME clock; a reqtrace blob from a process that also contributed a span
    blob (matched by host + OS pid) shares that process's lane. Returns
    ``path``."""
    states = list(states)
    reqtrace_states = list(reqtrace_states)
    for st in states:
        v = st.get("v", TRACE_STATE_VERSION)
        if v != TRACE_STATE_VERSION:
            raise ValueError(f"trace state version {v} is not supported "
                             f"(this build reads v{TRACE_STATE_VERSION})")
    for st in reqtrace_states:
        v = st.get("v", REQTRACE_STATE_VERSION)
        if v != REQTRACE_STATE_VERSION:
            raise ValueError(f"reqtrace state version {v} is not supported "
                             f"(this build reads v{REQTRACE_STATE_VERSION})")
    origins = [int(_wall_starts(st).min()) for st in states
               if len(np.asarray(st["t0_ns"])) > 0]
    for st in reqtrace_states:
        marks = reqtrace_marks(st)
        if marks:
            origins.append(min(m["wall_ns"] for m in marks))
    instant_events = list(instant_events)
    if not origins and instant_events:
        # Every ring is empty (recording off — an armed recorder without
        # AUTODIST_TELEMETRY still snapshots): anchor the timeline on the
        # earliest event so markers sit near t=0, not at epoch scale.
        stamps = [float(r["t_wall_s"]) for r in instant_events
                  if r.get("t_wall_s") is not None]
        origins = [int(min(stamps) * 1e9)] if stamps else []
    origin_ns = min(origins) if origins else 0
    events: List[Dict[str, Any]] = []
    used: set = set()
    lane_by_proc: Dict[Tuple[Any, Any], int] = {}
    for st in states:
        pid = _assign_pid(st, used)
        lane_by_proc.setdefault((st.get("host"), st.get("pid")), pid)
        events.extend(trace_state_events(st, pid, origin_ns))
    for st in reqtrace_states:
        key = (st.get("host"), st.get("pid"))
        pid = lane_by_proc.get(key)
        if pid is None:
            pid = lane_by_proc[key] = _assign_pid(st, used)
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": _lane_label(st)}})
        events.extend(reqtrace_trace_events(st, pid, origin_ns))
    if instant_events:
        pid = max(used) + 1 if used else 0
        events.extend(instant_trace_events(instant_events, pid, origin_ns))
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(doc, f)
    n_spans = sum(1 for ev in events if ev["ph"] == "X")
    logging.info("Wrote cluster trace: %d span(s) across %d lane(s) to %s",
                 n_spans, len(states), path)
    return path


def collect_cluster_trace(path: str, server=None, peers: Iterable = (),
                          since_ns: Optional[int] = None,
                          include_local: bool = True) -> str:
    """Emit ONE clock-aligned Chrome trace for the cluster at ``path``.

    Lanes, in order:

    - this process's own ring (``include_local``, offset 0 — the caller IS
      the timeline's reference clock; on the chief that is exactly right),
    - every blob pulled from ``peers`` — objects with a
      ``trace(since_ns)`` method, e.g. a
      :class:`~autodist_tpu.parallel.ps_transport.RemotePSWorker` pulling
      the chief's ring from a worker process,
    - every blob workers PUSHED to ``server`` (a
      :class:`~autodist_tpu.parallel.ps_transport.PSServer`; workers deposit
      their rings via ``RemotePSWorker.push_trace()``, automatic at close
      under ``AUTODIST_TRACE_PULL=1``), already carrying each pusher's
      estimated chief-clock offset.

    ``AsyncPSRunner.collect_cluster_trace(path)`` is the chief-side
    convenience wrapper that passes its own PSServer. Load the file in
    ui.perfetto.dev next to a ``jax.profiler`` device trace; each worker is
    its own ``pid`` lane."""
    states: List[Dict[str, Any]] = []
    if include_local:
        states.append(local_trace_state(since_ns))
    for peer in peers:
        states.append(peer.trace(since_ns))
    if server is not None:
        for _, st in sorted(server.worker_traces().items(), key=lambda kv:
                            (str(kv[0]))):
            states.append(st)
    return merge_trace_states(states, path)


def dump_spans_jsonl(path: str, worker_id: Optional[int] = None,
                     since_ns: Optional[int] = None,
                     clock_offset_ns: int = 0) -> str:
    """Dump this process's span ring as JSONL for offline merging.

    Line 1 is the blob's metadata (``{"meta": {...}}``); every following
    line is one span ``[name, tid, t0_ns, dur_ns, args]``. The offline
    counterpart of the ``trace``/``push_trace`` wire path — each worker
    dumps its own file, ``tools/tracedump.py`` merges them afterwards."""
    state = local_trace_state(since_ns, worker_id=worker_id,
                              clock_offset_ns=clock_offset_ns)
    meta = {k: state[k] for k in ("v", "pid", "host", "worker_id", "wall_ns",
                                  "perf_ns", "epoch_ns", "clock_offset_ns",
                                  "thread_names")}
    names = state["names"]
    tids = state["tids"]
    args_map = _parse_args_json(state)
    with open(path, "w") as f:
        f.write(json.dumps({"meta": meta}) + "\n")
        for i in range(len(state["name_idx"])):
            f.write(json.dumps([names[state["name_idx"][i]],
                                tids[state["tid_idx"][i]],
                                int(state["t0_ns"][i]),
                                int(state["dur_ns"][i]),
                                args_map.get(i)]) + "\n")
    return path


def load_trace_jsonl(path: str,
                     clock_offset_ns: Optional[int] = None) -> Dict[str, Any]:
    """Load a :func:`dump_spans_jsonl` file back into a trace blob;
    ``clock_offset_ns`` overrides the dumped offset (the ``tracedump
    --offset`` hook for dumps written before an offset was known)."""
    with open(path) as f:
        header = json.loads(f.readline())
        if not isinstance(header, dict) or "meta" not in header:
            raise ValueError(f"{path}: not a span JSONL dump (no meta line)")
        meta = header["meta"]
        if meta.get("v", TRACE_STATE_VERSION) != TRACE_STATE_VERSION:
            raise ValueError(f"{path}: trace dump version {meta.get('v')} is "
                             f"not supported (this build reads "
                             f"v{TRACE_STATE_VERSION})")
        rows = [json.loads(line) for line in f if line.strip()]
    names: List[str] = []
    name_ix: Dict[str, int] = {}
    tids: List[int] = []
    tid_ix: Dict[int, int] = {}
    n = len(rows)
    name_idx = np.empty(n, np.int32)
    tid_idx = np.empty(n, np.int32)
    t0_ns = np.empty(n, np.int64)
    dur_ns = np.empty(n, np.int64)
    args_map: Dict[int, Dict[str, Any]] = {}
    for i, (name, tid, t0, dur, args) in enumerate(rows):
        j = name_ix.get(name)
        if j is None:
            j = name_ix[name] = len(names)
            names.append(name)
        name_idx[i] = j
        k = tid_ix.get(tid)
        if k is None:
            k = tid_ix[tid] = len(tids)
            tids.append(int(tid))
        tid_idx[i] = k
        t0_ns[i] = t0
        dur_ns[i] = dur
        if args:
            args_map[i] = args
    state = dict(meta)
    if clock_offset_ns is not None:
        state["clock_offset_ns"] = int(clock_offset_ns)
    state.update(names=names, name_idx=name_idx, tids=tids, tid_idx=tid_idx,
                 t0_ns=t0_ns, dur_ns=dur_ns,
                 args_json=_args_json(args_map))
    state["thread_names"] = {int(t): nm for t, nm in
                             dict(meta.get("thread_names", {})).items()}
    return state


# --------------------------------------------------------- request traces

# Named sub-intervals a request's marks imply, rendered as "X" slices on the
# request's lane: (slice name, start phase, end phase). First occurrence of
# the start phase, last of the end phase — a replayed request's repeated
# marks widen the interval instead of fragmenting it.
_REQ_INTERVALS = (
    ("queue", "queued", "admitted"),
    ("prefill", "prefill_start", "prefill_end"),
    ("decode", "first_token", "done"),
    ("route", "received", "finished"),
)
# Phases rendered as instant markers (discrete lifecycle facts, no duration).
_REQ_INSTANTS = ("shed", "replayed")
# Request lanes use tids far above any interned-span lane index but well
# below real pthread idents, so a merged file never collides either way.
_REQ_TID_BASE = 1_000_000


def local_reqtrace_state(since_ns: Optional[int] = None,
                         worker_id: Optional[int] = None,
                         clock_offset_ns: int = 0) -> Dict[str, Any]:
    """Snapshot this process's request-lifecycle ring
    (:mod:`autodist_tpu.telemetry.reqtrace`) as a wire-encodable columnar
    blob — the ``reqtrace`` opcode's payload, same shape discipline as
    :func:`local_trace_state`: a de-duplicated phase table, an int32 phase
    index column, int64 mark stamps, rids verbatim (they are the join key
    and unbounded — interning them would leak), sparse mark args as one
    JSON string, and the back-to-back ``(wall_ns, perf_ns)`` pair the merge
    rebases with."""
    (pid, epoch_ns, phases, rids, phase_idx, t_ns, args,
     wall_ns, perf_ns) = _reqtrace._export_columns(since_ns)
    return {
        "v": REQTRACE_STATE_VERSION,
        "pid": pid,
        "host": socket.gethostname(),
        "worker_id": worker_id,
        "wall_ns": wall_ns,
        "perf_ns": perf_ns,
        "epoch_ns": epoch_ns,
        "clock_offset_ns": int(clock_offset_ns),
        "phases": phases,
        "rids": [str(r) for r in rids],
        "phase_idx": np.array(phase_idx, np.int32),
        "t_ns": np.array(t_ns, np.int64),
        "args_json": _args_json({i: _sanitize_args(a)
                                 for i, a in enumerate(args) if a}),
    }


def reqtrace_marks(state: Dict[str, Any]) -> List[Dict[str, Any]]:
    """One blob's marks rebased onto the merged timeline's wall clock:
    ``{rid, phase, wall_ns, args}`` dicts, oldest first. ``wall_ns`` is the
    blob's wall/perf pair applied to the mark stamp plus the blob's
    ``clock_offset_ns`` — the exact :func:`_wall_starts` arithmetic, so
    span slices and request marks from one process land on one clock."""
    base = (int(state["wall_ns"]) - int(state["perf_ns"])
            + int(state.get("clock_offset_ns", 0)))
    phases = list(state["phases"])
    args_map = _parse_args_json(state)
    out: List[Dict[str, Any]] = []
    rids = list(state["rids"])
    phase_idx = np.asarray(state["phase_idx"], np.int64)
    t_ns = np.asarray(state["t_ns"], np.int64)
    for i in range(len(rids)):
        out.append({"rid": rids[i], "phase": phases[phase_idx[i]],
                    "wall_ns": int(t_ns[i]) + base,
                    "args": args_map.get(i) or {}})
    return out


def reqtrace_trace_events(state: Dict[str, Any], pid: int,
                          origin_ns: int) -> List[Dict[str, Any]]:
    """One reqtrace blob as Chrome trace events on lane ``pid``: each rid
    gets its own request lane (tid), its marks become "X" slices for the
    :data:`_REQ_INTERVALS` its phases bound (a ``received`` mark carrying a
    ``wire_ns`` arg additionally yields a ``wire`` slice ENDING at the
    receive — the wire time the trace-context token decomposed), instant
    markers for shed/replay, plus the FLOW halves: a ``"s"`` (flow start)
    at every ``sent`` mark and a ``"f"`` (flow end) at every ``received``
    mark, id ``<rid>/<hop>`` — the merge pairs a router's send arrow with
    the replica's receive across lanes."""
    by_rid = _reqtrace.group_records(reqtrace_marks(state))
    events: List[Dict[str, Any]] = []
    for lane, rid in enumerate(sorted(by_rid, key=str)):
        tid = _REQ_TID_BASE + lane
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": f"req {rid}"}})
        recs = by_rid[rid]
        first = {}
        last = {}
        for phase, t, args in recs:
            first.setdefault(phase, (t, args))
            last[phase] = (t, args)
        for name, p0, p1 in _REQ_INTERVALS:
            if p0 in first and p1 in last:
                t0, t1 = first[p0][0], last[p1][0]
                if t1 >= t0:
                    events.append({
                        "name": name, "ph": "X", "cat": "reqtrace",
                        "ts": float(t0 - origin_ns) / 1e3,
                        "dur": float(t1 - t0) / 1e3,
                        "pid": pid, "tid": tid, "args": {"rid": str(rid)}})
        for phase, t, args in recs:
            if phase == "received" and args.get("wire_ns"):
                wire_ns = max(0, int(args["wire_ns"]))
                events.append({
                    "name": "wire", "ph": "X", "cat": "reqtrace",
                    "ts": float(t - wire_ns - origin_ns) / 1e3,
                    "dur": float(wire_ns) / 1e3,
                    "pid": pid, "tid": tid, "args": {"rid": str(rid)}})
                events.append({
                    "name": "req", "ph": "f", "bp": "e", "cat": "reqtrace",
                    "id": f"{rid}/{args.get('hop', 0)}",
                    "ts": float(t - origin_ns) / 1e3,
                    "pid": pid, "tid": tid})
            elif phase == "sent":
                events.append({
                    "name": "req", "ph": "s", "cat": "reqtrace",
                    "id": f"{rid}/{args.get('hop', 0)}",
                    "ts": float(t - origin_ns) / 1e3,
                    "pid": pid, "tid": tid})
            elif phase in _REQ_INSTANTS:
                events.append({
                    "name": phase, "ph": "i", "s": "t", "cat": "reqtrace",
                    "ts": float(t - origin_ns) / 1e3,
                    "pid": pid, "tid": tid,
                    "args": dict(_sanitize_args(args) or {}, rid=str(rid))})
    return events


def dump_reqtrace_jsonl(path: str, worker_id: Optional[int] = None,
                        since_ns: Optional[int] = None,
                        clock_offset_ns: int = 0) -> str:
    """Dump this process's request-lifecycle ring as JSONL for offline
    merging (the reqtrace twin of :func:`dump_spans_jsonl`): line 1 is the
    blob metadata (``{"meta": {...}}``), every following line one mark
    ``[rid, phase, t_ns, args]``."""
    state = local_reqtrace_state(since_ns, worker_id=worker_id,
                                 clock_offset_ns=clock_offset_ns)
    meta = {k: state[k] for k in ("v", "pid", "host", "worker_id", "wall_ns",
                                  "perf_ns", "epoch_ns", "clock_offset_ns")}
    meta["kind"] = "reqtrace"
    phases = state["phases"]
    args_map = _parse_args_json(state)
    with open(path, "w") as f:
        f.write(json.dumps({"meta": meta}) + "\n")
        for i in range(len(state["phase_idx"])):
            f.write(json.dumps([state["rids"][i],
                                phases[state["phase_idx"][i]],
                                int(state["t_ns"][i]),
                                args_map.get(i)]) + "\n")
    return path


def load_reqtrace_jsonl(path: str,
                        clock_offset_ns: Optional[int] = None
                        ) -> Dict[str, Any]:
    """Load a :func:`dump_reqtrace_jsonl` file back into a reqtrace blob;
    ``clock_offset_ns`` overrides the dumped offset (the ``tracedump
    --offset`` hook)."""
    with open(path) as f:
        header = json.loads(f.readline())
        if not isinstance(header, dict) or "meta" not in header \
                or header["meta"].get("kind") != "reqtrace":
            raise ValueError(f"{path}: not a reqtrace JSONL dump")
        meta = dict(header["meta"])
        if meta.get("v", REQTRACE_STATE_VERSION) != REQTRACE_STATE_VERSION:
            raise ValueError(f"{path}: reqtrace dump version {meta.get('v')} "
                             f"is not supported (this build reads "
                             f"v{REQTRACE_STATE_VERSION})")
        rows = [json.loads(line) for line in f if line.strip()]
    meta.pop("kind", None)
    phases: List[str] = []
    phase_ix: Dict[str, int] = {}
    n = len(rows)
    phase_idx = np.empty(n, np.int32)
    t_ns = np.empty(n, np.int64)
    rids: List[str] = []
    args_map: Dict[int, Dict[str, Any]] = {}
    for i, (rid, phase, t, args) in enumerate(rows):
        j = phase_ix.get(phase)
        if j is None:
            j = phase_ix[phase] = len(phases)
            phases.append(phase)
        phase_idx[i] = j
        t_ns[i] = t
        rids.append(str(rid))
        if args:
            args_map[i] = args
    state = meta
    if clock_offset_ns is not None:
        state["clock_offset_ns"] = int(clock_offset_ns)
    state.update(phases=phases, rids=rids, phase_idx=phase_idx, t_ns=t_ns,
                 args_json=_args_json(args_map))
    return state


def dump_events_jsonl(path: str, events=None) -> str:
    """Dump structured registry events (``telemetry.events()``) as JSONL —
    one record per line — so anomaly records survive process exit. The event
    ring is in-process and drain-only otherwise; this is its offline leg
    (the flight recorder writes one per snapshot, ``tools/tracedump.py
    --events`` merges the file back into a timeline as instant markers).
    ``events`` defaults to the process registry's current ring."""
    from autodist_tpu.telemetry import metrics as _metrics
    if events is None:
        events = _metrics.events()
    with open(path, "w") as f:
        for rec in events:
            f.write(json.dumps(rec, default=str) + "\n")
    return path


def load_events_jsonl(path: str) -> List[Dict[str, Any]]:
    """Load a :func:`dump_events_jsonl` file back into event records,
    oldest first (each line must be one JSON object with at least a
    ``name``)."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for i, line in enumerate(f):
            if not line.strip():
                continue
            rec = json.loads(line)
            if not isinstance(rec, dict) or "name" not in rec:
                raise ValueError(f"{path}:{i + 1}: not an event record "
                                 f"(expected a JSON object with 'name')")
            out.append(rec)
    return out
