"""Empirical strategy tuning: measure candidate builders, pick the fastest.

Complements :class:`AutoStrategy` (the analytic model): where the cost model
predicts, the tuner *measures* — each candidate strategy is compiled and run for
a few steps on the real model, batch, and devices, and the winner is whatever
was actually fastest. This is the measurement loop the reference's docs leave to
the user (its performance guide tunes ``chunk_size`` per model by hand,
``examples/benchmark/imagenet.py:150-160``), packaged as an API.

Candidates that fail to build or run (OOM, unsupported model shape) are
recorded and skipped rather than aborting the search.

:func:`measure_candidate` is the ONE build/run/timing loop: ``tune_strategy``
drives it over its candidate sweep, and the plan autotuner
(:mod:`autodist_tpu.strategy.autotune`) reuses it as its stage-2 probe — the
failure-skip semantics (a candidate OOMing or landing in the async regime is
recorded, never fatal) live here so the two paths cannot drift.
"""

import dataclasses
import gc
import time
from typing import Any, Callable, List, Optional, Sequence

from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import StrategyBuilder
from autodist_tpu.utils import logging


@dataclasses.dataclass
class CandidateResult:
    builder: StrategyBuilder
    name: str
    steps_per_sec: Optional[float]    # None = failed or skipped
    error: Optional[str] = None
    accumulation_steps: int = 1
    unroll: int = 1
    zero: int = 0


@dataclasses.dataclass
class TuneResult:
    best: StrategyBuilder
    results: List[CandidateResult]
    best_accumulation_steps: int = 1

    def report(self) -> str:
        """Human-readable ranking table."""
        rows = sorted(self.results,
                      key=lambda r: -(r.steps_per_sec or float("-inf")))
        width = max(len(r.name) for r in rows)
        lines = []
        for r in rows:
            if r.steps_per_sec is None:
                label = "SKIPPED" if (r.error or "").startswith("skipped") \
                    else "FAILED"
                lines.append(f"{r.name:<{width}}  {label}: {r.error}")
            else:
                marker = "  <- best" if (
                    r.builder is self.best
                    and r.accumulation_steps == self.best_accumulation_steps) \
                    else ""
                lines.append(f"{r.name:<{width}}  {r.steps_per_sec:8.2f} steps/s"
                             f"{marker}")
        return "\n".join(lines)


def _default_candidates(has_sparse: bool) -> List[StrategyBuilder]:
    from autodist_tpu.strategy import (AllReduce, AutoStrategy, Parallax,
                                       PSLoadBalancing)
    cands: List[StrategyBuilder] = [AllReduce(), PSLoadBalancing(), AutoStrategy()]
    if has_sparse:
        cands.insert(2, Parallax())
    return cands


def measure_candidate(builder: StrategyBuilder, loss_fn: Callable, params: Any,
                      optimizer, example_batch: Any, *,
                      name: Optional[str] = None,
                      resource_spec: Optional[ResourceSpec] = None,
                      warmup_steps: int = 2, measure_steps: int = 8,
                      sparse_names: Optional[Sequence[str]] = None,
                      has_aux: bool = False, accumulation_steps: int = 1,
                      unroll: int = 1,
                      zero: Optional[int] = None) -> CandidateResult:
    """Build ONE candidate's session and time a few real steps on this
    process's devices — the shared probe loop behind :func:`tune_strategy`
    and the autotuner's stage 2.

    The candidate gets ``warmup_steps`` dispatches (compile + first dispatch,
    pipeline-fenced by a host read of the loss) then ``measure_steps`` timed
    dispatches; with ``unroll=K`` each dispatch is one fused K-step block
    (:meth:`DistributedRunner.run_many` over a pre-stacked block of the same
    batch), so ``steps_per_sec`` always counts OPTIMIZER steps and stays
    comparable across unroll factors. The batch is pre-placed once, so the
    timed loop measures the strategy + knobs, not the host link.

    ``zero=None`` (the default) leaves the session reading the
    ``AUTODIST_ZERO`` flag — the pre-refactor tuner behavior; the autotuner
    passes each candidate's explicit value.

    Failure-skip semantics (test-pinned): a candidate that fails to build or
    run returns ``steps_per_sec=None`` with the error recorded; a candidate
    landing in the async regime (``sync=False`` / ``staleness>0``) is
    recorded as skipped — its gate-dominated wall-clock is not comparable to
    a synchronous step. Everything the candidate launched is torn down and
    the process-default AutoDist instance is restored before returning."""
    from autodist_tpu.autodist import (AutoDist, get_default_autodist,
                                       set_default_autodist)

    # Argument errors raise HERE, before the failure-skip guard: a bad
    # warmup_steps must surface as the caller's mistake, not be swallowed
    # into a fake every-candidate-failed search result.
    if warmup_steps < 1:
        raise ValueError("warmup_steps must be >= 1 (the timed loop needs a "
                         "compiled, pipeline-fenced step to start from)")
    if measure_steps < 1:
        raise ValueError("measure_steps must be >= 1")
    if unroll < 1:
        raise ValueError("unroll must be >= 1")
    if name is None:
        name = type(builder).__name__
    zero_rec = int(zero or 0)   # result-record value; None stays env-driven
    prior_default = get_default_autodist()  # the candidate must not leak as default
    ad = None
    runner = state = batch = block = loss = None
    try:
        ad = AutoDist(resource_spec, builder)
        runner = ad.create_distributed_session(
            loss_fn, params, optimizer, example_batch=example_batch,
            sparse_names=sparse_names, has_aux=has_aux,
            accumulation_steps=accumulation_steps, zero=zero, tune=False)
        from autodist_tpu.parallel.staleness import AsyncPSRunner
        if isinstance(runner, AsyncPSRunner):
            # Gate-dominated wall-clock is not comparable to a sync step;
            # record the skip instead of a misleading rate.
            logging.warning("measure_candidate %s: skipped (async regime)",
                            name)
            return CandidateResult(
                builder, name, None,
                "skipped: async candidate (sync=False / staleness>0) — "
                "candidate measurement ranks synchronous strategies only",
                accumulation_steps=accumulation_steps, unroll=unroll,
                zero=zero_rec)
        state = runner.init(params)

        def run_once(s):
            if unroll > 1:
                return runner.run_many(s, block)
            return runner.run(s, batch)

        # Pre-place the batch (run()'s resident-array check then makes the
        # per-step shard a no-op) / pre-stack the block, so the timed loop
        # measures the strategy, not the host link.
        if unroll > 1:
            block = runner.shard_block([example_batch] * unroll)
        else:
            batch = runner.shard_batch(example_batch)
        for _ in range(warmup_steps):
            state, fetched = run_once(state)
        loss = fetched[0] if has_aux else fetched
        _fence(loss)  # compile + pipeline fence before the clock starts
        t0 = time.perf_counter()
        for _ in range(measure_steps):
            state, fetched = run_once(state)
        loss = fetched[0] if has_aux else fetched
        _fence(loss)  # completion fence (device->host read)
        rate = measure_steps * unroll / (time.perf_counter() - t0)
        logging.info("measure_candidate %s: %.2f steps/s", name, rate)
        return CandidateResult(builder, name, rate,
                               accumulation_steps=accumulation_steps,
                               unroll=unroll, zero=zero_rec)
    except Exception as e:  # noqa: BLE001 — a candidate OOMing must not abort
        logging.warning("measure_candidate %s failed: %s", name, e)
        return CandidateResult(builder, name, None,
                               f"{type(e).__name__}: {e}",
                               accumulation_steps=accumulation_steps,
                               unroll=unroll, zero=zero_rec)
    finally:
        # Tear down anything the candidate launched (clusters, PS
        # transports) and drop state + executables before the next
        # candidate is timed.
        if ad is not None:
            try:
                ad._teardown()
            except Exception as e:  # noqa: BLE001
                logging.warning("measure_candidate %s teardown: %s", name, e)
        state = batch = block = runner = ad = loss = None  # noqa: F841
        gc.collect()
        set_default_autodist(prior_default)


def _fence(loss):
    """Host-read the (possibly ``[K]``-stacked) loss: the dispatch fence both
    ends of the timed loop need."""
    import numpy as np
    np.asarray(loss).reshape(-1)[-1].item()


def tune_strategy(loss_fn: Callable, params: Any, optimizer,
                  example_batch: Any,
                  candidates: Optional[Sequence[StrategyBuilder]] = None,
                  resource_spec: Optional[ResourceSpec] = None,
                  warmup_steps: int = 2, measure_steps: int = 8,
                  sparse_names: Optional[Sequence[str]] = None,
                  has_aux: bool = False,
                  accumulation_steps=1) -> TuneResult:
    """Measure each candidate builder on the real (model, batch, devices).

    Returns the fastest builder plus the full ranking; pass ``result.best`` to
    :class:`AutoDist`. Each candidate gets ``warmup_steps`` (compile + first
    dispatch) then ``measure_steps`` timed steps, fenced by a host read of the
    loss. State and compiled executables are dropped between candidates.

    **Ranking is synchronous and local.** Every candidate is stepped on this
    process's devices through the synchronous SPMD runner, so rankings are
    comparable only within that regime: a multi-node ``resource_spec`` is
    rejected (the local measurement would say nothing about cross-node wire
    cost — benchmark those through a real cluster launch), and an async
    candidate (``sync=False`` / ``staleness>0``) is recorded as skipped rather
    than measured (its wall-clock is gate-dominated and not comparable to a
    synchronous step).

    ``accumulation_steps`` may be a single int or a sequence to sweep: each
    candidate is measured at each value (examples/sec comparable because the
    global batch is fixed); ``result.best_accumulation_steps`` carries the
    winner's setting.
    """
    from autodist_tpu.model_spec import ModelSpec

    if warmup_steps < 1:
        raise ValueError("warmup_steps must be >= 1 (the timed loop needs a "
                         "compiled, pipeline-fenced step to start from)")
    if measure_steps < 1:
        raise ValueError("measure_steps must be >= 1")
    if resource_spec is not None and resource_spec.num_nodes > 1:
        raise ValueError(
            "tune_strategy measures candidates synchronously on THIS process's "
            "local devices; a multi-node resource spec would be ranked by a "
            "measurement that ignores the cross-node wire. Tune with a "
            "single-node spec, or benchmark multi-node candidates through a "
            "real cluster launch (examples/benchmark)")
    # bool is an int subclass: True would silently sweep [True]; reject it.
    # numbers.Integral (rather than int) admits numpy integer sweeps like
    # np.arange(1, 5); values are normalized to plain int below.
    import numbers
    if isinstance(accumulation_steps, bool):
        raise TypeError("accumulation_steps must be an int or a sequence of "
                        "ints, not a bool")
    accum_sweep = ([accumulation_steps]
                   if isinstance(accumulation_steps, numbers.Integral)
                   else tuple(accumulation_steps))  # materialize generators
    if not accum_sweep or any(isinstance(a, bool)
                              or not isinstance(a, numbers.Integral)
                              or a < 1 for a in accum_sweep):
        raise ValueError(
            f"accumulation_steps must be an int >= 1 or a non-empty sequence "
            f"of such ints, got {accumulation_steps!r}")
    accum_sweep = [int(a) for a in accum_sweep]
    if candidates is None:
        spec = (ModelSpec(params, sparse_names=sparse_names)
                if sparse_names is not None
                else ModelSpec.from_loss_fn(loss_fn, params, example_batch))
        has_sparse = any(p.sparse for p in spec.trainable.values())
        candidates = _default_candidates(has_sparse)

    results: List[CandidateResult] = []
    for builder, accum in ((b, a) for b in candidates for a in accum_sweep):
        name = type(builder).__name__
        if len(accum_sweep) > 1:
            name = f"{name}[accum={accum}]"
        results.append(measure_candidate(
            builder, loss_fn, params, optimizer, example_batch, name=name,
            resource_spec=resource_spec, warmup_steps=warmup_steps,
            measure_steps=measure_steps, sparse_names=sparse_names,
            has_aux=has_aux, accumulation_steps=accum))

    ranked = [r for r in results if r.steps_per_sec is not None]
    if not ranked:
        raise RuntimeError(
            "tune_strategy: every candidate failed or was skipped:\n" +
            "\n".join(f"  {r.name}: {r.error}" for r in results))
    best = max(ranked, key=lambda r: r.steps_per_sec)
    logging.info("tune_strategy winner: %s (%.2f steps/s)", best.name,
                 best.steps_per_sec)
    return TuneResult(best=best.builder, results=results,
                      best_accumulation_steps=best.accumulation_steps)
